"""Device-side string operations via dictionary lookup tables.

Strings live on device as int32 dictionary ids (core/schema.py
StringDictionary). The reference evaluates string functions row-by-row
inside Spark SQL (``spark.sql`` at CommonProcessorFactory.scala:257);
the TPU-native equivalent computes each string expression ONCE PER
DISTINCT STRING on the host — as a lookup table over the dictionary —
and the device applies it as a single int32 gather per row:

- ``map``    tables: string -> string   (UPPER, TRIM, SUBSTRING, ...)
             id -> id of the result string (result strings are encoded
             into the shared dictionary, so downstream equality /
             GROUP BY / JOIN on transformed strings stay exact)
- ``pred``   tables: string -> boolean  (LIKE, RLIKE, CONTAINS, ...)
- ``scalar`` tables: string -> int32    (LENGTH, INSTR, ...)
- ``rank`` / ``unrank``: the sort permutation of the dictionary,
             enabling string ORDER BY, range comparisons (< > <= >=)
             and MIN/MAX aggregates with exact lexicographic semantics.

The tables are ordinary traced inputs of the jitted step (shape = a
power-of-two capacity >= dictionary size), refreshed incrementally on
the host as the dictionary grows; growth past capacity retraces the
step with the next capacity — amortized, since dictionaries converge
for real streams. This is dramatically cheaper than per-row string
processing: the host does O(new distinct strings) Python-level work per
batch, the device does O(rows) int32 gathers on data that stays in HBM.
"""

from __future__ import annotations

import logging
import re
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

import numpy as np

from ..core.schema import StringDictionary

logger = logging.getLogger(__name__)

# table value kinds -> numpy dtype
_KIND_DTYPE = {
    "map": np.int32,     # result string id
    "pred": np.bool_,    # predicate result
    "scalar": np.int32,  # integer result
}

# keys reserved for the ordering tables
RANK_KEY = "__rank"
UNRANK_KEY = "__unrank"

# keys reserved for the computed-string hash tables. A deferred string
# (CONCAT/CAST result) has no dictionary id, but equality/grouping/joins
# only need a device value that discriminates strings: a polynomial
# rolling hash composes over concatenation —
#   H_p(a + b) = H_p(a) * p^len(b) + H_p(b)   (mod 2^32)
# so per-id tables of H_p(s) and p^len(s) let the device compute the
# hash of any concatenation with one multiply-add per part. TWO
# independent hashes (different odd multipliers) are compared together,
# making an accidental collision a ~2^-64 event — the practical price of
# keeping computed strings fully device-resident (the dictionary stays
# exact for plain string columns).
HASH1_KEY = "__strhash1"
HASH2_KEY = "__strhash2"
PLEN1_KEY = "__strplen1"
PLEN2_KEY = "__strplen2"
HASH_P1 = 1000003
HASH_P2 = 92821

_MASK32 = (1 << 32) - 1


def _wrap_i32(v: int) -> int:
    """uint32 bits as the int32 value numpy will accept (device integer
    arithmetic wraps, so int32 bit patterns compose identically)."""
    v &= _MASK32
    return v - (1 << 32) if v >= (1 << 31) else v


def poly_hash(s: str, p: int) -> int:
    h = 0
    for ch in s:
        h = (h * p + ord(ch) + 1) & _MASK32
    return _wrap_i32(h)


def pow_len(s: str, p: int) -> int:
    return _wrap_i32(pow(p, len(s), 1 << 32))


def register_strhash(registry: "AuxRegistry") -> None:
    """Register the four computed-string hash tables."""
    registry.register(HASH1_KEY, "scalar", lambda s: poly_hash(s, HASH_P1))
    registry.register(HASH2_KEY, "scalar", lambda s: poly_hash(s, HASH_P2))
    registry.register(PLEN1_KEY, "scalar", lambda s: pow_len(s, HASH_P1))
    registry.register(PLEN2_KEY, "scalar", lambda s: pow_len(s, HASH_P2))

# default bound on image-cascade rounds when building map tables:
# functions whose results are new strings (which then need their own
# mapping, e.g. REPLACE(REPLACE(x))) converge within a couple of rounds
# for real flows; pathological self-growing chains stop at the bound,
# which is configurable per flow (``process.stringmap.maxrounds``) along
# with a strict mode (``process.stringmap.strict``) that fails loud
# instead of leaving unconverged entries NULL
_MAX_ROUNDS = 4


@dataclass(frozen=True)
class AuxSpec:
    """One host-computed dictionary table."""

    key: str                 # stable identity (function + const args)
    kind: str                # "map" | "pred" | "scalar"
    fn: Callable[[str], object]  # host fn over a non-null string


class AuxRegistry:
    """Compile-time collection of the dictionary tables a pipeline needs.

    Shared by every ExprCompiler/SelectCompiler of one flow so identical
    subexpressions (same function + same constant args) share a table.
    """

    def __init__(self):
        self.specs: Dict[str, AuxSpec] = {}
        self.needs_rank = False

    def register(self, key: str, kind: str, fn: Callable[[str], object]) -> str:
        if key not in self.specs:
            self.specs[key] = AuxSpec(key, kind, fn)
        return key

    def require_rank(self) -> None:
        self.needs_rank = True

    @property
    def empty(self) -> bool:
        return not self.specs and not self.needs_rank


def _pow2_capacity(n: int, minimum: int = 1024) -> int:
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


class AuxTableBuilder:
    """Maintains the host-side numpy tables for a registry + dictionary.

    ``tables()`` returns ``{key: jnp.ndarray}`` sized to the current
    power-of-two capacity; map/pred/scalar tables extend incrementally
    (already-computed entries never change), rank tables recompute fully
    whenever the dictionary grew (ranks are global).
    """

    def __init__(
        self,
        registry: AuxRegistry,
        dictionary: StringDictionary,
        *,
        max_rounds: int = _MAX_ROUNDS,
        strict: bool = False,
    ):
        self.registry = registry
        self.dictionary = dictionary
        self.max_rounds = max_rounds
        self.strict = strict
        self._np: Dict[str, np.ndarray] = {}
        self._filled = 0          # entries computed per incremental table
        self._built_len = -1      # dictionary length at last build
        self._device: Optional[Dict[str, object]] = None

    # -- host-side table maintenance --------------------------------------
    def _extend_incremental(self) -> None:
        """Compute table entries for dictionary ids added since last call.

        Encoding a map's result strings can itself add dictionary
        entries (whose own mappings are then needed if maps compose on
        device); iterate until the dictionary stops growing or the
        round bound hits.
        """
        d = self.dictionary
        specs = [s for s in self.registry.specs.values()]
        rounds = 0
        while self._filled < len(d) and rounds < self.max_rounds:
            rounds += 1
            start, end = self._filled, len(d)
            # decode once per new id, apply every spec
            strings = [d.decode(i) for i in range(start, end)]
            for spec in specs:
                vals = np.zeros(end - start, dtype=_KIND_DTYPE[spec.kind])
                for j, s in enumerate(strings):
                    if s is None:
                        # null string: map->null id, pred->False, scalar->0
                        continue
                    try:
                        r = spec.fn(s)
                    except Exception:  # noqa: BLE001 — per-entry host fn
                        r = None
                    if r is None:
                        continue
                    if spec.kind == "map":
                        vals[j] = d.encode(str(r))
                    elif spec.kind == "pred":
                        vals[j] = bool(r)
                    else:
                        vals[j] = int(r)
                prev = self._np.get(spec.key)
                if prev is None or len(prev) < end:
                    grown = np.zeros(
                        _pow2_capacity(len(d)), dtype=_KIND_DTYPE[spec.kind]
                    )
                    if prev is not None:
                        grown[: len(prev)] = prev
                    self._np[spec.key] = grown
                self._np[spec.key][start:end] = vals
            self._filled = end
        if self._filled < len(self.dictionary):
            # every batch that leaves entries unmapped is reported (the
            # set of affected strings changes batch to batch), with a
            # sample of the strings that will evaluate to NULL
            sample = [
                repr(self.dictionary.decode(i))
                for i in range(self._filled, min(self._filled + 5, len(d)))
            ]
            msg = (
                f"string-map cascade did not converge in {self.max_rounds} "
                f"rounds ({self._filled} of {len(self.dictionary)} "
                f"dictionary entries mapped); unconverged entries evaluate "
                f"to NULL, e.g. {', '.join(sample)} — raise "
                f"datax.job.process.stringmap.maxrounds"
            )
            if self.strict:
                from ..core.config import EngineException
                raise EngineException(msg)
            logger.warning(
                "%s, or set datax.job.process.stringmap.strict=true to "
                "fail loud", msg
            )

    def _build_rank(self, capacity: int) -> None:
        """Full lexicographic rank of every dictionary entry.

        None (id 0) ranks first, matching SQL NULLS FIRST on ascending
        order. rank is a permutation of [0, len); unrank inverts it.
        """
        d = self.dictionary
        n = len(d)
        entries = [(d.decode(i) or "", i != 0, i) for i in range(n)]
        # null first, then lexicographic
        order = sorted(range(n), key=lambda i: (entries[i][1], entries[i][0]))
        rank = np.zeros(capacity, dtype=np.int32)
        unrank = np.zeros(capacity, dtype=np.int32)
        for r, i in enumerate(order):
            rank[i] = r
            unrank[r] = i
        self._np[RANK_KEY] = rank
        self._np[UNRANK_KEY] = unrank

    def tables(self) -> Dict[str, object]:
        """Current device tables (jnp arrays), rebuilt only on growth."""
        import jax.numpy as jnp

        if self.registry.empty:
            return {}
        if self._device is not None and self._built_len == len(self.dictionary):
            return self._device
        self._extend_incremental()
        capacity = _pow2_capacity(len(self.dictionary))
        out: Dict[str, object] = {}
        for key in self.registry.specs:
            tbl = self._np[key]
            if len(tbl) < capacity:
                grown = np.zeros(capacity, dtype=tbl.dtype)
                grown[: len(tbl)] = tbl
                self._np[key] = tbl = grown
            out[key] = jnp.asarray(tbl[:capacity])
        if self.registry.needs_rank:
            self._build_rank(capacity)
            out[RANK_KEY] = jnp.asarray(self._np[RANK_KEY])
            out[UNRANK_KEY] = jnp.asarray(self._np[UNRANK_KEY])
        # record what was actually COMPUTED (_filled), not the current
        # dictionary length: a decode-ahead ingest thread may append
        # entries between the extend above and here, and marking those
        # as built would leave their table slots 0/NULL forever
        self._built_len = self._filled
        self._device = out
        return out


# ---------------------------------------------------------------------------
# Host implementations of the SQL string function library.
#
# Each builder returns (key, host_fn). Semantics follow Spark SQL (the
# engine the reference delegates to, CommonProcessorFactory.scala:257):
# 1-based positions, SUBSTRING clamping, LIKE with % and _.
# ---------------------------------------------------------------------------
def like_to_regex(pattern: str) -> str:
    """SQL LIKE pattern -> anchored regex (% = .*, _ = ., rest literal)."""
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 2
            continue
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
        i += 1
    return "".join(out)


def spark_substring(s: str, pos: int, length: Optional[int]) -> str:
    """Spark SUBSTRING: 1-based; pos<=0 counts from the end when
    negative, pos==0 behaves like 1; length clamps."""
    n = len(s)
    if pos > 0:
        start = pos - 1
    elif pos < 0:
        start = max(0, n + pos)
    else:
        start = 0
    if length is None:
        return s[start:]
    if length <= 0:
        return ""
    return s[start : start + length]


def spark_instr(s: str, sub: str) -> int:
    """1-based position of the first occurrence; 0 if absent."""
    return s.find(sub) + 1


def spark_split_at(s: str, delim_regex: str, index: int) -> Optional[str]:
    """element_at(split(s, d), i): 1-based, negative from end; None OOB."""
    parts = re.split(delim_regex, s) if delim_regex else list(s)
    if index == 0:
        return None
    i = index - 1 if index > 0 else len(parts) + index
    if 0 <= i < len(parts):
        return parts[i]
    return None
