"""Persistent XLA compilation cache, optionally shared via objstore://.

The runtime half of the zero-cold-start path (ISSUE: compile manifest +
AOT warm): ``FlowProcessor._aot_warm`` compiles every manifest entry at
init; with this cache enabled the compiles inside that warm resolve
from serialized executables on disk — and, when a shared object store
is configured, newly compiled entries are pushed back so the NEXT start
(restart, preemption recovery, scale-out replica, a LiveQuery kernel
pool on another box) deserializes instead of compiling.

Layering:

- **local dir** (``datax.job.process.compile.cachedir``): jax's own
  persistent compilation cache (``jax_compilation_cache_dir``), tuned
  so every entry persists (no min-size/min-compile-time gating — a
  restart should never recompile something this process already paid
  for).
- **shared store** (``datax.job.process.compile.cacheurl``, an
  ``objstore://host:port/bucket/prefix`` URL): ``enable()`` pulls
  entries absent locally before arming the cache; ``push()`` uploads
  entries created since ``enable()``. Cache files are opaque bytes to
  us — jax names them by its own cache key (backend + jaxlib version +
  computation fingerprint), so a stale entry can never be *loaded*
  wrongly, only ignored.

File counting is at jax-cache-entry granularity (the ``*-cache``
files; ``*-atime`` bookkeeping files are ignored), which is what the
``Compile_Cache_{Hit,Miss}_Count`` metrics report.
"""

from __future__ import annotations

import logging
import os
from typing import List, Optional, Set, Tuple

logger = logging.getLogger(__name__)


def _reset_jax_cache() -> None:
    """Drop jax's memoized cache object so a config change made after
    earlier compiles (the normal case: the engine jits plenty before a
    flow's cache conf is read) actually takes effect."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:  # noqa: BLE001 — private API; degrade to no cache
        logger.warning("jax compilation-cache reset unavailable")


def compile_conf_for(cache_dir: str,
                     cache_url: Optional[str] = None) -> dict:
    """The ``datax.job.process.compile.*`` conf keys that arm this
    cache for a kernel pool — the one way LiveQuery surfaces (REST
    kernel pool, serving-plane warm cache, one-box server) build their
    shared compile conf, so the layers can't drift on key names."""
    conf = {"datax.job.process.compile.cachedir": cache_dir}
    if cache_url:
        conf["datax.job.process.compile.cacheurl"] = cache_url
    return conf


def _parse_objstore_url(url: str) -> Tuple[str, str, str]:
    """objstore://host:port/bucket/prefix -> (endpoint, bucket, prefix)."""
    if url.startswith("objstore+https://"):
        scheme, rest = "https", url[len("objstore+https://"):]
    elif url.startswith("objstore://"):
        scheme, rest = "http", url[len("objstore://"):]
    else:
        raise ValueError(f"not an objstore URL: {url!r}")
    host, _, bucket_key = rest.partition("/")
    bucket, _, prefix = bucket_key.partition("/")
    if not bucket:
        raise ValueError(f"objstore URL needs a bucket: {url!r}")
    return f"{scheme}://{host}", bucket, prefix.strip("/")


class PersistentCompileCache:
    """One flow's compile-cache session: local jax cache dir + optional
    shared objstore layer."""

    def __init__(
        self, cache_dir: Optional[str] = None,
        cache_url: Optional[str] = None,
    ):
        if not cache_dir and not cache_url:
            raise ValueError("cache_dir or cache_url required")
        self.url = cache_url
        self._client = None
        self._prefix = ""
        if cache_url:
            from ..serve.objectstore import ObjectStoreClient

            endpoint, bucket, prefix = _parse_objstore_url(cache_url)
            token = os.environ.get("DATAX_OBJSTORE_TOKEN")
            self._client = ObjectStoreClient(endpoint, bucket, token=token)
            self._prefix = prefix
        if not cache_dir:
            # deterministic local layer per shared prefix so co-located
            # flows sharing a cacheurl also share the local dir
            import hashlib
            import tempfile

            cache_dir = os.path.join(
                tempfile.gettempdir(), "dxtpu-compile-cache",
                hashlib.sha256(cache_url.encode()).hexdigest()[:16],
            )
        self.dir = cache_dir
        self._baseline: Set[str] = set()
        self._prev_config: Optional[tuple] = None

    # -- local entries ---------------------------------------------------
    def _entries(self) -> List[str]:
        try:
            return sorted(
                fn for fn in os.listdir(self.dir)
                if not fn.endswith("-atime") and not fn.endswith(".tmp")
            )
        except OSError:
            return []

    def file_count(self) -> int:
        return len(self._entries())

    # -- lifecycle -------------------------------------------------------
    def enable(self) -> None:
        """Pull shared entries, then arm jax's persistent cache at the
        local dir. Remembers the pre-existing config so ``disable()``
        can restore it (tests; production leaves it armed so later
        re-traces also persist)."""
        os.makedirs(self.dir, exist_ok=True)
        self.pull()
        import jax

        self._prev_config = (
            jax.config.jax_compilation_cache_dir,
            jax.config.jax_persistent_cache_min_entry_size_bytes,
            jax.config.jax_persistent_cache_min_compile_time_secs,
        )
        jax.config.update("jax_compilation_cache_dir", self.dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _reset_jax_cache()
        self._baseline = set(self._entries())

    def disable(self) -> None:
        """Restore the jax cache config captured by ``enable()``."""
        if self._prev_config is None:
            return
        import jax

        d, s, t = self._prev_config
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", s)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", t)
        _reset_jax_cache()
        self._prev_config = None

    # -- shared layer ----------------------------------------------------
    def _key(self, fn: str) -> str:
        return f"{self._prefix}/{fn}" if self._prefix else fn

    def pull(self) -> int:
        """Download shared entries absent locally. FAIL-OPEN: the
        client retries transient failures with bounded jittered backoff
        (serve/objectstore.py), and whatever still fails degrades to
        the local-only cache — a cold compile beats a dead host. Each
        entry fails independently so one bad object can't abort the
        rest of the pull (contrast the state-snapshot store, which is
        fail-closed: runtime/statepartition.py)."""
        if self._client is None:
            return 0
        n = 0
        try:
            have = set(self._entries())
            keys = self._client.list(self._prefix)
        except Exception as e:  # noqa: BLE001 — shared layer is best-effort
            logger.warning("compile-cache pull failed: %s", e)
            return 0
        for key in keys:
            fn = key.rsplit("/", 1)[-1]
            if fn in have or fn.endswith("-atime"):
                continue
            try:
                data = self._client.get(key)
                if data is None:
                    continue
                path = os.path.join(self.dir, fn)
                tmp = path + ".tmp"
                with open(tmp, "wb") as f:
                    f.write(data)
                os.replace(tmp, path)
                n += 1
            except Exception as e:  # noqa: BLE001 — best-effort per entry
                logger.warning("compile-cache pull %s failed: %s", fn, e)
        return n

    def push(self) -> int:
        """Upload entries created since ``enable()`` (the compiles this
        process actually paid for) and return how many there were —
        the ``Compile_Cache_Miss_Count`` number. With no shared store
        the new-entry count still reports (local misses)."""
        new = [fn for fn in self._entries() if fn not in self._baseline]
        if self._client is not None:
            for fn in new:
                try:
                    with open(os.path.join(self.dir, fn), "rb") as f:
                        self._client.put(self._key(fn), f.read())
                except Exception as e:  # noqa: BLE001 — best-effort
                    logger.warning("compile-cache push %s failed: %s", fn, e)
        self._baseline |= set(new)
        return len(new)
