"""Select/pipeline planner: lower parsed SQL onto the ops layer.

The compiled artifact is a pure function over columnar tables — the
whole transform pipeline (all ``--DataXQuery--`` statements of a flow)
composes into one traced program the runtime jits once and reuses every
micro-batch. This replaces the reference's per-batch ``spark.sql``
planning/execution (CommonProcessorFactory.scala:249-293).

Tables flow through as ``TableData`` (columns dict + validity mask);
capacities are static and derived per statement (input capacity for
project/filter/group-by, configured bound for joins, sum for unions).

Deferred string columns (CONCAT results etc.) materialize their device
inputs as hidden ``__defer.`` columns so they ride along through
downstream selects and become strings only on the host at sink time.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field, replace
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp

from ..core.config import EngineException
from ..core.schema import StringDictionary
from ..ops import (
    compact_indices,
    distinct_mask,
    group_ids,
    inner_join_indices,
    segment_aggregate,
)
from ..ops.join import left_join_indices
from .exprs import (
    AGGREGATE_FNS,
    ArrayValue,
    CompiledExpr,
    EvalEnv,
    ExprCompiler,
    HostStr,
    Scope,
    StructValue,
    Value,
    is_device,
)
from .sqlparser import (
    BinOp,
    Col,
    Expr,
    Func,
    Select,
    SelectItem,
    Star,
    parse_select,
)

# ---------------------------------------------------------------------------
# Schemas and table data
# ---------------------------------------------------------------------------
DeferredPart = Union[str, Tuple[str, str]]  # literal | (hidden_col, type)


@dataclass(frozen=True)
class ViewSchema:
    """Device column types + deferred host-string column templates."""

    types: Dict[str, str]
    deferred: Dict[str, Tuple[DeferredPart, ...]] = field(default_factory=dict)

    def all_names(self) -> List[str]:
        """User-visible column names (device + deferred, no hidden)."""
        return [c for c in self.types if not c.startswith("__defer.")] + list(
            self.deferred
        )


import jax


@jax.tree_util.register_pytree_node_class
@dataclass
class TableData:
    cols: Dict[str, jnp.ndarray]
    valid: jnp.ndarray

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[0])

    def count(self) -> jnp.ndarray:
        return jnp.sum(self.valid.astype(jnp.int32))

    def tree_flatten(self):
        names = tuple(sorted(self.cols))
        return tuple(self.cols[n] for n in names) + (self.valid,), names

    @classmethod
    def tree_unflatten(cls, names, children):
        return cls(dict(zip(names, children[:-1])), children[-1])


# ORDER BY two-tier resolution bindings (see _OrderKeyScope)
_OUT_BINDING = "__ob.out"
_SRC_BINDING_PREFIX = "__ob.src:"


class _OrderKeyScope(Scope):
    """Per-REFERENCE two-tier resolution for ORDER BY keys (Spark
    semantics): each column ref binds to an output alias first, then to
    a FROM-scope column. Resolving the whole expression against one
    scope or the other would rebind aliases that shadow source columns
    in mixed expressions like ``ORDER BY a + b`` with ``SELECT b AS a``.
    """

    def __init__(self, out_scope: Scope, src_scope: Scope):
        tables = {_OUT_BINDING: dict(out_scope.tables[""])}
        deferred = {}
        for b, cols in src_scope.tables.items():
            tables[_SRC_BINDING_PREFIX + b] = cols
        for b, d in src_scope.deferred.items():
            deferred[_SRC_BINDING_PREFIX + b] = d
        super().__init__(tables=tables, deferred=deferred)
        self._out = out_scope
        self._src = src_scope

    def resolve(self, parts):
        try:
            _, col = self._out.resolve(parts)
            return (_OUT_BINDING, col)
        except EngineException as out_err:
            try:
                b, col = self._src.resolve(parts)
            except EngineException:
                raise EngineException(
                    f"cannot resolve ORDER BY reference "
                    f"'{'.'.join(parts)}' against the select list or the "
                    f"FROM scope: {out_err}"
                ) from None
            return (_SRC_BINDING_PREFIX + b, col)


# ---------------------------------------------------------------------------
# Stage plan metadata: what the planner DECIDED, recorded at lowering
# time for the device-plan analyzer (analysis/deviceplan.py). Shapes are
# static, so every capacity/algorithm choice below is exact — the cost
# model reads these instead of re-deriving (and possibly mis-deriving)
# the lowering.
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class JoinSite:
    """One JOIN in a statement's FROM chain, as actually lowered."""

    kind: str  # "INNER" | "LEFT"
    right_table: str
    left_rows: int  # static rows feeding the left side of this site
    right_rows: int
    out_rows: int  # shared statement join capacity
    algorithm: str  # "sort-merge" | "match-matrix"
    n_eq_keys: int  # compiled equality key pairs
    has_residual: bool  # non-equi ON terms force the match matrix


@dataclass(frozen=True)
class StagePlan:
    """Static execution shape of one compiled view."""

    kind: str  # "project" | "group" | "union"
    input_rows: int  # FROM-scope capacity feeding the select
    output_rows: int  # final output capacity (post ORDER/LIMIT)
    # table names the FROM chain reads (base + join right sides; union:
    # all branches' sources) — the mesh partition planner
    # (analysis/meshcheck.py) walks these to find reshard edges
    sources: Tuple[str, ...] = ()
    # names of Pallas-kernel UDFs the view's expressions call: a custom
    # call has no SPMD partitioning rule, so the partitioner replicates
    # the stage — the mesh planner must model it as a replication origin
    unshardable_udfs: Tuple[str, ...] = ()
    joins: Tuple[JoinSite, ...] = ()
    grouped: bool = False
    group_keys: int = 0
    # column names the group keys read (for cardinality lints)
    group_key_cols: Tuple[str, ...] = ()
    n_aggregates: int = 0
    groups_bound: int = 0  # static group capacity (0 when ungrouped)
    distinct: bool = False
    order_keys: int = 0
    limit: Optional[int] = None
    union_branches: int = 1


@dataclass
class CompiledView:
    name: str
    schema: ViewSchema
    capacity: int
    # fn(tables: {name: TableData}, base_s, now_rel_ms) -> TableData
    fn: Callable[[Dict[str, TableData], jnp.ndarray, jnp.ndarray], TableData]
    # select list in declaration order, for ORDER BY <ordinal> binding
    # (None for views not built from a select list, e.g. inputs)
    select_values: Optional[List[Tuple[str, Value]]] = None
    # ORDER BY keys naming deferred (computed-string) output columns
    # cannot sort on device; the runtime applies this ordering (+ limit)
    # on the materialized host rows instead — [(column, ascending)]
    host_order: Optional[List[Tuple[str, bool]]] = None
    host_limit: Optional[int] = None
    # lowering decisions, for static cost analysis (None for views built
    # outside the select compiler, e.g. raw inputs)
    plan: Optional[StagePlan] = None


# ---------------------------------------------------------------------------
# Aggregate-aware expression compiler
# ---------------------------------------------------------------------------
class _AggCollector(ExprCompiler):
    """ExprCompiler that records aggregate calls and compiles them into
    placeholder reads from the "__agg" scope."""

    def __init__(self, scope, dictionary, udfs, aux=None):
        super().__init__(scope, dictionary, udfs, aux=aux)
        self.agg_nodes: Dict[str, Tuple[str, Optional[Expr], bool]] = {}
        # custom aggregates (UDAF tier): key -> (udf, [arg exprs])
        self.udaf_nodes: Dict[str, Tuple[object, Tuple[Expr, ...]]] = {}
        self._counter = itertools.count()

    def _func(self, e: Func):
        if e.name in AGGREGATE_FNS:
            key = f"agg{next(self._counter)}"
            arg = None if (not e.args or isinstance(e.args[0], Star)) else e.args[0]
            self.agg_nodes[key] = (e.name, arg, e.distinct)
            out_t = self._agg_type(e.name, arg)
            return CompiledExpr(
                out_t, lambda env, key=key: env.scopes["__agg"][key]
            )
        udaf = self.udfs.get(e.name.lower())
        if udaf is not None and getattr(udaf, "is_aggregate", False):
            key = f"agg{next(self._counter)}"
            self.udaf_nodes[key] = (udaf, tuple(e.args))
            plain = ExprCompiler(self.scope, self.dictionary, self.udfs, aux=self.aux)
            arg_types = []
            for a in e.args:
                inner = plain.compile(a)
                if not is_device(inner):
                    raise EngineException(
                        f"cannot aggregate non-device expression {a!r}"
                    )
                arg_types.append(inner.type)
            out_t = udaf.result_type(arg_types)
            return CompiledExpr(
                out_t, lambda env, key=key: env.scopes["__agg"][key]
            )
        return super()._func(e)

    def _agg_type(self, name: str, arg: Optional[Expr]) -> str:
        if name == "COUNT":
            return "long"
        if arg is None:
            raise EngineException(f"{name} requires an argument")
        inner = ExprCompiler(self.scope, self.dictionary, self.udfs, aux=self.aux).compile(arg)
        if not is_device(inner):
            raise EngineException(f"cannot aggregate non-device expression {arg!r}")
        if name == "AVG":
            return "double"
        if name == "SUM":
            return "double" if inner.type == "double" else "long"
        return inner.type  # MIN/MAX preserve


def _has_aggregate(e: Expr) -> bool:
    if isinstance(e, Func):
        if e.name in AGGREGATE_FNS:
            return True
        return any(_has_aggregate(a) for a in e.args if not isinstance(a, Star))
    for attr in ("left", "right", "operand", "expr"):
        sub = getattr(e, attr, None)
        if sub is not None and not isinstance(sub, (str, tuple)) and _has_aggregate(sub):
            return True
    if hasattr(e, "whens"):
        for c, v in e.whens:
            if _has_aggregate(c) or _has_aggregate(v):
                return True
        if e.otherwise is not None and _has_aggregate(e.otherwise):
            return True
    if hasattr(e, "options"):
        return any(_has_aggregate(o) for o in e.options)
    return False


# ---------------------------------------------------------------------------
# Planner config
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class PlannerConfig:
    join_capacity_factor: float = 1.0  # out_cap = factor * max(left, right)
    min_join_capacity: int = 64
    # flow-configured absolute join output bound (conf
    # process.joincapacity); overrides the factor sizing when set
    join_capacity: Optional[int] = None
    # grouped outputs are compacted to the front, so their capacity can be
    # bounded below the input capacity — this is what keeps downstream
    # shapes small when grouping huge windowed tables (groups beyond the
    # bound drop; the runtime surfaces overflow as a metric, and the
    # flow sets the bound via conf process.maxgroups)
    max_group_capacity: int = 4096


# ---------------------------------------------------------------------------
# Select compiler
# ---------------------------------------------------------------------------
class SelectCompiler:
    def __init__(
        self,
        catalog: Dict[str, ViewSchema],
        capacities: Dict[str, int],
        dictionary: StringDictionary,
        udfs: Optional[dict] = None,
        config: PlannerConfig = PlannerConfig(),
        aux: Optional["AuxRegistry"] = None,
    ):
        self.catalog = catalog
        self.capacities = capacities
        self.dictionary = dictionary
        self.udfs = udfs or {}
        self.config = config
        # shared dictionary-table registry (device string ops); the
        # runtime materializes these tables per batch and passes them in
        # under the "__aux" pseudo-table (compile/stringops.py)
        from .stringops import AuxRegistry

        self.aux = aux if aux is not None else AuxRegistry()
        # every expression compiler built while compiling the current
        # view — compile_select drains it to attribute UDF calls to the
        # view's StagePlan (see StagePlan.unshardable_udfs)
        self._view_expr_compilers: List[ExprCompiler] = []

    def _expr_compiler(self, scope: Scope) -> ExprCompiler:
        ec = ExprCompiler(scope, self.dictionary, self.udfs, aux=self.aux)
        self._view_expr_compilers.append(ec)
        return ec

    # -- entry -----------------------------------------------------------
    def compile_select(self, name: str, sel: Select) -> CompiledView:
        mark = len(self._view_expr_compilers)
        if sel.union is not None:
            view = self._compile_union(name, sel)
        else:
            view = self._compile_single(name, sel)
        # attribute the UDF calls compiled for this view (union: all
        # branches) to its plan; only Pallas kernels matter — a custom
        # call cannot be SPMD-partitioned, so the mesh planner treats
        # the stage as a replication origin
        called = [
            u for ec in self._view_expr_compilers[mark:]
            for u in ec.called_udfs
        ]
        del self._view_expr_compilers[mark:]
        pallas = tuple(sorted({
            str(getattr(u, "name", type(u).__name__))
            for u in called if hasattr(u, "kernel")
        }))
        if pallas and view.plan is not None:
            view.plan = replace(view.plan, unshardable_udfs=pallas)
        return view

    @staticmethod
    def _inject_aux(scopes, tables) -> None:
        """Expose the dictionary string-op tables to expressions (the
        "__aux" pseudo-scope; see compile/stringops.py)."""
        scopes["__aux"] = tables.get("__aux", {})

    # -- union -----------------------------------------------------------
    def _compile_union(self, name: str, sel: Select) -> CompiledView:
        branches: List[Select] = []
        cur: Optional[Select] = sel
        while cur is not None:
            branches.append(replace(cur, union=None, union_distinct=False))
            cur = cur.union
        # a trailing ORDER BY/LIMIT parses into the last branch but (per
        # SQL) applies to the whole union — hoist it
        order_by, limit = branches[-1].order_by, branches[-1].limit
        branches[-1] = replace(branches[-1], order_by=(), limit=None)
        compiled = [self._compile_single(f"{name}${i}", b) for i, b in enumerate(branches)]
        first = compiled[0]
        names0 = list(first.schema.types) + list(first.schema.deferred)
        for c in compiled[1:]:
            if len(list(c.schema.types)) != len(list(first.schema.types)):
                raise EngineException(
                    f"UNION branches of {name} have different column counts"
                )
        capacity = sum(c.capacity for c in compiled)
        # align by position onto the first branch's names
        maps = []
        for c in compiled:
            maps.append(dict(zip(c.schema.types, first.schema.types)))

        def run(tables, base_s, now_rel_ms, compiled=compiled, maps=maps):
            outs = [c.fn(tables, base_s, now_rel_ms) for c in compiled]
            cols = {}
            for target in first.schema.types:
                parts = []
                for out, m in zip(outs, maps):
                    src = [k for k, v in m.items() if v == target]
                    parts.append(out.cols[src[0]])
                cols[target] = jnp.concatenate(parts)
            valid = jnp.concatenate([o.valid for o in outs])
            return TableData(cols, valid)

        schema = ViewSchema(dict(first.schema.types), dict(first.schema.deferred))
        view = CompiledView(
            name, schema, capacity, run,
            select_values=compiled[0].select_values,
            plan=StagePlan(
                kind="union",
                input_rows=sum(
                    c.plan.input_rows if c.plan else c.capacity
                    for c in compiled
                ),
                output_rows=capacity,
                sources=tuple(dict.fromkeys(
                    s for c in compiled if c.plan for s in c.plan.sources
                )),
                joins=tuple(
                    s for c in compiled if c.plan for s in c.plan.joins
                ),
                union_branches=len(compiled),
            ),
        )
        if order_by or limit is not None:
            view = self._apply_order_limit(view, order_by, limit)
        return view

    # -- single select ---------------------------------------------------
    def _compile_single(self, name: str, sel: Select) -> CompiledView:
        if sel.from_table is None:
            raise EngineException(f"SELECT without FROM not supported ({name})")

        # 1. FROM/JOIN scope
        scope, build_scope, scope_capacity, join_sites = self._compile_from(sel)
        from_tables = tuple(dict.fromkeys(
            [sel.from_table.name] + [j.table.name for j in sel.joins]
        ))

        compiler = _AggCollector(scope, self.dictionary, self.udfs, aux=self.aux)
        self._view_expr_compilers.append(compiler)

        # 2. WHERE
        where_fn = None
        if sel.where is not None:
            where_c = self._expr_compiler(scope).compile(sel.where)
            if not is_device(where_c):
                raise EngineException("WHERE must be device-computable")
            where_fn = where_c.fn

        grouped = bool(sel.group_by) or any(
            _has_aggregate(i.expr) for i in sel.items if not isinstance(i.expr, Star)
        ) or (sel.having is not None and _has_aggregate(sel.having))

        # 3. select items -> named output values
        out_values: List[Tuple[str, Value]] = []
        for item in sel.items:
            out_values.extend(self._expand_item(item, scope, compiler))

        out_types, deferred, flat_outputs = self._flatten_outputs(out_values)

        if grouped:
            # HAVING compiles with the SAME collector so its aggregates
            # (possibly absent from the select list) compute per group
            having_c = (
                compiler.compile(sel.having) if sel.having is not None else None
            )
            if having_c is not None and not is_device(having_c):
                raise EngineException("HAVING must be device-computable")
            view = self._compile_grouped(
                name, sel, scope, compiler, build_scope, scope_capacity,
                where_fn, out_types, deferred, flat_outputs, out_values,
                having_fn=having_c.fn if having_c is not None else None,
                join_sites=join_sites, from_tables=from_tables,
            )
            view.select_values = out_values
            if sel.order_by or sel.limit is not None:
                # grouped: output rows are groups, not source rows, so
                # keys resolve against the output scope only (as Spark
                # requires grouping/aggregate expressions here)
                view = self._apply_order_limit(view, sel.order_by, sel.limit)
            return view

        if sel.having is not None:
            raise EngineException(
                f"HAVING without aggregation in {name}; use WHERE"
            )
        if compiler.udaf_nodes:
            names = ", ".join(u.name for u, _ in compiler.udaf_nodes.values())
            raise EngineException(
                f"aggregate UDF ({names}) requires GROUP BY in {name}"
            )

        # 4. plain projection/filter
        distinct_keys = None
        if sel.distinct:
            distinct_keys = self._distinct_key_exprs(out_values)

        def run(tables, base_s, now_rel_ms):
            scopes, valid, shape = build_scope(tables, base_s, now_rel_ms)
            self._inject_aux(scopes, tables)
            env = EvalEnv(scopes, base_s, now_rel_ms, shape)
            if where_fn is not None:
                valid = valid & where_fn(env)
            cols = {n: fn(env) for n, fn in flat_outputs}
            if distinct_keys is not None:
                env2 = EvalEnv(scopes, base_s, now_rel_ms, shape)
                keys = [k.fn(env2) for k in distinct_keys]
                valid = distinct_mask(keys, valid)
            meta = scopes.get("__meta")
            if meta is not None and "join_dropped" in meta:
                # rows lost to the join capacity bound ride along as a
                # hidden column -> Output_<n>_JoinRowsDropped metric
                cols["__overflow.joins"] = jnp.broadcast_to(
                    meta["join_dropped"], shape
                )
            return TableData(cols, valid)

        schema = ViewSchema(out_types, deferred)
        view = CompiledView(
            name, schema, scope_capacity, run, select_values=out_values,
            plan=StagePlan(
                kind="project",
                input_rows=scope_capacity,
                output_rows=scope_capacity,
                sources=from_tables,
                joins=tuple(join_sites),
                distinct=bool(sel.distinct),
            ),
        )
        if sel.order_by or sel.limit is not None:
            # Spark rejects DISTINCT + ORDER BY on unselected columns
            # (the sort key would come from an arbitrary representative
            # row), so the source-scope fallback is withheld there
            view = self._apply_order_limit(
                view, sel.order_by, sel.limit,
                src_scope=None if sel.distinct else scope,
                src_build=None if sel.distinct else build_scope,
            )
        return view

    # -- FROM / JOIN -----------------------------------------------------
    def _view(self, table: str) -> ViewSchema:
        if table not in self.catalog:
            raise EngineException(f"unknown table '{table}'")
        return self.catalog[table]

    def _compile_from(self, sel: Select):
        """Returns (scope, build_scope_fn, capacity, join_sites).

        build_scope_fn(tables, base_s, now) -> (scopes dict, valid, shape)
        """
        base = sel.from_table
        base_schema = self._view(base.name)
        base_cap = self.capacities[base.name]

        if not sel.joins:
            scope = Scope(
                tables={base.binding: dict(base_schema.types)},
                deferred={base.binding: self._deferred_exprs(base.binding, base_schema)},
            )

            def build(tables, base_s, now_rel_ms, b=base):
                t = tables[b.name]
                return {b.binding: t.cols}, t.valid, t.valid.shape

            return scope, build, base_cap, []

        # join chain: fold joins left-to-right into one merged table
        bindings = [(base.binding, base.name, base_schema)]
        for j in sel.joins:
            bindings.append((j.table.binding, j.table.name, self._view(j.table.name)))
        if len({b for b, _, _ in bindings}) != len(bindings):
            raise EngineException("duplicate table bindings in join")

        # merged column names: bare when unique, else qualified
        all_cols: Dict[str, int] = {}
        for _, _, sch in bindings:
            for c in sch.types:
                all_cols[c] = all_cols.get(c, 0) + 1
            for c in sch.deferred:
                all_cols[c] = all_cols.get(c, 0) + 1

        def merged_name(binding: str, col: str) -> str:
            return col if all_cols[col] == 1 else f"{binding}.{col}"

        merged_types: Dict[str, str] = {}
        merged_deferred: Dict[str, Tuple[DeferredPart, ...]] = {}
        for b, _, sch in bindings:
            for c, t in sch.types.items():
                merged_types[merged_name(b, c)] = t
            for c, parts in sch.deferred.items():
                merged_deferred[merged_name(b, c)] = tuple(
                    p if isinstance(p, str) else (merged_name(b, p[0]), p[1])
                    for p in parts
                )

        merged_schema = ViewSchema(merged_types, merged_deferred)
        out_cap = self._join_capacity(sel)

        # compile each join's ON condition against the two-sided scope
        join_plans = []
        left_bindings = [bindings[0]]
        for j, jb in zip(sel.joins, bindings[1:]):
            lscope = Scope(
                tables={b: dict(sch.types) for b, _, sch in left_bindings},
            )
            rscope = Scope(tables={jb[0]: dict(jb[2].types)})
            eq_pairs, residual = self._split_on(j.on, lscope, rscope)
            join_plans.append((j, jb, eq_pairs, residual, list(left_bindings)))
            left_bindings.append(jb)

        # record the lowering decisions per site (cost-model metadata):
        # the left side of site 0 is the base table; every later site
        # reads the previous site's capacity-bounded output
        join_sites: List[JoinSite] = []
        left_rows = base_cap
        for j, jb, eq_pairs, residual, _lbs in join_plans:
            join_sites.append(JoinSite(
                kind=j.kind,
                right_table=jb[1],
                left_rows=left_rows,
                right_rows=self.capacities[jb[1]],
                out_rows=out_cap,
                algorithm="match-matrix" if residual is not None
                else "sort-merge",
                n_eq_keys=len(eq_pairs),
                has_residual=residual is not None,
            ))
            left_rows = out_cap

        def build(tables, base_s, now_rel_ms):
            # left side accumulates as a single merged col-dict keyed by
            # (binding, col)
            b0, n0, sch0 = bindings[0]
            acc_cols = {(b0, c): tables[n0].cols[c] for c in sch0.types}
            acc_valid = tables[n0].valid
            acc_dropped = jnp.asarray(0, jnp.int32)

            for j, jb, eq_pairs, residual, lbs in join_plans:
                rb, rn, rsch = jb
                right = tables[rn]
                shape_l = acc_valid.shape
                shape_r = right.valid.shape
                aux_tables = tables.get("__aux", {})
                lscopes = {"__aux": aux_tables}
                for (b, c), arr in acc_cols.items():
                    lscopes.setdefault(b, {})[c] = arr
                lenv = EvalEnv(lscopes, base_s, now_rel_ms, shape_l)
                renv = EvalEnv(
                    {rb: right.cols, "__aux": aux_tables},
                    base_s, now_rel_ms, shape_r,
                )

                lkeys = [le.fn(lenv) for le, _ in eq_pairs]
                rkeys = [re_.fn(renv) for _, re_ in eq_pairs]

                res_fn = None
                if residual is not None:
                    def res_fn(li, ri, residual=residual, lscopes=lscopes,
                               right=right, rb=rb, aux_tables=aux_tables):
                        pl_scopes = {
                            b: {c: arr[li] for c, arr in cols.items()}
                            for b, cols in lscopes.items()
                            if b != "__aux"
                        }
                        pl_scopes[rb] = {c: arr[ri] for c, arr in right.cols.items()}
                        pl_scopes["__aux"] = aux_tables
                        env2 = EvalEnv(pl_scopes, base_s, now_rel_ms, li.shape)
                        return residual.fn(env2)

                if res_fn is None:
                    # pure equi-join: sort-merge, O((n+m+cap) log) — the
                    # path that keeps batch x windowed-table joins off
                    # the O(n*m) match-matrix cliff
                    from ..ops.join import sort_join_indices

                    li, ri, valid, is_null, dropped = sort_join_indices(
                        lkeys, rkeys, acc_valid, right.valid, out_cap,
                        left_outer=(j.kind == "LEFT"),
                    )
                    if j.kind != "LEFT":
                        is_null = None
                elif j.kind == "LEFT":
                    li, ri, valid, is_null, dropped = left_join_indices(
                        lkeys, rkeys, acc_valid, right.valid, out_cap, res_fn
                    )
                else:
                    li, ri, valid, dropped = inner_join_indices(
                        lkeys, rkeys, acc_valid, right.valid, out_cap, res_fn
                    )
                    is_null = None
                acc_dropped = acc_dropped + dropped

                new_cols = {}
                for (b, c), arr in acc_cols.items():
                    new_cols[(b, c)] = arr[li]
                for c, arr in right.cols.items():
                    gathered = arr[ri]
                    if is_null is not None:
                        gathered = jnp.where(is_null, jnp.zeros_like(gathered), gathered)
                    new_cols[(rb, c)] = gathered
                acc_cols = new_cols
                acc_valid = valid

            # merge to final names under a single "" binding + per-binding
            final_scopes: Dict[str, Dict[str, jnp.ndarray]] = {"": {}}
            for (b, c), arr in acc_cols.items():
                final_scopes[""][merged_name(b, c)] = arr
                final_scopes.setdefault(b, {})[c] = arr
            # pairs lost to the join capacity bound ride along as scope
            # metadata (never row-shaped) so the output view can surface
            # them as an overflow column for the runtime's metric
            final_scopes["__meta"] = {"join_dropped": acc_dropped}
            return final_scopes, acc_valid, acc_valid.shape

        # scope: merged columns under "" plus per-binding scopes
        scope_tables = {"": dict(merged_types)}
        scope_deferred = {"": self._deferred_exprs("", merged_schema)}
        for b, _, sch in bindings:
            scope_tables[b] = dict(sch.types)
            scope_deferred[b] = self._deferred_exprs(b, sch)
        scope = Scope(tables=scope_tables, deferred=scope_deferred)
        return scope, build, out_cap, join_sites

    def _join_capacity(self, sel: Select) -> int:
        if self.config.join_capacity is not None:
            return self.config.join_capacity
        caps = [self.capacities[sel.from_table.name]] + [
            self.capacities[j.table.name] for j in sel.joins
        ]
        cap = max(caps)
        return max(
            self.config.min_join_capacity, int(cap * self.config.join_capacity_factor)
        )

    def _split_on(self, on: Expr, lscope: Scope, rscope: Scope):
        """Split ON into equi pairs (left expr, right expr) + residual."""
        conjuncts: List[Expr] = []

        def walk(e: Expr):
            if isinstance(e, BinOp) and e.op == "AND":
                walk(e.left)
                walk(e.right)
            else:
                conjuncts.append(e)

        walk(on)
        eq_pairs = []
        residual_parts: List[Expr] = []
        for c in conjuncts:
            if isinstance(c, BinOp) and c.op == "=":
                sides = []
                for s in (c.left, c.right):
                    side = self._side_of(s, lscope, rscope)
                    sides.append(side)
                if sides == ["L", "R"]:
                    eq_pairs.append((c.left, c.right))
                    continue
                if sides == ["R", "L"]:
                    eq_pairs.append((c.right, c.left))
                    continue
            residual_parts.append(c)
        if not eq_pairs:
            raise EngineException(
                "JOIN requires at least one equality between the two tables"
            )
        compiled_pairs = []
        for le, re_ in eq_pairs:
            lc = self._expr_compiler(lscope)
            rc = self._expr_compiler(rscope)
            lv = lc.compile(le)
            rv = rc.compile(re_)
            if isinstance(lv, HostStr) or isinstance(rv, HostStr):
                # computed-string join key: equate the device hash pair;
                # a third pair tags NULLs differently per side so a NULL
                # key never matches anything (SQL join semantics)
                lk = lc.hash_keys(lv)
                rk = rc.hash_keys(rv)
                if lk is None or rk is None:
                    raise EngineException(
                        "JOIN on a computed string requires both sides "
                        "built from string columns/literals: "
                        f"{le!r} = {re_!r}"
                    )
                compiled_pairs.append((lk[0], rk[0]))
                compiled_pairs.append((lk[1], rk[1]))
                compiled_pairs.append(
                    (_null_tag(lk[2], 1), _null_tag(rk[2], 2))
                )
            else:
                for v, side in ((lv, "left"), (rv, "right")):
                    if not is_device(v):
                        raise EngineException(
                            f"JOIN {side} key must be device-computable: "
                            f"{le!r} = {re_!r}"
                        )
                compiled_pairs.append((lv, rv))
        residual = None
        if residual_parts:
            expr = residual_parts[0]
            for p in residual_parts[1:]:
                expr = BinOp("AND", expr, p)
            both = Scope(
                tables={**lscope.tables, **rscope.tables},
            )
            residual = self._expr_compiler(both).compile_device(expr)
        return compiled_pairs, residual

    def _side_of(self, e: Expr, lscope: Scope, rscope: Scope) -> str:
        """Which side an expression's columns come from: 'L', 'R', or '?'."""
        cols: List[Col] = []

        def walk(x):
            if isinstance(x, Col):
                cols.append(x)
            for attr in ("left", "right", "operand", "expr"):
                sub = getattr(x, attr, None)
                if sub is not None and not isinstance(sub, (str, tuple)):
                    walk(sub)
            if isinstance(x, Func):
                for a in x.args:
                    if not isinstance(a, Star):
                        walk(a)

        walk(e)
        if not cols:
            return "?"
        sides = set()
        for c in cols:
            inl = self._resolves(lscope, c)
            inr = self._resolves(rscope, c)
            if inl and not inr:
                sides.add("L")
            elif inr and not inl:
                sides.add("R")
            else:
                sides.add("?")
        return sides.pop() if len(sides) == 1 else "?"

    @staticmethod
    def _resolves(scope: Scope, c: Col) -> bool:
        try:
            scope.resolve(c.parts)
            return True
        except EngineException:
            return False

    # -- select item expansion -------------------------------------------
    def _deferred_exprs(
        self, binding: str, schema: ViewSchema
    ) -> Dict[str, HostStr]:
        out = {}
        for col, parts in schema.deferred.items():
            new_parts: List[Union[str, CompiledExpr]] = []
            deps: Tuple[Tuple[str, str], ...] = ()
            for p in parts:
                if isinstance(p, str):
                    new_parts.append(p)
                else:
                    hidden, t = p
                    new_parts.append(
                        CompiledExpr(
                            t,
                            lambda env, b=binding, c=hidden: env.column(b, c),
                            deps=((binding, hidden),),
                        )
                    )
                    deps += ((binding, hidden),)
            out[col] = HostStr(new_parts, deps)
        return out

    def _expand_item(
        self, item: SelectItem, scope: Scope, compiler: ExprCompiler
    ) -> List[Tuple[str, Value]]:
        if isinstance(item.expr, Star):
            out = []
            bindings = (
                [item.expr.table] if item.expr.table else
                [b for b in scope.tables if b != "" or len(scope.tables) == 1]
            )
            # for join scopes prefer the merged "" binding to avoid dupes
            if "" in scope.tables and item.expr.table is None:
                bindings = [""]
            for b in bindings:
                for c, t in scope.tables[b].items():
                    if c.startswith("__defer."):
                        continue
                    out.append(
                        (
                            c,
                            CompiledExpr(
                                t,
                                lambda env, b=b, c=c: env.column(b, c),
                                deps=((b, c),),
                            ),
                        )
                    )
                for c, h in scope.deferred.get(b, {}).items():
                    out.append((c, h))
            return out

        value = compiler.compile(item.expr)
        name = item.alias
        if name is None:
            if isinstance(item.expr, Col):
                name = item.expr.parts[-1]
            else:
                raise EngineException(
                    f"select expression requires an alias: {item.expr!r}"
                )
        return [(name, value)]

    def _flatten_outputs(self, out_values: List[Tuple[str, Value]]):
        """Flatten named Values into device columns + deferred templates.

        Returns (types, deferred, flat: [(col_name, fn)]).
        """
        types: Dict[str, str] = {}
        deferred: Dict[str, Tuple[DeferredPart, ...]] = {}
        flat: List[Tuple[str, Callable]] = []

        def add_device(col: str, ce: CompiledExpr):
            if col in types:
                raise EngineException(f"duplicate output column {col}")
            types[col] = ce.type
            flat.append((col, ce.fn))

        def walk(prefix: str, v: Value):
            if isinstance(v, CompiledExpr):
                add_device(prefix, v)
            elif isinstance(v, StructValue):
                if v.validity is not None:
                    add_device(prefix + ".__valid", v.validity)
                for f, sub in v.fields.items():
                    walk(prefix + "." + f, sub)
            elif isinstance(v, ArrayValue):
                for i, el in enumerate(v.elements):
                    if isinstance(el, StructValue) and el.validity is None:
                        el = StructValue(el.fields, validity=CompiledExpr(
                            "boolean",
                            lambda env: jnp.broadcast_to(jnp.asarray(True), env.shape),
                        ))
                    walk(f"{prefix}.{i}", el)
            elif isinstance(v, HostStr):
                parts: List[DeferredPart] = []
                for i, p in enumerate(v.parts):
                    if isinstance(p, str):
                        parts.append(p)
                    else:
                        hidden = f"__defer.{prefix}.{i}"
                        add_device(hidden, p)
                        parts.append((hidden, p.type))
                deferred[prefix] = tuple(parts)
            else:
                raise EngineException(f"cannot output value {v!r}")

        for name, v in out_values:
            walk(name, v)
        return types, deferred, flat

    def _distinct_key_exprs(self, out_values) -> List[CompiledExpr]:
        keys: List[CompiledExpr] = []
        for _, v in out_values:
            keys.extend(self._device_keys_of(v))
        return keys

    def _device_keys_of(self, v: Value) -> List[CompiledExpr]:
        if isinstance(v, CompiledExpr):
            return [v]
        if isinstance(v, StructValue):
            out = []
            if v.validity is not None:
                out.append(v.validity)
            for sub in v.fields.values():
                out.extend(self._device_keys_of(sub))
            return out
        if isinstance(v, ArrayValue):
            out = []
            for el in v.elements:
                out.extend(self._device_keys_of(el))
            return out
        if isinstance(v, HostStr):
            return [p for p in v.parts if isinstance(p, CompiledExpr)]
        return []

    # -- ORDER BY / LIMIT ------------------------------------------------
    @staticmethod
    def _col_refs(expr) -> List[str]:
        """Dotted names of every column reference inside an expression."""
        refs: List[str] = []

        def walk(node):
            if isinstance(node, Col):
                refs.append(".".join(node.parts))
                return
            if hasattr(node, "__dataclass_fields__"):
                for f in node.__dataclass_fields__:
                    walk(getattr(node, f))
            elif isinstance(node, (tuple, list)):
                for el in node:
                    walk(el)

        walk(expr)
        return refs

    def _apply_order_limit(
        self, view: CompiledView, order_by, limit,
        *, src_scope=None, src_build=None,
    ) -> CompiledView:
        """Wrap a view with device-side ordering and/or row limiting.

        ORDER BY sorts valid rows to the front with a stable lexsort
        (invalid rows last); string keys sort by dictionary rank, i.e.
        true lexicographic order. LIMIT keeps the first N rows — with an
        ORDER BY the output capacity shrinks to N, so downstream shapes
        (and transfers) get smaller, the fixed-shape analog of Spark's
        TakeOrdered.

        Keys resolve against the view's OUTPUT columns (select aliases)
        first, then — Spark semantics — against the FROM-scope columns
        when the caller supplies one (``src_scope``/``src_build``; only
        sound for ungrouped selects, where output row i is scope row i).
        ``view.select_values`` (the select list in declaration order)
        binds ``ORDER BY <ordinal>`` including deferred-string items.
        """
        from .stringops import RANK_KEY

        visible = [
            c for c in view.schema.types
            if not c.startswith("__defer.") and not c.endswith(".__valid")
        ]
        out_scope = Scope(tables={"": {
            c: view.schema.types[c] for c in visible
        }})
        if src_scope is not None:
            key_scope: Scope = _OrderKeyScope(out_scope, src_scope)
        else:
            key_scope = out_scope
        compiler = self._expr_compiler(key_scope)
        select_values = view.select_values
        # keys: (CompiledExpr, ascending)
        keys: List[Tuple[CompiledExpr, bool]] = []
        from .sqlparser import Literal as _Lit

        # host-order path: a key NAMING a deferred (computed-string)
        # output column has no device representation to sort by. When
        # every key is a plain output-column reference (or ordinal),
        # the whole ordering + limit moves to the host, applied to the
        # materialized rows — Spark-composable ORDER BY on CONCAT/CAST
        # results, at host cost for only the rows that cross the
        # boundary. Keys that EMBED a deferred column in a larger
        # expression still fail below.
        def _plain_name(expr) -> Optional[str]:
            if (
                isinstance(expr, _Lit) and expr.kind == "int"
                and select_values and 1 <= expr.value <= len(select_values)
            ):
                return select_values[expr.value - 1][0]
            if isinstance(expr, Col) and len(expr.parts) == 1:
                return expr.parts[0]
            return None

        plain_names = [_plain_name(i.expr) for i in order_by]
        if any(n in view.schema.deferred for n in plain_names if n):
            if all(
                n and (n in view.schema.deferred or n in view.schema.types)
                for n in plain_names
            ):
                return replace(
                    view,
                    host_order=[
                        (n, i.ascending)
                        for n, i in zip(plain_names, order_by)
                    ],
                    host_limit=limit,
                )
            raise EngineException(
                "ORDER BY mixing a computed-string column with "
                "non-column expressions is not supported; order by the "
                "output columns directly"
            )

        for item in order_by:
            expr = item.expr
            if isinstance(expr, _Lit) and expr.kind == "int":
                # ORDER BY <ordinal>: 1-based select-list position,
                # counted over the FULL select list (deferred strings
                # and structs included), not just device columns
                if select_values is not None:
                    if not (1 <= expr.value <= len(select_values)):
                        raise EngineException(
                            f"ORDER BY position {expr.value} is out of range "
                            f"(select list has {len(select_values)} items)"
                        )
                    sel_name, sel_val = select_values[expr.value - 1]
                    if isinstance(sel_val, HostStr):
                        raise EngineException(
                            f"ORDER BY position {expr.value} refers to a "
                            f"deferred string expression ('{sel_name}'); "
                            "computed strings cannot be ordering keys"
                        )
                    if isinstance(sel_val, (StructValue, ArrayValue)):
                        raise EngineException(
                            f"ORDER BY position {expr.value} refers to "
                            f"composite column '{sel_name}'; order by a "
                            "scalar field instead"
                        )
                    expr = Col((sel_name,))
                else:
                    if not (1 <= expr.value <= len(visible)):
                        raise EngineException(
                            f"ORDER BY position {expr.value} is out of range "
                            f"(select list has {len(visible)} device columns)"
                        )
                    expr = Col((visible[expr.value - 1],))
            # any column ref naming a deferred-string output item must
            # error (not silently fall through to a same-named source
            # column the alias shadows) — also inside larger expressions
            shadowed = [
                r for r in self._col_refs(expr)
                if r in view.schema.deferred
            ]
            if shadowed:
                raise EngineException(
                    f"ORDER BY key references deferred string "
                    f"expression(s) {shadowed}; computed strings cannot "
                    "be ordering keys"
                )
            ce = compiler.compile(expr)
            if not is_device(ce):
                raise EngineException(
                    "ORDER BY key must be a device column/expression "
                    f"(deferred strings cannot order): {item.expr!r}"
                )
            if ce.type == "string":
                self.aux.require_rank()
            keys.append((ce, item.ascending))

        # does any key read a FROM-scope column the output lacks?
        need_src = any(
            b.startswith(_SRC_BINDING_PREFIX)
            for ce, _ in keys for b, _c in ce.deps
        )

        def run(tables, base_s, now_rel_ms):
            t = view.fn(tables, base_s, now_rel_ms)
            valid = t.valid
            cols = t.cols
            if keys:
                # output columns are visible under both the plain ""
                # binding and the _OUT binding the two-tier scope emits
                scopes = {"": cols, _OUT_BINDING: cols}
                if need_src:
                    # re-derive the FROM scope; XLA CSEs the duplicate
                    # subgraph with the projection's own evaluation
                    scopes_s, _, _shape_s = src_build(tables, base_s, now_rel_ms)
                    for b, sc_cols in scopes_s.items():
                        scopes[_SRC_BINDING_PREFIX + b] = sc_cols
                self._inject_aux(scopes, tables)
                env = EvalEnv(scopes, base_s, now_rel_ms, valid.shape)
                sort_keys = []
                for ce, asc in keys:
                    arr = ce.fn(env)
                    if ce.type == "string":
                        rank_t = scopes["__aux"][RANK_KEY]
                        arr = rank_t[jnp.clip(arr, 0, rank_t.shape[0] - 1)]
                    if arr.dtype == jnp.bool_:
                        arr = arr.astype(jnp.int32)
                    if not asc:
                        arr = -arr
                    sort_keys.append(arr)
                # lexsort: LAST key is primary -> invalid rows sort last,
                # then keys in reverse significance order (stable)
                perm = jnp.lexsort(
                    tuple(reversed(sort_keys))
                    + (jnp.logical_not(valid).astype(jnp.int32),)
                )
                cols = {
                    c: (a[perm] if a.shape[:1] == valid.shape else a)
                    for c, a in cols.items()
                }
                valid = valid[perm]
            if limit is not None:
                if keys:
                    # rows are sorted valid-first: a plain prefix mask
                    keep = jnp.arange(valid.shape[0]) < limit
                else:
                    # unsorted: keep the first N valid rows in place
                    keep = jnp.cumsum(valid.astype(jnp.int32)) <= limit
                valid = valid & keep
                if keys and limit < valid.shape[0]:
                    cols = {
                        c: (a[:limit] if a.shape[:1] == (valid.shape[0],) else a)
                        for c, a in cols.items()
                    }
                    valid = valid[:limit]
            return TableData(cols, valid)

        capacity = view.capacity
        if limit is not None and keys and limit < capacity:
            capacity = limit
        plan = view.plan
        if plan is not None:
            plan = replace(
                plan, output_rows=capacity,
                order_keys=len(keys), limit=limit,
            )
        return CompiledView(
            view.name, view.schema, capacity, run,
            select_values=view.select_values,
            plan=plan,
        )

    # -- grouped path ----------------------------------------------------
    def _compile_grouped(
        self, name, sel, scope, compiler, build_scope, scope_capacity,
        where_fn, out_types, deferred, flat_outputs, out_values,
        having_fn=None, join_sites=(), from_tables=(),
    ) -> CompiledView:
        # group keys: resolve against select aliases first, then scope
        alias_map = {}
        for item in sel.items:
            if item.alias is not None:
                alias_map[item.alias.lower()] = item.expr
        key_exprs: List[Expr] = []
        for g in sel.group_by:
            if isinstance(g, Col) and len(g.parts) == 1 and g.parts[0].lower() in alias_map:
                key_exprs.append(alias_map[g.parts[0].lower()])
            else:
                key_exprs.append(g)

        key_compiled: List[CompiledExpr] = []
        plain = self._expr_compiler(scope)
        for g in key_exprs:
            v = plain.compile(g)
            if isinstance(v, HostStr):
                # computed string key: group by its device hash triple
                # (exact string-equality classes; stringified integers
                # hash their decimal rendering on device); when the
                # deferred expression embeds parts with no device tier
                # (CAST of doubles), fall back to grouping by the part
                # tuple — a refinement of string equality (may split
                # "a"+"bc" from "ab"+"c")
                hk = plain.hash_keys(v)
                if hk is not None:
                    key_compiled.extend(hk)
                else:
                    key_compiled.extend(
                        p for p in v.parts if isinstance(p, CompiledExpr)
                    )
            elif is_device(v):
                key_compiled.append(v)
            else:
                raise EngineException(f"cannot group by composite value {g!r}")

        agg_nodes = compiler.agg_nodes  # populated during _expand_item
        agg_args: Dict[str, Optional[CompiledExpr]] = {}
        for key, (fname, arg, dist) in agg_nodes.items():
            agg_args[key] = (
                None if arg is None else plain.compile_device(arg, f"{fname} argument")
            )
            if (
                fname in ("MIN", "MAX")
                and agg_args[key] is not None
                and agg_args[key].type == "string"
            ):
                # string MIN/MAX aggregate in rank space (lexicographic),
                # mapped back to ids via the inverse table
                self.aux.require_rank()
        udaf_nodes = compiler.udaf_nodes
        udaf_args: Dict[str, List[CompiledExpr]] = {
            key: [
                plain.compile_device(a, f"{udf.name} argument")
                for a in args
            ]
            for key, (udf, args) in udaf_nodes.items()
        }

        capacity = min(scope_capacity, self.config.max_group_capacity)

        def run(tables, base_s, now_rel_ms):
            scopes, valid, shape = build_scope(tables, base_s, now_rel_ms)
            self._inject_aux(scopes, tables)
            aux_tables = scopes["__aux"]
            env = EvalEnv(scopes, base_s, now_rel_ms, shape)
            if where_fn is not None:
                valid = valid & where_fn(env)

            keys = [k.fn(env) for k in key_compiled]
            order, seg, num_groups, first = group_ids(keys, valid)
            valid_s = valid[order]

            # aggregate values
            agg_results: Dict[str, jnp.ndarray] = {}
            for key, (fname, arg, dist) in agg_nodes.items():
                if fname == "COUNT" and agg_args[key] is None:
                    agg_results[key] = segment_aggregate(
                        None, seg, capacity, "count", valid_s
                    )
                    continue
                vals = agg_args[key].fn(env)[order]
                if fname == "COUNT" and dist:
                    agg_results[key] = _distinct_count(
                        agg_args[key].fn(env), order, seg, valid_s, capacity
                    )
                elif fname == "COUNT":
                    agg_results[key] = segment_aggregate(
                        None, seg, capacity, "count", valid_s
                    )
                elif fname == "SUM":
                    z = jnp.where(valid_s, vals, jnp.zeros_like(vals))
                    agg_results[key] = segment_aggregate(
                        z, seg, capacity, "sum", valid_s
                    )
                elif fname == "AVG":
                    zf = jnp.where(valid_s, vals, jnp.zeros_like(vals)).astype(
                        jnp.float32
                    )
                    s = segment_aggregate(zf, seg, capacity, "sum", valid_s)
                    c = segment_aggregate(None, seg, capacity, "count", valid_s)
                    agg_results[key] = s / jnp.maximum(c, 1).astype(jnp.float32)
                elif fname in ("MIN", "MAX"):
                    op = fname.lower()
                    is_string = agg_args[key].type == "string"
                    live = valid_s
                    if is_string:
                        # lexicographic min/max: aggregate ranks, invert.
                        # SQL MIN/MAX ignore NULLs, so null ids (0) are
                        # masked out like invalid rows
                        from .stringops import RANK_KEY, UNRANK_KEY

                        live = live & (vals != 0)
                        rank_t = aux_tables[RANK_KEY]
                        vals = rank_t[jnp.clip(vals, 0, rank_t.shape[0] - 1)]
                    ident = (
                        jnp.iinfo(jnp.int32).max if vals.dtype in (jnp.int32,)
                        else jnp.asarray(jnp.inf, vals.dtype)
                    )
                    if fname == "MAX":
                        ident = (
                            jnp.iinfo(jnp.int32).min if vals.dtype in (jnp.int32,)
                            else jnp.asarray(-jnp.inf, vals.dtype)
                        )
                    z = jnp.where(live, vals, jnp.full_like(vals, ident))
                    res = segment_aggregate(z, seg, capacity, op, live)
                    if is_string:
                        # group with no non-null value -> NULL (rank 0 is
                        # always the null entry, so unrank[0] == id 0)
                        unrank_t = aux_tables[UNRANK_KEY]
                        res = jnp.where(res == ident, 0, res)
                        res = unrank_t[jnp.clip(res, 0, unrank_t.shape[0] - 1)]
                    agg_results[key] = res
            for key, (udf, _args) in udaf_nodes.items():
                arg_arrays = [a.fn(env)[order] for a in udaf_args[key]]
                agg_results[key] = udf.reduce(arg_arrays, seg, capacity, valid_s)

            # representative row per group (first sorted row)
            rep_sorted_idx, rep_valid = compact_indices(first, capacity)
            rep_idx = order[rep_sorted_idx]

            rep_scopes = {
                b: {c: arr[rep_idx] for c, arr in cols.items()}
                for b, cols in scopes.items()
                # dictionary tables / join metadata are not row-shaped
                if b not in ("__aux", "__meta")
            }
            rep_scopes["__agg"] = agg_results
            rep_scopes["__aux"] = aux_tables
            group_env = EvalEnv(rep_scopes, base_s, now_rel_ms, (capacity,))

            cols = {n: fn(group_env) for n, fn in flat_outputs}
            out_valid = jnp.arange(capacity) < num_groups
            if having_fn is not None:
                out_valid = out_valid & having_fn(group_env)
            # groups beyond the static capacity are dropped; ride the
            # drop count along as a hidden column so the runtime can
            # emit it as an overflow metric (Output_<n>_GroupsDropped)
            dropped = jnp.maximum(num_groups - capacity, 0).astype(jnp.int32)
            cols["__overflow.groups"] = jnp.broadcast_to(dropped, (capacity,))
            meta = scopes.get("__meta")
            if meta is not None and "join_dropped" in meta:
                cols["__overflow.joins"] = jnp.broadcast_to(
                    meta["join_dropped"], (capacity,)
                )
            return TableData(cols, out_valid)

        schema = ViewSchema(out_types, deferred)
        return CompiledView(
            name, schema, capacity, run,
            plan=StagePlan(
                kind="group",
                input_rows=scope_capacity,
                output_rows=capacity,
                sources=tuple(from_tables),
                joins=tuple(join_sites),
                grouped=True,
                group_keys=len(key_compiled),
                group_key_cols=tuple(sorted({
                    c for k in key_compiled for (_b, c) in k.deps
                })),
                n_aggregates=len(agg_nodes) + len(udaf_nodes),
                groups_bound=capacity,
            ),
        )


def _null_tag(null_expr: CompiledExpr, tag: int) -> CompiledExpr:
    """0 for non-null rows, a per-side tag for null rows — joined as an
    extra equality key so null never equals null across sides."""

    def run(env, n=null_expr, tag=tag):
        return jnp.where(n.fn(env), jnp.int32(tag), jnp.int32(0))

    return CompiledExpr("long", run, deps=null_expr.deps)


def _distinct_count(vals, order, seg, valid_s, capacity):
    """COUNT(DISTINCT x) per group: sort (seg, x) pairs, count pair-firsts."""
    x_s = vals[order]
    pair_order = jnp.lexsort([x_s.astype(jnp.int32), seg])
    seg_p = seg[pair_order]
    x_p = x_s[pair_order]
    valid_p = valid_s[pair_order]
    new_pair = jnp.concatenate(
        [
            jnp.ones((1,), jnp.bool_),
            (seg_p[1:] != seg_p[:-1]) | (x_p[1:] != x_p[:-1]),
        ]
    )
    flags = (new_pair & valid_p).astype(jnp.int32)
    out = segment_aggregate(flags, seg_p, capacity, "sum", valid_p)
    return out
