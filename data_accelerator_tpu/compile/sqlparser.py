"""SQL subset parser for DataXQuery statements.

Parses the SELECT dialect used by flows (reference queries all flow
through Spark SQL — ``spark.sql(statement)`` at
CommonProcessorFactory.scala:257 — so the subset here mirrors what the
reference's sample flows, rule templates, and codegen actually emit):

  SELECT [DISTINCT] expr [AS alias], ...
  FROM table [alias] [ [INNER|LEFT] JOIN table [alias] ON cond ]*
  [WHERE cond] [GROUP BY expr, ...] [UNION [ALL] select]

Expressions: literals, (back)quoted/dotted identifiers, arithmetic,
comparison, AND/OR/NOT, IN (...), function calls (incl. aggregate
functions, CAST(x AS type), IF, CASE WHEN, MAP/STRUCT/Array literals),
``*`` and ``t.*``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Tuple, Union


class SqlParseError(Exception):
    """Parse failure; ``pos`` (when known) is the 0-based character
    offset of the offending token in the statement text, so design-time
    diagnostics can point at the exact source location."""

    def __init__(self, message: str, pos: Optional[int] = None):
        super().__init__(message)
        self.pos = pos


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class Literal:
    value: Union[int, float, str, bool, None]
    kind: str  # "int" | "float" | "str" | "bool" | "null"


@dataclass(frozen=True)
class Col:
    parts: Tuple[str, ...]  # dotted path, possibly table-qualified

    @property
    def dotted(self) -> str:
        return ".".join(self.parts)


@dataclass(frozen=True)
class Star:
    table: Optional[str] = None  # for "t.*"


@dataclass(frozen=True)
class Func:
    name: str  # upper-cased
    args: Tuple["Expr", ...]
    distinct: bool = False  # COUNT(DISTINCT x)


@dataclass(frozen=True)
class Cast:
    expr: "Expr"
    target: str  # upper-cased type name


@dataclass(frozen=True)
class BinOp:
    op: str  # +,-,*,/,%, =,!=,<,<=,>,>=, AND, OR
    left: "Expr"
    right: "Expr"


@dataclass(frozen=True)
class UnaryOp:
    op: str  # NOT, -
    operand: "Expr"


@dataclass(frozen=True)
class InList:
    expr: "Expr"
    options: Tuple["Expr", ...]
    negated: bool = False


@dataclass(frozen=True)
class CaseWhen:
    whens: Tuple[Tuple["Expr", "Expr"], ...]
    otherwise: Optional["Expr"]


@dataclass(frozen=True)
class IsNull:
    expr: "Expr"
    negated: bool = False


@dataclass(frozen=True)
class LikeOp:
    """``expr [NOT] LIKE 'pattern'`` / ``expr RLIKE 'regex'``."""

    expr: "Expr"
    pattern: "Expr"  # must be a string literal at compile time
    negated: bool = False
    regex: bool = False  # RLIKE / REGEXP


Expr = Union[
    Literal, Col, Star, Func, Cast, BinOp, UnaryOp, InList, CaseWhen,
    IsNull, LikeOp,
]


@dataclass(frozen=True)
class OrderItem:
    expr: "Expr"
    ascending: bool = True


@dataclass(frozen=True)
class SelectItem:
    expr: Expr
    alias: Optional[str]


@dataclass(frozen=True)
class TableRef:
    name: str
    alias: Optional[str] = None

    @property
    def binding(self) -> str:
        return self.alias or self.name


@dataclass(frozen=True)
class JoinClause:
    table: TableRef
    kind: str  # "INNER" | "LEFT"
    on: Expr


@dataclass(frozen=True)
class Select:
    items: Tuple[SelectItem, ...]
    from_table: Optional[TableRef]
    joins: Tuple[JoinClause, ...] = ()
    where: Optional[Expr] = None
    group_by: Tuple[Expr, ...] = ()
    having: Optional[Expr] = None
    order_by: Tuple[OrderItem, ...] = ()
    limit: Optional[int] = None
    distinct: bool = False
    union: Optional["Select"] = None  # UNION ALL chain
    union_distinct: bool = False


# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------
_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<num>\d+\.\d+(?:[eE][+-]?\d+)?|\d+[eE][+-]?\d+|\d+)
  | (?P<str>'(?:[^']|'')*')
  | (?P<bq>`[^`]*`)
  | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<op><>|!=|<=|>=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.)
    """,
    re.VERBOSE,
)

KEYWORDS = {
    "SELECT", "DISTINCT", "FROM", "WHERE", "GROUP", "BY", "ORDER", "LIMIT",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "ON", "AS", "AND",
    "OR", "NOT", "IN", "IS", "NULL", "TRUE", "FALSE", "UNION", "ALL",
    "CASE", "WHEN", "THEN", "ELSE", "END", "CAST", "LIKE", "BETWEEN",
}

# contextual keywords: recognized only in their clause position, so
# columns/aliases named "desc", "having", "regexp" etc. keep parsing
# (they are not reserved words in this dialect's existing surface)
_CONTEXTUAL = ("HAVING", "ASC", "DESC", "RLIKE", "REGEXP")


@dataclass
class Token:
    kind: str  # "num" | "str" | "ident" | "bq" | "op" | "kw" | "eof"
    value: str
    pos: int = -1  # 0-based character offset in the source text


def tokenize(text: str) -> List[Token]:
    tokens: List[Token] = []
    pos = 0
    while pos < len(text):
        m = _TOKEN_RE.match(text, pos)
        if not m:
            raise SqlParseError(
                f"unexpected character {text[pos]!r} at {pos}: ...{text[max(0,pos-20):pos+20]!r}",
                pos=pos,
            )
        start = pos
        pos = m.end()
        if m.lastgroup == "ws":
            continue
        kind, value = m.lastgroup, m.group()
        if kind == "ident" and value.upper() in KEYWORDS:
            tokens.append(Token("kw", value.upper(), start))
        else:
            tokens.append(Token(kind, value, start))
    tokens.append(Token("eof", "", len(text)))
    return tokens


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------
class _Parser:
    def __init__(self, tokens: List[Token], text: str):
        self.toks = tokens
        self.i = 0
        self.text = text

    # -- primitives ------------------------------------------------------
    def peek(self, offset: int = 0) -> Token:
        return self.toks[min(self.i + offset, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[str]:
        t = self.peek()
        if t.kind == "kw" and t.value in kws:
            self.next()
            return t.value
        return None

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SqlParseError(
                f"expected {kw}, got {self.peek().value!r} in: {self.text[:200]}",
                pos=self.peek().pos,
            )

    def accept_op(self, op: str) -> bool:
        t = self.peek()
        if t.kind == "op" and t.value == op:
            self.next()
            return True
        return False

    def accept_ctx_kw(self, *words: str) -> Optional[str]:
        """Accept a contextual keyword (plain ident matched by value)."""
        t = self.peek()
        if t.kind == "ident" and t.value.upper() in words:
            self.next()
            return t.value.upper()
        return None

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SqlParseError(
                f"expected {op!r}, got {self.peek().value!r} in: {self.text[:200]}",
                pos=self.peek().pos,
            )

    # -- grammar ---------------------------------------------------------
    def parse_select(self) -> Select:
        self.expect_kw("SELECT")
        distinct = bool(self.accept_kw("DISTINCT"))
        items = [self.parse_select_item()]
        while self.accept_op(","):
            items.append(self.parse_select_item())

        from_table = None
        joins: List[JoinClause] = []
        if self.accept_kw("FROM"):
            from_table = self.parse_table_ref()
            while True:
                kind = None
                if self.accept_kw("INNER"):
                    self.expect_kw("JOIN")
                    kind = "INNER"
                elif self.accept_kw("LEFT"):
                    self.accept_kw("OUTER")
                    self.expect_kw("JOIN")
                    kind = "LEFT"
                elif self.accept_kw("JOIN"):
                    kind = "INNER"
                else:
                    break
                table = self.parse_table_ref()
                self.expect_kw("ON")
                on = self.parse_expr()
                joins.append(JoinClause(table, kind, on))

        where = None
        if self.accept_kw("WHERE"):
            where = self.parse_expr()

        group_by: List[Expr] = []
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            group_by.append(self.parse_expr())
            while self.accept_op(","):
                group_by.append(self.parse_expr())

        having = None
        if self.accept_ctx_kw("HAVING"):
            having = self.parse_expr()

        union = None
        union_distinct = False
        if self.accept_kw("UNION"):
            union_distinct = not self.accept_kw("ALL")
            union = self.parse_select()

        # trailing ORDER BY / LIMIT (after a UNION chain they apply to
        # the whole union, which the planner honors by hoisting)
        order_by: List[OrderItem] = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                e = self.parse_expr()
                asc = self.accept_ctx_kw("ASC", "DESC") != "DESC"
                order_by.append(OrderItem(e, asc))
                if not self.accept_op(","):
                    break

        limit = None
        if self.accept_kw("LIMIT"):
            t = self.next()
            if t.kind != "num" or "." in t.value:
                raise SqlParseError(
                    f"LIMIT expects an integer, got {t.value!r}", pos=t.pos
                )
            limit = int(t.value)

        return Select(
            items=tuple(items),
            from_table=from_table,
            joins=tuple(joins),
            where=where,
            group_by=tuple(group_by),
            having=having,
            order_by=tuple(order_by),
            limit=limit,
            distinct=distinct,
            union=union,
            union_distinct=union_distinct,
        )

    def parse_table_ref(self) -> TableRef:
        t = self.next()
        if t.kind not in ("ident", "bq"):
            raise SqlParseError(
                f"expected table name, got {t.value!r}", pos=t.pos
            )
        name = t.value.strip("`")
        alias = None
        if self.accept_kw("AS"):
            alias = self.next().value
        elif (
            self.peek().kind == "ident"
            and self.peek().value.upper() not in _CONTEXTUAL
        ):
            # bare alias — but not a clause word in clause position
            # (FROM t HAVING ... / ORDER BY x DESC must not eat it)
            alias = self.next().value
        return TableRef(name, alias)

    def parse_select_item(self) -> SelectItem:
        # "*" or "t.*"
        if self.peek().kind == "op" and self.peek().value == "*":
            self.next()
            return SelectItem(Star(), None)
        if (
            self.peek().kind == "ident"
            and self.peek(1).kind == "op" and self.peek(1).value == "."
            and self.peek(2).kind == "op" and self.peek(2).value == "*"
        ):
            table = self.next().value
            self.next()  # .
            self.next()  # *
            return SelectItem(Star(table), None)
        expr = self.parse_expr()
        alias = None
        if self.accept_kw("AS"):
            t = self.next()
            alias = t.value.strip("`")
        elif self.peek().kind in ("ident", "bq"):
            alias = self.next().value.strip("`")
        return SelectItem(expr, alias)

    # precedence: OR < AND < NOT < comparison < additive < multiplicative < unary
    def parse_expr(self) -> Expr:
        return self.parse_or()

    def parse_or(self) -> Expr:
        left = self.parse_and()
        while self.accept_kw("OR"):
            left = BinOp("OR", left, self.parse_and())
        return left

    def parse_and(self) -> Expr:
        left = self.parse_not()
        while self.accept_kw("AND"):
            left = BinOp("AND", left, self.parse_not())
        return left

    def parse_not(self) -> Expr:
        if self.accept_kw("NOT"):
            return UnaryOp("NOT", self.parse_not())
        return self.parse_comparison()

    def parse_comparison(self) -> Expr:
        left = self.parse_additive()
        t = self.peek()
        if t.kind == "op" and t.value in ("=", "!=", "<>", "<", "<=", ">", ">="):
            self.next()
            op = "!=" if t.value == "<>" else t.value
            return BinOp(op, left, self.parse_additive())
        negated = False
        if (
            self.peek().kind == "kw" and self.peek().value == "NOT"
            and self.peek(1).value.upper()
            in ("IN", "LIKE", "RLIKE", "REGEXP", "BETWEEN")
        ):
            self.next()
            negated = True
        if self.accept_kw("LIKE"):
            return LikeOp(left, self.parse_additive(), negated, regex=False)
        if self.accept_ctx_kw("RLIKE", "REGEXP"):
            return LikeOp(left, self.parse_additive(), negated, regex=True)
        if self.accept_kw("IN"):
            self.expect_op("(")
            options = [self.parse_expr()]
            while self.accept_op(","):
                options.append(self.parse_expr())
            self.expect_op(")")
            return InList(left, tuple(options), negated)
        if self.accept_kw("IS"):
            neg = bool(self.accept_kw("NOT"))
            self.expect_kw("NULL")
            return IsNull(left, neg)
        if self.accept_kw("BETWEEN"):
            lo = self.parse_additive()
            self.expect_kw("AND")
            hi = self.parse_additive()
            if negated:
                # NOT BETWEEN desugars to strict comparisons, NOT to
                # NOT(range): comparisons over NULL are false on both
                # sides, so NULL rows stay excluded (Spark semantics),
                # where a bare NOT would flip them to included
                return BinOp(
                    "OR", BinOp("<", left, lo), BinOp(">", left, hi)
                )
            return BinOp("AND", BinOp(">=", left, lo), BinOp("<=", left, hi))
        if negated:
            raise SqlParseError(
                "NOT must be followed by IN/LIKE/RLIKE/BETWEEN near "
                f"{self.peek().value!r}",
                pos=self.peek().pos,
            )
        return left

    def parse_additive(self) -> Expr:
        left = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("+", "-"):
                self.next()
                left = BinOp(t.value, left, self.parse_multiplicative())
            else:
                return left

    def parse_multiplicative(self) -> Expr:
        left = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == "op" and t.value in ("*", "/", "%"):
                self.next()
                left = BinOp(t.value, left, self.parse_unary())
            else:
                return left

    def parse_unary(self) -> Expr:
        if self.accept_op("-"):
            return UnaryOp("-", self.parse_unary())
        if self.accept_op("+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> Expr:
        t = self.peek()
        if t.kind == "num":
            self.next()
            if "." in t.value or "e" in t.value or "E" in t.value:
                return Literal(float(t.value), "float")
            return Literal(int(t.value), "int")
        if t.kind == "str":
            self.next()
            return Literal(t.value[1:-1].replace("''", "'"), "str")
        if t.kind == "kw":
            if t.value in ("TRUE", "FALSE"):
                self.next()
                return Literal(t.value == "TRUE", "bool")
            if t.value == "NULL":
                self.next()
                return Literal(None, "null")
            if t.value == "CASE":
                return self.parse_case()
            if t.value == "CAST":
                self.next()
                self.expect_op("(")
                inner = self.parse_expr()
                self.expect_kw("AS")
                target = self.next().value.upper()
                self.expect_op(")")
                return Cast(inner, target)
        if t.kind == "op" and t.value == "(":
            self.next()
            inner = self.parse_expr()
            self.expect_op(")")
            return inner
        if t.kind in ("ident", "bq"):
            return self.parse_identifier_or_call()
        raise SqlParseError(
            f"unexpected token {t.value!r} in: {self.text[:200]}", pos=t.pos
        )

    def parse_case(self) -> Expr:
        self.expect_kw("CASE")
        whens = []
        while self.accept_kw("WHEN"):
            cond = self.parse_expr()
            self.expect_kw("THEN")
            val = self.parse_expr()
            whens.append((cond, val))
        otherwise = None
        if self.accept_kw("ELSE"):
            otherwise = self.parse_expr()
        self.expect_kw("END")
        return CaseWhen(tuple(whens), otherwise)

    def parse_identifier_or_call(self) -> Expr:
        t = self.next()
        name = t.value.strip("`")
        # function call?
        if t.kind == "ident" and self.peek().kind == "op" and self.peek().value == "(":
            self.next()  # (
            if self.accept_op(")"):
                return Func(name.upper(), ())
            if self.peek().kind == "op" and self.peek().value == "*":
                self.next()
                self.expect_op(")")
                return Func(name.upper(), (Star(),))
            distinct = bool(self.accept_kw("DISTINCT"))
            args = [self.parse_expr()]
            while self.accept_op(","):
                args.append(self.parse_expr())
            self.expect_op(")")
            return Func(name.upper(), tuple(args), distinct)
        # dotted path: a.b.c (backquoted segments keep dots inside as one part)
        parts = [name]
        while (
            self.peek().kind == "op" and self.peek().value == "."
            and self.peek(1).kind in ("ident", "bq")
        ):
            self.next()
            parts.append(self.next().value.strip("`"))
        return Col(tuple(parts))


def parse_select(text: str) -> Select:
    p = _Parser(tokenize(text), text)
    sel = p.parse_select()
    if p.peek().kind != "eof":
        raise SqlParseError(
            f"trailing tokens starting at {p.peek().value!r} in: {text[:200]}",
            pos=p.peek().pos,
        )
    return sel
