"""Schema-driven flattener: hierarchical flow/job JSON -> flat conf keys.

The runtime engine reads a flat ``datax.job.*`` key=value map; the design
side produces hierarchical JSON. A flattener schema (same JSON format as
the reference's ``flattenerConfig.json``) maps one onto the other, so
flow documents written for the reference flatten identically here.

Mapping node types (reference: DataX.Config/ConfigDataModel/Flattener/*.cs,
golden behavior: DataX.Config.Test/Resource/Flattener/{input.json,config.json,
output.conf}):

- ``"fieldname"`` (bare string)          -> emit ``<ns>.<fieldname>=value``
- ``{"type": "object"}``                 -> recurse with namespace appended
- ``{"type": "scopedObject",
     "namespaceField": f}``              -> namespace extended by value[f]
- ``{"type": "array", "element": m}``    -> apply ``m`` per element
- ``{"type": "map", "fields": ...}``     -> per-key scoped object
- ``{"type": "stringList"}``             -> values joined with ";"
- ``{"type": "mapProps"}``               -> emit every key/value under ns
- ``{"type": "excludeDefaultValue",
     "defaultValue": v}``                -> emit only when value != v
"""

from __future__ import annotations

from typing import Dict, List, Optional, Union

from ..core.config import SettingNamespace

JsonVal = Union[dict, list, str, int, float, bool, None]


def _join(prefix: Optional[str], ns: Optional[str]) -> str:
    parts = [p for p in (prefix, ns) if p]
    return SettingNamespace.Separator.join(parts)


def _scalar(value: JsonVal) -> str:
    if isinstance(value, bool):
        return "true" if value else "false"
    return str(value)


class ConfigFlattener:
    """reference: InternalService/ConfigFlattener.cs + Flattener/*.cs"""

    def __init__(self, schema: dict):
        self.schema = schema

    def flatten(self, config: dict) -> Dict[str, str]:
        out: Dict[str, str] = {}
        self._apply(self.schema, config, "", out)
        return out

    def flatten_to_conf(self, config: dict) -> str:
        return "\n".join(f"{k}={v}" for k, v in self.flatten(config).items())

    # -- node dispatch ---------------------------------------------------
    def _apply(
        self, mapping: Union[str, dict], value: JsonVal, prefix: str, out: Dict[str, str]
    ) -> None:
        if value is None:
            return
        if isinstance(mapping, str):
            out[_join(prefix, mapping)] = _scalar(value)
            return

        mtype = mapping.get("type", "object")
        ns = mapping.get("namespace")

        if mtype == "object":
            self._apply_fields(mapping.get("fields", {}), value, _join(prefix, ns), out)
        elif mtype == "scopedObject":
            ns_field = mapping.get("namespaceField")
            if not isinstance(value, dict):
                return
            scope = value.get(ns_field) if ns_field else None
            self._apply_fields(
                mapping.get("fields", {}), value, _join(_join(prefix, ns), scope), out
            )
        elif mtype == "array":
            element = mapping.get("element")
            if not isinstance(value, list):
                return
            for item in value:
                # element namespace nests under the array's own namespace
                self._apply(element, item, _join(prefix, ns), out)
        elif mtype == "map":
            if not isinstance(value, dict):
                return
            base = _join(prefix, ns)
            for key, sub in value.items():
                self._apply_fields(mapping.get("fields", {}), sub, _join(base, key), out)
        elif mtype == "stringList":
            if not isinstance(value, list):
                return
            joined = SettingNamespace.ValueSeparator.join(_scalar(v) for v in value)
            out[_join(prefix, ns)] = joined
        elif mtype == "mapProps":
            if not isinstance(value, dict):
                return
            base = _join(prefix, ns)
            for key, sub in value.items():
                if sub is not None:
                    out[_join(base, key)] = _scalar(sub)
        elif mtype == "excludeDefaultValue":
            if value != mapping.get("defaultValue"):
                out[_join(prefix, ns)] = _scalar(value)
        else:
            raise ValueError(f"unknown flattener mapping type: {mtype!r}")

    def _apply_fields(
        self, fields: Dict[str, Union[str, dict]], value: JsonVal, prefix: str,
        out: Dict[str, str],
    ) -> None:
        if not isinstance(value, dict):
            return
        for field_name, mapping in fields.items():
            if field_name in value:
                self._apply(mapping, value[field_name], prefix, out)
