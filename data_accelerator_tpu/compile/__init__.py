"""Flow compiler: DataXQuery parsing, rules codegen, SQL planning, flattening."""

from .transform_parser import (
    SqlCommand,
    ParsedResult,
    TransformParser,
    COMMAND_TYPE_QUERY,
    COMMAND_TYPE_COMMAND,
)

__all__ = [
    "SqlCommand",
    "ParsedResult",
    "TransformParser",
    "COMMAND_TYPE_QUERY",
    "COMMAND_TYPE_COMMAND",
]
