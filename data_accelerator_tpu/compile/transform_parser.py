"""Parser for the DataXQuery transform dialect.

A transform script is a sequence of sections separated by ``--DataXQuery--``
lines; each section is either a named assignment ``name = SELECT ...`` (a
*Query* creating a temp view) or a bare statement (a *Command*). The parser
also counts how many later statements reference each created view, which
the pipeline executor uses to decide caching/materialization.

reference: datax-host sql/TransformSqlParser.scala:18-105
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

from ..constants import ProductConstant
from ..core.config import EngineException

COMMAND_TYPE_QUERY = "Query"
COMMAND_TYPE_COMMAND = "Command"

_SEPARATOR_RE = re.compile(ProductConstant.ProductQuery)
_STATES_SEPARATOR_RE = re.compile(ProductConstant.ProductStates)
_COMMENT_RE = re.compile(r"^\s*--")
_ASSIGN_RE = re.compile(r"^\s*([a-zA-Z0-9_]+)\s*=(.*)$", re.DOTALL)


@dataclass(frozen=True)
class SqlCommand:
    text: str
    name: Optional[str]
    command_type: str
    # 1-based source span of the statement body in the parsed script
    # (0 = unknown, for callers constructing commands by hand); the
    # analyzer points diagnostics here
    line: int = 0
    end_line: int = 0


@dataclass(frozen=True)
class ParsedResult:
    commands: List[SqlCommand] = field(default_factory=list)
    view_reference_count: Dict[str, int] = field(default_factory=dict)


class TransformParser:
    """reference: TransformSqlParser.scala:18-105 (same semantics)."""

    @staticmethod
    def parse(lines: Sequence[str]) -> ParsedResult:
        commands: List[SqlCommand] = []
        view_refs: Dict[str, int] = {}
        statement_buffer: List[str] = []
        table_name: Optional[str] = None
        start_line = end_line = 0  # 1-based span of the current buffer

        def append_table(name: Optional[str]) -> None:
            sql = " ".join(s for s in statement_buffer if s)
            ctype = COMMAND_TYPE_COMMAND if name is None else COMMAND_TYPE_QUERY
            commands.append(
                SqlCommand(sql, name, ctype, line=start_line,
                           end_line=end_line)
            )
            if name:
                if name in view_refs:
                    raise EngineException(
                        f"dataset name '{name}' has been created, please check the "
                        "query to make sure it is not created again"
                    )
                view_refs[name] = 0
                for k in view_refs:
                    if re.search(rf"\b{re.escape(k)}\b", sql):
                        view_refs[k] += 1

        for lineno, line in enumerate(lines, start=1):
            if not line.strip():
                continue
            if _SEPARATOR_RE.match(line):
                if statement_buffer:
                    append_table(table_name)
                table_name = None
                statement_buffer.clear()
            elif _COMMENT_RE.match(line):
                continue
            else:
                if not statement_buffer:
                    start_line = lineno
                    m = _ASSIGN_RE.match(line)
                    if m:
                        table_name = m.group(1)
                        statement_buffer.append(m.group(2).strip())
                    else:
                        statement_buffer.append(line.strip())
                else:
                    statement_buffer.append(line.strip())
                end_line = lineno

        # flush the trailing section; unlike the reference (which only keeps
        # it when named, TransformSqlParser.scala:88-92) we also keep a
        # trailing unnamed command rather than silently dropping it
        if statement_buffer and (table_name is not None or statement_buffer[0]):
            append_table(table_name)

        return ParsedResult(commands, view_refs)

    @staticmethod
    def parse_text(text: str) -> ParsedResult:
        return TransformParser.parse(text.split("\n"))

    @staticmethod
    def replace_table_names(statement: str, mappings: Dict[str, str]) -> str:
        """reference: TransformSqlParser.scala:97-104"""
        for old, new in mappings.items():
            statement = re.sub(rf"\b{re.escape(old)}\b", new, statement)
        return statement

    @staticmethod
    def split_states_sections(text: str) -> tuple:
        """Split a script into (states_ddl_lines, transform_lines).

        ``--DataXStates--`` sections carry ``CREATE TABLE`` DDL for
        accumulation tables; everything else is the transform proper.
        reference: the C# codegen splits these before writing the
        transform file (Engine.cs state handling); the Scala engine sees
        state tables via ``process.statetable.*`` conf instead.
        """
        states: List[str] = []
        transform: List[str] = []
        in_states = False
        for line in text.split("\n"):
            if _STATES_SEPARATOR_RE.match(line):
                in_states = True
                continue
            if _SEPARATOR_RE.match(line):
                in_states = False
            (states if in_states else transform).append(line)
        return states, transform
