"""Typed expression compilation: SQL AST -> jax array programs.

Every expression compiles to a ``CompiledExpr`` whose ``fn(env)`` returns
a device array; ``env`` is an ``EvalEnv`` carrying the in-scope column
arrays and the batch time context. Plan-level types extend the storage
types with time encodings and composite values:

- "long"/"double"/"boolean"/"string": as in core.schema (string = dict id)
- "timestamp": int32 ms relative to the batch base (whole-second base)
- "tssec":     int32 s  relative to the batch base (unix_timestamp math)
- StructValue: named fields (MAP with literal keys / STRUCT)
- ArrayValue:  fixed-length element list (Array/filterNull), elements may
  carry validity (IF(cond, x, NULL))
- HostStr:     deferred host-side string computation (CONCAT etc.) — the
  device carries its input columns; the string materializes on the host
  at sink/display time for the (few) surviving rows.

Time design: the device never sees absolute epochs wider than int32.
``base_s`` (int32 epoch seconds, whole-second) and ``now_rel_ms`` (int32)
come in as traced scalars, so absolute-time functions (hour(),
DATE_TRUNC) are exact integer math. reference analog: Spark SQL evaluates
these on JVM longs; the contract (same results) is preserved, the
representation is TPU-first.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

from ..core.config import EngineException
from ..core.schema import StringDictionary
from .sqlparser import (
    BinOp,
    CaseWhen,
    Cast,
    Col,
    Expr,
    Func,
    InList,
    IsNull,
    LikeOp,
    Literal,
    Star,
    UnaryOp,
)
from .stringops import (
    RANK_KEY,
    AuxRegistry,
    like_to_regex,
    spark_instr,
    spark_split_at,
    spark_substring,
)

AGGREGATE_FNS = {"AVG", "MIN", "MAX", "SUM", "COUNT"}

_DTYPES = {
    "long": jnp.int32,
    "double": jnp.float32,
    "boolean": jnp.bool_,
    "string": jnp.int32,
    "timestamp": jnp.int32,
    "tssec": jnp.int32,
}


@dataclass
class EvalEnv:
    """Columns in scope + time context, all device values."""

    # binding -> {column dotted name -> array}
    scopes: Dict[str, Dict[str, jnp.ndarray]]
    base_s: jnp.ndarray  # scalar int32 epoch seconds (whole second)
    now_rel_ms: jnp.ndarray  # scalar int32: "now" relative to base
    shape: Tuple[int, ...] = ()  # row-shape for literal broadcasting

    def column(self, binding: str, name: str) -> jnp.ndarray:
        return self.scopes[binding][name]


@dataclass
class CompiledExpr:
    type: str  # "long" | "double" | "boolean" | "string" | "timestamp" | "tssec"
    fn: Callable[[EvalEnv], jnp.ndarray]
    # source column dependencies (binding, column) — used for DISTINCT on
    # deferred strings and for join-side analysis
    deps: Tuple[Tuple[str, str], ...] = ()


@dataclass
class StructValue:
    fields: Dict[str, "Value"]
    validity: Optional[CompiledExpr] = None  # IF(cond, struct, NULL)


@dataclass
class ArrayValue:
    elements: List["Value"]


# reserved literal prefix marking a CONCAT_WS deferred template: the
# marker part carries the separator, every following part is ONE
# argument (null arguments are skipped at materialization, Spark
# concat_ws semantics). "\x00" cannot occur in user literals.
WS_MARKER = "\x00ws:"


@dataclass
class HostStr:
    """Deferred string expression: parts are literal strs or CompiledExpr
    whose device value gets decoded/stringified on the host at sink time.
    A first part starting with ``WS_MARKER`` switches the template to
    concat_ws (skip-null) rendering."""

    parts: List[Union[str, CompiledExpr]]
    deps: Tuple[Tuple[str, str], ...] = ()


Value = Union[CompiledExpr, StructValue, ArrayValue, HostStr]


def is_device(v: Value) -> bool:
    return isinstance(v, CompiledExpr)


def _int_str_hash(n: jnp.ndarray, p: int):
    """Rolling hash of ``str(n)`` computed ON DEVICE for int32 ``n`` —
    the tier that makes ``CONCAT(..., CAST(n AS STRING))`` first-class
    (stringified numerics have unbounded value space, so no dictionary
    table can cover them; their decimal rendering is integer math).

    Returns ``(H_p(str(n)), p^len(str(n)))`` as int32 bit patterns,
    matching ``stringops.poly_hash``/``pow_len`` of the host rendering
    exactly (uint32 arithmetic == int32 wrap-around bit-for-bit). The
    magnitude runs in uint32 so INT32_MIN's absolute value survives."""
    from .stringops import _MASK32

    u = jax.lax.bitcast_convert_type(
        jnp.asarray(n, jnp.int32), jnp.uint32
    )
    neg = n < 0
    a = jnp.where(neg, jnp.uint32(0) - u, u)
    ndigits = jnp.ones(a.shape, jnp.int32)
    for k in range(1, 10):
        ndigits = ndigits + (a >= jnp.uint32(10 ** k)).astype(jnp.int32)
    # chars are '-' then most-significant digit first: walk fixed 10
    # digit slots, folding only the active ones (XLA unrolls; no loop)
    h = jnp.where(neg, jnp.uint32(ord("-") + 1), jnp.uint32(0))
    pu = jnp.uint32(p & _MASK32)
    for i in range(9, -1, -1):
        digit = (a // jnp.uint32(10 ** i)) % jnp.uint32(10)
        folded = h * pu + (jnp.uint32(ord("0") + 1) + digit)
        h = jnp.where(ndigits > i, folded, h)
    # p^len (len includes the sign char) via a 12-entry constant table
    pow_tbl = jnp.asarray(
        [pow(p, k, 1 << 32) for k in range(12)], jnp.uint32
    )
    plen = pow_tbl[ndigits + neg.astype(jnp.int32)]
    return (
        jax.lax.bitcast_convert_type(h, jnp.int32),
        jax.lax.bitcast_convert_type(plen, jnp.int32),
    )


# ---------------------------------------------------------------------------
# Name resolution
# ---------------------------------------------------------------------------
@dataclass
class Scope:
    """Resolution scope: bindings (table aliases) -> column name -> type.

    Column values may be plan types (str) or composite Values for columns
    that are themselves deferred (HostStr passthrough).
    """

    tables: Dict[str, Dict[str, str]]  # binding -> {col -> type}
    deferred: Dict[str, Dict[str, HostStr]] = field(default_factory=dict)

    def resolve(self, parts: Sequence[str]) -> Tuple[str, str]:
        """Resolve a dotted reference to (binding, column_name).

        Rules (covering the reference flows' usage):
        1. if parts[0] is a binding, resolve the remainder inside it;
        2. otherwise search all bindings for an exact dotted match, then a
           unique dot-boundary suffix match (``deviceId`` matches
           ``deviceDetails.deviceId``).
        """
        dotted = ".".join(parts)
        if parts[0] in self.tables and len(parts) > 1:
            binding = parts[0]
            rest = ".".join(parts[1:])
            col = self._match_in(binding, rest)
            if col is not None:
                return binding, col
            if rest in self.deferred.get(binding, {}):
                return binding, rest
            # fall through: maybe "deviceDetails.deviceId" where
            # deviceDetails coincides with nothing
        candidates: List[Tuple[str, str]] = []
        for binding in self.tables:
            col = self._match_in(binding, dotted)
            if col is not None:
                candidates.append((binding, col))
        # deferred (computed-string) columns resolve by exact name
        for binding, dcols in self.deferred.items():
            if dotted in dcols:
                candidates.append((binding, dotted))
        if len(candidates) == 1:
            return candidates[0]
        if len(candidates) > 1:
            # a join scope's merged "" binding subsumes the per-table
            # bindings (it exists exactly so unqualified names resolve
            # once); prefer it
            merged = [c for c in candidates if c[0] == ""]
            if len(merged) == 1:
                return merged[0]
            # then prefer exact-name matches over suffix matches
            exact = [c for c in candidates if c[1] == dotted]
            if len(exact) == 1:
                return exact[0]
            raise EngineException(
                f"ambiguous column reference '{dotted}' across {sorted(t for t, _ in candidates)}"
            )
        raise EngineException(
            f"cannot resolve column '{dotted}' in scope "
            f"{ {b: sorted(cols) for b, cols in self.tables.items()} }"
        )

    def _match_in(self, binding: str, dotted: str) -> Optional[str]:
        cols = self.tables[binding]
        if dotted in cols:
            return dotted
        suffix_matches = [c for c in cols if c.endswith("." + dotted)]
        if len(suffix_matches) == 1:
            return suffix_matches[0]
        if len(suffix_matches) > 1:
            raise EngineException(
                f"ambiguous column suffix '{dotted}' in table '{binding}': {suffix_matches}"
            )
        return None

    def type_of(self, binding: str, col: str) -> str:
        return self.tables[binding][col]


# ---------------------------------------------------------------------------
# Numeric promotion helpers
# ---------------------------------------------------------------------------
def _promote(a: str, b: str) -> str:
    if a == b:
        return a
    numeric_rank = {"boolean": 0, "long": 1, "tssec": 1, "timestamp": 1, "double": 2}
    if a in numeric_rank and b in numeric_rank:
        return "double" if numeric_rank[a] == 2 or numeric_rank[b] == 2 else "long"
    raise EngineException(f"cannot combine types {a} and {b}")


def _to_dtype(arr: jnp.ndarray, t: str) -> jnp.ndarray:
    return arr.astype(_DTYPES[t])


# ---------------------------------------------------------------------------
# Expression compiler
# ---------------------------------------------------------------------------
class ExprCompiler:
    """Compile AST expressions against a Scope.

    ``udfs``: name -> callable(device arrays...) -> (array, type) for the
    jax UDF tier; host UDFs (str -> str) come through the registry and
    produce HostStr values.
    """

    def __init__(
        self,
        scope: Scope,
        dictionary: StringDictionary,
        udfs: Optional[dict] = None,
        aux: Optional[AuxRegistry] = None,
    ):
        self.scope = scope
        self.dictionary = dictionary
        self.udfs = udfs or {}
        # UDF objects this compiler's expressions actually called — the
        # select compiler attributes them to the view's StagePlan so
        # the mesh partition planner knows which stages embed custom
        # kernels the SPMD partitioner cannot shard
        self.called_udfs: list = []
        # dictionary-table registry for device string ops; shared across
        # every compiler of one flow (see compile/stringops.py)
        self.aux = aux if aux is not None else AuxRegistry()

    # -- public ----------------------------------------------------------
    def compile(self, e: Expr) -> Value:
        if isinstance(e, Literal):
            return self._literal(e)
        if isinstance(e, Col):
            return self._column(e)
        if isinstance(e, BinOp):
            return self._binop(e)
        if isinstance(e, UnaryOp):
            return self._unary(e)
        if isinstance(e, Func):
            return self._func(e)
        if isinstance(e, Cast):
            return self._cast(e)
        if isinstance(e, InList):
            return self._in_list(e)
        if isinstance(e, CaseWhen):
            return self._case(e)
        if isinstance(e, IsNull):
            return self._is_null(e)
        if isinstance(e, LikeOp):
            return self._like(e)
        if isinstance(e, Star):
            raise EngineException("* only allowed as a top-level select item")
        raise EngineException(f"unsupported expression {e!r}")

    def compile_device(self, e: Expr, what: str = "expression") -> CompiledExpr:
        v = self.compile(e)
        if not is_device(v):
            raise EngineException(
                f"{what} must be device-computable, got deferred/composite: {e!r}"
            )
        return v

    # -- leaves ----------------------------------------------------------
    def _literal(self, e: Literal) -> Value:
        if e.kind == "str":
            sid = self.dictionary.encode(e.value)
            return CompiledExpr(
                "string",
                lambda env, sid=sid: jnp.broadcast_to(
                    jnp.asarray(sid, jnp.int32), env.shape
                ),
            )
        if e.kind == "null":
            # bare NULL only appears inside IF(cond, x, NULL); handled there
            return CompiledExpr(
                "long", lambda env: jnp.broadcast_to(jnp.asarray(0, jnp.int32), env.shape)
            )
        if e.kind == "bool":
            return CompiledExpr(
                "boolean",
                lambda env, v=e.value: jnp.broadcast_to(jnp.asarray(v), env.shape),
            )
        if e.kind == "float":
            return CompiledExpr(
                "double",
                lambda env, v=e.value: jnp.broadcast_to(
                    jnp.asarray(v, jnp.float32), env.shape
                ),
            )
        return CompiledExpr(
            "long",
            lambda env, v=e.value: jnp.broadcast_to(jnp.asarray(v, jnp.int32), env.shape),
        )

    def _column(self, e: Col) -> Value:
        binding, col = self.scope.resolve(e.parts)
        deferred = self.scope.deferred.get(binding, {})
        if col in deferred:
            h = deferred[col]
            return HostStr(list(h.parts), h.deps)
        t = self.scope.type_of(binding, col)
        return CompiledExpr(
            t,
            lambda env, b=binding, c=col: env.column(b, c),
            deps=((binding, col),),
        )

    # -- operators -------------------------------------------------------
    def _binop(self, e: BinOp) -> Value:
        op = e.op
        if op in ("AND", "OR"):
            l = self.compile_device(e.left, "boolean operand")
            r = self.compile_device(e.right, "boolean operand")
            f = jnp.logical_and if op == "AND" else jnp.logical_or
            return CompiledExpr(
                "boolean",
                lambda env, l=l, r=r, f=f: f(l.fn(env), r.fn(env)),
                deps=l.deps + r.deps,
            )

        lv = self.compile(e.left)
        rv = self.compile(e.right)
        if op in ("=", "!=") and (
            isinstance(lv, HostStr) or isinstance(rv, HostStr)
        ):
            # computed strings (CONCAT/CAST results) compare via the
            # device hash tier instead of dictionary ids
            return self._deferred_equality(op, lv, rv, e)

        l = self._as_device_value(lv, e.left)
        r = self._as_device_value(rv, e.right)

        if op in ("=", "!=", "<", "<=", ">", ">="):
            return self._comparison(op, l, r)
        return self._arith(op, l, r)

    def _as_device(self, e: Expr) -> CompiledExpr:
        return self._as_device_value(self.compile(e), e)

    def _as_device_value(self, v: Value, e: Expr) -> CompiledExpr:
        if isinstance(v, HostStr):
            raise EngineException(
                "deferred string expressions (CONCAT/CAST-to-string results) "
                f"cannot be used in device computation: {e!r}"
            )
        if not is_device(v):
            raise EngineException(f"composite value not usable here: {e!r}")
        return v

    # -- computed-string device keys --------------------------------------
    def hash_keys(self, v: Value) -> Optional[List[CompiledExpr]]:
        """Device key triple ``[h1, h2, isnull]`` for a string value.

        Gives deferred strings (CONCAT/CAST-to-string results) a
        first-class device tier for equality / GROUP BY / JOIN: two
        independent rolling hashes compose over concatenation via the
        per-id hash/p^len tables (see stringops.register_strhash), so a
        computed string never needs a dictionary id to participate in
        device comparisons. ``CAST(<long> AS STRING)`` parts have
        unbounded value space — no table can cover them — but their
        decimal rendering is pure integer math, so the device computes
        the rolling hash of the digit string directly (see
        ``_int_str_hash``). Returns None when ``v`` is not a string or
        contains parts with no device tier (CAST of double — float
        formatting is not device math; CONCAT_WS — skip-null breaks the
        rolling-hash composition).

        reference parity: the reference composes string expressions
        freely because Spark SQL evaluates them row-by-row
        (CommonProcessorFactory.scala:257); this is the TPU-resident
        equivalent for the equality-class uses.
        """
        from .stringops import (
            HASH1_KEY,
            HASH2_KEY,
            HASH_P1,
            HASH_P2,
            PLEN1_KEY,
            PLEN2_KEY,
            poly_hash,
            pow_len,
            register_strhash,
        )

        if is_device(v) and v.type == "string":
            parts: List[Union[str, CompiledExpr]] = [v]
        elif isinstance(v, HostStr):
            if v.parts and isinstance(v.parts[0], str) \
                    and v.parts[0].startswith(WS_MARKER):
                # concat_ws skips null arguments — a rolling hash over
                # fixed parts cannot express that; no device tier
                return None
            parts = []
            for p in v.parts:
                if isinstance(p, str):
                    parts.append(p)
                elif is_device(p) and p.type in ("string", "long"):
                    # long: CAST(n AS STRING) — digit hash computed on
                    # device (_int_str_hash); other types have no exact
                    # device rendering (double formatting, timestamp
                    # patterns) and fall back to host-only
                    parts.append(p)
                else:
                    return None
        else:
            return None
        register_strhash(self.aux)
        deps = tuple(
            d
            for p in parts
            if not isinstance(p, str)
            for d in p.deps
        )

        def null_of(env, parts=parts):
            n = jnp.broadcast_to(jnp.asarray(False), env.shape)
            for p in parts:
                # only STRING parts can be null (id 0); a long part's 0
                # is the number zero, which stringifies to "0"
                if not isinstance(p, str) and p.type == "string":
                    n = n | (p.fn(env) == 0)
            return n

        def make(hkey, pkey, hp):
            consts = [
                (poly_hash(p, hp), pow_len(p, hp))
                if isinstance(p, str) else None
                for p in parts
            ]

            def run(env, parts=parts, consts=consts, hkey=hkey, pkey=pkey,
                    hp=hp):
                th = env.scopes["__aux"][hkey]
                tq = env.scopes["__aux"][pkey]
                h_acc = jnp.zeros(env.shape, jnp.int32)
                for p, c in zip(parts, consts):
                    if c is not None:
                        # H(a+lit) = H(a)*p^len(lit) + H(lit), int32 wrap
                        h_acc = h_acc * jnp.asarray(c[1], jnp.int32) \
                            + jnp.asarray(c[0], jnp.int32)
                    elif p.type == "string":
                        idx = jnp.clip(p.fn(env), 0, th.shape[0] - 1)
                        h_acc = h_acc * tq[idx] + th[idx]
                    else:
                        # stringified integer: hash of the decimal
                        # rendering, computed in uint32 device math
                        ph, pl = _int_str_hash(p.fn(env), hp)
                        h_acc = h_acc * pl + ph
                # a NULL part nulls the whole string; zero the hash so
                # every null row carries the same key (SQL groups NULLs
                # together)
                return jnp.where(null_of(env), 0, h_acc)

            return CompiledExpr("long", run, deps=deps)

        return [
            make(HASH1_KEY, PLEN1_KEY, HASH_P1),
            make(HASH2_KEY, PLEN2_KEY, HASH_P2),
            CompiledExpr("boolean", null_of, deps=deps),
        ]

    def _deferred_equality(self, op: str, lv: Value, rv: Value, e) -> CompiledExpr:
        lk = self.hash_keys(lv)
        rk = self.hash_keys(rv)
        if lk is None or rk is None:
            raise EngineException(
                "string comparison with a computed string requires both "
                "sides to be strings built from string columns/literals "
                "or stringified integers; CAST of double/timestamp values "
                f"to string cannot compare on device: {e!r}"
            )
        h1l, h2l, nl = lk
        h1r, h2r, nr = rk

        def run(env):
            eq = (h1l.fn(env) == h1r.fn(env)) & (h2l.fn(env) == h2r.fn(env))
            notnull = jnp.logical_not(nl.fn(env)) & jnp.logical_not(nr.fn(env))
            if op == "=":
                return eq & notnull
            return jnp.logical_not(eq) & notnull

        return CompiledExpr("boolean", run, deps=h1l.deps + h1r.deps)

    def _comparison(self, op: str, l: CompiledExpr, r: CompiledExpr) -> CompiledExpr:
        lt, rt = l.type, r.type
        if ("string" in (lt, rt)) and lt != rt:
            raise EngineException(f"cannot compare {lt} with {rt}")
        if lt == "string" and op not in ("=", "!="):
            # lexicographic ordering via the dictionary rank table:
            # rank[id] is the string's position in sorted order, so
            # integer comparison of ranks IS string comparison. A NULL
            # operand (id 0) makes the comparison NULL -> false.
            self.aux.require_rank()
            import operator as _op

            f = {"<": _op.lt, "<=": _op.le, ">": _op.gt, ">=": _op.ge}[op]

            def run_rank(env, l=l, r=r, f=f):
                t = env.scopes["__aux"][RANK_KEY]
                hi = t.shape[0] - 1
                a, b = l.fn(env), r.fn(env)
                ra = t[jnp.clip(a, 0, hi)]
                rb = t[jnp.clip(b, 0, hi)]
                return f(ra, rb) & (a != 0) & (b != 0)

            return CompiledExpr("boolean", run_rank, deps=l.deps + r.deps)
        if lt == "string":
            # = / != with SQL null semantics: NULL compares as NULL ->
            # false either way (ids are exact string identity otherwise)
            def run_eq(env, l=l, r=r, eq=(op == "=")):
                a, b = l.fn(env), r.fn(env)
                nn = (a != 0) & (b != 0)
                return ((a == b) if eq else (a != b)) & nn

            return CompiledExpr("boolean", run_eq, deps=l.deps + r.deps)
        # timestamp/tssec comparisons: both sides share the batch base, so
        # relative values compare exactly
        cast = None
        if lt != rt and "string" not in (lt, rt):
            cast = _promote(lt, rt)

        import operator as _op

        fns = {
            "=": _op.eq, "!=": _op.ne, "<": _op.lt,
            "<=": _op.le, ">": _op.gt, ">=": _op.ge,
        }
        f = fns[op]

        def run(env, l=l, r=r, f=f, cast=cast):
            a, b = l.fn(env), r.fn(env)
            if cast is not None:
                a, b = _to_dtype(a, cast), _to_dtype(b, cast)
            return f(a, b)

        return CompiledExpr("boolean", run, deps=l.deps + r.deps)

    def _arith(self, op: str, l: CompiledExpr, r: CompiledExpr) -> CompiledExpr:
        lt, rt = l.type, r.type
        if "string" in (lt, rt):
            raise EngineException("arithmetic on strings is not supported")

        # time-typed special cases (see module docstring)
        if op == "*" and lt == "tssec" and rt == "long":
            # unix_timestamp()*1000 -> absolute epoch ms; keep it relative
            def run_ms(env, l=l, r=r):
                return l.fn(env).astype(jnp.int32) * 1000
            return CompiledExpr("timestamp", run_ms, deps=l.deps + r.deps)
        if op == "-" and lt in ("timestamp", "tssec") and rt == lt:
            out_t = "long"

            def run_diff(env, l=l, r=r):
                return l.fn(env).astype(jnp.int32) - r.fn(env).astype(jnp.int32)

            return CompiledExpr(out_t, run_diff, deps=l.deps + r.deps)
        if lt in ("timestamp", "tssec") and rt == "long" and op in ("+", "-"):
            def run_shift(env, l=l, r=r, neg=(op == "-")):
                b = r.fn(env).astype(jnp.int32)
                return l.fn(env) + (-b if neg else b)
            return CompiledExpr(lt, run_shift, deps=l.deps + r.deps)

        out_t = _promote(lt, rt)
        if op == "/":
            out_t = "double"

        import operator as _op

        # '%' is TRUNCATED modulo (sign follows the dividend) per
        # Spark/SQL semantics — jnp.mod/Python % are floored and flip
        # the sign for negative dividends
        fns = {"+": _op.add, "-": _op.sub, "*": _op.mul, "%": jnp.fmod}

        def run(env, l=l, r=r, op=op, out_t=out_t):
            a, b = _to_dtype(l.fn(env), out_t), _to_dtype(r.fn(env), out_t)
            if op == "/":
                return a / b
            return fns[op](a, b)

        return CompiledExpr(out_t, run, deps=l.deps + r.deps)

    def _unary(self, e: UnaryOp) -> Value:
        v = self._as_device(e.operand)
        if e.op == "NOT":
            return CompiledExpr(
                "boolean", lambda env, v=v: jnp.logical_not(v.fn(env)), deps=v.deps
            )
        return CompiledExpr(v.type, lambda env, v=v: -v.fn(env), deps=v.deps)

    def _in_list(self, e: InList) -> Value:
        v = self._as_device(e.expr)
        opts = [self._as_device(o) for o in e.options]

        def run(env, v=v, opts=opts, neg=e.negated):
            a = v.fn(env)
            m = jnp.zeros_like(a, dtype=jnp.bool_)
            for o in opts:
                m = m | (a == o.fn(env).astype(a.dtype))
            return jnp.logical_not(m) if neg else m

        deps = v.deps + tuple(d for o in opts for d in o.deps)
        return CompiledExpr("boolean", run, deps=deps)

    def _case(self, e: CaseWhen) -> Value:
        whens = [
            (self._as_device(c), self._as_device(x)) for c, x in e.whens
        ]
        otherwise = self._as_device(e.otherwise) if e.otherwise else None
        out_t = whens[0][1].type
        for _, x in whens[1:]:
            out_t = _promote(out_t, x.type)
        if otherwise is not None:
            out_t = _promote(out_t, otherwise.type)

        def run(env, whens=whens, otherwise=otherwise, out_t=out_t):
            if otherwise is not None:
                acc = _to_dtype(otherwise.fn(env), out_t)
            else:
                acc = jnp.zeros(env.shape, dtype=_DTYPES[out_t])
            for cond, val in reversed(whens):
                acc = jnp.where(cond.fn(env), _to_dtype(val.fn(env), out_t), acc)
            return acc

        deps = tuple(
            d for c, x in whens for d in c.deps + x.deps
        ) + (otherwise.deps if otherwise else ())
        return CompiledExpr(out_t, run, deps=deps)

    def _is_null(self, e: IsNull) -> Value:
        # strings carry a real null (dictionary id 0); for other types
        # row-validity is the null mechanism, so present values are
        # non-null
        v = self.compile(e.expr)
        if is_device(v) and v.type == "string":
            def run(env, v=v, neg=e.negated):
                ids = v.fn(env)
                return (ids != 0) if neg else (ids == 0)

            return CompiledExpr("boolean", run, deps=v.deps)
        val = bool(e.negated)
        return CompiledExpr(
            "boolean", lambda env, v=val: jnp.broadcast_to(jnp.asarray(v), env.shape)
        )

    # -- dictionary-table string ops (compile/stringops.py) ---------------
    def _const_str(self, e: Expr, what: str) -> str:
        if isinstance(e, Literal) and e.kind == "str":
            return e.value
        raise EngineException(f"{what} must be a string literal, got {e!r}")

    def _const_int(self, e: Expr, what: str) -> int:
        if isinstance(e, Literal) and e.kind == "int":
            return e.value
        if isinstance(e, UnaryOp) and e.op == "-" \
                and isinstance(e.operand, Literal) and e.operand.kind == "int":
            return -e.operand.value
        raise EngineException(f"{what} must be an integer literal, got {e!r}")

    def _string_arg(self, e: Expr, fname: str) -> CompiledExpr:
        v = self.compile(e)
        if isinstance(v, HostStr):
            raise EngineException(
                f"{fname} over a deferred string (CONCAT/CAST result) is "
                "not supported on device — apply string functions to the "
                "columns before concatenating"
            )
        if not is_device(v) or v.type != "string":
            raise EngineException(f"{fname} expects a string argument, got {e!r}")
        return v

    def _aux_gather(
        self, key: str, kind: str, host_fn, arg: CompiledExpr, out_type: str
    ) -> CompiledExpr:
        """Register a dictionary table and compile to a device gather."""
        self.aux.register(key, kind, host_fn)

        def run(env, key=key, arg=arg):
            t = env.scopes["__aux"][key]
            ids = arg.fn(env)
            return t[jnp.clip(ids, 0, t.shape[0] - 1)]

        return CompiledExpr(out_type, run, deps=arg.deps)

    def _string_map(self, fname: str, e_arg: Expr, key: str, host_fn) -> Value:
        return self._aux_gather(
            f"map:{key}", "map", host_fn, self._string_arg(e_arg, fname), "string"
        )

    def _string_pred(self, fname: str, e_arg: Expr, key: str, host_fn) -> Value:
        return self._aux_gather(
            f"pred:{key}", "pred", host_fn, self._string_arg(e_arg, fname), "boolean"
        )

    def _string_scalar(self, fname: str, e_arg: Expr, key: str, host_fn) -> Value:
        return self._aux_gather(
            f"scalar:{key}", "scalar", host_fn, self._string_arg(e_arg, fname), "long"
        )

    def _like(self, e: LikeOp) -> Value:
        pattern = self._const_str(e.pattern, "LIKE/RLIKE pattern")
        if e.regex:
            rx = re.compile(pattern)
            key = f"RLIKE:{pattern}"
            fn = lambda s, rx=rx: rx.search(s) is not None  # noqa: E731
        else:
            rx = re.compile(like_to_regex(pattern), re.DOTALL)
            key = f"LIKE:{pattern}"
            fn = lambda s, rx=rx: rx.fullmatch(s) is not None  # noqa: E731
        pred = self._string_pred("LIKE", e.expr, key, fn)
        if not e.negated:
            return pred
        # NOT LIKE: null stays excluded (pred[null]=False either way is
        # SQL-correct for WHERE: NULL NOT LIKE p is NULL, not TRUE) — we
        # negate the table-level result but force null ids to False
        arg = self._string_arg(e.expr, "NOT LIKE")

        def run(env, pred=pred, arg=arg):
            ids = arg.fn(env)
            return jnp.logical_not(pred.fn(env)) & (ids != 0)

        return CompiledExpr("boolean", run, deps=pred.deps)

    def _cast(self, e: Cast) -> Value:
        target = e.target
        if target in ("STRING", "VARCHAR"):
            inner = self._as_device(e.expr)
            if inner.type == "string":
                return inner
            # stringification is a host-side finishing step
            return HostStr(parts=["", inner], deps=inner.deps)
        inner = self._as_device(e.expr)
        t = {
            "LONG": "long", "INT": "long", "INTEGER": "long", "BIGINT": "long",
            "DOUBLE": "double", "FLOAT": "double", "BOOLEAN": "boolean",
            "TIMESTAMP": "timestamp",
        }.get(target)
        if t is None:
            raise EngineException(f"unsupported CAST target {target}")
        return CompiledExpr(
            t, lambda env, inner=inner, t=t: _to_dtype(inner.fn(env), t), deps=inner.deps
        )

    # -- functions -------------------------------------------------------
    def _func(self, e: Func) -> Value:
        name = e.name

        if name in AGGREGATE_FNS:
            raise EngineException(
                f"aggregate {name} outside aggregation context"
            )

        if name == "IF":
            if len(e.args) != 3:
                raise EngineException("IF takes 3 arguments")
            cond = self._as_device(e.args[0])
            then_v = self.compile(e.args[1])
            else_v = self.compile(e.args[2])
            # IF(cond, <struct/map>, NULL): nullable struct
            if isinstance(then_v, StructValue) and isinstance(e.args[2], Literal) \
                    and e.args[2].kind == "null":
                return StructValue(then_v.fields, validity=cond)
            if not is_device(then_v) or not is_device(else_v):
                raise EngineException("IF branches must be device values")
            out_t = _promote(then_v.type, else_v.type) if then_v.type != else_v.type \
                else then_v.type

            def run(env, cond=cond, a=then_v, b=else_v, out_t=out_t):
                return jnp.where(
                    cond.fn(env), _to_dtype(a.fn(env), out_t), _to_dtype(b.fn(env), out_t)
                )

            return CompiledExpr(
                out_t, run, deps=cond.deps + then_v.deps + else_v.deps
            )

        if name == "COALESCE":
            args = [self._as_device(a) for a in e.args]
            return args[0]  # no value-level nulls on device

        if name in ("MAP",):
            # MAP('k1', v1, 'k2', v2, ...) with literal keys == struct
            if len(e.args) % 2 != 0:
                raise EngineException("MAP needs key/value pairs")
            fields: Dict[str, Value] = {}
            for i in range(0, len(e.args), 2):
                k = e.args[i]
                if not (isinstance(k, Literal) and k.kind == "str"):
                    raise EngineException("MAP keys must be string literals")
                fields[k.value] = self.compile(e.args[i + 1])
            return StructValue(fields)

        if name == "STRUCT":
            fields = {}
            for a in e.args:
                if isinstance(a, Col):
                    fields[a.parts[-1]] = self.compile(a)
                else:
                    raise EngineException(
                        "STRUCT arguments must be columns (use MAP for expressions)"
                    )
            return StructValue(fields)

        if name == "ARRAY":
            return ArrayValue([self.compile(a) for a in e.args])

        if name == "FILTERNULL":
            inner = self.compile(e.args[0])
            if not isinstance(inner, ArrayValue):
                raise EngineException("filterNull expects an Array")
            return inner

        if name == "CONCAT":
            parts: List[Union[str, CompiledExpr]] = []
            deps: Tuple[Tuple[str, str], ...] = ()
            for a in e.args:
                v = self.compile(a)
                if isinstance(v, HostStr):
                    if v.parts and isinstance(v.parts[0], str) \
                            and v.parts[0].startswith(WS_MARKER):
                        raise EngineException(
                            "CONCAT over a CONCAT_WS result is not supported"
                        )
                    parts.extend(v.parts)
                    deps += v.deps
                elif isinstance(v, CompiledExpr):
                    if isinstance(a, Literal) and a.kind == "str":
                        parts.append(a.value)
                    else:
                        parts.append(v)
                        deps += v.deps
                else:
                    raise EngineException("CONCAT of composite values unsupported")
            return HostStr(parts, deps)

        if name == "CURRENT_TIMESTAMP":
            return CompiledExpr(
                "timestamp",
                lambda env: jnp.broadcast_to(env.now_rel_ms, env.shape),
            )
        if name == "UNIX_TIMESTAMP":
            if e.args:
                ts = self._as_device(e.args[0])
                return CompiledExpr(
                    "tssec",
                    lambda env, ts=ts: ts.fn(env) // 1000,
                    deps=ts.deps,
                )
            return CompiledExpr(
                "tssec",
                lambda env: jnp.broadcast_to(env.now_rel_ms // 1000, env.shape),
            )
        if name == "TO_UNIX_TIMESTAMP":
            ts = self._as_device(e.args[0])
            if ts.type not in ("timestamp", "tssec"):
                raise EngineException("to_unix_timestamp expects a timestamp")
            if ts.type == "tssec":
                return ts
            return CompiledExpr(
                "tssec", lambda env, ts=ts: ts.fn(env) // 1000, deps=ts.deps
            )
        if name in ("STRINGTOTIMESTAMP", "TO_TIMESTAMP"):
            # reference: BuiltInFunctionsHandler.scala:15-17 registers
            # stringToTimestamp (ConcurrentDateFormat) as the one
            # built-in UDF. Here: per-distinct-string parse on the host
            # via two aux tables (epoch seconds + millis fraction),
            # composed into batch-relative ms on device. Unparseable or
            # NULL strings yield relative 0 (the missing-timestamp
            # encode convention) rather than SQL NULL — int32 columns
            # carry no null slot.
            if len(e.args) != 1:
                raise EngineException(
                    f"{name} takes exactly one string argument (custom "
                    "format patterns are not supported; timestamps parse "
                    "as ISO-8601 or epoch seconds/millis)"
                )
            v = self._string_arg(e.args[0], name)
            from ..core.batch import parse_timestamp_ms

            int_min = -(2 ** 31)

            def sec_of(s: str):
                # aux tables are int32: any epoch-second value outside
                # the range (e.g. an 11-digit id parsed as a huge epoch,
                # or post-2038 dates) counts as unparseable — the table
                # write itself would otherwise OverflowError per batch
                ms = parse_timestamp_ms(s)
                if ms is None:
                    return int_min
                sec = int(ms // 1000)
                return sec if int_min < sec < 2 ** 31 else int_min

            def msfrac_of(s: str):
                ms = parse_timestamp_ms(s)
                return 0 if ms is None else int(ms % 1000)

            self.aux.register("ts.sec", "scalar", sec_of)
            self.aux.register("ts.msfrac", "scalar", msfrac_of)

            def run(env, arg=v, int_min=int_min):
                tsec = env.scopes["__aux"]["ts.sec"]
                tms = env.scopes["__aux"]["ts.msfrac"]
                ids = arg.fn(env)
                idx = jnp.clip(ids, 0, tsec.shape[0] - 1)
                sec = tsec[idx]
                bad = (ids <= 0) | (sec == int_min)
                # saturate the batch-relative delta at ~±23 days before
                # the ms scaling (the ingest paths clip the same way) —
                # int32 would otherwise wrap and pass comparisons it
                # should fail
                delta_s = jnp.clip(sec - env.base_s, -2_000_000, 2_000_000)
                rel = delta_s * 1000 + tms[idx]
                return jnp.where(bad, 0, rel).astype(jnp.int32)

            return CompiledExpr("timestamp", run, deps=v.deps)

        if name == "DATE_TRUNC":
            unit_lit = e.args[0]
            if not isinstance(unit_lit, Literal):
                raise EngineException("DATE_TRUNC unit must be a literal")
            unit = str(unit_lit.value).lower()
            ts = self._as_device(e.args[1])
            secs = {"second": 1, "minute": 60, "hour": 3600, "day": 86400}.get(unit)
            if secs is None:
                raise EngineException(f"unsupported DATE_TRUNC unit {unit}")
            abs_s = self._abs_seconds(ts)

            def run(env, abs_s=abs_s, secs=secs):
                total_s = abs_s(env)
                trunc_s = total_s - total_s % secs
                return ((trunc_s - env.base_s) * 1000).astype(jnp.int32)

            return CompiledExpr("timestamp", run, deps=ts.deps)
        if name in ("HOUR", "MINUTE", "SECOND"):
            ts = self._as_device(e.args[0])
            div = {"HOUR": 3600, "MINUTE": 60, "SECOND": 1}[name]
            mod = {"HOUR": 24, "MINUTE": 60, "SECOND": 60}[name]
            abs_s = self._abs_seconds(ts)

            def run(env, abs_s=abs_s, div=div, mod=mod):
                total_s = abs_s(env)
                return ((total_s // div) % mod).astype(jnp.int32)

            return CompiledExpr("long", run, deps=ts.deps)

        if name in ("GREATEST", "LEAST"):
            if len(e.args) < 2:
                raise EngineException(f"{name} needs at least two arguments")
            vals = [self._as_device(a) for a in e.args]
            for v in vals:
                if v.type not in ("long", "double", "timestamp", "tssec"):
                    raise EngineException(
                        f"{name} expects numeric arguments, got {v.type}"
                    )
            out_t = "double" if any(v.type == "double" for v in vals) else "long"
            jf = jnp.maximum if name == "GREATEST" else jnp.minimum
            dt = _DTYPES[out_t]

            def run(env, vals=vals, jf=jf, dt=dt):
                acc = vals[0].fn(env).astype(dt)
                for v in vals[1:]:
                    acc = jf(acc, v.fn(env).astype(dt))
                return acc

            return CompiledExpr(
                out_t, run,
                deps=tuple(d for v in vals for d in v.deps),
            )
        if name in ("POW", "POWER"):
            if len(e.args) != 2:
                raise EngineException(f"{name} takes exactly two arguments")
            base_v = self._as_device(e.args[0])
            exp_v = self._as_device(e.args[1])
            _promote(base_v.type, exp_v.type)  # rejects strings/booleans mix
            if "string" in (base_v.type, exp_v.type):
                raise EngineException("POW expects numeric arguments")
            return CompiledExpr(
                "double",
                lambda env, b=base_v, x=exp_v: jnp.power(
                    b.fn(env).astype(jnp.float32),
                    x.fn(env).astype(jnp.float32),
                ),
                deps=base_v.deps + exp_v.deps,
            )
        if name == "MOD":
            if len(e.args) != 2:
                raise EngineException("MOD takes exactly two arguments")
            # delegate to the '%' operator path: same promotion, same
            # string guard, same truncated-modulo semantics
            return self._arith(
                "%", self._as_device(e.args[0]), self._as_device(e.args[1])
            )
        if name == "SIGN":
            v = self._as_device(e.args[0])
            if v.type not in ("long", "double"):
                raise EngineException(
                    f"SIGN expects a numeric argument, got {v.type}"
                )
            return CompiledExpr(
                "double",
                lambda env, v=v: jnp.sign(v.fn(env)).astype(jnp.float32),
                deps=v.deps,
            )
        if name in ("ABS", "FLOOR", "CEIL", "ROUND", "SQRT", "EXP", "LOG",
                    "LOG10", "LOG2", "CBRT"):
            v = self._as_device(e.args[0])
            jf = {
                "ABS": jnp.abs, "FLOOR": jnp.floor, "CEIL": jnp.ceil,
                "ROUND": jnp.round, "SQRT": jnp.sqrt, "EXP": jnp.exp,
                "LOG": jnp.log, "LOG10": jnp.log10, "LOG2": jnp.log2,
                "CBRT": jnp.cbrt,
            }[name]
            always_double = ("SQRT", "EXP", "LOG", "LOG10", "LOG2", "CBRT")
            out_t = "double" if name in always_double else v.type

            def run(env, v=v, jf=jf, out_t=out_t):
                x = v.fn(env)
                if jf is not jnp.abs:
                    x = x.astype(jnp.float32)
                return _to_dtype(jf(x), out_t)

            return CompiledExpr(out_t, run, deps=v.deps)

        v = self._string_func(e)
        if v is not None:
            return v
        v = self._date_func(e)
        if v is not None:
            return v

        # UDF tiers
        lowered = name.lower()
        if lowered in self.udfs:
            obj = self.udfs[lowered]
            self.called_udfs.append(obj)
            return obj.compile_call(self, e)

        raise EngineException(f"unknown function {name}")

    # -- string function library (dictionary tables) ----------------------
    _SIMPLE_MAPS = {
        "UPPER": str.upper, "UCASE": str.upper,
        "LOWER": str.lower, "LCASE": str.lower,
        "TRIM": str.strip, "LTRIM": str.lstrip, "RTRIM": str.rstrip,
        "REVERSE": lambda s: s[::-1],
        "INITCAP": lambda s: " ".join(
            w[:1].upper() + w[1:].lower() for w in s.split(" ")
        ),
    }

    def _string_func(self, e: Func) -> Optional[Value]:
        """Spark string functions lowered to dictionary-table gathers.

        Semantics match Spark SQL (the engine behind the reference's
        ``spark.sql`` calls): 1-based positions, clamped SUBSTRING,
        NULL in -> NULL/false/0 out. Constant arguments are required
        wherever the table is keyed on them (patterns, positions).
        """
        name, args = e.name, e.args
        if name in self._SIMPLE_MAPS:
            return self._string_map(name, args[0], name, self._SIMPLE_MAPS[name])
        if name in ("LENGTH", "CHAR_LENGTH", "CHARACTER_LENGTH", "LEN"):
            return self._string_scalar("LENGTH", args[0], "LENGTH", len)
        if name in ("SUBSTRING", "SUBSTR"):
            pos = self._const_int(args[1], "SUBSTRING position")
            ln = (
                self._const_int(args[2], "SUBSTRING length")
                if len(args) > 2 else None
            )
            return self._string_map(
                name, args[0], f"SUBSTRING:{pos}:{ln}",
                lambda s, pos=pos, ln=ln: spark_substring(s, pos, ln),
            )
        if name == "REPLACE":
            search = self._const_str(args[1], "REPLACE search")
            repl = self._const_str(args[2], "REPLACE replacement") \
                if len(args) > 2 else ""
            return self._string_map(
                name, args[0], f"REPLACE:{search!r}:{repl!r}",
                lambda s, a=search, b=repl: s.replace(a, b),
            )
        if name == "TRANSLATE":
            frm = self._const_str(args[1], "TRANSLATE from")
            to = self._const_str(args[2], "TRANSLATE to")
            tbl = str.maketrans(frm[: len(to)], to[: len(frm)], frm[len(to):])
            return self._string_map(
                name, args[0], f"TRANSLATE:{frm!r}:{to!r}",
                lambda s, tbl=tbl: s.translate(tbl),
            )
        if name == "INSTR":
            sub = self._const_str(args[1], "INSTR substring")
            return self._string_scalar(
                name, args[0], f"INSTR:{sub!r}",
                lambda s, sub=sub: spark_instr(s, sub),
            )
        if name == "LOCATE":
            # LOCATE(substr, str[, pos]) — note the flipped arg order.
            # Spark returns 0 (not a 1-based hit) whenever pos < 1.
            sub = self._const_str(args[0], "LOCATE substring")
            start = self._const_int(args[2], "LOCATE pos") if len(args) > 2 else 1
            return self._string_scalar(
                name, args[1], f"LOCATE:{sub!r}:{start}",
                lambda s, sub=sub, p=start: (
                    0 if p < 1 else s.find(sub, p - 1) + 1
                ),
            )
        if name == "CONTAINS":
            sub = self._const_str(args[1], "CONTAINS substring")
            return self._string_pred(
                name, args[0], f"CONTAINS:{sub!r}", lambda s, sub=sub: sub in s
            )
        if name in ("STARTSWITH", "STARTS_WITH"):
            sub = self._const_str(args[1], "STARTSWITH prefix")
            return self._string_pred(
                name, args[0], f"STARTSWITH:{sub!r}",
                lambda s, sub=sub: s.startswith(sub),
            )
        if name in ("ENDSWITH", "ENDS_WITH"):
            sub = self._const_str(args[1], "ENDSWITH suffix")
            return self._string_pred(
                name, args[0], f"ENDSWITH:{sub!r}",
                lambda s, sub=sub: s.endswith(sub),
            )
        if name == "REGEXP_EXTRACT":
            pat = self._const_str(args[1], "REGEXP_EXTRACT pattern")
            idx = self._const_int(args[2], "REGEXP_EXTRACT group") \
                if len(args) > 2 else 1
            rx = re.compile(pat)

            def rex(s, rx=rx, idx=idx):
                m = rx.search(s)
                if m is None:
                    return ""  # Spark returns empty string on no match
                try:
                    return m.group(idx) or ""
                except (IndexError, re.error):
                    return ""

            return self._string_map(
                name, args[0], f"REGEXP_EXTRACT:{pat!r}:{idx}", rex
            )
        if name == "REGEXP_REPLACE":
            pat = self._const_str(args[1], "REGEXP_REPLACE pattern")
            repl = self._const_str(args[2], "REGEXP_REPLACE replacement")
            rx = re.compile(pat)
            # Spark uses Java's $N group refs; Python uses \g<N>. A Java
            # \$ escape means a literal dollar — protect it before the
            # group rewrite, and escape Python's own backslash refs.
            # Java binds the LONGEST digit run that is still a valid
            # group number ($10 with one group = group 1 + literal '0')
            # and errors when even the first digit names no group.
            def _java_repl_to_py(r: str, ngroups: int) -> str:
                out = []
                i = 0
                while i < len(r):
                    c = r[i]
                    if c == "\\":
                        if i + 1 >= len(r):
                            raise EngineException(
                                "REGEXP_REPLACE replacement ends with a "
                                "lone backslash (character to be escaped "
                                "is missing)"
                            )
                        nxt = r[i + 1]
                        # Java-escaped literal ($, \) — emit literally,
                        # re-escaping \ for Python's repl grammar
                        out.append("\\\\" if nxt == "\\" else nxt)
                        i += 2
                        continue
                    # Java's replacement grammar treats only ASCII 0-9
                    # as group digits (str.isdigit would admit Unicode
                    # digits and crash or mis-bind)
                    ascii_digit = lambda ch: "0" <= ch <= "9"
                    if c == "$":
                        if i + 1 >= len(r) or not ascii_digit(r[i + 1]):
                            raise EngineException(
                                "REGEXP_REPLACE replacement has an "
                                "illegal group reference: '$' must be "
                                "followed by a group number (escape a "
                                "literal dollar as \\$)"
                            )
                        j = i + 1
                        while (
                            j + 1 < len(r) and ascii_digit(r[j + 1])
                            and int(r[i + 1:j + 2]) <= ngroups
                        ):
                            j += 1
                        group = int(r[i + 1:j + 1])
                        if group > ngroups:
                            raise EngineException(
                                f"REGEXP_REPLACE replacement refers to "
                                f"group ${group} but the pattern has only "
                                f"{ngroups} group(s)"
                            )
                        out.append(f"\\g<{group}>")
                        i = j + 1
                        continue
                    out.append("\\\\" if c == "\\" else c)
                    i += 1
                return "".join(out)

            py_repl = _java_repl_to_py(repl, rx.groups)
            return self._string_map(
                name, args[0], f"REGEXP_REPLACE:{pat!r}:{repl!r}",
                lambda s, rx=rx, r=py_repl: rx.sub(r, s),
            )
        if name == "REPEAT":
            times = self._const_int(args[1], "REPEAT count")
            return self._string_map(
                name, args[0], f"REPEAT:{times}",
                lambda s, t=times: s * max(t, 0),
            )
        if name == "ASCII":
            # scalar tables are int32 and carry no NULL slot: NULL in ->
            # 0 out, the engine-wide scalar-table convention (LENGTH
            # shares it); Spark returns NULL here
            return self._string_scalar(
                "ASCII", args[0], "ASCII", lambda s: ord(s[0]) if s else 0
            )
        if name in ("LPAD", "RPAD"):
            ln = self._const_int(args[1], f"{name} length")
            pad = self._const_str(args[2], f"{name} pad") if len(args) > 2 else " "

            def dopad(s, ln=ln, pad=pad, left=(name == "LPAD")):
                if len(s) >= ln:
                    return s[:ln]
                fill = (pad * ln)[: ln - len(s)]
                return fill + s if left else s + fill

            return self._string_map(name, args[0], f"{name}:{ln}:{pad!r}", dopad)
        if name == "SPLIT_PART":
            delim = self._const_str(args[1], "SPLIT_PART delimiter")
            idx = self._const_int(args[2], "SPLIT_PART index")
            return self._string_map(
                name, args[0], f"SPLIT_PART:{delim!r}:{idx}",
                lambda s, d=delim, i=idx: spark_split_at(s, re.escape(d), i),
            )
        if name == "ELEMENT_AT" and args and isinstance(args[0], Func) \
                and args[0].name == "SPLIT":
            # element_at(split(s, regex), i): the composed function is one
            # dictionary table — SPLIT alone (an array) has no device form
            inner = args[0]
            delim = self._const_str(inner.args[1], "SPLIT delimiter")
            idx = self._const_int(args[1], "ELEMENT_AT index")
            return self._string_map(
                "SPLIT", inner.args[0], f"SPLIT_AT:{delim!r}:{idx}",
                lambda s, d=delim, i=idx: spark_split_at(s, d, i),
            )
        if name == "SPLIT":
            raise EngineException(
                "SPLIT returns an array; use ELEMENT_AT(SPLIT(s, d), i) or "
                "SPLIT_PART(s, d, i) to take one element"
            )
        if name == "CONCAT_WS":
            # Spark concat_ws SKIPS null arguments (and their
            # separators) instead of nulling the result like CONCAT, so
            # the deferred template keeps per-ARGUMENT structure: a
            # marker literal carries the separator and every following
            # part is one argument. The materializer joins the non-null
            # renders; nested computed-string arguments would lose their
            # grouping in this representation, so they are rejected.
            sep = self._const_str(args[0], "CONCAT_WS separator")
            parts: List[Union[str, CompiledExpr]] = [WS_MARKER + sep]
            deps: Tuple[Tuple[str, str], ...] = ()
            for a in args[1:]:
                v = self.compile(a)
                if isinstance(v, HostStr):
                    raise EngineException(
                        "CONCAT_WS over computed-string arguments is not "
                        "supported; CONCAT the pieces first or pass "
                        "plain columns/literals"
                    )
                if isinstance(v, CompiledExpr):
                    if isinstance(a, Literal) and a.kind == "str":
                        parts.append(a.value)
                    else:
                        parts.append(v)
                        deps += v.deps
                else:
                    raise EngineException("CONCAT_WS of composite values unsupported")
            return HostStr(parts, deps)
        return None

    # -- date/time function library ---------------------------------------
    def _abs_seconds(self, ts: CompiledExpr):
        """env -> absolute epoch seconds; honors the two time encodings
        (timestamp = relative ms, tssec = relative s)."""
        if ts.type == "tssec":
            return lambda env, ts=ts: env.base_s + ts.fn(env)
        if ts.type != "timestamp":
            raise EngineException(
                f"expected a timestamp-typed expression, got {ts.type}"
            )
        return lambda env, ts=ts: env.base_s + ts.fn(env) // 1000

    def _civil(self, ts: CompiledExpr):
        """(year, month, day) from a timestamp expr, UTC proleptic
        Gregorian (Howard Hinnant's civil_from_days, pure int32 math —
        no data-dependent control flow, fuses into the surrounding XLA
        program)."""
        abs_s = self._abs_seconds(ts)

        def parts(env, abs_s=abs_s):
            total_s = abs_s(env)
            days = jnp.floor_divide(total_s, 86400)
            z = days + 719468
            era = jnp.floor_divide(z, 146097)
            doe = z - era * 146097
            yoe = jnp.floor_divide(
                doe - doe // 1460 + doe // 36524 - doe // 146096, 365
            )
            y = yoe + era * 400
            doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
            mp = jnp.floor_divide(5 * doy + 2, 153)
            day = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
            month = mp + jnp.where(mp < 10, 3, -9)
            year = y + (month <= 2)
            return year.astype(jnp.int32), month.astype(jnp.int32), day.astype(jnp.int32)

        return parts

    def _date_func(self, e: Func) -> Optional[Value]:
        name, args = e.name, e.args
        if name in ("YEAR", "MONTH", "DAY", "DAYOFMONTH"):
            ts = self._as_device(args[0])
            if ts.type not in ("timestamp", "tssec"):
                raise EngineException(f"{name} expects a timestamp")
            parts = self._civil(ts)
            pick = {"YEAR": 0, "MONTH": 1, "DAY": 2, "DAYOFMONTH": 2}[name]
            return CompiledExpr(
                "long", lambda env, parts=parts, pick=pick: parts(env)[pick],
                deps=ts.deps,
            )
        if name == "DAYOFWEEK":
            # Spark: 1 = Sunday .. 7 = Saturday; epoch day 0 is a Thursday
            ts = self._as_device(args[0])
            abs_s = self._abs_seconds(ts)

            def dow(env, abs_s=abs_s):
                days = jnp.floor_divide(abs_s(env), 86400)
                return (jnp.mod(days + 4, 7) + 1).astype(jnp.int32)

            return CompiledExpr("long", dow, deps=ts.deps)
        if name == "DATEDIFF":
            a = self._as_device(args[0])
            b = self._as_device(args[1])
            abs_a, abs_b = self._abs_seconds(a), self._abs_seconds(b)

            def diff(env, abs_a=abs_a, abs_b=abs_b):
                da = jnp.floor_divide(abs_a(env), 86400)
                db = jnp.floor_divide(abs_b(env), 86400)
                return (da - db).astype(jnp.int32)

            return CompiledExpr("long", diff, deps=a.deps + b.deps)
        if name == "TO_DATE":
            ts = self._as_device(args[0])
            abs_s = self._abs_seconds(ts)

            def trunc_day(env, abs_s=abs_s):
                total_s = abs_s(env)
                t = total_s - jnp.mod(total_s, 86400)
                return ((t - env.base_s) * 1000).astype(jnp.int32)

            return CompiledExpr("timestamp", trunc_day, deps=ts.deps)
        if name == "FROM_UNIXTIME":
            # Spark returns a formatted string; here it stays a timestamp
            # (the host renders it at the sink boundary) — comparisons and
            # windowing on the result are exact either way
            v = self._as_device(args[0])
            if v.type == "tssec":  # already batch-relative seconds
                return CompiledExpr(
                    "timestamp",
                    lambda env, v=v: (v.fn(env) * 1000).astype(jnp.int32),
                    deps=v.deps,
                )

            def from_unix(env, v=v):  # absolute epoch seconds
                secs = v.fn(env).astype(jnp.int32)
                return ((secs - env.base_s) * 1000).astype(jnp.int32)

            return CompiledExpr("timestamp", from_unix, deps=v.deps)
        return None
