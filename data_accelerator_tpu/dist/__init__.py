from .mesh import (
    DATA_AXIS,
    make_mesh,
    replicated,
    ring_sharding,
    row_sharding,
    step_shardings,
)

__all__ = [
    "DATA_AXIS",
    "make_mesh",
    "replicated",
    "ring_sharding",
    "row_sharding",
    "step_shardings",
]
