from .ingest import (
    HostIngestPlan,
    assigned_partitions,
    global_batch_from_local,
    local_row_range,
)
from .mesh import (
    DATA_AXIS,
    make_mesh,
    replicated,
    ring_sharding,
    row_sharding,
    step_shardings,
)

__all__ = [
    "DATA_AXIS",
    "HostIngestPlan",
    "assigned_partitions",
    "global_batch_from_local",
    "local_row_range",
    "make_mesh",
    "replicated",
    "ring_sharding",
    "row_sharding",
    "step_shardings",
]
