"""Device mesh + sharding layout for the flow step.

The reference scales by partitioning RDDs across Spark executors and
letting Spark's shuffle service move rows for GROUP BY/JOIN
(CommonProcessorFactory.scala:405-421; shuffle implicit in the
``spark.sql`` calls at :257,271). TPU-native equivalent: one
``jax.sharding.Mesh`` over the slice with a single ``data`` axis —

- micro-batch rows shard over ``data`` (the executor-partition analog);
- window ring buffers ``[slots, capacity]`` shard their *capacity* dim
  over ``data`` so each chip retains only its shard of window history
  (the sequence/context-parallel layout: long windows never materialize
  on one chip);
- reference/state tables replicate (they are small and join-broadcast,
  like Spark broadcast joins);
- aggregation outputs replicate — XLA GSPMD inserts the
  all-gather/reduce-scatter collectives over ICI that replace Spark's
  host shuffle.

The whole step stays ONE jitted program: GSPMD partitions it from these
in/out shardings, so sorts (group-by) lower to distributed sorts and
segment reductions lower to psum-style collectives without any
host-level communication code.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    axis_name: str = DATA_AXIS,
) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (all by
    default). Multi-host: pass ``jax.devices()`` of the whole slice."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows of a [capacity] column shard over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def ring_sharding(mesh: Mesh) -> NamedSharding:
    """Window ring cols are [slots, capacity]: shard capacity."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def step_shardings(mesh: Mesh):
    """(in_shardings, out_shardings) pytree prefixes for
    ``FlowProcessor``'s step signature:

    in:  (raw tables per source — rows shard, rings per windowed table —
          capacity dim shards, state, refdata, base_s, now_rel_ms,
          counter, delta_ms, aux string-op dictionary tables —
          replicated: every chip gathers locally, like a broadcast join
          side)
    out: (datasets, new_rings, new_state, counts_vec)

    The prefixes apply leaf-wise over the dict pytrees, so N sources and
    N rings inherit the same layout without per-flow sharding code.
    """
    row = row_sharding(mesh)
    ring = ring_sharding(mesh)
    rep = replicated(mesh)
    in_shardings = (row, ring, rep, rep, rep, rep, rep, rep, rep)
    out_shardings = (rep, ring, rep, rep)
    return in_shardings, out_shardings
