"""Device mesh + sharding layout for the flow step.

The reference scales by partitioning RDDs across Spark executors and
letting Spark's shuffle service move rows for GROUP BY/JOIN
(CommonProcessorFactory.scala:405-421; shuffle implicit in the
``spark.sql`` calls at :257,271). TPU-native equivalent: one
``jax.sharding.Mesh`` over the slice with a single ``data`` axis —

- micro-batch rows shard over ``data`` (the executor-partition analog);
- window ring buffers ``[slots, capacity]`` shard their *capacity* dim
  over ``data`` so each chip retains only its shard of window history
  (the sequence/context-parallel layout: long windows never materialize
  on one chip);
- reference/state tables replicate (they are small and join-broadcast,
  like Spark broadcast joins);
- aggregation outputs replicate — XLA GSPMD inserts the
  all-gather/reduce-scatter collectives over ICI that replace Spark's
  host shuffle.

The whole step stays ONE jitted program: GSPMD partitions it from these
in/out shardings, so sorts (group-by) lower to distributed sorts and
segment reductions lower to psum-style collectives without any
host-level communication code.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"


def make_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    axis_name: str = DATA_AXIS,
) -> Mesh:
    """1-D mesh over the first ``n_devices`` local devices (all by
    default). Multi-host: pass ``jax.devices()`` of the whole slice."""
    devs = list(devices) if devices is not None else jax.devices()
    if n_devices is not None:
        if n_devices > len(devs):
            raise ValueError(
                f"requested {n_devices} devices, only {len(devs)} available"
            )
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis_name,))


def row_sharding(mesh: Mesh) -> NamedSharding:
    """Rows of a [capacity] column shard over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def ring_sharding(mesh: Mesh) -> NamedSharding:
    """Window ring cols are [slots, capacity]: shard capacity."""
    return NamedSharding(mesh, P(None, DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def step_shardings(mesh: Mesh):
    """(in_shardings, out_shardings) pytree prefixes for
    ``FlowProcessor``'s step signature:

    in:  (raw tables per source — rows shard, rings per windowed table —
          capacity dim shards, state, refdata, base_s, now_rel_ms,
          counter, delta_ms, aux string-op dictionary tables —
          replicated: every chip gathers locally, like a broadcast join
          side)
    out: (datasets, new_rings, new_state, counts_vec)

    The prefixes apply leaf-wise over the dict pytrees, so N sources and
    N rings inherit the same layout without per-flow sharding code.
    """
    row = row_sharding(mesh)
    ring = ring_sharding(mesh)
    rep = replicated(mesh)
    in_shardings = (row, ring, rep, rep, rep, rep, rep, rep, rep)
    out_shardings = (rep, ring, rep, rep)
    return in_shardings, out_shardings


# ---------------------------------------------------------------------------
# Observed collective communication: what the SPMD partitioner actually
# put on the ICI.
#
# GSPMD inserts the collectives during compilation (the StableHLO the
# tracer produces is still logical), so the ground truth for "how many
# bytes does this program move over the interconnect per batch" is the
# compiled module's HLO text. `collective_summary` parses it into a
# typed per-op-kind byte census. Two consumers, one convention:
#
# - the runtime (`FlowProcessor` under a mesh) summarizes its own
#   compiled step and exports the census per batch as the
#   `Mesh_ICI_Bytes` / `Mesh_Reshard_Count` registry series — the real
#   observation the DX51x conformance ratios judge;
# - the DX7xx mesh analyzer (`analysis/meshcheck.py`) summarizes its
#   per-stage lowerings and asserts the closed-form model equals the
#   extraction exactly.
#
# Byte convention: `result_bytes` per collective = the full logical
# size of the op's result (chip-count-independent; the exactness
# contract's unit). Wire bytes apply the ring closed forms
# (`analysis/costmodel.py collective_wire_bytes`) per op kind.
# ---------------------------------------------------------------------------

# compiled-HLO scalar type -> bytes (everything this engine lowers is
# 32-bit except bool; wider types listed for robustness)
_HLO_DTYPE_BYTES: Dict[str, int] = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8, "f64": 8,
}

_COLLECTIVE_RE = re.compile(
    r"=\s*((?:\()?[a-z0-9\[\],{}\s]*?)\s*"
    r"(all-reduce|all-gather|all-to-all|collective-permute|reduce-scatter)"
    r"(?:-start|-done)?\("
)
_SHAPE_RE = re.compile(r"([a-z]+[0-9]*)\[([0-9,]*)\]")


@dataclass
class MeshCollectives:
    """Census of the collective ops in one compiled SPMD program."""

    # op kind -> (instruction count, total result bytes)
    ops: Dict[str, Tuple[int, int]] = field(default_factory=dict)

    @property
    def op_count(self) -> int:
        return sum(c for c, _b in self.ops.values())

    @property
    def result_bytes(self) -> int:
        return sum(b for _c, b in self.ops.values())

    def wire_bytes(self, chips: int) -> float:
        """Total slice-wide ICI bytes per execution under the ring
        closed forms (the Mesh_ICI_Bytes unit)."""
        from ..analysis.costmodel import collective_wire_bytes

        return sum(
            collective_wire_bytes(op, b, chips)
            for op, (_c, b) in self.ops.items()
        )

    def to_dict(self) -> dict:
        return {
            op: {"count": c, "resultBytes": b}
            for op, (c, b) in sorted(self.ops.items())
        }


def collective_summary(compiled_hlo_text: str) -> MeshCollectives:
    """Parse a compiled module's HLO text into a collective census.

    Counts every all-reduce / all-gather / all-to-all /
    collective-permute / reduce-scatter instruction (async start/done
    pairs count once, on the start) and sums each instruction's result
    shape bytes."""
    ops: Dict[str, Tuple[int, int]] = {}
    for m in _COLLECTIVE_RE.finditer(compiled_hlo_text):
        shapes, op = m.group(1), m.group(2)
        # async form: -done repeats the -start result; count the start
        if m.group(0).rstrip("(").endswith("-done"):
            continue
        total = 0
        for sm in _SHAPE_RE.finditer(shapes):
            dt, dims = sm.group(1), sm.group(2)
            n_el = 1
            for d in dims.split(","):
                if d:
                    n_el *= int(d)
            total += n_el * _HLO_DTYPE_BYTES.get(dt, 4)
        c, b = ops.get(op, (0, 0))
        ops[op] = (c + 1, b + total)
    return MeshCollectives(ops)


def summarize_compiled(compiled) -> MeshCollectives:
    """Census of a ``jax`` compiled executable (``lowered.compile()``
    result)."""
    return collective_summary(compiled.as_text())
