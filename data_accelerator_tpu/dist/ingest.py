"""Multi-host ingest: per-host feeds assembled into one sharded batch.

reference role: EventHub/Kafka partitions are consumed by whichever
executor holds them and rows live where they land; Spark's shuffle
repairs placement later (SURVEY §2.3 P1/C2). TPU-native shape: each
host process ingests its own slice of the stream over DCN (its
SocketSource port / its Kafka partition set), encodes rows into the
row-range its local devices own, and the global device array is
assembled WITHOUT any cross-host data movement —
``jax.make_array_from_process_local_data`` just stamps the local shards
as one global array. Cross-chip movement then happens only inside the
compiled step, over ICI, where XLA schedules it.

Partition assignment mirrors the reference's EventProcessorHost lease
model (partitions balanced across consumers): partition p belongs to
host ``p % process_count``.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh

from ..compile.planner import TableData
from .mesh import row_sharding


def assigned_partitions(
    n_partitions: int,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> List[int]:
    """Stream partitions this host consumes (lease-balance analog)."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if process_count is None else process_count
    return [p for p in range(n_partitions) if p % pc == pi]


def local_row_range(
    mesh: Mesh, global_rows: int, process_index: Optional[int] = None
) -> range:
    """The [start, stop) row range of a globally row-sharded array that
    this host's local devices own. Hosts encode ONLY these rows."""
    sharding = row_sharding(mesh)
    pi = jax.process_index() if process_index is None else process_index
    lo, hi = None, None
    for device, idx in sharding.devices_indices_map((global_rows,)).items():
        if device.process_index != pi:
            continue
        sl = idx[0]
        start = sl.start or 0
        stop = sl.stop if sl.stop is not None else global_rows
        lo = start if lo is None else min(lo, start)
        hi = stop if hi is None else max(hi, stop)
    if lo is None:
        return range(0)
    return range(lo, hi)


def global_batch_from_local(
    mesh: Mesh,
    local_cols: Dict[str, np.ndarray],
    local_valid: np.ndarray,
    global_rows: int,
) -> TableData:
    """Assemble the globally row-sharded device batch from this host's
    locally-ingested rows (no cross-host transfer: every host calls this
    with its own shard; jax stitches the metadata)."""
    sharding = row_sharding(mesh)

    def put(arr: np.ndarray) -> jax.Array:
        shape = (global_rows,) + arr.shape[1:]
        return jax.make_array_from_process_local_data(sharding, arr, shape)

    cols = {c: put(v) for c, v in local_cols.items()}
    return TableData(cols, put(local_valid))


class HostIngestPlan:
    """One host's slice of the ingest work for a flow.

    Carries what the control plane computes per TPU host at job-config
    time: which stream partitions to consume, how many of the global
    batch rows to encode, and the per-host rate share of the flow's
    maxRate (EventHubStreamingFactory.scala:43's rate limiter, split
    across hosts).
    """

    def __init__(
        self,
        mesh: Mesh,
        global_capacity: int,
        n_partitions: int,
        max_rate: float,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
    ):
        self.mesh = mesh
        self.global_capacity = global_capacity
        pc = jax.process_count() if process_count is None else process_count
        self.partitions = assigned_partitions(
            n_partitions, process_index, process_count
        )
        self.rows = local_row_range(mesh, global_capacity, process_index)
        self.local_capacity = len(self.rows)
        self.max_rate = max_rate / max(1, pc)

    def make_global(
        self, local_cols: Dict[str, np.ndarray], local_valid: np.ndarray
    ) -> TableData:
        if len(local_valid) != self.local_capacity:
            raise ValueError(
                f"host shard must be exactly {self.local_capacity} rows, "
                f"got {len(local_valid)}"
            )
        return global_batch_from_local(
            self.mesh, local_cols, local_valid, self.global_capacity
        )
