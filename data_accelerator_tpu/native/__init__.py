from .decoder import (
    KAFKA_CODEC_NAMES,
    NativeDecoder,
    PackedBufferPool,
    native_available,
    native_crc32c,
)

__all__ = [
    "KAFKA_CODEC_NAMES",
    "NativeDecoder",
    "PackedBufferPool",
    "native_available",
    "native_crc32c",
]
