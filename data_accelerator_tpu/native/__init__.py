from .decoder import NativeDecoder, native_available

__all__ = ["NativeDecoder", "native_available"]
