"""ctypes binding for the native JSON->columnar ingest decoder.

The C++ library (``native/decoder.cpp``) replaces the role Spark's
executor-side ``from_json`` plays in the reference
(CommonProcessorFactory.scala:90-103): every event's JSON parse happens
in native code straight into numpy buffers. The shared library builds
lazily with g++ on first use and is cached next to the source.

Three decode surfaces:

- ``decode``: newline-JSON -> per-column numpy arrays (the row layout;
  the mesh path and golden-parity tests use it);
- ``decode_packed``: newline-JSON straight into a persistent
  [n_cols+1, capacity] int32 matrix — the exact single-transfer H2D
  layout ``runtime/processor.py pack_raw`` builds, so the hot path
  performs zero per-batch column allocations and no pack copy. The
  matrices come from a :class:`PackedBufferPool` (64-byte-aligned, so
  the CPU backend's ``jnp.asarray`` transfer is zero-copy) and are
  double-buffered against the pipelined in-flight window by the
  processor (a slot is only reused after its batch lands or abandons);
- ``decode_kafka_packed``: native Kafka v2 record-batch walking
  (varint framing, CRC-32C verification, control-batch skip,
  typed rejection of compressed batches) feeding each record value to
  the same JSON column decoder in the same call — the production wire
  format never touches a Python object per record.

The decoder owns a string dictionary (string -> int32) kept consistent
with the Python ``StringDictionary`` by push-before/pull-after syncs
around each decode call; both sides assign ids sequentially so ids
stay stable across the boundary.

Shard count: ``datax.job.process.ingest.decoderthreads`` (plumbed via
the ``threads`` ctor arg) > ``DATAX_DECODER_THREADS`` env override >
the engine default (cap 4 — ingest shares the host with the engine
loop and sinks).
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.schema import ColType, Schema, StringDictionary

logger = logging.getLogger(__name__)

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "decoder.cpp",
)
_LIB_PATH = os.path.join(os.path.dirname(_SRC), "libdxdecoder.so")
_build_lock = threading.Lock()
_lib = None
_lib_error: Optional[str] = None

_CTYPE_NAME = {
    ColType.LONG: "long",
    ColType.DOUBLE: "double",
    ColType.BOOLEAN: "boolean",
    ColType.STRING: "string",
    ColType.TIMESTAMP: "timestamp",
}

_NP_DTYPE = {
    ColType.LONG: np.int32,
    ColType.DOUBLE: np.float32,
    ColType.BOOLEAN: np.uint8,
    ColType.STRING: np.int32,
    ColType.TIMESTAMP: np.int64,
}

# Kafka v2 attribute codec ids (message format v2)
KAFKA_CODEC_NAMES = {1: "gzip", 2: "snappy", 3: "lz4", 4: "zstd"}

# dx_decode_kafka_packed stats vector layout (decoder.cpp KStat)
_KSTAT_RECORDS = 0
_KSTAT_MALFORMED = 1
_KSTAT_CORRUPT = 2
_KSTAT_CONTROL = 3
_KSTAT_OVERFLOW = 4
_KSTAT_CODEC = 5


def _build_library() -> Optional[str]:
    if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(
        _SRC
    ):
        return _LIB_PATH
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        "-o", _LIB_PATH, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("native decoder build failed: %s", e)
        return None
    return _LIB_PATH


def _load():
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_error is not None:
            return _lib
        path = _build_library()
        if path is None:
            _lib_error = "build failed"
            return None
        lib = ctypes.CDLL(path)
        lib.dx_decoder_create.restype = ctypes.c_void_p
        lib.dx_decoder_create.argtypes = [ctypes.c_char_p]
        lib.dx_decoder_destroy.argtypes = [ctypes.c_void_p]
        lib.dx_num_columns.restype = ctypes.c_int64
        lib.dx_num_columns.argtypes = [ctypes.c_void_p]
        lib.dx_decode.restype = ctypes.c_int64
        lib.dx_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.dx_decode_mt.restype = ctypes.c_int64
        lib.dx_decode_mt.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ]
        lib.dx_decode_packed.restype = ctypes.c_int64
        lib.dx_decode_packed.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ]
        lib.dx_decode_kafka_packed.restype = ctypes.c_int64
        lib.dx_decode_kafka_packed.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ]
        lib.dx_crc32c.restype = ctypes.c_uint32
        lib.dx_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_int64]
        lib.dx_bad_timestamps.restype = ctypes.c_int64
        lib.dx_bad_timestamps.argtypes = [ctypes.c_void_p]
        lib.dx_dict_size.restype = ctypes.c_int64
        lib.dx_dict_size.argtypes = [ctypes.c_void_p]
        lib.dx_dict_push.restype = ctypes.c_int32
        lib.dx_dict_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.dx_dict_get.restype = ctypes.c_int64
        lib.dx_dict_get.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def native_crc32c(data: bytes) -> Optional[int]:
    """CRC-32C via the native library (None when unavailable) — shared
    with the wire client so checksum math exists exactly once."""
    lib = _load()
    if lib is None:
        return None
    return int(lib.dx_crc32c(data, len(data)))


def _decode_threads(conf_threads: Optional[int] = None) -> int:
    """Decoder shard count: DATAX_DECODER_THREADS env (operator
    override) > the conf'd ``process.ingest.decoderthreads`` > default
    (cap 4 — ingest shares the host with the engine loop and sinks)."""
    env = os.environ.get("DATAX_DECODER_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    if conf_threads is not None:
        return max(1, int(conf_threads))
    return max(1, min(4, (os.cpu_count() or 1) - 1))


class PackedBufferPool:
    """Persistent, reused, 64-byte-aligned ingest matrices in the
    packed H2D layout ([n_rows, capacity] int32, row stride ==
    capacity).

    64-byte alignment makes the CPU backend's ``jnp.asarray`` a
    zero-copy view (the same property PR 13 had to defend against for
    ring snapshots) — which is exactly why a matrix may NOT be reused
    while its batch is still in flight: the device step reads the
    buffer directly. The processor releases a slot only once its
    ``PendingBatch`` has landed (or abandoned after the step
    completed), double-buffering the pool against the pipelined
    window. The pool grows on demand (decode-ahead at depth N holds up
    to N+1 matrices) and every reuse is counted for the
    ``Decode_BufferReuse_Count`` metric."""

    def __init__(self, n_rows: int, capacity: int):
        self.n_rows = int(n_rows)
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._free: List[np.ndarray] = []
        self.alloc_count = 0
        self.reuse_count = 0
        self._reuse_drained = 0

    def _new_matrix(self) -> np.ndarray:
        n = self.n_rows * self.capacity
        raw = np.empty(n + 16, dtype=np.int32)
        off = (-raw.ctypes.data % 64) // 4
        m = raw[off: off + n].reshape(self.n_rows, self.capacity)
        assert m.ctypes.data % 64 == 0 and m.flags["C_CONTIGUOUS"]
        return m

    def acquire(self) -> np.ndarray:
        with self._lock:
            if self._free:
                self.reuse_count += 1
                return self._free.pop()
            self.alloc_count += 1
        return self._new_matrix()

    def release(self, matrix: np.ndarray) -> None:
        # an attached BufferSanitizer (debug.buffersanitizer) poisons
        # the slot on release: the pool owns it now, so any sentinel
        # that later surfaces downstream is a use-after-release
        san = getattr(self, "sanitizer", None)
        if san is not None:
            san.poison(matrix)
        with self._lock:
            self._free.append(matrix)

    def take_reuse_count(self) -> int:
        """Reuses since the last take (the Decode_BufferReuse_Count
        delta drained at collect)."""
        with self._lock:
            n = self.reuse_count - self._reuse_drained
            self._reuse_drained = self.reuse_count
            return n


class NativeDecoder:
    """Decode newline-delimited JSON (or Kafka v2 record batches) into
    columnar output typed by the flow's input schema."""

    def __init__(
        self,
        schema: Schema,
        dictionary: StringDictionary,
        threads: Optional[int] = None,
    ):
        lib = _load()
        if lib is None:
            raise RuntimeError("native decoder unavailable (g++ build failed)")
        self._lib = lib
        self.schema = schema
        self.dictionary = dictionary
        # conf'd shard count (datax.job.process.ingest.decoderthreads);
        # None = engine default, env DATAX_DECODER_THREADS always wins
        self.threads = threads
        desc = "".join(
            f"{c.name}\t{_CTYPE_NAME[c.ctype]}\n" for c in schema.columns
        )
        self._d = lib.dx_decoder_create(desc.encode("utf-8"))
        self._cols = list(schema.columns)
        self._synced = 0
        self.last_bad_timestamps = 0
        self.last_shards = 1
        self._push_python_entries()

    def close(self):
        if self._d:
            self._lib.dx_decoder_destroy(self._d)
            self._d = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def shard_count(self) -> int:
        return _decode_threads(self.threads)

    # -- dictionary sync --------------------------------------------------
    def _push_python_entries(self):
        """Push Python-side dictionary entries the native map hasn't seen
        (ids are sequential on both sides, so push in id order)."""
        native_n = self._lib.dx_dict_size(self._d)
        py_n = len(self.dictionary)
        for i in range(native_n, py_n):
            s = self.dictionary.decode(i)
            got = self._lib.dx_dict_push(self._d, (s or "").encode("utf-8"))
            if got != i:
                raise RuntimeError(
                    f"dictionary desync: pushed {s!r} expecting id {i}, got {got}"
                )
        self._synced = py_n

    def _pull_native_entries(self):
        """Pull entries the native decode added into the Python dict."""
        native_n = self._lib.dx_dict_size(self._d)
        py_n = len(self.dictionary)
        buf = ctypes.create_string_buffer(4096)
        for i in range(py_n, native_n):
            n = self._lib.dx_dict_get(self._d, i, buf, len(buf))
            if n < 0:
                raise RuntimeError(f"dictionary id {i} missing on native side")
            if n >= len(buf):
                bigger = ctypes.create_string_buffer(int(n) + 1)
                self._lib.dx_dict_get(self._d, i, bigger, len(bigger))
                s = bigger.value.decode("utf-8", "replace")
            else:
                s = buf.value.decode("utf-8", "replace")
            got = self.dictionary.encode(s)
            if got != i:
                raise RuntimeError(
                    f"dictionary desync pulling {s!r}: expected id {i}, got {got}"
                )

    # -- decode -----------------------------------------------------------
    def decode(
        self, data: bytes, max_rows: int
    ) -> Tuple[Dict[str, np.ndarray], np.ndarray, int, int]:
        """Row-layout decode: returns (columns, valid, rows,
        bytes_consumed).

        ``valid`` is the ONLY authoritative row mask: on the sharded
        path malformed lines leave zeroed gap slots at chunk tails, so
        valid rows are NOT a packed prefix and ``arrays[:rows]`` would
        both drop real rows and include gaps. ``rows`` is the
        decoded-row COUNT (== valid.sum()), for metrics."""
        self._push_python_entries()
        arrays: Dict[str, np.ndarray] = {}
        ptrs = (ctypes.c_void_p * len(self._cols))()
        for i, c in enumerate(self._cols):
            a = np.zeros(max_rows, dtype=_NP_DTYPE[c.ctype])
            arrays[c.name] = a
            ptrs[i] = a.ctypes.data_as(ctypes.c_void_p)
        valid = np.zeros(max_rows, dtype=np.uint8)
        consumed = ctypes.c_int64(0)
        n_threads = self.shard_count()
        self.last_shards = n_threads
        rows = self._lib.dx_decode_mt(
            self._d, data, len(data), max_rows, ptrs,
            valid.ctypes.data_as(ctypes.c_void_p), ctypes.byref(consumed),
            n_threads,
        )
        self.last_bad_timestamps = int(self._lib.dx_bad_timestamps(self._d))
        self._pull_native_entries()
        return arrays, valid.astype(bool), int(rows), int(consumed.value)

    def _packed_args(
        self, matrix: np.ndarray, col_rows: Sequence[int], valid_row: int,
    ):
        if matrix.dtype != np.int32 or not matrix.flags["C_CONTIGUOUS"]:
            raise ValueError("packed decode needs a C-contiguous int32 matrix")
        cr = (ctypes.c_int64 * len(self._cols))(*[int(r) for r in col_rows])
        return (
            matrix.ctypes.data_as(ctypes.c_void_p),
            int(matrix.shape[1]), cr, int(valid_row),
        )

    def decode_packed(
        self,
        data: bytes,
        matrix: np.ndarray,
        col_rows: Sequence[int],
        valid_row: int,
        base_ms: int,
        max_rows: Optional[int] = None,
    ) -> Tuple[int, int]:
        """Newline-JSON straight into the packed H2D matrix: column i
        of the schema writes matrix row ``col_rows[i]`` (floats
        bitcast, bools widened, timestamps rebased to int32
        batch-relative ms against ``base_ms``), validity into
        ``matrix[valid_row]`` as int32 0/1. The decoder zeroes its own
        rows first, so reused (dirty) pool matrices are fine. Returns
        (rows decoded, bytes consumed)."""
        self._push_python_entries()
        base, stride, cr, vrow = self._packed_args(matrix, col_rows, valid_row)
        cap = int(matrix.shape[1]) if max_rows is None else int(max_rows)
        consumed = ctypes.c_int64(0)
        n_threads = self.shard_count()
        self.last_shards = n_threads
        rows = self._lib.dx_decode_packed(
            self._d, data, len(data), cap, base, stride, cr, vrow,
            int(base_ms), ctypes.byref(consumed), n_threads,
        )
        self.last_bad_timestamps = int(self._lib.dx_bad_timestamps(self._d))
        self._pull_native_entries()
        return int(rows), int(consumed.value)

    def decode_kafka_packed(
        self,
        data: bytes,
        matrix: np.ndarray,
        col_rows: Sequence[int],
        valid_row: int,
        base_ms: int,
        max_rows: Optional[int] = None,
    ) -> Tuple[int, Dict[str, int]]:
        """Kafka v2 record batches straight into the packed H2D matrix
        — CRC-32C verified per batch (corrupt batches skip + count
        instead of mis-parsing), control batches skipped, compressed
        batches rejected with a typed :class:`UnsupportedCodecError`
        naming the codec. Returns (rows decoded, stats) where stats
        carries ``records``/``malformed``/``corrupt_batches``/
        ``control_batches``/``overflow_dropped``."""
        self._push_python_entries()
        base, stride, cr, vrow = self._packed_args(matrix, col_rows, valid_row)
        cap = int(matrix.shape[1]) if max_rows is None else int(max_rows)
        stats = (ctypes.c_int64 * 6)()
        n_threads = self.shard_count()
        self.last_shards = n_threads
        rows = self._lib.dx_decode_kafka_packed(
            self._d, data, len(data), cap, base, stride, cr, vrow,
            int(base_ms), stats, n_threads,
        )
        self.last_bad_timestamps = int(self._lib.dx_bad_timestamps(self._d))
        self._pull_native_entries()
        codec = int(stats[_KSTAT_CODEC])
        if codec >= 0:
            from ..runtime.kafka_wire import UnsupportedCodecError

            raise UnsupportedCodecError(KAFKA_CODEC_NAMES.get(codec, str(codec)))
        return int(rows), {
            "records": int(stats[_KSTAT_RECORDS]),
            "malformed": int(stats[_KSTAT_MALFORMED]),
            "corrupt_batches": int(stats[_KSTAT_CORRUPT]),
            "control_batches": int(stats[_KSTAT_CONTROL]),
            "overflow_dropped": int(stats[_KSTAT_OVERFLOW]),
        }
