"""ctypes binding for the native JSON->columnar ingest decoder.

The C++ library (``native/decoder.cpp``) replaces the role Spark's
executor-side ``from_json`` plays in the reference
(CommonProcessorFactory.scala:90-103): every event's JSON parse happens
in native code straight into numpy buffers. The shared library builds
lazily with g++ on first use and is cached next to the source.

The decoder owns a string dictionary (string -> int32) kept consistent
with the Python ``StringDictionary`` by push-before/pull-after syncs
around each decode call; both sides assign ids sequentially so ids
stay stable across the boundary.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Dict, Optional, Tuple

import numpy as np

from ..core.schema import ColType, Schema, StringDictionary

logger = logging.getLogger(__name__)

_SRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "native",
    "decoder.cpp",
)
_LIB_PATH = os.path.join(os.path.dirname(_SRC), "libdxdecoder.so")
_build_lock = threading.Lock()
_lib = None
_lib_error: Optional[str] = None

_CTYPE_NAME = {
    ColType.LONG: "long",
    ColType.DOUBLE: "double",
    ColType.BOOLEAN: "boolean",
    ColType.STRING: "string",
    ColType.TIMESTAMP: "timestamp",
}

_NP_DTYPE = {
    ColType.LONG: np.int32,
    ColType.DOUBLE: np.float32,
    ColType.BOOLEAN: np.uint8,
    ColType.STRING: np.int32,
    ColType.TIMESTAMP: np.int64,
}


def _build_library() -> Optional[str]:
    if os.path.exists(_LIB_PATH) and os.path.getmtime(_LIB_PATH) >= os.path.getmtime(
        _SRC
    ):
        return _LIB_PATH
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
        "-o", _LIB_PATH, _SRC,
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (subprocess.SubprocessError, FileNotFoundError) as e:
        logger.warning("native decoder build failed: %s", e)
        return None
    return _LIB_PATH


def _load():
    global _lib, _lib_error
    if _lib is not None or _lib_error is not None:
        return _lib
    with _build_lock:
        if _lib is not None or _lib_error is not None:
            return _lib
        path = _build_library()
        if path is None:
            _lib_error = "build failed"
            return None
        lib = ctypes.CDLL(path)
        lib.dx_decoder_create.restype = ctypes.c_void_p
        lib.dx_decoder_create.argtypes = [ctypes.c_char_p]
        lib.dx_decoder_destroy.argtypes = [ctypes.c_void_p]
        lib.dx_num_columns.restype = ctypes.c_int64
        lib.dx_num_columns.argtypes = [ctypes.c_void_p]
        lib.dx_decode.restype = ctypes.c_int64
        lib.dx_decode.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64),
        ]
        lib.dx_decode_mt.restype = ctypes.c_int64
        lib.dx_decode_mt.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_void_p), ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_int64), ctypes.c_int32,
        ]
        lib.dx_bad_timestamps.restype = ctypes.c_int64
        lib.dx_bad_timestamps.argtypes = [ctypes.c_void_p]
        lib.dx_dict_size.restype = ctypes.c_int64
        lib.dx_dict_size.argtypes = [ctypes.c_void_p]
        lib.dx_dict_push.restype = ctypes.c_int32
        lib.dx_dict_push.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.dx_dict_get.restype = ctypes.c_int64
        lib.dx_dict_get.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int64,
        ]
        _lib = lib
        return _lib


def native_available() -> bool:
    return _load() is not None


def _decode_threads() -> int:
    """Worker count for parallel decode (DATAX_DECODER_THREADS
    overrides; default caps at 4 — ingest shares the host with the
    engine loop and sinks)."""
    env = os.environ.get("DATAX_DECODER_THREADS")
    if env:
        try:
            return max(1, int(env))
        except ValueError:
            pass
    return max(1, min(4, (os.cpu_count() or 1) - 1))


class NativeDecoder:
    """Decode newline-delimited JSON event batches into columnar numpy
    arrays typed by the flow's input schema."""

    def __init__(self, schema: Schema, dictionary: StringDictionary):
        lib = _load()
        if lib is None:
            raise RuntimeError("native decoder unavailable (g++ build failed)")
        self._lib = lib
        self.schema = schema
        self.dictionary = dictionary
        desc = "".join(
            f"{c.name}\t{_CTYPE_NAME[c.ctype]}\n" for c in schema.columns
        )
        self._d = lib.dx_decoder_create(desc.encode("utf-8"))
        self._cols = list(schema.columns)
        self._synced = 0
        self.last_bad_timestamps = 0
        self._push_python_entries()

    def close(self):
        if self._d:
            self._lib.dx_decoder_destroy(self._d)
            self._d = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    # -- dictionary sync --------------------------------------------------
    def _push_python_entries(self):
        """Push Python-side dictionary entries the native map hasn't seen
        (ids are sequential on both sides, so push in id order)."""
        native_n = self._lib.dx_dict_size(self._d)
        py_n = len(self.dictionary)
        for i in range(native_n, py_n):
            s = self.dictionary.decode(i)
            got = self._lib.dx_dict_push(self._d, (s or "").encode("utf-8"))
            if got != i:
                raise RuntimeError(
                    f"dictionary desync: pushed {s!r} expecting id {i}, got {got}"
                )
        self._synced = py_n

    def _pull_native_entries(self):
        """Pull entries the native decode added into the Python dict."""
        native_n = self._lib.dx_dict_size(self._d)
        py_n = len(self.dictionary)
        buf = ctypes.create_string_buffer(4096)
        for i in range(py_n, native_n):
            n = self._lib.dx_dict_get(self._d, i, buf, len(buf))
            if n < 0:
                raise RuntimeError(f"dictionary id {i} missing on native side")
            if n >= len(buf):
                bigger = ctypes.create_string_buffer(int(n) + 1)
                self._lib.dx_dict_get(self._d, i, bigger, len(bigger))
                s = bigger.value.decode("utf-8", "replace")
            else:
                s = buf.value.decode("utf-8", "replace")
            got = self.dictionary.encode(s)
            if got != i:
                raise RuntimeError(
                    f"dictionary desync pulling {s!r}: expected id {i}, got {got}"
                )

    # -- decode -----------------------------------------------------------
    def decode(
        self, data: bytes, max_rows: int
    ) -> Tuple[Dict[str, np.ndarray], np.ndarray, int, int]:
        """Returns (columns, valid, rows, bytes_consumed).

        ``valid`` is the ONLY authoritative row mask: on the parallel
        path (payloads over ~1MB) malformed lines leave zeroed gap
        slots at chunk tails, so valid rows are NOT a packed prefix and
        ``arrays[:rows]`` would both drop real rows and include gaps.
        ``rows`` is the decoded-row COUNT (== valid.sum()), for
        metrics."""
        self._push_python_entries()
        arrays: Dict[str, np.ndarray] = {}
        ptrs = (ctypes.c_void_p * len(self._cols))()
        for i, c in enumerate(self._cols):
            a = np.zeros(max_rows, dtype=_NP_DTYPE[c.ctype])
            arrays[c.name] = a
            ptrs[i] = a.ctypes.data_as(ctypes.c_void_p)
        valid = np.zeros(max_rows, dtype=np.uint8)
        consumed = ctypes.c_int64(0)
        # parallel decode for big payloads: newline-chunked worker
        # threads with a serial dictionary merge (decoder.cpp
        # dx_decode_mt); small payloads stay on the single-thread path
        n_threads = _decode_threads()
        rows = self._lib.dx_decode_mt(
            self._d, data, len(data), max_rows, ptrs,
            valid.ctypes.data_as(ctypes.c_void_p), ctypes.byref(consumed),
            n_threads,
        )
        self.last_bad_timestamps = int(self._lib.dx_bad_timestamps(self._d))
        self._pull_native_entries()
        return arrays, valid.astype(bool), int(rows), int(consumed.value)
