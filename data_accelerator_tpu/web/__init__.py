"""Web UI layer: dashboard server + single-page app.

reference: Website/ — Node Express server (REST fan-out to the Gateway,
Redis metric poller pushing socket.io 'datapoints') plus React packages
(datax-home/-pipeline/-query/-metrics/-jobs) composed via
web.composition.json. Here: a Python HTTP server (server.py) serving a
static SPA (static/) with Server-Sent Events for the live metric feed.
"""

from .server import WebsiteServer

__all__ = ["WebsiteServer"]
