/* Data Accelerator TPU — single-page app.
   reference roles: datax-home (flow list), datax-pipeline (flow
   designer tabs), datax-query (LiveQuery editor), datax-metrics (live
   dashboard over the datapoints feed), datax-jobs (job ops). Routing is
   hash-based; API calls go through the website server's /api bridge. */

"use strict";

const $ = (sel, el) => (el || document).querySelector(sel);
const h = (tag, attrs, ...kids) => {
  const el = document.createElement(tag);
  for (const [k, v] of Object.entries(attrs || {})) {
    if (v == null) continue;
    if (k === "onclick" || k.startsWith("on")) el.addEventListener(k.slice(2), v);
    else if (k === "html") el.innerHTML = v;
    else el.setAttribute(k, v);
  }
  for (const k of kids.flat()) {
    if (k == null) continue;
    el.append(k.nodeType ? k : document.createTextNode(k));
  }
  return el;
};

function toast(msg, ok = true) {
  const t = $("#toast");
  t.textContent = msg;
  t.style.borderColor = ok ? "var(--border)" : "var(--serious)";
  t.hidden = false;
  clearTimeout(toast._t);
  toast._t = setTimeout(() => (t.hidden = true), 3500);
}

async function api(method, path, body) {
  const resp = await fetch(path, {
    method,
    headers: body ? { "Content-Type": "application/json" } : undefined,
    body: body ? JSON.stringify(body) : undefined,
  });
  const payload = await resp.json().catch(() => ({}));
  if (!resp.ok) {
    const msg = payload.error && payload.error.message || resp.statusText;
    throw new Error(msg);
  }
  return payload.result !== undefined ? payload.result : payload;
}

/* ---------------- theme ---------------- */
const theme = localStorage.getItem("dxtheme");
if (theme) document.documentElement.dataset.theme = theme;
$("#themeToggle").onclick = () => {
  const cur = document.documentElement.dataset.theme === "dark" ? "light" : "dark";
  document.documentElement.dataset.theme = cur;
  localStorage.setItem("dxtheme", cur);
};

/* ---------------- router ---------------- */
const routes = {};
function route(prefix, fn) { routes[prefix] = fn; }
async function render() {
  const hash = location.hash || "#/flows";
  const view = $("#view");
  view.textContent = "";
  closeLiveFeeds();
  const key = Object.keys(routes)
    .sort((a, b) => b.length - a.length)
    .find((p) => hash.startsWith(p));
  $("#nav").replaceChildren(
    ...[["#/flows", "Flows"], ["#/query", "Query"],
        ["#/metrics", "Metrics"], ["#/jobs", "Jobs"],
        ["#/fleet", "Fleet"]].map(([href, label]) =>
      h("a", { href, class: hash.startsWith(href) ? "active" : "" }, label))
  );
  try {
    await routes[key || "#/flows"](view, hash);
  } catch (e) {
    view.append(h("div", { class: "card" }, `Error: ${e.message}`));
  }
}
window.addEventListener("hashchange", render);

/* ---------------- flows (datax-home) ---------------- */
route("#/flows", async (view) => {
  view.append(h("h1", {}, "Flows"));
  const flows = await api("GET", "/api/flow/flow/getall/min");
  const tbl = h("table", { class: "grid" },
    h("thead", {}, h("tr", {},
      h("th", {}, "Name"), h("th", {}, "Jobs"), h("th", {}, "Actions"))),
    h("tbody", {}, flows.map((f) => h("tr", {},
      h("td", {}, h("a", { href: `#/flow/${f.name}` }, f.displayName || f.name)),
      h("td", {}, String((f.jobNames || []).length)),
      h("td", {},
        h("button", { class: "ghost", onclick: () => location.hash = `#/metrics/${f.name}` }, "metrics"),
        " ",
        h("button", {
          class: "ghost danger", onclick: async () => {
            if (!confirm(`Delete flow ${f.name}?`)) return;
            await api("POST", "/api/flow/flow/delete", { flowName: f.name });
            toast(`deleted ${f.name}`); render();
          },
        }, "delete"))))));
  view.append(tbl);
  const name = h("input", { placeholder: "new-flow-name" });
  view.append(h("div", { class: "row" }, name,
    h("button", {
      onclick: async () => {
        if (!name.value) return;
        await api("POST", "/api/flow/flow/save",
          { name: name.value, displayName: name.value });
        location.hash = `#/flow/${name.value}`;
      },
    }, "New flow")));
});

/* ---------------- flow designer (datax-pipeline) ---------------- */
const TABS = ["info", "input", "query", "rules", "functions", "outputs",
              "scale", "schedule"];

route("#/flow/", async (view, hash) => {
  const [, , name, tab = "info"] = hash.split("/");
  const doc = await api("GET", `/api/flow/flow/get?flowName=${encodeURIComponent(name)}`);
  const gui = doc.gui || {};
  view.append(h("h1", {}, `Flow: ${gui.displayName || name}`));
  view.append(h("div", { class: "tabs" }, TABS.map((t) =>
    h("a", { href: `#/flow/${name}/${t}`, class: t === tab ? "active" : "" }, t))));
  const pane = h("div", {});
  view.append(pane);

  const save = async () => {
    await api("POST", "/api/flow/flow/save", gui);
    toast("flow saved");
  };
  // inline diagnostics from the flow static analyzer (flow/validate —
  // same DXnnn diagnostics as `python -m data_accelerator_tpu.analysis`,
  // device + udf tiers included: DX2xx lints + per-stage cost table,
  // DX3xx UDF tracing-safety lints + analyzed-function summary)
  const diagBox = h("div", { class: "diags" });
  const fmtBytes = (n) => {
    for (const u of ["B", "KB", "MB", "GB"]) {
      if (Math.abs(n) < 1024 || u === "GB")
        return (u === "B" ? Math.round(n) : n.toFixed(1)) + u;
      n /= 1024;
    }
  };
  const renderCostTable = (dev) => {
    if (!dev || !dev.stages || !dev.stages.length) return null;
    const t = dev.totals || {};
    /* roofline latency model (analysis/costmodel.py latency_model):
       per-stage predicted ms + the deviceStep/d2h decomposition; the
       per-stage column joins by stage name */
    const lm = dev.latencyModel || {};
    const lmStageMs = {};
    for (const s of lm.stages || []) lmStageMs[s.name] = s.computeMs;
    const lt = lm.totals || {};
    return h("div", { class: "cost" },
      h("div", { class: "muted" },
        `device plan @ ${dev.chips} chips — HBM ${fmtBytes(t.hbmBytes || 0)}` +
        ` (persistent ${fmtBytes(t.persistentBytes || 0)}),` +
        ` ICI ${fmtBytes(t.iciBytesPerBatch || 0)}/batch,` +
        ` D2H ${fmtBytes(t.d2hBytesPerBatch || 0)}/batch,` +
        ` ~${fmtVal(t.flops || 0)} FLOP/batch`),
      lt.batchMs != null ? h("div", { class: "muted" },
        `roofline latency (${lm.profileSource} profile): device step ` +
        `${fmtVal(lt.deviceStepMs)} ms + D2H ${fmtVal(lt.d2hMs || 0)} ms` +
        ` = ${fmtVal(lt.batchMs)} ms/batch (lower bound)`) : null,
      h("table", { class: "grid cost-table" },
        h("thead", {}, h("tr", {},
          h("th", {}, "stage"), h("th", {}, "kind"), h("th", {}, "rows"),
          h("th", {}, "HBM"), h("th", {}, "FLOPs"), h("th", {}, "ICI/batch"),
          h("th", {}, "D2H/batch"), h("th", {}, "roofline ms"))),
        h("tbody", {}, dev.stages.map((s) => h("tr", {},
          h("td", { class: "mono" }, s.name),
          h("td", {}, s.kind),
          h("td", { class: "num" }, fmtVal(s.rows)),
          h("td", { class: "num" }, fmtBytes(s.hbmBytes)),
          h("td", { class: "num" }, s.flops ? fmtVal(s.flops) : "–"),
          h("td", { class: "num" }, s.iciBytes ? fmtBytes(s.iciBytes) : "–"),
          h("td", { class: "num" }, s.d2hBytes ? fmtBytes(s.d2hBytes) : "–"),
          h("td", { class: "num" },
            lmStageMs[s.name] != null ? fmtVal(lmStageMs[s.name]) : "–"))))));
  };
  const renderPlacement = (f) => {
    // fleet tier (flow/validate fleet: true): placement plan of this
    // flow + every registered flow on the fleet spec — chip -> flows ->
    // packed HBM/headroom (the DX4xx admission-gate surface)
    if (!f || !f.placement) return null;
    const p = f.placement;
    const spec = f.spec || {};
    const chips = p.chips || [];
    const probs = [].concat(p.unplaced || [], p.oversized || []);
    return h("div", { class: "cost placement" },
      h("div", { class: "muted" },
        `fleet placement @ ${spec.chips} chip(s) x ` +
        `${fmtBytes(spec.hbmPerChipBytes || 0)} HBM — ` +
        (p.feasible ? "feasible" : "INFEASIBLE") +
        (probs.length ? ` (no fit: ${probs.join(", ")})` : "")),
      h("table", { class: "grid cost-table placement-table" },
        h("thead", {}, h("tr", {},
          h("th", {}, "chip"), h("th", {}, "flows"),
          h("th", {}, "predicted HBM"), h("th", {}, "headroom"))),
        h("tbody", {}, chips.map((c) => h("tr", {},
          h("td", { class: "num" }, String(c.chip)),
          h("td", { class: "mono" }, (c.flows || []).join(", ")),
          h("td", { class: "num" }, fmtBytes(c.hbmBytes || 0)),
          h("td", { class: "num" },
            ((c.headroom || 0) * 100).toFixed(1) + "%"))))));
  };
  const renderUdfSummary = (u) => {
    if (!u || !u.functions || !u.functions.length) return null;
    return h("div", { class: "muted" },
      "udf tier: " + u.functions.map((f) =>
        `${f.name} [${f.tier}] ${f.kind || "unloadable"}` +
        (f.analyzed && f.analyzed.length ? ` (${f.analyzed.join(",")})` : "")
      ).join(" · "));
  };
  const renderCompileSurface = (c) => {
    // compile tier (flow/validate compile: true): the enumerated jit
    // entry points + AOT manifest summary — "stable" means the flow
    // ships precompiled and restarts warm-start in sub-second
    if (!c || !c.entries) return null;
    return h("div", { class: "muted" },
      `compile surface: ${c.entries} entries (1 step + ` +
      `${c.helperEntries} transfer-helper over ` +
      `${(c.buckets || []).length} bucket(s)) — ` +
      (c.stable ? "stable (AOT manifest covers every dispatch; " +
                  "warm starts skip first-dispatch compiles)"
                : "OPEN (manifest covers the initial surface only; " +
                  "runtime re-traces surface as Retrace_Count)") +
      `, jit-cache cap ${c.jitCacheCap}`);
  };
  const renderShardingTable = (m) => {
    // mesh tier (flow/validate mesh: true): the static SPMD partition
    // plan — stage -> shard axis -> per-chip bytes -> ICI bytes, with
    // the modeled reshard points (the DX7xx surface). "validated"
    // means every byte was asserted equal to a real Mesh lowering.
    if (!m || !m.stages || !m.stages.length) return null;
    const t = m.totals || {};
    return h("div", { class: "cost sharding" },
      h("div", { class: "muted" },
        `mesh plan @ ${m.chips} chips — ` +
        `ICI ${fmtBytes(t.iciWireBytesPerBatch || 0)}/batch wire ` +
        `(${t.reshardCount || 0} reshard(s)), ` +
        `per-chip HBM ${fmtBytes(t.perChipHbmBytes || 0)} — ` +
        (m.validated ? "model validated against the Mesh lowering"
                     : "model UNVALIDATED (no multi-device backend)")),
      h("table", { class: "grid cost-table sharding-table" },
        h("thead", {}, h("tr", {},
          h("th", {}, "stage"), h("th", {}, "kind"), h("th", {}, "axis"),
          h("th", {}, "rows"), h("th", {}, "per-chip"),
          h("th", {}, "ICI/batch"), h("th", {}, "reshards"))),
        h("tbody", {}, m.stages.map((s) => h("tr", {},
          h("td", { class: "mono" }, s.name),
          h("td", {}, s.kind),
          h("td", {}, s.axis),
          h("td", { class: "num" }, fmtVal(s.rows)),
          h("td", { class: "num" }, fmtBytes(s.perChipBytes || 0)),
          h("td", { class: "num" },
            s.iciWireBytes ? fmtBytes(s.iciWireBytes) : "–"),
          h("td", { class: "mono" },
            (s.reshards || []).map((e) => e.table).join(", ") || "–"))))));
  };
  const renderRaceGate = (rc) => {
    // race tier (flow/validate race: true): the DX8xx buffer-lifetime
    // gate over the ENGINE the flow deploys onto — any error here is
    // an engine bug, not a flow bug, so the summary line names the
    // analyzed surface (merged DX8xx diagnostics render above)
    if (!rc || !rc.analyzedFiles) return null;
    return h("div", { class: "muted" },
      `race gate: ${rc.analyzedFiles} engine module(s) analyzed — ` +
      `${rc.allowedZeroCopySites} pinned zero-copy site(s), ` +
      `${rc.ownerHandoffSites} owner handoff(s)`);
  };
  const renderProtocolGate = (pc) => {
    // protocol tier (flow/validate protocol: true): the DX90x
    // exactly-once delivery gate over the engine + rescale handoff —
    // like the race gate, an error here is an engine bug (merged
    // DX90x diagnostics render above)
    if (!pc || !pc.analyzedFiles) return null;
    return h("div", { class: "muted" },
      `protocol gate: ${pc.analyzedFiles} engine module(s) analyzed — ` +
      `${pc.effectEvents} effect event(s), ` +
      `${pc.postCommitSites} pinned post-commit site(s), ` +
      `${pc.requeueUpstreamSites} requeue-upstream site(s)`);
  };
  const renderConfGate = (cf) => {
    // conf tier (flow/validate conf: true): the DX10xx configuration
    // lattice gate — engine read sites + generated keys checked
    // against the typed conf registry, plus this flow's effective
    // conf (merged DX10xx diagnostics render above)
    if (!cf || !cf.analyzedFiles) return null;
    return h("div", { class: "muted" },
      `conf gate: ${cf.analyzedFiles} module(s) scanned — ` +
      `${cf.readSites} read site(s) / ${cf.readKeys} key(s), ` +
      `${cf.producedKeys} produced key(s), ` +
      `${cf.registryKeys} registry row(s)`);
  };
  const renderDiags = (r) => {
    diagBox.replaceChildren(
      h("div", { class: "muted" },
        r.ok ? `analyzer: clean (${r.warningCount} warning(s))`
             : `analyzer: ${r.errorCount} error(s), ${r.warningCount} warning(s)`),
      ...r.diagnostics.map((d) => h("div", { class: `diag sev-${d.severity}` },
        h("span", { class: "diag-code" }, d.code),
        d.table ? h("span", { class: "diag-table" }, d.table) : null,
        h("span", {}, d.message),
        d.span && d.span.line ? h("span", { class: "muted" }, ` line ${d.span.line}`) : null)),
      renderUdfSummary(r.udfs),
      renderCompileSurface(r.compile),
      renderRaceGate(r.race),
      renderProtocolGate(r.protocol),
      renderConfGate(r.conf),
      renderCostTable(r.device),
      renderShardingTable(r.mesh),
      renderPlacement(r.fleet));
  };
  const validate = async () => {
    await save();
    // all: true = every analysis tier in one call (semantic + device +
    // udfs + fleet + compile + mesh + race + protocol), one merged
    // diagnostics list
    const r = await api("POST", "/api/flow/flow/validate",
      { flow: gui, all: true });
    renderDiags(r);
    toast(r.ok ? "flow is clean" : `${r.errorCount} error(s) found`, r.ok);
    return r;
  };
  const actions = h("div", { class: "row" },
    h("button", { onclick: save }, "Save"),
    h("button", { class: "ghost", onclick: validate }, "Validate"),
    h("button", {
      class: "ghost", onclick: async () => {
        const r0 = await validate();
        if (!r0.ok) { toast("fix analyzer errors before generating", false); return; }
        const r = await api("POST", "/api/flow/flow/generateconfigs", { flowName: name });
        toast(`generated: ${(r.jobNames || []).join(", ")}`);
      },
    }, "Generate configs"),
    h("button", {
      class: "ghost", onclick: async () => {
        const r = await api("POST", "/api/flow/flow/startjobs", { flowName: name });
        toast(`started ${r.length} job(s)`);
      },
    }, "Start"),
    h("button", {
      class: "ghost", onclick: async () => {
        const r = await api("POST", "/api/flow/flow/stopjobs", { flowName: name });
        toast(`stopped ${r.length} job(s)`);
      },
    }, "Stop"));
  view.append(actions, diagBox);

  const field = (obj, key, label, opts) => {
    const input = opts && opts.options
      ? h("select", {}, opts.options.map((o) =>
          h("option", { value: o, selected: (obj[key] || "") === o ? "" : null }, o)))
      : h("input", { value: obj[key] || "", placeholder: (opts && opts.ph) || "" });
    input.addEventListener("change", () => (obj[key] = input.value));
    return h("label", { class: "f" }, h("span", {}, label), input);
  };
  const area = (obj, key, label) => {
    const ta = h("textarea", { class: "code" });
    ta.value = obj[key] || "";
    ta.addEventListener("change", () => (obj[key] = ta.value));
    return h("label", { class: "f" }, h("span", {}, label), ta);
  };

  gui.input = gui.input || {}; gui.input.properties = gui.input.properties || {};
  gui.process = gui.process || {}; gui.rules = gui.rules || [];
  gui.outputs = gui.outputs || []; gui.scale = gui.scale || {};
  gui.batch = gui.batch || [];

  if (tab === "info") {
    pane.append(field(gui, "displayName", "Display name"));
    pane.append(field(gui, "databaseName", "Database"));
    pane.append(h("div", { class: "muted" }, `internal name: ${name}`));
  } else if (tab === "input") {
    pane.append(field(gui.input, "mode", "Mode",
      { options: ["streaming", "batching"] }));
    pane.append(field(gui.input, "type", "Input type",
      { options: ["local", "socket", "file", "blobpointer", "events"] }));
    pane.append(area(gui.input.properties, "inputSchemaFile", "Input schema (JSON)"));
    pane.append(area(gui.input.properties, "normalizationSnippet", "Normalization"));
    pane.append(h("button", {
      class: "ghost", onclick: async () => {
        const r = await api("POST", "/api/schemainference/inputdata/inferschema",
          { name, seconds: 10 });
        gui.input.properties.inputSchemaFile =
          typeof r.Schema === "string" ? r.Schema : JSON.stringify(r.Schema, null, 1);
        render(); toast("schema inferred from sample");
      },
    }, "Infer schema from sample"));
    // additional named sources (multi-source flows: each projects into
    // its own table; TIMEWINDOW over any table enables cross-stream
    // sliding-window joins)
    gui.input.sources = gui.input.sources || [];
    const srcs = gui.input.sources;
    const srcList = h("div", {});
    const renderSrcs = () => {
      srcList.replaceChildren(...srcs.map((sr, i) => {
        sr.properties = sr.properties || {};
        return h("div", { class: "card" },
          field(sr, "id", "Source name", { ph: "weather" }),
          field(sr, "type", "Input type",
            { options: ["local", "socket", "file", "kafka", "eventhub-kafka"] }),
          field(sr.properties, "target", "Projected table",
            { ph: "Weather (defaults to the source name)" }),
          area(sr.properties, "inputSchemaFile", "Schema (JSON)"),
          area(sr.properties, "normalizationSnippet", "Normalization"),
          h("button", {
            class: "ghost danger",
            onclick: () => { srcs.splice(i, 1); renderSrcs(); },
          }, "remove source"));
      }));
    };
    renderSrcs();
    pane.append(
      h("h3", {}, "Additional sources"),
      srcList,
      h("button", {
        class: "ghost",
        onclick: () => { srcs.push({ id: "", type: "local", properties: {} }); renderSrcs(); },
      }, "+ add source"));
  } else if (tab === "query") {
    // gui contract: process.queries is a list of script chunks
    const qobj = { text: (gui.process.queries || []).join("\n") };
    const ta = area(qobj, "text", "DataXQuery transform");
    $("textarea", ta).addEventListener("change", (ev) => {
      gui.process.queries = [ev.target.value];
    });
    pane.append(ta);
    pane.append(h("div", { class: "muted" },
      "--DataXQuery-- blocks; TIMEWINDOW('5 minutes'); OUTPUT t TO sink;"));
  } else if (tab === "rules") {
    const AGG_FNS = ["AVG", "SUM", "COUNT", "MIN", "MAX", "DCOUNT"];
    // csv editor over a LIST-valued model key: displays joined, stores
    // an array on change, and never mutates the model just by rendering
    // (the backend contract is a list; a render must not turn it into a
    // string that codegen would then iterate char-by-char)
    const csvField = (obj, key, label, opts) => {
      const disp = {
        v: Array.isArray(obj[key]) ? obj[key].join(",") : (obj[key] || ""),
      };
      const f = field(disp, "v", label, opts);
      $("input", f).addEventListener("change", (ev) => {
        obj[key] = ev.target.value.split(",").map((x) => x.trim()).filter(Boolean);
      });
      return f;
    };
    const list = h("div", {});
    const renderRules = () => {
      list.replaceChildren(...gui.rules.map((r, i) => {
        r.properties = r.properties || {};
        const p = r.properties;
        const sinksField = csvField(p, "_S_alertSinks", "Alert sinks (csv)", { ph: "Metrics" });
        const typeField = field(p, "_S_ruleType", "Type",
          { options: ["SimpleRule", "AggregateRule"] });
        $("select", typeField).addEventListener("change", () => renderRules());
        const card = h("div", { class: "card" },
          field(p, "_S_ruleDescription", "Description"),
          typeField);
        if ((p._S_ruleType || "SimpleRule") === "AggregateRule") {
          // pivot/agg builders (datax-pipeline AggregateRule editors):
          // pivots are the GROUP BY columns; each agg row contributes
          // "<FN>(<field>)" to $aggs, aliased FN_field for the condition
          card.append(csvField(p, "_S_pivots",
            "Pivot by (group-by columns, csv)", { ph: "deviceId, homeId" }));
          if (!Array.isArray(p._S_aggs)) {
            p._S_aggs = typeof p._S_aggs === "string" && p._S_aggs
              ? p._S_aggs.split(",").map((x) => x.trim()) : [];
          }
          const aggList = h("div", {});
          const renderAggs = () => {
            aggList.replaceChildren(
              ...p._S_aggs.map((agg, j) => {
                const m = /^(\w+)\((.*)\)$/.exec(agg) || [null, "AVG", ""];
                const fnSel = h("select", {}, AGG_FNS.map((o) =>
                  h("option", { value: o, selected: o === m[1] ? "" : null }, o)));
                const fieldIn = h("input", { value: m[2], placeholder: "temperature" });
                const sync = () => {
                  p._S_aggs[j] = `${fnSel.value}(${fieldIn.value.trim()})`;
                };
                fnSel.addEventListener("change", sync);
                fieldIn.addEventListener("change", sync);
                return h("div", { class: "row" }, fnSel, fieldIn,
                  h("span", { class: "muted" },
                    ` alias: ${(m[1] || "AVG")}_${(m[2] || "").replace(/\W/g, "_")}`),
                  h("button", {
                    class: "ghost danger",
                    onclick: () => { p._S_aggs.splice(j, 1); renderAggs(); },
                  }, "x"));
              }),
              h("button", {
                class: "ghost",
                onclick: () => { p._S_aggs.push("AVG()"); renderAggs(); },
              }, "+ add aggregate"));
          };
          renderAggs();
          card.append(h("label", { class: "f" },
            h("span", {}, "Aggregates"), aggList));
          card.append(field(p, "_S_condition", "Alert condition (over agg aliases)",
            { ph: "AVG_temperature > 75" }));
        } else {
          card.append(field(p, "_S_condition", "Condition (SQL expr)",
            { ph: "deviceType = 'DoorLock' AND status = 0" }));
        }
        card.append(
          sinksField,
          field(p, "_S_severity", "Severity", { options: ["Critical", "Medium", "Low"] }),
          field(p, "_S_isAlert", "Is alert", { options: ["", "true", "false"] }),
          h("button", {
            class: "ghost danger",
            onclick: () => { gui.rules.splice(i, 1); renderRules(); },
          }, "remove rule"));
        return card;
      }));
    };
    renderRules();
    pane.append(list, h("button", {
      class: "ghost",
      onclick: () => { gui.rules.push({ id: `rule${Date.now()}`, type: "Rule", properties: {} }); renderRules(); },
    }, "+ add rule"));
  } else if (tab === "functions") {
    // UDF / UDAF / external-function editor (datax-pipeline function
    // editors); entries land in process.functions and S500 routes them
    // to processJarUDFs / processJarUDAFs / processAzureFunctions
    gui.process.functions = gui.process.functions || [];
    const fns = gui.process.functions;
    const list = h("div", {});
    const renderFns = () => {
      list.replaceChildren(...fns.map((f, i) => {
        f.properties = f.properties || {};
        const fp = f.properties;
        const typeField = field(f, "type", "Kind",
          { options: ["udf", "udaf", "azureFunction"] });
        $("select", typeField).addEventListener("change", () => renderFns());
        const card = h("div", { class: "card" },
          field(f, "id", "Function name", { ph: "anomalyscore" }),
          typeField);
        if ((f.type || "udf") === "azureFunction") {
          card.append(
            field(fp, "serviceEndpoint", "Service endpoint", { ph: "https://fn.example" }),
            field(fp, "api", "API name", { ph: "score" }),
            field(fp, "code", "Function key/code"),
            field(fp, "methodType", "Method", { options: ["get", "post"] }));
        } else {
          card.append(
            field(fp, "module", "Python path (module:attribute)",
              { ph: "data_accelerator_tpu.udf.samples:anomalyscore" }),
            h("div", { class: "muted" },
              (f.type || "udf") === "udaf"
                ? "attribute must be/build a UdfAggregate (see udf/samples.py)"
                : "attribute must be/build a jax-callable UDF (see udf/samples.py)"));
        }
        card.append(h("button", {
          class: "ghost danger",
          onclick: () => { fns.splice(i, 1); renderFns(); },
        }, "remove function"));
        return card;
      }));
    };
    renderFns();
    pane.append(list, h("button", {
      class: "ghost",
      onclick: () => { fns.push({ id: "", type: "udf", properties: {} }); renderFns(); },
    }, "+ add function"));
  } else if (tab === "outputs") {
    const list = h("div", {});
    const renderOutputs = () => {
      list.replaceChildren(...gui.outputs.map((o, i) => {
        o.properties = o.properties || {};
        const destKey = { blob: "folder", file: "folder", local: "folder",
                          httppost: "endpoint", eventhub: "connection",
                          cosmosdb: "connection", sql: "connection" }[o.type];
        const typeField = field(o, "type", "Sink type",
          { options: ["blob", "file", "sql", "cosmosdb", "eventhub", "httppost", "metric", "console"] });
        $("select", typeField).addEventListener("change", () => renderOutputs());
        return h("div", { class: "card" },
          field(o, "id", "Output name", { ph: "myOutput" }),
          typeField,
          destKey ? field(o.properties, destKey,
            destKey === "folder" ? "Output folder" :
            destKey === "endpoint" ? "Endpoint URL" : "Connection string") : null,
          h("button", {
            class: "ghost danger",
            onclick: () => { gui.outputs.splice(i, 1); renderOutputs(); },
          }, "remove output"));
      }));
    };
    renderOutputs();
    pane.append(list, h("button", {
      class: "ghost",
      onclick: () => { gui.outputs.push({ id: "", type: "blob", properties: {} }); renderOutputs(); },
    }, "+ add output"));
  } else if (tab === "scale") {
    gui.process.jobconfig = gui.process.jobconfig || {};
    pane.append(field(gui.process.jobconfig, "jobNumChips", "TPU chips", { ph: "1" }));
    pane.append(field(gui.process.jobconfig, "jobBatchCapacity", "Batch capacity (rows)", { ph: "65536" }));
    pane.append(field(gui.process.jobconfig, "jobDecoderThreads", "Ingest decoder shards", { ph: "engine default" }));
    pane.append(h("div", { class: "muted" },
      "capacity shards over the chip mesh; collectives ride ICI; " +
      "decoder shards fan the host-side ingest parse across cores"));
    pane.append(field(gui.process.jobconfig, "jobLqMaxBatchWaitMs", "LiveQuery batch wait (ms)", { ph: "8" }));
    pane.append(field(gui.process.jobconfig, "jobLqTenantMaxSessions", "LiveQuery sessions/tenant", { ph: "8" }));
    pane.append(field(gui.process.jobconfig, "jobLqTenantMaxQps", "LiveQuery QPS/tenant", { ph: "50" }));
    pane.append(h("div", { class: "muted" },
      "LiveQuery serving plane: executes queue per compile signature and " +
      "micro-batch into one device dispatch per tick; over-quota tenants " +
      "get 429 + Retry-After"));
  } else if (tab === "schedule") {
    const list = h("div", {});
    const renderBatches = () => {
      list.replaceChildren(...gui.batch.map((b, i) => {
        b.properties = b.properties || {};
        return h("div", { class: "card" },
          field(b.properties, "type", "Type", { options: ["recurring", "oneTime"] }),
          field(b.properties, "intervalSeconds", "Interval (s)", { ph: "3600" }),
          field(b.properties, "path", "Input path pattern", { ph: "/data/{yyyy-MM-dd}/*.json" }),
          field(b.properties, "startTime", "Window start (ISO)"),
          field(b.properties, "endTime", "Window end (ISO)"),
          h("button", {
            class: "ghost danger",
            onclick: () => { gui.batch.splice(i, 1); renderBatches(); },
          }, "remove"));
      }));
    };
    renderBatches();
    pane.append(list, h("button", {
      class: "ghost",
      onclick: () => { gui.batch.push({ properties: {} }); renderBatches(); },
    }, "+ add batch window"));
  }
});

/* ---------------- LiveQuery (datax-query) ---------------- */
route("#/query", async (view) => {
  view.append(h("h1", {}, "LiveQuery"));
  const flows = await api("GET", "/api/flow/flow/getall/min");
  const sel = h("select", {}, flows.map((f) => h("option", { value: f.name }, f.name)));
  const kernelLabel = h("span", { class: "muted" }, "no kernel");
  let kernelId = null;
  const editor = h("textarea", { class: "code", placeholder:
    "--DataXQuery--\nT = SELECT * FROM DataXProcessedInput WHERE ..." });
  const out = h("div", {});

  const showTable = (rows, title) => {
    out.replaceChildren();
    out.append(h("h2", {}, title));
    if (!rows || !rows.length) { out.append(h("div", { class: "muted" }, "no rows")); return; }
    const cols = Object.keys(rows[0]);
    out.append(h("table", { class: "grid" },
      h("thead", {}, h("tr", {}, cols.map((c) => h("th", {}, c)))),
      h("tbody", {}, rows.map((r) => h("tr", {}, cols.map((c) =>
        h("td", { class: "mono" }, JSON.stringify(r[c]))))))));
  };

  view.append(h("div", { class: "row" },
    sel,
    h("button", {
      class: "ghost", onclick: async () => {
        const r = await api("POST", "/api/interactivequery/kernel",
          { name: sel.value });
        kernelId = r.kernelId;
        kernelLabel.textContent = `kernel ${kernelId.slice(0, 8)}…`;
        toast("kernel ready");
      },
    }, "Create kernel"),
    h("button", {
      class: "ghost", onclick: async () => {
        const r = await api("POST", "/api/interactivequery/kernel/refresh",
          { name: sel.value });
        kernelId = r.kernelId;
        kernelLabel.textContent = `kernel ${kernelId.slice(0, 8)}…`;
        toast("kernel refreshed with fresh sample");
      },
    }, "Refresh sample"),
    kernelLabel));
  view.append(editor);
  view.append(h("div", { class: "row" },
    h("button", {
      onclick: async () => {
        if (!kernelId) { toast("create a kernel first", false); return; }
        const r = await api("POST", "/api/interactivequery/kernel/executequery",
          { kernelId, query: editor.value, maxRows: 50 });
        showTable(r.rows || r.result || r, "Result");
      },
    }, "Execute"),
    h("button", {
      class: "ghost", onclick: async () => {
        if (!kernelId) { toast("create a kernel first", false); return; }
        const r = await api("POST", "/api/interactivequery/kernel/executequery",
          { kernelId, query: "DataXProcessedInput", maxRows: 20 });
        showTable(r.rows || r.result || r, "Sample input");
      },
    }, "Show sample input")));
  view.append(out);
});

/* ---------------- metrics dashboard (datax-metrics) ---------------- */
const liveFeeds = [];
function closeLiveFeeds() {
  while (liveFeeds.length) liveFeeds.pop().close();
}

const SERIES_VARS = ["--series-1", "--series-2", "--series-3",
                     "--series-4", "--series-5", "--series-6"];

/* canonical engine stages (constants.py MetricName.STAGES minus the
   whole-batch rollup) and their Latency-<Stage> metric stems */
const STAGES = ["decode", "dispatch", "device-step", "sync", "collect",
                "sinks", "checkpoint"];
const stageMetric = (s) =>
  "Latency-" + s.split("-").map((w) => w[0].toUpperCase() + w.slice(1)).join("");
const LATENCY_PCTL_RE = /^Latency-[A-Za-z]+-p(50|95|99)$/;

function lineChart(container, title) {
  /* single-metric timechart: 2px line, crosshair+tooltip, recessive
     grid; series identity from the title (single series, no legend). */
  const W = 800, H = 180, PL = 54, PB = 18, PT = 8;
  const card = h("div", { class: "card chart-card" },
    h("div", { class: "chart-title" }, title));
  const wrap = h("div", { class: "chart-wrap" });
  const svg = document.createElementNS("http://www.w3.org/2000/svg", "svg");
  svg.setAttribute("viewBox", `0 0 ${W} ${H}`);
  const tip = h("div", { class: "tooltip" });
  wrap.append(svg, tip);
  card.append(wrap);
  container.append(card);
  const pts = [];  // {t, v}
  const MAX_POINTS = 600;

  function draw() {
    svg.replaceChildren();
    if (pts.length < 2) return;
    const t0 = pts[0].t, t1 = pts[pts.length - 1].t || t0 + 1;
    let vmin = Math.min(...pts.map((p) => p.v));
    let vmax = Math.max(...pts.map((p) => p.v));
    if (vmin === vmax) { vmin -= 1; vmax += 1; }
    const x = (t) => PL + (W - PL - 8) * (t - t0) / Math.max(1, t1 - t0);
    const y = (v) => PT + (H - PT - PB) * (1 - (v - vmin) / (vmax - vmin));
    const mk = (n, attrs) => {
      const el = document.createElementNS("http://www.w3.org/2000/svg", n);
      for (const [k, v] of Object.entries(attrs)) el.setAttribute(k, v);
      svg.append(el);
      return el;
    };
    for (const frac of [0, 0.5, 1]) {
      const v = vmin + (vmax - vmin) * frac;
      mk("line", { x1: PL, x2: W - 8, y1: y(v), y2: y(v), class: "grid-line" });
      const t = mk("text", { x: PL - 6, y: y(v) + 3, "text-anchor": "end" });
      t.textContent = fmtVal(v);
      t.setAttribute("fill", "var(--text-muted)");
      t.setAttribute("font-size", "10");
    }
    const d = pts.map((p, i) => `${i ? "L" : "M"}${x(p.t).toFixed(1)},${y(p.v).toFixed(1)}`).join("");
    mk("path", { d, class: "series", stroke: `var(${SERIES_VARS[0]})` });
    const cross = mk("line", { y1: PT, y2: H - PB, stroke: "var(--text-muted)", "stroke-dasharray": "3,3", visibility: "hidden" });
    const dot = mk("circle", { r: 4, fill: `var(${SERIES_VARS[0]})`, stroke: "var(--surface-2)", "stroke-width": 2, visibility: "hidden" });
    svg.onmousemove = (ev) => {
      const rect = svg.getBoundingClientRect();
      const mx = (ev.clientX - rect.left) * W / rect.width;
      let best = pts[0], bd = Infinity;
      for (const p of pts) {
        const dd = Math.abs(x(p.t) - mx);
        if (dd < bd) { bd = dd; best = p; }
      }
      cross.setAttribute("x1", x(best.t)); cross.setAttribute("x2", x(best.t));
      cross.setAttribute("visibility", "visible");
      dot.setAttribute("cx", x(best.t)); dot.setAttribute("cy", y(best.v));
      dot.setAttribute("visibility", "visible");
      tip.style.display = "block";
      tip.style.left = `${(x(best.t) / W) * rect.width + 12}px`;
      tip.style.top = `${(y(best.v) / H) * rect.height - 10}px`;
      tip.textContent = `${new Date(best.t).toLocaleTimeString()} — ${fmtVal(best.v)}`;
    };
    svg.onmouseleave = () => {
      cross.setAttribute("visibility", "hidden");
      dot.setAttribute("visibility", "hidden");
      tip.style.display = "none";
    };
  }
  return {
    push(t, v) {
      pts.push({ t, v });
      if (pts.length > MAX_POINTS) pts.shift();
      draw();
    },
    seed(points) {
      pts.splice(0, pts.length, ...points.map((p) => ({ t: p.uts, v: +p.val })));
      draw();
    },
  };
}

function fmtVal(v) {
  if (Math.abs(v) >= 1e6) return (v / 1e6).toFixed(1) + "M";
  if (Math.abs(v) >= 1e3) return (v / 1e3).toFixed(1) + "k";
  return (+v).toFixed(Math.abs(v) < 10 && v % 1 ? 2 : 0);
}

function multiChart(container, title, seriesNames) {
  /* multi-series timechart (per-stage latency): one 2px line per
     series, shared scale, legend keyed to the categorical palette. */
  const W = 800, H = 200, PL = 54, PB = 18, PT = 8;
  const card = h("div", { class: "card chart-card" },
    h("div", { class: "chart-title" }, title));
  const wrap = h("div", { class: "chart-wrap" });
  const svg = document.createElementNS("http://www.w3.org/2000/svg", "svg");
  svg.setAttribute("viewBox", `0 0 ${W} ${H}`);
  const tip = h("div", { class: "tooltip" });
  wrap.append(svg, tip);
  const colorOf = (name) =>
    `var(${SERIES_VARS[seriesNames.indexOf(name) % SERIES_VARS.length]})`;
  card.append(wrap, h("div", { class: "legend" }, seriesNames.map((n) =>
    h("span", {},
      h("span", { class: "sw", style: `background:${colorOf(n)}` }), n))));
  container.append(card);
  const data = {};  // series -> [{t, v}]
  for (const n of seriesNames) data[n] = [];
  const MAX_POINTS = 600;

  function draw() {
    svg.replaceChildren();
    const all = seriesNames.flatMap((n) => data[n]);
    if (all.length < 2) return;
    const t0 = Math.min(...all.map((p) => p.t));
    const t1 = Math.max(...all.map((p) => p.t));
    let vmin = 0;  // latency: zero-based scale reads honestly
    let vmax = Math.max(...all.map((p) => p.v));
    if (vmax <= vmin) vmax = vmin + 1;
    const x = (t) => PL + (W - PL - 8) * (t - t0) / Math.max(1, t1 - t0);
    const y = (v) => PT + (H - PT - PB) * (1 - (v - vmin) / (vmax - vmin));
    const mk = (n, attrs) => {
      const el = document.createElementNS("http://www.w3.org/2000/svg", n);
      for (const [k, v] of Object.entries(attrs)) el.setAttribute(k, v);
      svg.append(el);
      return el;
    };
    for (const frac of [0, 0.5, 1]) {
      const v = vmin + (vmax - vmin) * frac;
      mk("line", { x1: PL, x2: W - 8, y1: y(v), y2: y(v), class: "grid-line" });
      const t = mk("text", { x: PL - 6, y: y(v) + 3, "text-anchor": "end" });
      t.textContent = fmtVal(v);
      t.setAttribute("fill", "var(--text-muted)");
      t.setAttribute("font-size", "10");
    }
    for (const name of seriesNames) {
      const pts = data[name];
      if (pts.length < 2) continue;
      const d = pts.map((p, i) =>
        `${i ? "L" : "M"}${x(p.t).toFixed(1)},${y(p.v).toFixed(1)}`).join("");
      mk("path", { d, class: "series", stroke: colorOf(name) });
    }
    svg.onmousemove = (ev) => {
      const rect = svg.getBoundingClientRect();
      const mx = (ev.clientX - rect.left) * W / rect.width;
      const my = (ev.clientY - rect.top) * H / rect.height;
      let best = null, bd = Infinity;
      for (const name of seriesNames) {
        for (const p of data[name]) {
          const dd = Math.abs(x(p.t) - mx) + Math.abs(y(p.v) - my) / 4;
          if (dd < bd) { bd = dd; best = { ...p, name }; }
        }
      }
      if (!best) return;
      tip.style.display = "block";
      tip.style.left = `${(x(best.t) / W) * rect.width + 12}px`;
      tip.style.top = `${(y(best.v) / H) * rect.height - 10}px`;
      tip.textContent =
        `${best.name} — ${new Date(best.t).toLocaleTimeString()} — ${fmtVal(best.v)} ms`;
    };
    svg.onmouseleave = () => { tip.style.display = "none"; };
  }
  return {
    push(name, t, v) {
      if (!data[name]) return;
      data[name].push({ t, v });
      if (data[name].length > MAX_POINTS) data[name].shift();
      draw();
    },
    seed(name, points) {
      if (!data[name]) return;
      data[name].splice(0, data[name].length,
        ...points.map((p) => ({ t: p.uts, v: +p.val })));
      draw();
    },
  };
}

route("#/metrics", async (view, hash) => {
  const flow = hash.split("/")[2] || "";
  view.append(h("h1", {}, flow ? `Metrics — ${flow}` : "Metrics"));
  const flows = await api("GET", "/api/flow/flow/getall/min").catch(() => []);
  const sel = h("select", {},
    h("option", { value: "" }, "select flow…"),
    flows.map((f) => h("option", { value: f.name, selected: f.name === flow ? "" : null }, f.name)));
  sel.addEventListener("change", () => (location.hash = `#/metrics/${sel.value}`));
  view.append(h("div", { class: "row" }, sel));
  if (!flow) return;

  const prefix = `DATAX-${flow}:`;

  /* firing-alert annotations: poll the alert engine's /alerts surface
     (obs/alerts.py) — a banner lists firing rules, and any tile/chart
     whose metric a firing rule watches gets the alerting outline */
  const alertBox = h("div", {});
  view.append(alertBox);

  /* latency percentile stat tiles (whole-batch p50/p95/p99, live from
     the engine's per-stage histograms) + per-stage p95 timechart */
  const pctlTiles = h("div", { class: "tiles" });
  const PCTLS = ["p50", "p95", "p99"];
  const pctlEls = {};
  for (const p of PCTLS) {
    const tile = h("div", { class: "tile" },
      h("div", { class: "k" }, `batch latency ${p}`),
      h("div", { class: "v" }, "–", h("span", { class: "u" }, "ms")));
    pctlTiles.append(tile);
    pctlEls[`Latency-Batch-${p}`] = $(".v", tile);
  }
  view.append(h("h2", {}, "Latency percentiles"), pctlTiles);

  /* autopilot tile (pilot/controller.py): the controller's live state —
     commanded pipeline depth, backpressure token balance, cumulative
     actuations — as a dedicated stat row so "is the pilot flying this
     job?" is one glance, not a hunt through the generic metric tiles */
  const PILOT_METRICS = [
    ["Pilot_Depth", "pilot depth"],
    ["Pilot_Backpressure_Tokens", "backpressure tokens"],
    ["Pilot_Actuations_Count", "pilot actuations"],
  ];
  const pilotTiles = h("div", { class: "tiles" });
  const pilotEls = {};
  for (const [metric, label] of PILOT_METRICS) {
    const tile = h("div", { class: "tile" },
      h("div", { class: "k" }, label),
      h("div", { class: "v" }, "–"));
    pilotTiles.append(tile);
    pilotEls[metric] = $(".v", tile);
  }
  const pilotSection = h("div", { style: "display:none" },
    h("h2", {}, "Autopilot"), pilotTiles);
  view.append(pilotSection);

  /* time-model tile row (PR 12 roofline conformance): live HBM
     watermark vs the DX2xx footprint, the DX520 device-step ratio
     against the calibrated roofline, and on-demand profiler captures —
     hidden until the host emits any of the series */
  const TIMEMODEL_METRICS = [
    ["Hbm_BytesInUse", "HBM in use (B)"],
    ["Hbm_PeakBytes", "HBM peak (B)"],
    ["Conformance_Hbm_Ratio", "HBM vs model"],
    ["Conformance_StageTime_DeviceStep_Ratio", "device-step vs roofline"],
    ["Calib_DispatchOverheadUs", "dispatch overhead (µs)"],
    ["Profiler_Captures_Count", "profiler captures"],
  ];
  const tmTiles = h("div", { class: "tiles" });
  const tmEls = {};
  for (const [metric, label] of TIMEMODEL_METRICS) {
    const tile = h("div", { class: "tile" },
      h("div", { class: "k" }, label),
      h("div", { class: "v" }, "–"));
    tmTiles.append(tile);
    tmEls[metric] = $(".v", tile);
  }
  const tmSection = h("div", { style: "display:none" },
    h("h2", {}, "Time model"), tmTiles);
  view.append(tmSection);
  const stageChartBox = h("div", {});
  view.append(stageChartBox);
  const STAGE_PCTL = "p95";
  const stageChart = multiChart(
    stageChartBox, `Per-stage latency ${STAGE_PCTL} (ms)`, STAGES);
  const stageKeyOf = {};  // metric -> stage
  for (const s of STAGES) stageKeyOf[`${stageMetric(s)}-${STAGE_PCTL}`] = s;

  const tiles = h("div", { class: "tiles" });
  const charts = h("div", {});
  view.append(h("h2", {}, "Engine metrics"), tiles, charts);

  const tileEls = {};   // metric -> value el
  const chartEls = {};  // metric -> chart handle
  const latest = {};

  const routePoint = (metric, point) => {
    /* percentile series feed the dedicated tiles/stage chart instead of
       spawning one generic chart per metric (24 series otherwise) */
    if (pctlEls[metric]) {
      pctlEls[metric].childNodes[0].textContent = fmtVal(point.val);
      return true;
    }
    if (pilotEls[metric]) {
      pilotSection.style.display = "";
      pilotEls[metric].textContent = fmtVal(point.val);
      return true;
    }
    if (tmEls[metric]) {
      tmSection.style.display = "";
      tmEls[metric].textContent = fmtVal(point.val);
      return true;
    }
    if (stageKeyOf[metric]) {
      stageChart.push(stageKeyOf[metric], point.uts, point.val);
      return true;
    }
    return LATENCY_PCTL_RE.test(metric);  // other pctls: tracked, unplotted
  };

  const ensure = async (metric) => {
    if (chartEls[metric]) return;
    const tile = h("div", { class: "tile" },
      h("div", { class: "k" }, metric),
      h("div", { class: "v" }, "–"));
    tiles.append(tile);
    tileEls[metric] = $(".v", tile);
    chartEls[metric] = lineChart(charts, metric);
    const history = await fetch(
      `/metrics/history?key=${encodeURIComponent(prefix + metric)}`).then((r) => r.json());
    chartEls[metric].seed(history.slice(-300));
  };

  const seedLatency = async (metric) => {
    const history = await fetch(
      `/metrics/history?key=${encodeURIComponent(prefix + metric)}`).then((r) => r.json());
    if (!history.length) return;
    if (stageKeyOf[metric]) {
      stageChart.seed(stageKeyOf[metric], history.slice(-300));
    }
    routePoint(metric, history[history.length - 1]);
  };

  const seedPilot = async (metric) => {
    const history = await fetch(
      `/metrics/history?key=${encodeURIComponent(prefix + metric)}`).then((r) => r.json());
    if (history.length) routePoint(metric, history[history.length - 1]);
  };

  const keys = await fetch(`/metrics/keys?prefix=${encodeURIComponent(prefix)}`)
    .then((r) => r.json());
  await Promise.all(keys.sort().map((k) => {
    const metric = k.slice(prefix.length);
    if (pilotEls[metric]) return seedPilot(metric);
    return LATENCY_PCTL_RE.test(metric) ? seedLatency(metric) : ensure(metric);
  }));

  const alertedMetrics = new Set();
  async function pollAlerts() {
    let payload;
    try {
      payload = await fetch(`/alerts?flow=${encodeURIComponent(flow)}`)
        .then((r) => (r.ok ? r.json() : null));
    } catch { return; }
    if (!payload) return;
    const firing = payload.firing || [];
    alertBox.replaceChildren();
    alertedMetrics.clear();
    if (firing.length) {
      alertBox.append(h("div", { class: "card alert-firing" },
        h("div", { class: "chart-title" },
          `⚠ ${firing.length} alert(s) firing`),
        firing.map((a) => h("div", { class: "alert-row" },
          h("span", { class: "mono" }, `${a.severity || "warn"}: ${a.name}`),
          ` — ${a.description || a.metric || ""}`))));
      for (const a of firing) if (a.metric) alertedMetrics.add(a.metric);
    }
    for (const [metric, el] of Object.entries(tileEls)) {
      const tile = el.closest(".tile");
      if (tile) tile.classList.toggle("alerting", alertedMetrics.has(metric));
    }
  }
  pollAlerts();
  const alertTimer = setInterval(pollAlerts, 5000);
  liveFeeds.push({ close: () => clearInterval(alertTimer) });

  const es = new EventSource(`/metrics/stream?prefix=${encodeURIComponent(prefix)}`);
  liveFeeds.push(es);
  es.addEventListener("datapoints", async (ev) => {
    const { key, member } = JSON.parse(ev.data);
    const metric = key.slice(prefix.length);
    let point;
    try { point = JSON.parse(member); } catch { return; }
    if (typeof point.val !== "number") return;
    if (routePoint(metric, point)) return;
    await ensure(metric);
    latest[metric] = point.val;
    tileEls[metric].textContent = fmtVal(point.val);
    chartEls[metric].push(point.uts, point.val);
  });
});

/* ---------------- jobs (datax-jobs) ---------------- */
route("#/jobs", async (view) => {
  view.append(h("h1", {}, "Jobs"));
  const jobs = await api("GET", "/api/flow/job/getall");
  const body = h("tbody", {}, jobs.map((j) => h("tr", {},
    h("td", { class: "mono" }, j.name),
    h("td", {}, h("span", { class: `status ${(j.state || "idle").toLowerCase()}` }, j.state || "idle")),
    h("td", {}, j.flow || ""),
    h("td", {},
      h("button", {
        class: "ghost", onclick: async () => {
          await api("POST", "/api/flow/flow/startjobs", { flowName: j.flow });
          toast("start requested"); render();
        },
      }, "start"), " ",
      h("button", {
        class: "ghost", onclick: async () => {
          await api("POST", "/api/flow/flow/stopjobs", { flowName: j.flow });
          toast("stop requested"); render();
        },
      }, "stop")))));
  view.append(h("table", { class: "grid" },
    h("thead", {}, h("tr", {},
      h("th", {}, "Job"), h("th", {}, "State"), h("th", {}, "Flow"), h("th", {}, "Actions"))),
    body));
  view.append(h("div", { class: "row" },
    h("button", {
      class: "ghost", onclick: async () => {
        await api("POST", "/api/flow/job/syncall", {});
        toast("synced"); render();
      },
    }, "Sync states")));
});

/* ---------------- fleet (cross-replica rollup) ---------------- */
route("#/fleet", async (view, hash) => {
  const flow = hash.split("/")[2];
  if (flow) return fleetFlowView(view, decodeURIComponent(flow));
  view.append(h("h1", {}, "Fleet"));
  let summary;
  try {
    summary = await api("GET", "/api/flow/fleet/metrics");
  } catch (e) {
    view.append(h("div", { class: "card" },
      "Fleet view unavailable — the control plane needs an object " +
      `store (objectstore=) to aggregate telemetry frames. (${e.message})`));
    return;
  }
  const flows = summary.flows || {};
  const names = Object.keys(flows).sort();
  if (!names.length) {
    view.append(h("div", { class: "card" },
      "No telemetry frames yet. Replica hosts publish one frame per " +
      "window once a flow with fleet publishing runs."));
  } else {
    view.append(h("table", { class: "grid" },
      h("thead", {}, h("tr", {},
        h("th", {}, "Flow"), h("th", {}, "Replicas"), h("th", {}, "Live"),
        h("th", {}, "Stale"), h("th", {}, "Completed"),
        h("th", {}, "Alerts"), h("th", {}, "Audit"))),
      h("tbody", {}, names.map((n) => {
        const f = flows[n];
        const statuses = Object.values(f.replicas || {}).map((r) => r.status);
        const count = (s) => statuses.filter((x) => x === s).length;
        const counts = (f.audit || {}).counts || {};
        const bad = Object.values(counts).some((c) => c > 0);
        return h("tr", {},
          h("td", {}, h("a", { href: `#/fleet/${encodeURIComponent(n)}` }, n)),
          h("td", {}, String(statuses.length)),
          h("td", {}, String(count("live"))),
          h("td", {}, String(count("stale"))),
          h("td", {}, String(count("completed"))),
          h("td", {}, String((f.alerts || []).length || 0)),
          h("td", {}, h("span", { class: bad ? "status failed" : "status running" },
            bad ? Object.entries(counts).filter(([, c]) => c > 0)
              .map(([code, c]) => `${code}×${c}`).join(" ") : "conserved")));
      }))));
  }
  view.append(h("div", { class: "row mono" },
    `frame decode errors: ${summary.decodeErrors ?? 0}`,
    ` · last merge: ${summary.mergeMs ?? 0} ms`));
});

async function fleetFlowView(view, flow) {
  view.append(h("h1", {}, `Fleet: ${flow}`));
  const f = await api("GET", `/api/flow/fleet/flows/${encodeURIComponent(flow)}`);
  const reps = f.replicas || {};
  view.append(h("h2", {}, "Replicas"));
  view.append(h("table", { class: "grid" },
    h("thead", {}, h("tr", {},
      h("th", {}, "Replica"), h("th", {}, "Status"), h("th", {}, "Frames"),
      h("th", {}, "Batches"), h("th", {}, "Windows"), h("th", {}, "Last seen"))),
    h("tbody", {}, Object.keys(reps).sort().map((name) => {
      const r = reps[name];
      const cls = { live: "running", completed: "idle", stale: "failed" }[r.status] || "idle";
      return h("tr", {},
        h("td", { class: "mono" }, name),
        h("td", {}, h("span", { class: `status ${cls}` }, r.status)),
        h("td", {}, String(r.frames ?? 0)),
        h("td", {}, String(r.batches ?? 0)),
        h("td", { class: "mono" }, (r.windows || []).join("–")),
        h("td", {}, r.lastSeenMs ? new Date(r.lastSeenMs).toLocaleTimeString() : "–"));
    }))));
  const hists = f.histograms || {};
  if (Object.keys(hists).length) {
    view.append(h("h2", {}, "Merged stage latency"));
    view.append(h("table", { class: "grid" },
      h("thead", {}, h("tr", {},
        h("th", {}, "Stage"), h("th", {}, "Count"),
        h("th", {}, "p50"), h("th", {}, "p95"), h("th", {}, "p99"))),
      h("tbody", {}, Object.keys(hists).sort().map((s) => h("tr", {},
        h("td", { class: "mono" }, s),
        h("td", {}, String(hists[s].count)),
        h("td", {}, `${hists[s].p50} ms`),
        h("td", {}, `${hists[s].p95} ms`),
        h("td", {}, `${hists[s].p99} ms`))))));
  }
  const lineage = f.lineage || [];
  if (lineage.length) {
    view.append(h("h2", {}, "Lineage"));
    view.append(h("div", { class: "card mono" }, lineage.map((l, i) =>
      h("div", {}, `${i ? "└→ " : ""}${l.replica}` +
        (l.status ? ` [${l.status}]` : l.state ? ` [${l.state}]` : "")))));
  }
  const audit = f.audit || {};
  view.append(h("h2", {}, "Delivery conservation"));
  view.append(h("div", { class: "card" },
    h("div", { class: "mono" }, `ingested: ${JSON.stringify(audit.ingested || {})}`),
    h("div", { class: "mono" }, `emitted: ${JSON.stringify(audit.emitted || {})}`),
    h("div", {}, audit.conserved
      ? h("span", { class: "status running" }, "conserved")
      : h("span", { class: "status failed" }, "NOT conserved")),
    (audit.events || []).map((e) => h("div", { class: "alert-row mono" },
      `${e.code}: ${e.name || ""} ${e.description || ""}`))));
  const firing = f.alerts || [];
  if (firing.length) {
    view.append(h("h2", {}, "Fleet alerts"));
    view.append(h("div", { class: "card alert-firing" },
      firing.map((a) => h("div", { class: "alert-row" },
        h("span", { class: "mono" }, `${a.severity || "warn"}: ${a.name}`),
        ` — ${a.description || a.metric || ""}`))));
  }
}

render();
