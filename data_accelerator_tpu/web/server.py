"""Website server: static SPA + API proxy + live metric feed.

reference: Website/Website — an Express server that (a) serves the
composed React packages per ``web.composition.json``, (b) proxies REST
calls to the Gateway, and (c) polls Redis sorted sets every 700 ms,
emitting ``datapoints`` over socket.io rooms per metric key
(metrics/dataProxy/redisProxy.js:21-52,71-80) with a
``zrangebyscore`` history backfill on init.

TPU-native stand-in: one ThreadingHTTPServer.

- ``/``, ``/static/*``      — the SPA (static/ directory).
- ``/api/*``                — forwarded to the Gateway (HTTP) or
                              dispatched in-process against a DataXApi
                              (the one-box wiring, like the reference's
                              DATAX_ENABLE_ONEBOX local mode).
- ``/metrics/stream``       — Server-Sent Events; every MetricStore
                              zadd is pushed as a ``datapoints`` event
                              (push replaces the reference's 700 ms
                              poll — the store publishes on write).
- ``/metrics/history``      — zrangebyscore backfill for a key.
- ``/metrics/keys``         — known metric keys by prefix.
- ``/metrics``              — Prometheus text exposition (per-stage
                              latency histograms + latest gauge values;
                              obs/exposition.py renders it, same dialect
                              as every runtime host's own endpoint).
- ``/alerts``               — alert rules + firing set of every
                              registered AlertEngine (``?flow=`` to
                              filter); the SPA's firing-alert
                              annotations poll this.
- ``/healthz``, ``/readyz`` — liveness/readiness probes for the website
                              process itself.
- ``/composition``          — page registry (web.composition.json role).
"""

from __future__ import annotations

import json
import logging
import os
import queue
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple
from urllib.parse import parse_qs, urlparse

from ..obs.exposition import render_prometheus
from ..obs.histogram import HISTOGRAMS
from ..obs.store import METRIC_STORE, MetricStore

logger = logging.getLogger(__name__)

STATIC_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "static")

_CONTENT_TYPES = {
    ".html": "text/html; charset=utf-8",
    ".js": "text/javascript; charset=utf-8",
    ".css": "text/css; charset=utf-8",
    ".json": "application/json",
    ".svg": "image/svg+xml",
    ".png": "image/png",
}

COMPOSITION = {
    "pages": [
        {"name": "home", "displayName": "Flows", "path": "#/flows"},
        {"name": "pipeline", "displayName": "Flow Designer", "path": "#/flow"},
        {"name": "query", "displayName": "Query", "path": "#/query"},
        {"name": "metrics", "displayName": "Metrics", "path": "#/metrics"},
        {"name": "jobs", "displayName": "Jobs", "path": "#/jobs"},
        {"name": "fleet", "displayName": "Fleet", "path": "#/fleet"},
    ]
}


class WebsiteServer:
    """Serves the SPA and bridges it to the control plane + metrics."""

    def __init__(
        self,
        api=None,
        gateway_url: Optional[str] = None,
        gateway_token: Optional[str] = None,
        store: Optional[MetricStore] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        static_dir: Optional[str] = None,
        alerts=None,
        fleet=None,
    ):
        if api is None and gateway_url is None:
            raise ValueError("need an in-process api or a gateway_url")
        self.api = api
        self.gateway_url = gateway_url
        self.gateway_token = gateway_token
        self.store = store if store is not None else METRIC_STORE
        self.static_dir = static_dir or STATIC_DIR
        # obs.fleetview.FleetView: when wired, /metrics appends the
        # fleet rollup (datax_fleet_*) to the per-process exposition
        self.fleet = fleet
        # obs.alerts.AlertEngine instances (one per flow) whose firing
        # sets the SPA annotates; register_alerts() adds more at runtime
        self.alert_engines = list(alerts or [])
        ws = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):
                logger.debug("web %s", fmt % args)

            def _send(self, status: int, body: bytes, ctype: str) -> None:
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send_json(self, status: int, payload) -> None:
                self._send(
                    status, json.dumps(payload, default=str).encode(),
                    "application/json",
                )

            def _handle(self, method: str) -> None:
                parsed = urlparse(self.path)
                path = parsed.path
                if path.startswith("/api/"):
                    length = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(length) if length else None
                    status, payload = ws.call_api(method, path, parsed.query, body)
                    self._send_json(status, payload)
                elif path == "/metrics/post" and method == "POST":
                    # jobs in local mode POST metric points here instead
                    # of Redis (the localMetricsHttpEndpoint path,
                    # MetricLogger.scala:65-69 -> website)
                    length = int(self.headers.get("Content-Length") or 0)
                    try:
                        points = json.loads(self.rfile.read(length) or b"[]")
                    except ValueError:
                        self._send_json(400, {"error": "invalid JSON"})
                        return
                    n = 0
                    for p in points if isinstance(points, list) else [points]:
                        try:
                            key = f"{p['app']}:{p['metric']}"
                            ws.store.add_point(key, int(p["uts"]), p["value"])
                            n += 1
                        except (KeyError, TypeError, ValueError):
                            continue
                    self._send_json(200, {"stored": n})
                elif path == "/metrics" and method == "GET":
                    # Prometheus scrape: stage histograms (one-box jobs
                    # share the process HISTOGRAMS registry) + the latest
                    # point of every MetricStore key as a gauge
                    body = render_prometheus(HISTOGRAMS, ws.store).encode()
                    if ws.fleet is not None:
                        from ..obs.fleetview import render_fleet_prometheus

                        try:
                            body += render_fleet_prometheus(
                                ws.fleet
                            ).encode()
                        except Exception:  # noqa: BLE001 — scrape survives
                            logger.exception("fleet exposition failed")
                    self._send(
                        200, body,
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif path == "/alerts":
                    q = parse_qs(parsed.query)
                    flow = (q.get("flow") or [""])[0]
                    snaps = [
                        e.snapshot() for e in ws.alert_engines
                        if not flow or e.flow == flow
                    ]
                    self._send_json(200, {
                        "alerts": snaps,
                        "firing": [
                            {**a, "flow": s["flow"]}
                            for s in snaps for a in s["firing"]
                        ],
                    })
                elif path == "/healthz":
                    self._send_json(200, {"status": "ok", "role": "website"})
                elif path == "/readyz":
                    ready = ws.api is not None or ws.gateway_url is not None
                    self._send_json(
                        200 if ready else 503,
                        {"ready": ready, "role": "website"},
                    )
                elif path == "/metrics/stream":
                    self._sse(parse_qs(parsed.query))
                elif path == "/metrics/history":
                    q = parse_qs(parsed.query)
                    key = (q.get("key") or [""])[0]
                    try:
                        lo = float((q.get("from") or ["0"])[0])
                        hi = float((q.get("to") or ["inf"])[0])
                    except ValueError:
                        self._send_json(400, {"error": "bad from/to"})
                        return
                    self._send_json(200, ws.store.points(key, lo, hi))
                elif path == "/metrics/keys":
                    q = parse_qs(parsed.query)
                    prefix = (q.get("prefix") or [""])[0]
                    self._send_json(200, ws.store.keys(prefix))
                elif path == "/composition":
                    self._send_json(200, COMPOSITION)
                else:
                    self._static(path)

            def _static(self, path: str) -> None:
                rel = path.lstrip("/") or "index.html"
                if rel.startswith("static/"):
                    rel = rel[len("static/"):]
                root = os.path.abspath(ws.static_dir)
                full = os.path.abspath(os.path.join(root, rel))
                if os.path.commonpath([full, root]) != root:
                    self._send_json(403, {"error": "forbidden"})
                    return
                if not os.path.isfile(full):
                    # SPA fallback: unknown paths load the app shell
                    full = os.path.join(ws.static_dir, "index.html")
                    if not os.path.isfile(full):
                        self._send_json(404, {"error": "not found"})
                        return
                ext = os.path.splitext(full)[1]
                with open(full, "rb") as f:
                    self._send(
                        200, f.read(),
                        _CONTENT_TYPES.get(ext, "application/octet-stream"),
                    )

            def _sse(self, q: Dict) -> None:
                """Push 'datapoints' events for keys matching ?prefix=
                (socket.io room-per-metric analog)."""
                prefix = (q.get("prefix") or [""])[0]
                feed: "queue.Queue" = queue.Queue(maxsize=1000)

                def on_add(key, score, member):
                    if key.startswith(prefix):
                        try:
                            feed.put_nowait((key, score, member))
                        except queue.Full:
                            pass

                ws.store.subscribe(on_add)
                try:
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    self.end_headers()
                    while True:
                        try:
                            key, score, member = feed.get(timeout=15.0)
                            payload = json.dumps(
                                {"key": key, "score": score, "member": member}
                            )
                            chunk = f"event: datapoints\ndata: {payload}\n\n"
                        except queue.Empty:
                            chunk = ": keepalive\n\n"
                        self.wfile.write(chunk.encode())
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass  # client went away
                finally:
                    ws.store.unsubscribe(on_add)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

        self._server = ThreadingHTTPServer((host, port), Handler)
        # SSE keeps sockets open; don't block shutdown on them
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread: Optional[threading.Thread] = None

    # -- API bridging -----------------------------------------------------
    def call_api(
        self, method: str, path: str, query: str, body: Optional[bytes]
    ) -> Tuple[int, dict]:
        if self.gateway_url:
            url = f"{self.gateway_url.rstrip('/')}{path}"
            if query:
                url += f"?{query}"
            headers = {"Content-Type": "application/json"}
            if self.gateway_token:
                headers["Authorization"] = f"Bearer {self.gateway_token}"
            req = urllib.request.Request(
                url, data=body, headers=headers, method=method
            )
            try:
                with urllib.request.urlopen(req, timeout=30) as resp:
                    return resp.status, json.loads(resp.read() or b"{}")
            except urllib.error.HTTPError as e:
                try:
                    return e.code, json.loads(e.read() or b"{}")
                except ValueError:
                    return e.code, {"error": {"message": str(e)}}
            except (urllib.error.URLError, OSError) as e:
                return 502, {"error": {"message": f"gateway unreachable: {e}"}}
        # one-box: dispatch straight into the in-process DataXApi;
        # strip the gateway's /api/{service} hop (single-service mode)
        parts = path.split("/", 3)  # '', 'api', maybe service, rest
        rest = parts[3] if len(parts) > 3 and parts[2] in (
            "flow", "interactivequery", "schemainference", "livedata"
        ) else path[len("/api/"):]
        parsed_body = None
        if body:
            try:
                parsed_body = json.loads(body)
            except ValueError:
                return 400, {"error": {"message": "invalid JSON body"}}
        return self.api.dispatch(
            method, rest, body=parsed_body, query=parse_qs(query)
        )

    def register_alerts(self, engine) -> None:
        """Register a flow's AlertEngine with the website's ``/alerts``
        surface (one-box hosts running in-process do this; remote hosts
        serve their own /alerts on the observability port)."""
        self.alert_engines.append(engine)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        logger.info("website on :%d", self.port)

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    def serve_forever(self) -> None:
        self._server.serve_forever()
