"""User-defined function tiers.

reference: the extension API surface —
- ``DynamicUDF.Generator0..3`` + per-batch refresh
  (datax-core/.../extension/DynamicUDF.scala:32-45,
  ExtendedUDFHandler.scala:23-112) -> ``JaxUdf`` with ``on_interval``.
- plain JAR UDFs / UDAFs loaded by reflection
  (JarUDFHandler.scala:13-100, SparkJarLoader.scala:24-165) ->
  ``load_udfs_from_conf`` importing ``module:attr`` python paths from the
  same ``datax.job.process.jar.udf.<name>.*`` conf namespace.
- custom aggregates (UserDefinedAggregateFunction) -> ``JaxUdaf`` with a
  segment-reduce over sorted groups.
- the Scala-tier escape hatch for custom kernels -> ``PallasUdf``
  (TPU Pallas kernel with interpreter fallback off-TPU).
- AzureFunctionHandler's per-row external calls -> the
  ``externalfn`` sink kind (runtime/sinks.py), keeping network I/O out
  of the compiled graph by design.
"""

from .api import (
    JaxUdf,
    JaxUdaf,
    PallasUdf,
    UdfRegistry,
    load_udfs_from_conf,
)

__all__ = [
    "JaxUdf",
    "JaxUdaf",
    "PallasUdf",
    "UdfRegistry",
    "load_udfs_from_conf",
]
