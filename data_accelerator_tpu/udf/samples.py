"""Sample UDFs: one per extension tier.

reference: datax-udf-samples/.../{udf/UdfHelloWorld,
udaf/UdafLastThreshold,dynamicudf/DynamicUdfHelloWorld,
normalizer/RemoveInvalidChars}.scala — the reference implementations of
all four extension interfaces, used by its tests and docs. These are the
conf-loadable equivalents (class = data_accelerator_tpu.udf.samples:<attr>).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..compile.exprs import HostStr, is_device
from ..core.config import EngineException
from .api import JaxUdaf, JaxUdf, PallasUdf


class HelloWorldUdf:
    """String-tier sample: ``hello(name)`` -> "Hello <name>".

    reference: UdfHelloWorld.scala — returns a device-deferred string
    template (strings materialize at the sink boundary, so arbitrary
    string construction stays off the device hot path).
    """

    name = "hello"
    is_aggregate = False

    def on_interval(self, batch_time_ms: int) -> bool:
        return False

    def compile_call(self, compiler, e):
        if len(e.args) != 1:
            raise EngineException("hello() takes one argument")
        arg = compiler.compile(e.args[0])
        if not is_device(arg):
            raise EngineException("hello() requires a device argument")
        return HostStr(parts=["Hello ", arg], deps=arg.deps)


def _scale_udf() -> JaxUdf:
    """Dynamic-tier sample: ``scaleby(x)`` multiplies by a factor that
    refreshes per interval (DynamicUdfHelloWorld.scala semantics: the
    generator's initialization captures state refreshed by onInterval)."""
    state = {"factor": 2.0, "refreshes": 0}

    def refresh(batch_time_ms: int) -> bool:
        state["refreshes"] += 1
        return False  # factor stable; flip to True when state changes

    return JaxUdf(
        "scaleby",
        lambda x: x.astype(jnp.float32) * state["factor"],
        out_type="double",
        on_interval=refresh,
    )


scaleby = _scale_udf


def _last_over_threshold(threshold: float = 0.0) -> JaxUdaf:
    """UDAF sample: latest value (by event time) above a threshold within
    each group. reference: UdafLastThreshold.scala:12-58 (stateful
    last-value-by-time aggregate)."""

    def reduce(arg_arrays, seg, capacity, valid_s):
        from ..ops.groupby import segment_aggregate

        value, ts = arg_arrays[0], arg_arrays[1]
        ok = valid_s & (value > threshold)
        neg = jnp.iinfo(jnp.int32).min
        ts_ok = jnp.where(ok, ts.astype(jnp.int32), neg)
        max_ts = segment_aggregate(ts_ok, seg, capacity, "max", valid_s)
        at_max = ok & (ts.astype(jnp.int32) == max_ts[jnp.clip(seg, 0, capacity - 1)])
        v = jnp.where(at_max, value.astype(jnp.float32), -jnp.inf)
        out = segment_aggregate(v, seg, capacity, "max", valid_s)
        return jnp.where(jnp.isfinite(out), out, jnp.zeros_like(out))

    return JaxUdaf("lastabove", reduce, out_type="double")


lastabove = _last_over_threshold


def _anomaly_kernel(x_ref, mu_ref, o_ref):
    """Pallas-tier sample: per-row anomaly score
    ``sigmoid(|x - mu| / (1 + |mu|))`` — an elementwise VPU kernel
    standing in for the reference's custom-Scala scoring UDFs."""
    x = x_ref[...].astype(jnp.float32)
    mu = mu_ref[...].astype(jnp.float32)
    d = jnp.abs(x - mu) / (1.0 + jnp.abs(mu))
    o_ref[...] = 1.0 / (1.0 + jnp.exp(-d))


def anomalyscore() -> PallasUdf:
    return PallasUdf(
        "anomalyscore", _anomaly_kernel, out_type="double",
        out_dtype=jnp.float32,
    )


def remove_invalid_chars(raw: str) -> str:
    """Normalizer-tier sample: strip control chars from raw event text
    before JSON parse. reference: RemoveInvalidChars.scala
    (StringNormalizer trait)."""
    return "".join(ch for ch in raw if ch >= " " or ch in "\t")
