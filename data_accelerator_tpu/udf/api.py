"""UDF tier implementations: jax scalar UDFs, custom aggregates, Pallas
kernels, and conf-driven loading.

Contract with the expression compiler (compile/exprs.py:636): a UDF is
an object with ``compile_call(compiler, func_ast) -> Value``; aggregate
UDFs additionally set ``is_aggregate`` and provide ``reduce(arg_arrays,
seg, capacity, valid_s)`` (consumed by the group-by planner). All device functions must be pure
and traceable — per-batch refresh state arrives through ``on_interval``
which triggers a step re-trace when it reports change (the reference's
``DynamicUDF.onInterval`` refreshed broadcast variables the same way,
ExtendedUDFHandler.scala:39 + CommonProcessorFactory.scala:351-353).
"""

from __future__ import annotations

import importlib
import logging
from typing import Callable, Dict, List, Optional, Sequence, Union

import jax.numpy as jnp

from ..core.config import EngineException, SettingDictionary

logger = logging.getLogger(__name__)


class JaxUdf:
    """Scalar (row-wise) device UDF: ``fn(*arrays) -> array``.

    ``out_type``: result type name, or callable(arg_types)->type.
    ``on_interval``: optional ``fn(batch_time_ms) -> bool`` returning
    True when captured state changed (forces step re-trace).
    reference: DynamicUDF.Generator0..3 (arity implied by the SQL call).
    """

    is_aggregate = False

    def __init__(
        self,
        name: str,
        fn: Callable,
        out_type: Union[str, Callable[[List[str]], str]] = "double",
        on_interval: Optional[Callable[[int], bool]] = None,
    ):
        self.name = name
        self.fn = fn
        self.out_type = out_type
        self._on_interval = on_interval

    def on_interval(self, batch_time_ms: int) -> bool:
        if self._on_interval is None:
            return False
        return bool(self._on_interval(batch_time_ms))

    def compile_call(self, compiler, e):
        from ..compile.exprs import CompiledExpr, is_device

        args = [compiler.compile(a) for a in e.args]
        bad = [a for a in args if not is_device(a)]
        if bad:
            raise EngineException(
                f"UDF {self.name} requires device-typed arguments"
            )
        arg_types = [a.type for a in args]
        out_t = (
            self.out_type(arg_types) if callable(self.out_type) else self.out_type
        )
        fn = self.fn

        def run(env):
            return fn(*[a.fn(env) for a in args])

        deps = tuple(d for a in args for d in a.deps)
        return CompiledExpr(out_t, run, deps=deps)


class JaxUdaf:
    """Custom aggregate: reduces each sorted group segment to one value.

    ``reduce(vals: [args x n], seg, capacity, valid_s) -> [capacity]``
    where ``vals`` are the compiled argument arrays re-ordered into
    group-sorted order. reference: UserDefinedAggregateFunction tier
    (JarUDFHandler registerJavaUDAF, SparkJarLoader.scala:139-165).
    """

    is_aggregate = True

    def __init__(
        self,
        name: str,
        reduce: Callable,
        out_type: Union[str, Callable[[List[str]], str]] = "double",
    ):
        self.name = name
        self.reduce = reduce
        self.out_type = out_type

    def result_type(self, arg_types: List[str]) -> str:
        return (
            self.out_type(arg_types) if callable(self.out_type) else self.out_type
        )

    def on_interval(self, batch_time_ms: int) -> bool:
        return False

    def compile_call(self, compiler, e):
        # non-grouped use: reduce over the whole (valid) batch is not
        # supported yet — match the reference, where UDAFs appear with
        # GROUP BY
        raise EngineException(
            f"aggregate UDF {self.name} requires a GROUP BY context"
        )


class PallasUdf(JaxUdf):
    """JaxUdf whose body is a Pallas TPU kernel.

    ``kernel(*refs)``: standard pallas kernel over 1-D row blocks; built
    with interpret=True automatically off-TPU so the same flow runs on
    the CPU one-box. The escape hatch the reference provides via custom
    Scala UDFs compiled into the job JAR (datax-udf-samples/) — here the
    user ships a Pallas kernel instead and keeps MXU/VPU control.
    """

    def __init__(
        self,
        name: str,
        kernel: Callable,
        out_type: str = "double",
        out_dtype=jnp.float32,
        block_rows: int = 1024,
        on_interval: Optional[Callable[[int], bool]] = None,
    ):
        self.kernel = kernel
        self.out_dtype = out_dtype
        self.block_rows = block_rows

        def fn(*arrays):
            return self._pallas_call(*arrays)

        super().__init__(name, fn, out_type, on_interval)

    def _pallas_call(self, *arrays):
        import jax
        from jax.experimental import pallas as pl

        n = arrays[0].shape[0]
        block = min(self.block_rows, n)
        grid = (n + block - 1) // block
        interpret = jax.default_backend() != "tpu"
        return pl.pallas_call(
            self.kernel,
            out_shape=jax.ShapeDtypeStruct((n,), self.out_dtype),
            grid=(grid,),
            in_specs=[
                pl.BlockSpec((block,), lambda i: (i,)) for _ in arrays
            ],
            out_specs=pl.BlockSpec((block,), lambda i: (i,)),
            interpret=interpret,
        )(*arrays)


class UdfRegistry:
    """name(lowercase) -> UDF object; the dict handed to FlowProcessor."""

    def __init__(self, udfs: Optional[Dict[str, object]] = None):
        self._udfs: Dict[str, object] = dict(udfs or {})
        self.last_errors: List[str] = []

    def register(self, udf) -> None:
        self._udfs[udf.name.lower()] = udf

    def as_dict(self) -> Dict[str, object]:
        return dict(self._udfs)

    def refresh(self, batch_time_ms: int) -> bool:
        """Run every UDF's interval hook; True if any state changed
        (caller re-traces the step). reference: udf.onInterval invocation
        at CommonProcessorFactory.scala:351-353.

        A throwing hook must not kill the batch loop: that refresh is
        skipped (the previous trace keeps serving, with its previous
        state) and the UDF's name lands in ``last_errors`` so the host
        can emit the ``UdfRefreshError`` metric."""
        changed = False
        self.last_errors = []
        for name, udf in self._udfs.items():
            hook = getattr(udf, "on_interval", None)
            if hook is None:
                continue
            try:
                if hook(batch_time_ms):
                    changed = True
            except Exception:  # noqa: BLE001 — user refresh hook
                logger.exception(
                    "on_interval failed for UDF %s; skipping refresh and "
                    "keeping the previous trace", name,
                )
                self.last_errors.append(name)
        return changed


def _import_attr(path: str):
    """``package.module:attr`` -> python object (reflection-load analog,
    ClassLoaderHost/SparkJarLoader)."""
    if ":" in path:
        mod_name, attr = path.split(":", 1)
    else:
        mod_name, attr = path.rsplit(".", 1)
    mod = importlib.import_module(mod_name)
    obj = mod
    for part in attr.split("."):
        obj = getattr(obj, part)
    return obj


def load_udfs_from_conf(dict_: SettingDictionary) -> Dict[str, object]:
    """Load UDFs/UDAFs declared in job conf.

    Conf shape (same namespaces the reference's flattener emits):
      datax.job.process.jar.udf.<name>.class  = pkg.mod:attr
      datax.job.process.jar.udaf.<name>.class = pkg.mod:attr
    The attr is either a UDF object or a zero-arg factory returning one.

    Registration is case-insensitive, so a name declared twice (across
    the udf/udaf tiers or differing only in case) would silently
    last-win, and a name matching an engine builtin would never be
    called (the compiler resolves builtins first) — both are rejected
    with a typed ``EngineException`` instead.
    """
    # lazy: analysis owns the builtin-function registry the compiler
    # resolves before UDFs (analysis/typeprop.py BUILTIN_FNS)
    from ..analysis.typeprop import BUILTIN_FNS

    out: Dict[str, object] = {}
    declared_as: Dict[str, str] = {}  # lowercase name -> "tier 'Name'"
    for tier in ("udf", "udaf"):
        ns = f"datax.job.process.jar.{tier}."
        grouped = dict_.get_sub_dictionary(ns).group_by_sub_namespace()
        for name, sub in grouped.items():
            cls_path = sub.get("class")
            if not cls_path:
                continue
            key = name.lower()
            if key in declared_as:
                raise EngineException(
                    f"duplicate UDF name: {tier} '{name}' is already "
                    f"declared as {declared_as[key]} (names are "
                    "case-insensitive; last-wins would silently shadow "
                    "the first)"
                )
            if name.upper() in BUILTIN_FNS:
                raise EngineException(
                    f"{tier} '{name}' shadows the engine builtin "
                    f"{name.upper()}: the compiler resolves builtins "
                    "first, so this UDF would never be called — rename it"
                )
            declared_as[key] = f"{tier} '{name}'"
            try:
                obj = _import_attr(cls_path)
                if isinstance(obj, type) or not hasattr(obj, "compile_call"):
                    obj = obj()  # class or factory -> instance
            except Exception as e:  # noqa: BLE001 — conf-driven load
                raise EngineException(
                    f"cannot load {tier} '{name}' from '{cls_path}': {e}"
                ) from e
            if not hasattr(obj, "compile_call"):
                raise EngineException(
                    f"{tier} '{name}' ({cls_path}) is not a UDF object"
                )
            obj.name = name
            out[key] = obj
            logger.info("registered %s %s from %s", tier, name, cls_path)
    return out
