"""Typed diagnostics for the flow static analyzer.

Every finding the analyzer emits is a ``Diagnostic`` carrying a stable
``DXnnn`` code, a severity, the table (view) it concerns, a message and
a source ``Span`` into the transform script that was analyzed. The code
registry below is the single source of truth — ``ANALYSIS.md`` is
generated from the same one-line cause/fix strings, and tests assert
codes (not messages), so wording can improve without breaking callers.

reference: the platform promise in PAPER.md §1 — design-time services
(SqlParser/Analyzer, schema inference, codegen validation) catch a bad
flow before the job is deployed.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional

SEV_ERROR = "error"
SEV_WARNING = "warning"


@dataclass(frozen=True)
class Span:
    """1-based location in the analyzed transform script.

    ``line`` is the first line of the statement; ``col`` is the 1-based
    character offset within the statement text (statements are joined to
    one logical line by the transform parser, so ``col`` indexes that
    joined text); ``end_line`` closes multi-line statements.
    """

    line: int = 0
    col: int = 1
    end_line: Optional[int] = None

    def to_dict(self) -> dict:
        d = {"line": self.line, "col": self.col}
        if self.end_line is not None:
            d["endLine"] = self.end_line
        return d


@dataclass(frozen=True)
class Diagnostic:
    code: str  # "DX001"
    severity: str  # SEV_ERROR | SEV_WARNING
    table: str  # view/table the finding concerns ("" = flow-level)
    message: str
    span: Span = Span()

    @property
    def is_error(self) -> bool:
        return self.severity == SEV_ERROR

    def to_dict(self) -> dict:
        return {
            "code": self.code,
            "severity": self.severity,
            "table": self.table,
            "message": self.message,
            "span": self.span.to_dict(),
        }

    def render(self) -> str:
        loc = f" (line {self.span.line})" if self.span.line else ""
        tbl = f" [{self.table}]" if self.table else ""
        return f"{self.severity.upper()} {self.code}{tbl} {self.message}{loc}"


# ---------------------------------------------------------------------------
# Code registry: code -> (default severity, one-line cause, one-line fix).
# Pass 1 reference resolution DX00x · pass 2 type propagation DX01x ·
# pass 3 aggregation/window legality DX02x · pass 4 dead flow DX03x ·
# pass 5 device-compilation risk DX04x.
# ---------------------------------------------------------------------------
CODES: Dict[str, tuple] = {
    # -- pass 1: reference resolution -----------------------------------
    "DX001": (SEV_ERROR, "FROM/JOIN references a table no statement or input source defines",
              "define the view earlier in the script, or declare the input source/TIMEWINDOW projecting it"),
    "DX002": (SEV_ERROR, "column is not produced by any table in the statement's FROM scope",
              "check spelling against the input schema / upstream view's select list"),
    "DX003": (SEV_ERROR, "OUTPUT routes a dataset no transform statement produces",
              "name an assigned view in the OUTPUT statement (the job would deploy producing nothing)"),
    "DX004": (SEV_ERROR, "OUTPUT routes to a sink the flow's outputs section does not declare",
              "add the sink under gui.outputs, or route to the built-in Metrics sink"),
    "DX005": (SEV_ERROR, "view referenced before its definition (cyclic dependency)",
              "reorder the statements, or back the cycle with a --DataXStates-- accumulation table"),
    "DX006": (SEV_ERROR, "function is neither an engine builtin nor a declared UDF/UDAF",
              "declare it under gui.process.functions or fix the name"),
    "DX007": (SEV_ERROR, "duplicate output column name in one select list",
              "alias one of the colliding select items"),
    "DX008": (SEV_ERROR, "statement does not parse in the DataXQuery SQL subset",
              "fix the syntax at the reported offset"),
    "DX009": (SEV_ERROR, "TIMEWINDOW targets a table that is not a projected input",
              "window the main projection or a declared source target table"),
    # -- pass 2: type propagation ---------------------------------------
    "DX010": (SEV_ERROR, "operands of a comparison/arithmetic op have incompatible types",
              "cast one side explicitly, or compare like-typed columns"),
    "DX011": (SEV_ERROR, "join keys on the two sides of ON have disagreeing types",
              "cast one key, or join on like-typed columns"),
    "DX012": (SEV_ERROR, "CAST of a literal that cannot convert to the target type",
              "fix the literal or the CAST target"),
    # -- pass 3: aggregation/window legality ----------------------------
    "DX020": (SEV_ERROR, "aggregate function used outside an aggregation context (WHERE/ON/GROUP BY)",
              "move the aggregate into the select list or HAVING of a GROUP BY statement"),
    "DX021": (SEV_WARNING, "TIMEWINDOW retention exceeds the configured state capacity budget",
              "shorten the window, raise the batch interval, or lower the batch capacity"),
    "DX022": (SEV_ERROR, "accumulation table misuse: never updated, or update columns disagree with its DDL",
              "assign the state table from a query whose output columns match the CREATE TABLE schema"),
    # -- pass 4: dead flow ----------------------------------------------
    "DX030": (SEV_WARNING, "view is computed but never reaches a sink, metric, accumulator or downstream view",
              "OUTPUT it, reference it downstream, or delete the statement"),
    "DX031": (SEV_WARNING, "flow routes nothing to any sink or accumulator",
              "add an OUTPUT statement so the job produces something"),
    # -- pass 5: device-compilation risk --------------------------------
    "DX040": (SEV_WARNING, "ORDER BY over a computed string sorts on the host (device round-trip per batch)",
              "sort on a device column, or accept the host-side finishing cost"),
    "DX041": (SEV_ERROR, "string-op argument must be constant: dictionary tables are keyed on it",
              "use a literal pattern/position (column-valued patterns have no device tier)"),
    "DX042": (SEV_ERROR, "string function over a computed string (CONCAT/CAST result) is unsupported on device",
              "apply the function to the inputs before concatenating"),
    # -- pass 6: device plan (analysis/deviceplan.py, the --device tier:
    #    abstract interpretation of the compiled plan's static shapes) --
    "DX200": (SEV_WARNING, "declared group-key cardinality exceeds the static group capacity: groups beyond the bound drop",
              "raise process.maxgroups above the key cardinality, or group by a lower-cardinality key"),
    "DX201": (SEV_WARNING, "join output capacity is below the left input capacity: even one match per row overflows and rows drop",
              "raise process.joincapacity to at least the left side's batch/window capacity"),
    "DX202": (SEV_WARNING, "string dictionary capacity is below the declared/sampled key cardinality: over-capacity keys collapse to NULL",
              "raise process.stringdictionary.maxsize above the distinct string-value count"),
    "DX203": (SEV_WARNING, "non-equi join terms force the O(n*m) match matrix at window scale",
              "add an equality conjunct carrying the selectivity, shrink the window, or bound the pair budget"),
    "DX204": (SEV_WARNING, "recompilation hazard: refresh-capable UDF or unbounded dictionary growth re-traces the jitted step",
              "bound the dictionary (process.stringdictionary.maxsize) and keep UDF refresh intervals coarse"),
    "DX205": (SEV_WARNING, "window retention approaches the int32 ring-rebase horizon (~24.8 days of relative millis)",
              "shorten the window/watermark well below a quarter of the 2^31 ms horizon"),
    "DX206": (SEV_WARNING, "output capacity exceeds the modeled row count by >64x: the sync stage transfers mostly padding device->host",
              "keep sized output transfer on (process.pipeline.sizedtransfer) or tighten process.maxgroups toward the modeled cardinality"),
    "DX290": (SEV_ERROR, "flow fails device lowering: the planner rejected a statement the runtime would also reject",
              "fix the statement per the planner's message (it is the production compiler's own error)"),
    "DX291": (SEV_WARNING, "device analysis unavailable: no concrete input schema or design-time-unloadable UDF",
              "inline the input schema JSON and declare UDF modules importable on the control plane"),
    # -- pass 8: fleet capacity/interference (analysis/fleetcheck.py,
    #    the --fleet tier: whole-fleet placement analysis over a SET of
    #    flow configs against a fleet spec, consuming the DX2xx cost
    #    model as its placement oracle) ------------------------------
    "DX400": (SEV_ERROR, "fleet oversubscribed: no feasible placement packs every flow's modeled HBM onto the fleet's chips",
              "add chips, shrink flow capacities (batch/window/maxgroups), or stop a co-resident flow"),
    "DX401": (SEV_ERROR, "single flow's modeled HBM footprint exceeds every chip in the fleet: it can never place",
              "lower the flow's batch capacity/window retention/group bounds, or provision chips with more HBM"),
    "DX402": (SEV_WARNING, "placement feasible but a chip lands above the configured headroom fraction: one capacity bump or retrace OOMs it",
              "rebalance by adding chips or shrinking the co-placed flows, or raise headroomFraction deliberately"),
    "DX403": (SEV_WARNING, "aggregate D2H/ICI bandwidth demand across the fleet exceeds the modeled budget: sync stages will contend",
              "stagger batch intervals, shrink output capacities (sized transfer), or raise the spec's bandwidth budgets"),
    "DX410": (SEV_ERROR, "two flows share a checkpoint/state/output directory: restarts corrupt each other's offsets and window state",
              "give each flow a distinct checkpoint dir and sink folder (flow names key the defaults — rename one flow)"),
    "DX411": (SEV_ERROR, "Kafka/EventHub consumer-group collision on overlapping topics: the broker splits records between the flows",
              "set a distinct kafka.groupid/consumerGroup per flow (the default group is shared) or de-overlap topics"),
    "DX412": (SEV_WARNING, "metric series collision: two flows emit under the same DATAX-<app> key so store/dashboard series interleave",
              "rename one flow (the metric app name derives from it) so every series key is unique in the shared store"),
    "DX413": (SEV_WARNING, "observability-port conflict: co-placed flows bind the same process.observability.port on one host",
              "give each co-placed flow a distinct jobObservabilityPort, or 0 for an ephemeral port"),
    # -- pass 7: UDF tracing-safety/purity/determinism (analysis/
    #    udfcheck.py, the --udfs tier: taint-lattice abstract
    #    interpretation of UDF device-function ASTs) -------------------
    "DX300": (SEV_ERROR, "data-dependent Python control flow on a traced value: if/while/short-circuit bool on a tracer raises TracerBoolConversionError under jit",
              "replace the branch with jnp.where/lax.select (or lax.cond) so control flow stays in the traced graph"),
    "DX301": (SEV_ERROR, "host sync point on a traced value: .item()/.tolist()/float()/int()/np.asarray of a tracer fails to concretize under jit",
              "keep the computation in jax.numpy; concretize only outside the jitted step"),
    "DX302": (SEV_WARNING, "impure device function: mutates global/closure state, does I/O, or draws host randomness (time.*/random/np.random) — runs once at trace time, then never again",
              "make the function pure; use jax.random with an explicit key, and move state behind on_interval"),
    "DX303": (SEV_WARNING, "captured mutable state with no on_interval declared: the jitted step bakes the state in at trace time and silently serves stale values",
              "declare on_interval so state changes re-trace the step (DynamicUDF.onInterval semantics), or capture immutable values"),
    "DX304": (SEV_WARNING, "declared out_type disagrees with the return dtype inferred under the type lattice: results decode through the wrong column type",
              "fix out_type (or the return expression) so the declared SQL type matches what the function computes"),
    "DX305": (SEV_ERROR, "Pallas kernel hazard: grid/BlockSpec derived from traced values or pallas_call without out_shape cannot lower",
              "derive grid/BlockSpec from static shapes only and always pass out_shape=jax.ShapeDtypeStruct(...)"),
    "DX310": (SEV_ERROR, "UDF conf entry does not load: bad package.module:attr, non-callable target, or aggregate without reduce",
              "point class/module at an importable UDF object or zero-arg factory; aggregates must provide reduce"),
    # -- pass 10: mesh sharding (analysis/meshcheck.py, the --mesh
    #    tier: static SPMD partition plan over the compiled views —
    #    per-stage shard axis, reshard edges, collective byte model
    #    cross-checked exactly against the Mesh lowering) -------------
    "DX700": (SEV_WARNING, "unshardable stage forces full replication: a global ORDER BY (device or host-side) or a Pallas-kernel UDF call materializes every row on every chip, so the stage gains nothing from more chips",
              "drop the ORDER BY (sinks can sort), push it behind a GROUP BY that shrinks the rows, or rewrite the kernel UDF in jax.numpy so GSPMD can shard it"),
    "DX701": (SEV_WARNING, "resharding between adjacent stages: the same sharded table is gathered onto every chip at two or more stage boundaries, paying the all-gather repeatedly",
              "fold the consumers into one statement, or materialize a shared intermediate view so the gather happens once"),
    "DX702": (SEV_ERROR, "per-chip shard exceeds chip HBM at the requested chip count: the sharded residency plus replicated tables cannot fit one chip",
              "add chips, shrink batch/window/group capacities, or provision chips with more HBM (fleet-spec hbmPerChipBytes)"),
    "DX703": (SEV_WARNING, "predicted ICI bytes/batch exceed the fleet-spec interconnect budget at the batch interval: collectives will dominate the step",
              "group/join on lower-cardinality keys, shrink output capacities, or raise the spec's iciBytesPerSecPerChip deliberately"),
    "DX704": (SEV_WARNING, "scaling cliff: the stage's modeled per-chip cost is flat or worse in the chip count (replicated compute at batch scale, or collective wire growth outpacing the compute shrink)",
              "reshape the stage so rows stay sharded (shard-friendly keys, no full-capacity replication), or stop adding chips past the cliff"),
    "DX705": (SEV_WARNING, "sized output transfer and donated output slots auto-disable under a mesh: every output fetch moves the full padded capacity and no background double-buffering applies",
              "expect full-capacity D2H under the mesh, or keep the flow single-chip until the sharded sized-transfer path exists"),
    "DX790": (SEV_ERROR, "mesh lowering failed or disagrees with the sharding model: the partition plan's closed-form collective bytes do not match what the SPMD partitioner emitted",
              "fix the statement per the lowering error, or regenerate after engine changes — the byte model must match the lowering exactly"),
    "DX791": (SEV_WARNING, "mesh analysis unavailable or unvalidated: no concrete input schema, or fewer than two devices to lower the partition plan against",
              "inline the input schema JSON; run under a multi-device backend (the CLI virtualizes CPU devices) to validate the model"),
    # -- pass 9: compile surface (analysis/compilecheck.py, the
    #    --compile tier: enumerate every jit entry point, lower each
    #    over eval_shape avals, prove the signature set finite and
    #    stable, emit the AOT compile manifest) -----------------------
    "DX600": (SEV_WARNING, "open trace surface: UDF interval refresh or unbounded dictionary growth re-traces the step with new signatures, so the jit cache (and any AOT promise) grows without bound",
              "drop the on_interval refresh or bound the dictionary (process.stringdictionary.maxsize) so the manifest covers every signature the flow can dispatch"),
    "DX601": (SEV_WARNING, "reachable sized-transfer capacity buckets alone exceed the transfer-helper jit cache bound: steady-state LRU eviction recompiles helpers mid-stream",
              "lower the batch capacity (fewer pow2 buckets) or raise process.compile.jitcachecap above the lattice size"),
    "DX602": (SEV_ERROR, "manifest donation/aliasing mismatch: a shipped manifest entry's donated argnums disagree with the runtime's donation contract",
              "regenerate the manifest (--compile emits it); never hand-edit donation patterns — they alias live device buffers"),
    "DX603": (SEV_ERROR, "manifest-vs-lowering drift: a shipped manifest's entries/avals/lowering digests no longer match what this flow compiles to",
              "regenerate the manifest after any flow, schema, capacity or engine change (warm starts from a stale manifest recompile at dispatch, surfacing as Compile_WarmMiss_Count)"),
    "DX690": (SEV_ERROR, "compile-surface lowering failed: the fused step (or a transfer helper) cannot trace/lower over the derived avals",
              "fix the statement per the lowering error (it is the production compiler's own failure, seen early)"),
    "DX691": (SEV_WARNING, "compile-surface analysis unavailable: no concrete input schema, design-time-unloadable UDF, or unreadable reference data",
              "inline the input schema JSON, make UDF modules importable on the control plane, and keep refdata CSVs readable at design time"),
    # -- pass 11: buffer lifetime / concurrency (analysis/racecheck.py,
    #    the --race tier: provenance-lattice abstract interpretation of
    #    the ENGINE'S OWN runtime/lq/pilot modules — the standing CI
    #    race gate against the donated/zero-copy bug class. DX805 is
    #    the runtime half (runtime/sanitizer.py), fired into the
    #    flight recorder, never by the static pass) -------------------
    "DX800": (SEV_ERROR, "donated/pooled buffer view escapes its guarded scope (return, attribute/container store, or cross-thread handoff) without a real copy: the next dispatch donates/reuses the memory under the escaped view — use-after-free, not just stale data",
              "copy before the escape (np.array(x, copy=True) / .copy()), or mark a designed ownership transfer with '# dx-race: owner-handoff <reason>'"),
    "DX801": (SEV_ERROR, "np.asarray/jnp.asarray of an aligned pool/ring buffer outside an annotated allowed-zero-copy site: on the CPU backend this is a zero-copy VIEW of memory the engine will donate or reuse",
              "use a real copy, or annotate the site '# dx-race: allow-zero-copy <reason>' if the view provably dies before the buffer is donated/reused"),
    "DX802": (SEV_ERROR, "shared state raced between the dispatch loop and a background thread: an attribute guarded by a lock elsewhere is mutated without that lock, or two locks are acquired in conflicting orders",
              "take the associated lock around the write (or mark a provably pre-thread path '# dx-race: single-threaded <reason>'); keep lock acquisition order consistent with the device-state lock"),
    "DX803": (SEV_ERROR, "transfer slot re-donated before its land ack: donation of an A/B slot buffer is not dominated by the previous batch's landed-event check, so XLA may free a buffer the background landing thread is still reading",
              "gate the donation on the previous slot's _landed.is_set()/wait() (the slot-rotation contract the compile manifest's donate pattern assumes)"),
    "DX804": (SEV_ERROR, "blocking device sync on a thread the pipeline model requires non-blocking: block_until_ready/device_get/a blocking wait inside a function marked '# dx-race: non-blocking' stalls the dispatch overlap the depth-N window exists to provide",
              "move the sync to the landing thread (collect_counts is the one sanctioned sync point), use the async copy path, or drop the non-blocking marker if the function is genuinely allowed to block"),
    # -- pass 12: exactly-once delivery protocol (analysis/protocheck.py,
    #    the --protocol tier: typed effect-trace extraction over the
    #    engine packages + serve/jobs.py, checked against the declared
    #    ordering-rule table in analysis/protospec.py. DX906 is the
    #    runtime half (runtime/protocolmonitor.py), fired into the
    #    flight recorder, never by the static pass) -------------------
    "DX900": (SEV_ERROR, "durability-before-ack violated: the upstream FIFO is acked before the durable pointer flip, or an os.replace runs without the tmp-file fsync before the rename and the parent-dir fsync after it",
              "move the ack after processor.commit()/the pointer flip; fence every checkpoint rename with fsync(tmp) then os.replace then fsync(dir) (use _durable_replace)"),
    "DX901": (SEV_ERROR, "sink-before-pointer-commit violated: the state-table pointer flips before the sinks accepted the batch, so a replay after a sink failure double-counts the committed rows",
              "dispatch to sinks first and flip the pointer only after dispatch returns (the order StreamingHost._finish_tail and the BatchHost landing tail establish)"),
    "DX902": (SEV_ERROR, "ack-at-most-once-per-batch violated: more than one ack call site on one batch path — a second ack releases a window the failure path still expects to requeue",
              "keep a single ack loop per batch tail; route every early-exit through the same commit point"),
    "DX903": (SEV_ERROR, "requeue-covers-unacked-window violated: a function that acks has no failure handler requeuing the unacked window, or a looped ack is paired with a single-source requeue",
              "requeue every source in the except handler that guards the ack (or mark a delegating wrapper '# dx-proto: requeue-upstream <reason>' when the caller owns the handler)"),
    "DX904": (SEV_ERROR, "effect-outside-requeue-scope: a pre-ack effect sits outside any try whose handler requeues, or a post-ack effect (offset commit / snapshot write) is not declared with a post-commit marker",
              "wrap pre-ack effects in the requeue-guarded try; annotate designed at-least-once tails '# dx-proto: post-commit <reason>' so the inventory pins them"),
    "DX905": (SEV_ERROR, "handoff-pull-before-first-dispatch violated: a rescale dispatches a successor job before pulling/stamping its owned-partition plan, so the replica boots without its state assignment",
              "compute _state_partition_plan and stamp statePartitionsOwned/confOverrides on the record before client.submit"),

    # 11. configuration lattice (analysis/confcheck.py, --conf): the
    #     designer knob -> S400 token -> S650 flat key -> runtime read
    #     chain checked against the ONE typed registry in
    #     analysis/confspec.py. DX1006 is the registry's runtime half
    #     (runtime/confaudit.py flight-records it at host/LQ init).
    "DX1000": (SEV_ERROR, "runtime-read-but-never-producible: a conf read site waits on a key no registry row covers — a dead knob or a typo'd key no generation path can produce",
               "register the key in analysis/confspec.py CONF_REGISTRY (with type/default/chain) or fix the read site's key string"),
    "DX1001": (SEV_WARNING, "generated-but-never-read: a produced conf key (generation stage, control plane or conf file) matches no registry row, or a registered read=True key has no read site — dead conf",
               "delete the production, or register the key (read=False for deliberate reference-parity keys)"),
    "DX1002": (SEV_ERROR, "broken designer->runtime chain: a gui token no generated key carries, or a registered knob whose declared conf key generation never writes — the designer's choice is dropped on the floor",
               "wire the token through S650/S640 to its registered key (or fix the registry row's knob/key chain)"),
    "DX1003": (SEV_WARNING, "default-value drift: a read-site fallback or S400 generation default disagrees with the registry's canonical default — 'unset' behaves differently per layer",
               "align the fallback literal with the registry default (the registry row is the single source of truth)"),
    "DX1004": (SEV_ERROR, "conf type/bounds violation: a concrete flow conf value fails its registry row's type, bounds or choices (pipeline.depth=0, a negative TTL, an HBM budget above the chip)",
               "fix the flow's designer knob / conf value to satisfy the registered type and bounds"),
    "DX1005": (SEV_ERROR, "incompatible conf combination: a declared mutual-exclusion constraint is violated (mesh+sizedtransfer, mesh+backgroundtransfer, state.filteringest without state partitions)",
               "drop one side of the combination — the constraint table in analysis/confspec.py documents why they cannot compose"),
    "DX1006": (SEV_ERROR, "live conf failed the registry audit: the host/LQ service booted with an unknown or out-of-bounds datax.job.process.* key (runtime/confaudit.py)",
               "regenerate the flow's conf (stale key) or fix the out-of-bounds value; the Conf_{Audited,Unknown,OutOfBounds}_Count metrics carry the counts"),
}

# which pass each code family belongs to (for grouping/reporting)
PASS_NAMES = {
    "DX00": "reference resolution",
    "DX01": "type propagation",
    "DX02": "aggregation/window legality",
    "DX03": "dead flow",
    "DX04": "device-compilation risk",
    "DX20": "device plan",
    "DX29": "device plan",
    "DX30": "udf tracing safety",
    "DX31": "udf tracing safety",
    "DX40": "fleet capacity",
    "DX41": "fleet interference",
    "DX60": "compile surface",
    "DX69": "compile surface",
    "DX70": "mesh sharding",
    "DX79": "mesh sharding",
    "DX80": "buffer lifetime/race",
    "DX90": "delivery protocol",
    "DX10": "configuration lattice",
}

# version of every ``--json`` report shape the analysis tiers emit (the
# CLI per-file/fleet reports and the ``flow/validate`` response). Bump
# when top-level keys change so downstream consumers (designer,
# admission gate, CI tooling) can detect report-format drift; a tier-1
# test pins the current key sets against this number.
# v2: the ``mesh`` report block (the --mesh tier's sharding plan).
# v3: the ``race`` report block (the --race tier's engine buffer-
# lifetime/concurrency gate).
# v4: the ``protocol`` report block (the --protocol tier's exactly-
# once delivery-protocol gate).
# v5: the ``conf`` report block (the --conf tier's configuration-
# lattice gate: typed registry + designer->runtime chain).
REPORT_SCHEMA_VERSION = 5


def make(code: str, table: str, message: str, span: Optional[Span] = None,
         severity: Optional[str] = None) -> Diagnostic:
    """Build a diagnostic, defaulting severity from the registry."""
    default_sev = CODES[code][0]
    return Diagnostic(
        code=code,
        severity=severity or default_sev,
        table=table,
        message=message,
        span=span or Span(),
    )


@dataclass
class AnalysisReport:
    diagnostics: List[Diagnostic]

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.is_error]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if not d.is_error]

    @property
    def ok(self) -> bool:
        return not self.errors

    def codes(self) -> List[str]:
        return [d.code for d in self.diagnostics]

    def to_dict(self) -> dict:
        return {
            "schemaVersion": REPORT_SCHEMA_VERSION,
            "ok": self.ok,
            "errorCount": len(self.errors),
            "warningCount": len(self.warnings),
            "diagnostics": [d.to_dict() for d in self.diagnostics],
        }
