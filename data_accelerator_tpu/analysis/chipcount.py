"""One chip-count parser for every surface that accepts N chips.

``--device --chips=N``, ``--mesh --chips=N``, the fleet spec's
``chips`` key and the REST ``"chips"`` body field used to parse and
validate their chip counts separately — a ``--chips=0`` typo was an
unhandled int() somewhere and a silent model-of-nothing somewhere else.
All of them now funnel through :func:`parse_chip_count`, which raises
one typed error (:class:`ChipCountError`, a ``ValueError``) naming the
offending surface, so the CLI exits 2 and the REST layer 400s with the
same message for the same mistake.
"""

from __future__ import annotations

from typing import Optional, Union


class ChipCountError(ValueError):
    """A chip count that is not a positive integer."""


def parse_chip_count(
    value: Union[str, int, float, None], source: str = "--chips"
) -> Optional[int]:
    """Parse a chip count from any surface (CLI flag text, fleet-spec
    JSON number, REST body field). ``None``/empty means "not given" and
    passes through as ``None`` so callers keep their defaults;
    everything else must be a positive integer or :class:`ChipCountError`
    is raised with the ``source`` label in the message."""
    if value is None or value == "":
        return None
    if isinstance(value, bool):  # bool is an int subclass; reject it
        raise ChipCountError(f"{source}: chip count must be an integer")
    try:
        n = int(value)
    except (TypeError, ValueError):
        raise ChipCountError(
            f"{source}: invalid chip count {value!r} (expected a positive "
            f"integer)"
        ) from None
    if isinstance(value, float) and value != n:
        raise ChipCountError(
            f"{source}: invalid chip count {value!r} (expected a positive "
            f"integer)"
        )
    if n < 1:
        raise ChipCountError(
            f"{source}: chip count must be >= 1, got {n}"
        )
    return n
