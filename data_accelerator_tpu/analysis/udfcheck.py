"""UDF static analyzer: tracing-safety, purity and determinism lints.

Third analysis tier (the ``--udfs`` tier). Where ``analyzer.py`` checks
what a flow *means* and ``deviceplan.py`` what its compiled plan will
*cost*, this tier checks what the flow's user code *does*: it resolves
every declared UDF/UDAF through the production loader
(``udf/api.py:load_udfs_from_conf`` — the same reflection path the
runtime jits blind) and abstract-interprets the device functions'
Python ASTs (``inspect.getsource`` + ``ast``) under a two-point taint
lattice: a traced argument is TRACED, anything derived from a traced
value stays TRACED, everything else is HOST. The DX3xx family falls
out of where TRACED values flow:

- **DX300** — TRACED value in a Python control-flow position
  (``if``/``while``/``assert``/short-circuit ``and``/``or``/
  ``range()``): the tracer cannot be collapsed to a Python bool, so
  the deployed job dies with ``TracerBoolConversionError``.
- **DX301** — host sync point (``.item()``, ``.tolist()``,
  ``float()``/``int()``, ``np.asarray``) on a TRACED value:
  ``ConcretizationTypeError`` under ``jax.jit``.
- **DX302** — impurity: mutating global/closure state, I/O,
  ``time.*`` or host randomness (``random``/``np.random`` instead of
  ``jax.random``). Runs ONCE at trace time, then never again — the
  documented "pure and traceable" contract in ``udf/api.py``.
- **DX303** — captured mutable state with no ``on_interval``
  declared: the jitted step bakes the state in at trace time and
  silently serves stale values (the reference's
  ``DynamicUDF.onInterval`` gap).
- **DX304** — declared ``out_type`` inconsistent with the return
  dtype inferred under a small dtype lattice (float/int/bool).
- **DX305** — Pallas kernel hazards: ``pallas_call`` without
  ``out_shape``, or ``grid``/``BlockSpec``/``out_shape`` derived from
  TRACED values.
- **DX310** — the conf entry itself does not load: bad
  ``package.module:attr``, non-callable target, aggregate without
  ``reduce``, duplicate declaration.

Verdicts are ground-truthed, not pattern-matched: for every code,
``tests/test_udfcheck.py`` pairs the golden-fixture analyzer test with
a runtime test asserting the flagged UDF really does raise / retrace /
desync under ``jax.jit`` while its clean twin traces exactly once —
the analyzer cannot drift from what the tracer actually rejects.
"""

from __future__ import annotations

import ast
import inspect
import textwrap
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from ..core.config import EngineException, SettingDictionary
from .diagnostics import Diagnostic, Span, make

# attribute reads that stay static under tracing (safe to branch on)
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "itemsize", "weak_type"}

# method calls that force a device->host sync on a traced receiver
_SYNC_METHODS = {"item", "tolist", "block_until_ready", "copy_to_host"}

# builtins that concretize (DX301) or bool-convert (DX300) a tracer
_HOST_CASTS = {"float", "int", "complex"}
_BOOL_BUILTINS = {"bool", "any", "all", "max", "min", "sorted", "range"}

# container-mutating method names (on a captured object -> impurity)
_MUTATORS = {
    "append", "extend", "insert", "add", "update", "pop", "popitem",
    "remove", "discard", "clear", "setdefault", "write", "writelines",
}

# plain-name calls that do I/O at trace time
_IO_CALLS = {"open", "print", "input"}

# dotted-call prefixes that make a device function nondeterministic or
# wall-clock dependent (jax.random is the sanctioned alternative)
_NONDET_PREFIXES = (
    "time.", "random.", "np.random.", "numpy.random.", "secrets.",
    "uuid.", "os.urandom", "datetime.",
)

# numpy conversion entry points that concretize a tracer
_NP_CONVERTERS = {
    "np.asarray", "np.array", "numpy.asarray", "numpy.array",
    "np.copy", "numpy.copy", "np.float32", "np.float64", "np.int32",
    "np.int64",
}

# declared SQL out_type -> dtype-lattice point
_DECLARED_DTYPE = {
    "double": "float", "float": "float",
    "long": "int", "int": "int", "integer": "int", "bigint": "int",
    "boolean": "bool", "bool": "bool",
}

# jnp/np function name -> result lattice point (by final attr segment)
_FLOAT_FNS = {
    "exp", "expm1", "log", "log1p", "log2", "log10", "sqrt", "cbrt",
    "sin", "cos", "tan", "tanh", "sinh", "cosh", "arcsin", "arccos",
    "arctan", "arctan2", "power", "sigmoid", "softmax", "logaddexp",
    "mean", "var", "std", "linspace",
}
_BOOL_FNS = {
    "isfinite", "isnan", "isinf", "isclose", "logical_and",
    "logical_or", "logical_not", "logical_xor", "equal", "not_equal",
    "greater", "less", "greater_equal", "less_equal",
}
_DTYPE_NAMES = {
    "float16": "float", "bfloat16": "float", "float32": "float",
    "float64": "float", "float_": "float",
    "int8": "int", "int16": "int", "int32": "int", "int64": "int",
    "uint8": "int", "uint16": "int", "uint32": "int", "uint64": "int",
    "int_": "int", "bool_": "bool", "bool": "bool",
}


def _dotted(node: ast.AST) -> str:
    """``pl.pallas_call`` -> "pl.pallas_call"; "" when not a plain
    dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


# ---------------------------------------------------------------------------
# Source resolution: callable -> AST node (+ absolute line numbers)
# ---------------------------------------------------------------------------
def _fn_node(fn) -> Optional[ast.AST]:
    """AST of a function/lambda's definition, or None when source is
    unavailable (C functions, exec'd code). Prefers parsing the whole
    defining file and locating the node by line number — that handles
    lambdas embedded mid-expression and keeps ``Span.line`` pointing at
    real module lines."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return None
    tree = None
    try:
        lines, _ = inspect.findsource(code)
        tree = ast.parse("".join(lines))
    except (OSError, TypeError, SyntaxError):
        tree = None
    if tree is not None:
        want = code.co_firstlineno
        best = None
        for n in ast.walk(tree):
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if n.name != code.co_name:
                    continue
            elif isinstance(n, ast.Lambda):
                if code.co_name != "<lambda>":
                    continue
            else:
                continue
            if n.lineno > want or (n.end_lineno or n.lineno) < want:
                continue
            if best is None or n.lineno > best.lineno:
                best = n
        if best is not None:
            return best
    # fallback: the function's own source block (dynamically defined
    # functions pytest writes to temp files, doctests, ...)
    try:
        src = textwrap.dedent(inspect.getsource(fn)).strip().rstrip(",")
    except (OSError, TypeError):
        return None
    try:
        mod = ast.parse(src)
    except SyntaxError:
        return None
    for n in ast.walk(mod):
        if isinstance(n, (ast.FunctionDef, ast.Lambda)):
            return n
    return None


def _param_names(node: ast.AST) -> List[str]:
    a = node.args
    names = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    if a.vararg:
        names.append(a.vararg.arg)
    if a.kwarg:
        names.append(a.kwarg.arg)
    return [n for n in names if n != "self"]


def _local_names(node: ast.AST) -> set:
    """Every name the function binds locally (params + assignment
    targets) — writes to anything else mutate captured state."""
    out = set(_param_names(node))
    body = node.body if isinstance(node.body, list) else []
    for n in ast.walk(node):
        if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
            out.add(n.id)
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) and n is not node:
            out.add(n.name)
        elif isinstance(n, ast.comprehension):
            for t in ast.walk(n.target):
                if isinstance(t, ast.Name):
                    out.add(t.id)
        elif isinstance(n, (ast.Import, ast.ImportFrom)):
            for alias in n.names:
                out.add((alias.asname or alias.name).split(".")[0])
    del body
    return out


# ---------------------------------------------------------------------------
# The per-function abstract interpreter
# ---------------------------------------------------------------------------
class _FnLinter:
    """One device function's taint walk. ``tainted`` holds names bound
    to traced values; findings dedupe on (code, line, message) so loop
    bodies can be walked twice for a cheap taint fixpoint."""

    def __init__(self, node: ast.AST, udf_name: str, role: str,
                 untraced_params: Sequence[str] = ()):
        self.node = node
        self.udf = udf_name
        self.role = role
        self.tainted = {
            p for p in _param_names(node) if p not in untraced_params
        }
        self.locals = _local_names(node)
        self.escaping: set = set()  # global/nonlocal declarations
        self.dtypes: Dict[str, Optional[str]] = {}
        self.return_dtypes: List[Optional[str]] = []
        self._found: set = set()
        self.diags: List[Diagnostic] = []

    # -- reporting -------------------------------------------------------
    def _emit(self, code: str, node: ast.AST, message: str) -> None:
        key = (code, getattr(node, "lineno", 0), message)
        if key in self._found:
            return
        self._found.add(key)
        self.diags.append(make(
            code, self.udf, f"{self.role}: {message}",
            Span(line=getattr(node, "lineno", 0)),
        ))

    # -- entry -----------------------------------------------------------
    def run(self) -> "_FnLinter":
        if isinstance(self.node, ast.Lambda):
            dt = self._expr(self.node.body)
            self.return_dtypes.append(dt)
        else:
            self._stmts(self.node.body)
            # second pass settles taint that loops feed back
            self._stmts(self.node.body)
        return self

    # -- statements ------------------------------------------------------
    def _stmts(self, body: List[ast.stmt]) -> None:
        for s in body:
            self._stmt(s)

    def _stmt(self, s: ast.stmt) -> None:
        if isinstance(s, (ast.Global, ast.Nonlocal)):
            self.escaping.update(s.names)
        elif isinstance(s, ast.Assign):
            dt = self._expr(s.value)
            taint = self._taint(s.value)
            for t in s.targets:
                self._assign_target(t, taint, dt, s)
        elif isinstance(s, ast.AnnAssign) and s.value is not None:
            dt = self._expr(s.value)
            self._assign_target(s.target, self._taint(s.value), dt, s)
        elif isinstance(s, ast.AugAssign):
            self._expr(s.value)
            taint = self._taint(s.value) or self._taint(s.target)
            self._assign_target(s.target, taint, None, s)
        elif isinstance(s, ast.If):
            self._expr(s.test)
            if self._taint(s.test):
                self._emit(
                    "DX300", s,
                    "`if` on a traced value — the tracer cannot become a "
                    "Python bool (TracerBoolConversionError at runtime); "
                    "use jnp.where/lax.select",
                )
            self._stmts(s.body)
            self._stmts(s.orelse)
        elif isinstance(s, ast.While):
            self._expr(s.test)
            if self._taint(s.test):
                self._emit(
                    "DX300", s,
                    "`while` on a traced value — data-dependent loop "
                    "bounds cannot trace; use lax.while_loop",
                )
            self._stmts(s.body)
            self._stmts(s.orelse)
        elif isinstance(s, ast.For):
            self._expr(s.iter)
            taint = self._taint(s.iter)
            self._assign_target(s.target, taint, None, s)
            self._stmts(s.body)
            self._stmts(s.orelse)
        elif isinstance(s, ast.Assert):
            self._expr(s.test)
            if self._taint(s.test):
                self._emit(
                    "DX300", s,
                    "`assert` on a traced value bool-converts the tracer; "
                    "use checkify or drop the assert",
                )
        elif isinstance(s, ast.Return):
            if s.value is not None:
                self.return_dtypes.append(self._expr(s.value))
                # taint handled by _expr side effects
        elif isinstance(s, ast.Expr):
            self._expr(s.value)
        elif isinstance(s, (ast.With,)):
            for item in s.items:
                self._expr(item.context_expr)
            self._stmts(s.body)
        elif isinstance(s, ast.Try):
            self._stmts(s.body)
            for h in s.handlers:
                self._stmts(h.body)
            self._stmts(s.orelse)
            self._stmts(s.finalbody)
        elif isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
            pass  # nested defs are traced when called; out of scope
        # Import/Pass/etc: nothing to do

    def _assign_target(self, t: ast.expr, taint: bool,
                       dt: Optional[str], stmt: ast.stmt) -> None:
        if isinstance(t, ast.Name):
            if t.id in self.escaping:
                self._emit(
                    "DX302", stmt,
                    f"writes global/nonlocal '{t.id}' — the write runs "
                    "once at trace time, then never again under jit",
                )
            if taint:
                self.tainted.add(t.id)
            else:
                self.tainted.discard(t.id)
            self.dtypes[t.id] = dt
        elif isinstance(t, (ast.Tuple, ast.List)):
            for el in t.elts:
                self._assign_target(el, taint, None, stmt)
        elif isinstance(t, (ast.Subscript, ast.Attribute)):
            base = t.value
            while isinstance(base, (ast.Subscript, ast.Attribute)):
                base = base.value
            if isinstance(base, ast.Name) and base.id not in self.locals:
                self._emit(
                    "DX302", stmt,
                    f"mutates captured object '{base.id}' — state writes "
                    "happen at trace time only; pure functions + "
                    "on_interval refresh is the supported pattern",
                )
            if isinstance(t, ast.Subscript):
                self._expr(t.slice)

    # -- expressions: returns the inferred dtype lattice point ----------
    def _taint(self, e: ast.expr) -> bool:
        """Is this expression derived from a traced value? (Pure
        query — no diagnostics; ``_expr`` must already have walked it.)"""
        if isinstance(e, ast.Name):
            return e.id in self.tainted
        if isinstance(e, ast.Constant):
            return False
        if isinstance(e, ast.Attribute):
            if e.attr in _STATIC_ATTRS:
                return False
            return self._taint(e.value)
        if isinstance(e, ast.Subscript):
            return self._taint(e.value) or self._taint(e.slice)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            return any(self._taint(x) for x in e.elts)
        if isinstance(e, ast.Dict):
            return any(
                self._taint(x) for x in (*e.keys, *e.values) if x is not None
            )
        if isinstance(e, ast.BinOp):
            return self._taint(e.left) or self._taint(e.right)
        if isinstance(e, ast.UnaryOp):
            return self._taint(e.operand)
        if isinstance(e, ast.BoolOp):
            return any(self._taint(v) for v in e.values)
        if isinstance(e, ast.Compare):
            return self._taint(e.left) or any(
                self._taint(c) for c in e.comparators
            )
        if isinstance(e, ast.IfExp):
            return (
                self._taint(e.test) or self._taint(e.body)
                or self._taint(e.orelse)
            )
        if isinstance(e, ast.Call):
            dotted = _dotted(e.func)
            if dotted in _HOST_CASTS or dotted in _NP_CONVERTERS:
                # flagged as a sync point; the RESULT is a host value,
                # so downstream use doesn't re-report
                return False
            return (
                self._taint(e.func)
                or any(self._taint(a) for a in e.args)
                or any(self._taint(k.value) for k in e.keywords)
            )
        if isinstance(e, ast.Starred):
            return self._taint(e.value)
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return any(self._taint(g.iter) for g in e.generators) or \
                self._taint(e.elt)
        if isinstance(e, ast.DictComp):
            return any(self._taint(g.iter) for g in e.generators)
        if isinstance(e, ast.JoinedStr):
            return any(
                self._taint(v.value) for v in e.values
                if isinstance(v, ast.FormattedValue)
            )
        if isinstance(e, ast.Slice):
            return any(
                self._taint(x) for x in (e.lower, e.upper, e.step)
                if x is not None
            )
        return False

    def _expr(self, e: ast.expr) -> Optional[str]:
        """Walk an expression emitting diagnostics; returns its dtype
        lattice point (float/int/bool/None)."""
        if isinstance(e, ast.Constant):
            if isinstance(e.value, bool):
                return "bool"
            if isinstance(e.value, int):
                return "int"
            if isinstance(e.value, float):
                return "float"
            return None
        if isinstance(e, ast.Name):
            return self.dtypes.get(e.id)
        if isinstance(e, ast.Attribute):
            self._expr(e.value)
            return _DTYPE_NAMES.get(e.attr)
        if isinstance(e, ast.Subscript):
            dt = self._expr(e.value)
            self._expr(e.slice) if isinstance(e.slice, ast.expr) else None
            return dt
        if isinstance(e, ast.BinOp):
            l, r = self._expr(e.left), self._expr(e.right)
            if isinstance(e.op, ast.Div):
                return "float"
            return _join_dtype(l, r)
        if isinstance(e, ast.UnaryOp):
            dt = self._expr(e.operand)
            if isinstance(e.op, ast.Not):
                if self._taint(e.operand):
                    self._emit(
                        "DX300", e,
                        "`not` on a traced value bool-converts the "
                        "tracer; use jnp.logical_not",
                    )
                return "bool"
            return dt
        if isinstance(e, ast.BoolOp):
            for v in e.values:
                self._expr(v)
            if any(self._taint(v) for v in e.values):
                self._emit(
                    "DX300", e,
                    "short-circuit and/or on a traced value "
                    "bool-converts the tracer; use & / | "
                    "(jnp.logical_and/or)",
                )
            return "bool"
        if isinstance(e, ast.Compare):
            self._expr(e.left)
            for c in e.comparators:
                self._expr(c)
            return "bool"
        if isinstance(e, ast.IfExp):
            self._expr(e.test)
            if self._taint(e.test):
                self._emit(
                    "DX300", e,
                    "conditional expression on a traced value "
                    "bool-converts the tracer; use jnp.where",
                )
            return _join_dtype(self._expr(e.body), self._expr(e.orelse))
        if isinstance(e, ast.Call):
            return self._call(e)
        if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
            for x in e.elts:
                self._expr(x)
            return None
        if isinstance(e, ast.Dict):
            for x in (*e.keys, *e.values):
                if x is not None:
                    self._expr(x)
            return None
        if isinstance(e, (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                          ast.DictComp)):
            for g in e.generators:
                self._expr(g.iter)
                if self._taint(g.iter):
                    # iterating a tracer unrolls; range(tracer) dies —
                    # both are flagged where the call is made (range)
                    pass
                for t in ast.walk(g.target):
                    if isinstance(t, ast.Name):
                        if self._taint(g.iter):
                            self.tainted.add(t.id)
            if isinstance(e, ast.DictComp):
                self._expr(e.key)
                self._expr(e.value)
            else:
                self._expr(e.elt)
            return None
        if isinstance(e, ast.JoinedStr):
            for v in e.values:
                if isinstance(v, ast.FormattedValue):
                    self._expr(v.value)
            return None
        if isinstance(e, ast.Starred):
            return self._expr(e.value)
        if isinstance(e, ast.Lambda):
            return None  # e.g. BlockSpec index maps — analyzed in place
        if isinstance(e, ast.Slice):
            for x in (e.lower, e.upper, e.step):
                if x is not None:
                    self._expr(x)
            return None
        return None

    # -- calls: where most DX3xx findings live --------------------------
    def _call(self, e: ast.Call) -> Optional[str]:
        dotted = _dotted(e.func)
        args_tainted = (
            any(self._taint(a) for a in e.args)
            or any(self._taint(k.value) for k in e.keywords)
        )

        # walk children first so nested calls report too
        for a in e.args:
            self._expr(a)
        kw = {}
        for k in e.keywords:
            self._expr(k.value)
            if k.arg:
                kw[k.arg] = k.value

        # method-style sync points: x.item(), x.tolist(), ...
        if isinstance(e.func, ast.Attribute):
            if e.func.attr in _SYNC_METHODS and self._taint(e.func.value):
                self._emit(
                    "DX301", e,
                    f".{e.func.attr}() on a traced value forces a host "
                    "sync — ConcretizationTypeError under jit",
                )
            if (
                e.func.attr in _MUTATORS
                and not self._taint(e.func.value)
            ):
                base = e.func.value
                while isinstance(base, (ast.Attribute, ast.Subscript)):
                    base = base.value
                if isinstance(base, ast.Name) and base.id not in self.locals:
                    self._emit(
                        "DX302", e,
                        f"mutating call .{e.func.attr}() on captured "
                        f"object '{base.id}' runs once at trace time "
                        "only",
                    )
            if e.func.attr == "astype":
                self._expr(e.func.value)
                if e.args:
                    return self._dtype_of_node(e.args[0])
                return None

        # builtin concretizers / bool-converters
        if dotted in _HOST_CASTS and args_tainted:
            self._emit(
                "DX301", e,
                f"{dotted}() of a traced value cannot concretize under "
                "jit (ConcretizationTypeError); keep it in jax.numpy",
            )
            return "float" if dotted == "float" else "int"
        if dotted in _BOOL_BUILTINS and args_tainted:
            self._emit(
                "DX300", e,
                f"{dotted}() over a traced value bool-converts tracer "
                "elements; use the jnp equivalent",
            )
            return None
        if dotted in _NP_CONVERTERS and args_tainted:
            self._emit(
                "DX301", e,
                f"{dotted}() of a traced value falls off the device "
                "(TracerArrayConversionError); use jnp instead of np",
            )
            return None

        # impurity: I/O + host randomness/clock
        if dotted in _IO_CALLS:
            self._emit(
                "DX302", e,
                f"{dotted}() is I/O — it runs at trace time, not per "
                "batch",
            )
            return None
        if dotted and not dotted.startswith("jax."):
            for p in _NONDET_PREFIXES:
                if dotted == p.rstrip(".") or dotted.startswith(p):
                    self._emit(
                        "DX302", e,
                        f"{dotted}() draws host entropy/wall-clock at "
                        "trace time — the value freezes into the "
                        "compiled step; use jax.random with an "
                        "explicit key (or on_interval state)",
                    )
                    return None

        # Pallas call-site hazards
        if dotted.endswith("pallas_call") or dotted == "pallas_call":
            self._pallas_call(e, kw)
            return None

        self._expr(e.func)

        # dtype inference for the common jnp constructors/math
        leaf = dotted.rsplit(".", 1)[-1] if dotted else ""
        if leaf in _FLOAT_FNS:
            return "float"
        if leaf in _BOOL_FNS:
            return "bool"
        if leaf in ("zeros", "ones", "full", "empty", "zeros_like",
                    "ones_like", "full_like", "arange"):
            if "dtype" in kw:
                return self._dtype_of_node(kw["dtype"])
            return None
        if leaf == "where" and len(e.args) == 3:
            return _join_dtype(
                self._expr(e.args[1]), self._expr(e.args[2])
            )
        if leaf in ("clip", "abs", "where", "maximum", "minimum"):
            return None
        if leaf in _DTYPE_NAMES and dotted.startswith(("jnp.", "jax.numpy.")):
            return _DTYPE_NAMES[leaf]
        return None

    def _dtype_of_node(self, n: ast.expr) -> Optional[str]:
        d = _dotted(n)
        if d:
            return _DTYPE_NAMES.get(d.rsplit(".", 1)[-1])
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            return _DTYPE_NAMES.get(n.value)
        return None

    def _pallas_call(self, e: ast.Call, kw: Dict[str, ast.expr]) -> None:
        """Hazards at a user-written ``pl.pallas_call`` site."""
        # out_shape: 2nd positional or keyword — required for lowering
        if "out_shape" not in kw and len(e.args) < 2:
            self._emit(
                "DX305", e,
                "pallas_call without out_shape — the kernel has no "
                "output aval to lower against; pass "
                "out_shape=jax.ShapeDtypeStruct(shape, dtype)",
            )
        for key in ("grid", "out_shape", "grid_spec"):
            node = kw.get(key)
            if node is not None and self._taint(node):
                self._emit(
                    "DX305", e,
                    f"pallas_call {key}= derived from a traced value — "
                    "the grid/output spec must be static; derive it "
                    "from .shape, not from array contents",
                )
        # BlockSpec(...) anywhere in the call's arguments
        for sub in ast.walk(e):
            if isinstance(sub, ast.Call):
                d = _dotted(sub.func)
                if d.endswith("BlockSpec") and (
                    any(self._taint(a) for a in sub.args)
                    or any(self._taint(k.value) for k in sub.keywords)
                ):
                    self._emit(
                        "DX305", sub,
                        "BlockSpec derived from a traced value — block "
                        "shapes/index maps must be static",
                    )


def _join_dtype(a: Optional[str], b: Optional[str]) -> Optional[str]:
    if a == b:
        return a
    if {a, b} == {"int", "float"}:
        return "float"
    return None


# ---------------------------------------------------------------------------
# Object-level checks (closure introspection + out_type lattice)
# ---------------------------------------------------------------------------
def _captured_mutable(fn) -> List[str]:
    """Names of mutable containers the function closes over or reads
    from module globals — the state ``on_interval`` exists to refresh."""
    code = getattr(fn, "__code__", None)
    if code is None:
        return []
    out = []
    for var, cell in zip(code.co_freevars, getattr(fn, "__closure__", None) or ()):
        try:
            v = cell.cell_contents
        except ValueError:
            continue
        if isinstance(v, (dict, list, set, bytearray)):
            out.append(var)
    g = getattr(fn, "__globals__", {})
    for var in code.co_names:
        if var in g and isinstance(g[var], (dict, list, set, bytearray)):
            out.append(var)
    return sorted(set(out))


def _declares_interval(obj) -> bool:
    """True when the UDF declares a refresh hook: a non-None
    ``_on_interval`` (the JaxUdf surface) or an ``on_interval`` the
    object's own class defines (duck-typed UDFs)."""
    if getattr(obj, "_on_interval", None) is not None:
        return True
    from ..udf import api as _api

    for klass in type(obj).__mro__:
        if "on_interval" in vars(klass):
            return klass.__module__ != _api.__name__
    return False


def _device_fns(obj) -> List[Tuple[str, object, Tuple[str, ...]]]:
    """(role, callable, untraced param names) per device function of a
    UDF object. PallasUdf analyzes the kernel (its ``fn`` is the
    library's own wrapper); scalar UDFs analyze ``fn``; aggregates
    analyze ``reduce`` (``capacity`` is a static Python int by
    contract)."""
    kernel = getattr(obj, "kernel", None)
    if callable(kernel):
        return [("kernel", kernel, ())]
    out: List[Tuple[str, object, Tuple[str, ...]]] = []
    fn = getattr(obj, "fn", None)
    if callable(fn):
        out.append(("fn", fn, ()))
    red = getattr(obj, "reduce", None)
    if getattr(obj, "is_aggregate", False) and callable(red):
        out.append(("reduce", red, ("capacity",)))
    return out


def check_udf_object(
    obj, name: Optional[str] = None
) -> Tuple[List[Diagnostic], List[str]]:
    """Analyze one loaded UDF object; returns (diagnostics, roles
    analyzed). The self-lint path for ``udf/samples.py`` objects; the
    flow path (``analyze_flow_udfs``) adds the DX310 loader findings."""
    udf_name = name or getattr(obj, "name", "") or type(obj).__name__
    diags: List[Diagnostic] = []
    roles: List[str] = []
    ret_dtypes: List[Optional[str]] = []
    for role, fn, untraced in _device_fns(obj):
        node = _fn_node(fn)
        # DX303 needs no source — it reads the live closure
        captured = _captured_mutable(fn)
        if captured and not _declares_interval(obj):
            diags.append(make(
                "DX303", udf_name,
                f"{role}: captures mutable state {captured} with no "
                "on_interval declared — the jitted step bakes the "
                "state in at trace time and silently serves stale "
                "values after any update",
                Span(line=node.lineno if node is not None else 0),
            ))
        if node is None:
            continue
        roles.append(role)
        lint = _FnLinter(
            node, udf_name, role, untraced_params=untraced
        ).run()
        diags.extend(lint.diags)
        if role in ("fn", "reduce"):
            ret_dtypes.extend(lint.return_dtypes)

    # DX304: declared out_type vs the inferred return dtype
    out_type = getattr(obj, "out_type", None)
    if isinstance(out_type, str):
        declared = _DECLARED_DTYPE.get(out_type.lower())
        known = {d for d in ret_dtypes if d is not None}
        if declared and len(known) == 1 and ret_dtypes and \
                all(d is not None for d in ret_dtypes):
            inferred = known.pop()
            if inferred != declared:
                diags.append(make(
                    "DX304", udf_name,
                    f"declared out_type '{out_type}' maps to {declared} "
                    f"but the function returns {inferred} under the "
                    "type lattice — results decode through the wrong "
                    "column type",
                ))
    return diags, roles


# ---------------------------------------------------------------------------
# Flow-level entry point (the production-loader path)
# ---------------------------------------------------------------------------
@dataclass
class UdfSummary:
    name: str
    tier: str  # udf | udaf
    path: str  # package.module:attr
    kind: str  # class name of the loaded object ("" when unloadable)
    analyzed: List[str] = field(default_factory=list)  # roles walked

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "tier": self.tier,
            "path": self.path,
            "kind": self.kind,
            "analyzed": list(self.analyzed),
        }


@dataclass
class UdfCheckReport:
    flow: str
    udfs: List[UdfSummary]
    diagnostics: List[Diagnostic]

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.is_error]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if not d.is_error]

    @property
    def ok(self) -> bool:
        return not self.errors

    def udfs_dict(self) -> dict:
        return {
            "flow": self.flow,
            "functions": [u.to_dict() for u in self.udfs],
        }

    def to_dict(self) -> dict:
        from .diagnostics import REPORT_SCHEMA_VERSION

        return {
            "schemaVersion": REPORT_SCHEMA_VERSION,
            "ok": self.ok,
            "errorCount": len(self.errors),
            "warningCount": len(self.warnings),
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "udfs": self.udfs_dict(),
        }


_UDF_TYPES = {"udf": "udf", "jarudf": "udf", "pythonudf": "udf",
              "udaf": "udaf", "jarudaf": "udaf"}


def analyze_flow_udfs(flow: dict) -> UdfCheckReport:
    """UDF-tier analysis of a flow config (gui JSON or full flow
    document): resolve every declared function through the PRODUCTION
    loader (``load_udfs_from_conf`` — same reflection path, same
    rejections), then abstract-interpret each device function's AST."""
    from ..udf.api import load_udfs_from_conf

    gui = flow.get("gui") if isinstance(flow.get("gui"), dict) else flow
    name = gui.get("name") or ""
    proc = gui.get("process") or {}
    diags: List[Diagnostic] = []
    summaries: List[UdfSummary] = []
    seen: Dict[str, str] = {}
    for entry in proc.get("functions") or []:
        ftype = (entry.get("type") or "udf").lower()
        tier = _UDF_TYPES.get(ftype)
        if tier is None:
            continue  # azure functions are a sink tier, not compiled
        fid = entry.get("id") or ""
        props = entry.get("properties") or {}
        path = props.get("module") or props.get("class") or ""
        if not fid or not path:
            diags.append(make(
                "DX310", fid,
                "ill-formed UDF conf entry: both id and "
                "properties.module (package.module:attr) are required",
            ))
            continue
        if fid.lower() in seen:
            diags.append(make(
                "DX310", fid,
                f"duplicate UDF name '{fid}' (also declared as "
                f"{seen[fid.lower()]}) — registration is "
                "case-insensitive and last-wins would silently shadow "
                "the first",
            ))
            continue
        seen[fid.lower()] = path
        conf = SettingDictionary({
            f"datax.job.process.jar.{tier}.{fid}.class": path,
        })
        try:
            obj = load_udfs_from_conf(conf)[fid.lower()]
        except EngineException as e:
            diags.append(make("DX310", fid, str(e)))
            summaries.append(UdfSummary(fid, tier, path, ""))
            continue
        if tier == "udaf" and not (
            getattr(obj, "is_aggregate", False)
            and callable(getattr(obj, "reduce", None))
        ):
            diags.append(make(
                "DX310", fid,
                f"udaf '{fid}' ({path}) is not an aggregate — it must "
                "set is_aggregate and provide reduce(arg_arrays, seg, "
                "capacity, valid_s)",
            ))
            summaries.append(
                UdfSummary(fid, tier, path, type(obj).__name__)
            )
            continue
        obj_diags, roles = check_udf_object(obj, name=fid)
        diags.extend(obj_diags)
        summaries.append(
            UdfSummary(fid, tier, path, type(obj).__name__, roles)
        )
    diags = sorted(
        diags, key=lambda d: (d.severity != "error", d.span.line, d.code)
    )
    return UdfCheckReport(name, summaries, diags)
