"""Device-plan analyzer: abstract interpretation of the compiled plan.

Second analysis tier (the ``--device`` tier). Where ``analyzer.py``
checks a flow's *meaning* (references, types, legality), this tier
checks what the compiled plan will *cost*: it reuses the production
lowering — the same ``SelectCompiler``/``PipelineCompiler`` the runtime
jits — then derives every stage's static shapes with ``jax.eval_shape``
(no device execution, no allocation) and emits

- a **cost report**: per-stage HBM footprint, FLOP estimate and
  expected ICI bytes/batch (closed forms over group cardinality and
  join fan-out; see ``costmodel.py`` and ANALYSIS.md "Scaling model"),
- the **DX2xx lint family**: capacity risk (group/join/dictionary
  bounds vs declared cardinality), O(n*m) match-matrix joins at window
  scale, recompilation hazards, and int32 ring-rebase proximity.

Two byte numbers per stage keep the model honest: ``hbm_bytes`` comes
from ``jax.eval_shape`` over the production lowering (ground truth
shapes), ``model_bytes`` from the closed forms. ``bench.py`` records
both, and a tier-1 test asserts they match the arrays a real batch
materializes — the static model can never silently drift from reality.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..compile.codegen import CodegenEngine, RulesCode
from ..compile.pipeline import (
    Pipeline,
    PipelineCompiler,
    parse_state_table_schema,
)
from ..compile.planner import (
    CompiledView,
    PlannerConfig,
    SelectCompiler,
    TableData,
    ViewSchema,
)
from ..constants import ColumnName, DatasetName
from ..core.config import EngineException, parse_duration_seconds
from ..core.schema import Schema, StringDictionary
from ..runtime.processor import (
    default_projection,
    projection_select,
    schema_to_view,
    window_target,
)
from ..runtime.timewindow import num_slots
from ..serve.flowbuilder import RuleDefinitionGenerator
from .costmodel import (
    DEFAULT_MATCH_MATRIX_BUDGET,
    OUTPUT_SLOT_BUFFERS,
    d2h_transfer_bytes,
    output_slot_bytes,
    row_bytes,
    stage_flops,
    stage_ici_bytes,
    stage_transient_bytes,
    table_bytes,
    view_output_bytes,
)
from .diagnostics import AnalysisReport, Diagnostic, make

# the north-star slice (v5e-16): default chip count for the ICI model
DEFAULT_CHIPS = 16

# int32 relative-millis horizon for ring timestamps (~24.8 days); DX205
# fires when retention crosses a quarter of it
INT32_MS_HORIZON = 2 ** 31
REBASE_PROXIMITY_FRACTION = 0.25

# DX206 fires when an OUTPUT view's static capacity exceeds the modeled
# row count (declared group-key cardinality) by this factor — the sync
# stage would transfer >98% padding on a full-capacity fetch
D2H_OVERSIZE_FACTOR = 64

_STRUCT_DTYPES = {"double": jnp.float32, "boolean": jnp.bool_}

# stage kinds that persist across batches (device-resident state) vs
# materialized per batch; "outslot" = the donated double-buffered
# output transfer slots the runtime keeps resident per output
PERSISTENT_KINDS = ("ring", "state", "refdata", "outslot")


def table_struct(schema: ViewSchema, rows: int) -> TableData:
    """Abstract TableData (ShapeDtypeStructs) for one input table —
    the exact dtypes the runtime encodes (core/schema.py)."""
    cols = {
        c: jax.ShapeDtypeStruct((rows,), _STRUCT_DTYPES.get(t, jnp.int32))
        for c, t in schema.types.items()
    }
    return TableData(cols, jax.ShapeDtypeStruct((rows,), jnp.bool_))


def _real_table(schema: ViewSchema, rows: int) -> TableData:
    cols = {
        c: jnp.zeros((rows,), _STRUCT_DTYPES.get(t, jnp.int32))
        for c, t in schema.types.items()
    }
    return TableData(cols, jnp.zeros((rows,), jnp.bool_))


def _leaf_bytes(a) -> int:
    return int(math.prod(a.shape)) * a.dtype.itemsize


def _table_data_bytes(td: TableData) -> int:
    return sum(_leaf_bytes(a) for a in td.cols.values()) + _leaf_bytes(td.valid)


# ---------------------------------------------------------------------------
# Report types
# ---------------------------------------------------------------------------
@dataclass
class StageCost:
    name: str
    kind: str  # input | project | ring | window | state | refdata | group | union
    rows: int
    hbm_bytes: int  # from eval_shape over the production lowering
    model_bytes: int  # closed-form prediction (costmodel.py)
    transient_bytes: int = 0  # peak in-stage intermediates (match matrix)
    flops: float = 0.0
    ici_bytes: float = 0.0  # expected interconnect bytes/batch at `chips`
    # device->host bytes a full-capacity fetch of this stage moves per
    # batch — non-zero only for OUTPUT views (the sync-stage wire cost)
    d2h_bytes: int = 0
    detail: str = ""

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "rows": self.rows,
            "hbmBytes": self.hbm_bytes,
            "modelBytes": self.model_bytes,
            "transientBytes": self.transient_bytes,
            "flops": round(self.flops, 1),
            "iciBytes": round(self.ici_bytes, 1),
            "d2hBytes": self.d2h_bytes,
            "detail": self.detail,
        }


@dataclass
class DevicePlanReport:
    flow: str
    chips: int
    stages: List[StageCost]
    diagnostics: List[Diagnostic]
    # OUTPUT dataset -> {"rows": modeled cardinality, "capacity": padded
    # static capacity} — the occupancy side of the runtime conformance
    # model (obs/conformance.py DX502)
    outputs: Dict[str, dict] = field(default_factory=dict)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.is_error]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if not d.is_error]

    @property
    def ok(self) -> bool:
        return not self.errors

    def totals(self) -> dict:
        persistent = sum(
            s.hbm_bytes for s in self.stages if s.kind in PERSISTENT_KINDS
        )
        per_batch = sum(
            s.hbm_bytes for s in self.stages if s.kind not in PERSISTENT_KINDS
        )
        return {
            "hbmBytes": persistent + per_batch,
            "persistentBytes": persistent,
            "perBatchBytes": per_batch,
            "modelBytes": sum(s.model_bytes for s in self.stages),
            "transientBytes": sum(s.transient_bytes for s in self.stages),
            "flops": round(sum(s.flops for s in self.stages), 1),
            "iciBytesPerBatch": round(
                sum(s.ici_bytes for s in self.stages), 1
            ),
            "d2hBytesPerBatch": sum(s.d2h_bytes for s in self.stages),
        }

    def plan_dict(self) -> dict:
        """The cost-report portion (no diagnostics) — what the designer
        renders beside the diagnostics list. Includes the roofline
        ``latencyModel`` (closed-form milliseconds under a machine
        profile — the datasheet default here; a *calibrated* profile
        replaces it wherever one is available: the host's DX520
        predictions and bench.py's roofline block)."""
        return {
            "flow": self.flow,
            "chips": self.chips,
            "stages": [s.to_dict() for s in self.stages],
            "totals": self.totals(),
            "latencyModel": self.latency_model(),
        }

    def latency_model(
        self, profile: Optional[dict] = None, source: str = "default",
    ) -> dict:
        """The time axis of this report: per-stage roofline ms + the
        deviceStep/d2h/ici decomposition (costmodel.latency_model)
        under ``profile`` (a ``MachineProfile.to_dict()``; the static
        datasheet default when None)."""
        from .costmodel import latency_model

        if profile is None:
            from ..obs.calibrate import DEFAULT_PROFILE

            profile = DEFAULT_PROFILE.to_dict()
            source = "default"
        return latency_model(
            [s.to_dict() for s in self.stages], self.totals(),
            profile, profile_source=source,
        )

    def to_dict(self) -> dict:
        from .diagnostics import REPORT_SCHEMA_VERSION

        return {
            "schemaVersion": REPORT_SCHEMA_VERSION,
            "ok": self.ok,
            "errorCount": len(self.errors),
            "warningCount": len(self.warnings),
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "device": self.plan_dict(),
        }

    def runtime_model(self) -> dict:
        """The machine-readable conformance artifact config generation
        embeds into the flow's conf
        (``datax.job.process.conformance.model``) — the slice of this
        report a running host checks itself against
        (``obs/conformance.py``)."""
        from .costmodel import runtime_conformance_model

        return runtime_conformance_model(
            self.totals(),
            [s.to_dict() for s in self.stages],
            self.outputs,
        )


def _ordered(diags: List[Diagnostic]) -> List[Diagnostic]:
    return sorted(
        diags, key=lambda d: (d.severity != "error", d.span.line, d.code)
    )


def combined_report_dict(
    base: AnalysisReport, device: Optional[DevicePlanReport] = None,
    udfs=None, fleet=None, compile_surface=None, mesh=None, race=None,
    protocol=None, conf=None,
) -> dict:
    """Merge the semantic tier with the optional device, UDF, fleet,
    compile, mesh, race and protocol tiers into one response: a
    superset of ``AnalysisReport.to_dict()`` plus a ``device`` cost
    report, a ``udfs`` summary, a ``fleet`` placement plan, a
    ``compile`` surface+manifest, a ``mesh`` sharding plan, a ``race``
    engine buffer-lifetime gate and/or a ``protocol`` exactly-once
    delivery gate — what ``flow/validate`` returns with ``device:
    true`` / ``udfs: true`` / ``fleet: true`` / ``compile: true`` /
    ``mesh: true`` / ``race: true`` / ``protocol: true`` (or ``all:
    true``) and what the CLI's tier flags (or ``--all``) ``--json``
    print: one ``schemaVersion``, one merged diagnostics list, one
    exit contract."""
    from .diagnostics import REPORT_SCHEMA_VERSION

    diags = list(base.diagnostics)
    if device is not None:
        diags += list(device.diagnostics)
    if udfs is not None:
        diags += list(udfs.diagnostics)
    if fleet is not None:
        diags += list(fleet.diagnostics)
    if compile_surface is not None:
        diags += list(compile_surface.diagnostics)
    if mesh is not None:
        diags += list(mesh.diagnostics)
    if race is not None:
        diags += list(race.diagnostics)
    if protocol is not None:
        diags += list(protocol.diagnostics)
    if conf is not None:
        diags += list(conf.diagnostics)
    diags = _ordered(diags)
    errors = [d for d in diags if d.is_error]
    out = {
        "schemaVersion": REPORT_SCHEMA_VERSION,
        "ok": not errors,
        "errorCount": len(errors),
        "warningCount": len(diags) - len(errors),
        "diagnostics": [d.to_dict() for d in diags],
    }
    if device is not None:
        out["device"] = device.plan_dict()
    if udfs is not None:
        out["udfs"] = udfs.udfs_dict()
    if fleet is not None:
        out["fleet"] = fleet.fleet_dict()
    if compile_surface is not None:
        out["compile"] = compile_surface.compile_dict()
    if mesh is not None:
        out["mesh"] = mesh.mesh_dict()
    if race is not None:
        out["race"] = race.race_dict()
    if protocol is not None:
        out["protocol"] = protocol.protocol_dict()
    if conf is not None:
        out["conf"] = conf.conf_dict()
    return out


# ---------------------------------------------------------------------------
# The compiled flow bundle both entry points produce
# ---------------------------------------------------------------------------
@dataclass
class FlowDevicePlan:
    """Everything the evaluator/linter needs, built from either a flow
    config (``analyze_flow_device``) or a live ``FlowProcessor``
    (``analyze_processor`` — the bench/test path)."""

    name: str
    pipeline: Pipeline
    projection_views: Dict[str, List[CompiledView]]  # source -> views
    raw_schemas: Dict[str, Tuple[ViewSchema, int]]  # source -> (schema, cap)
    target_of: Dict[str, str]  # source -> projected table
    target_schemas: Dict[str, ViewSchema]
    target_caps: Dict[str, int]
    ring_slots: Dict[str, int]  # windowed table -> slots
    windows: Dict[str, Tuple[str, float]]  # window name -> (table, dur_s)
    state: Dict[str, Tuple[ViewSchema, int]]
    refdata: Dict[str, Tuple[ViewSchema, int]]
    aux_tables: Dict[str, object]
    dict_max_size: Optional[int] = None
    declared_cardinality: Dict[str, int] = field(default_factory=dict)
    declared_strings: int = 0
    udf_refresh_names: List[str] = field(default_factory=list)
    uses_string_ops: bool = False
    watermark_s: float = 0.0
    interval_s: float = 1.0
    chips: int = DEFAULT_CHIPS
    # datasets routed to sinks — the views whose tables cross the
    # device->host boundary every batch (the D2H term + DX206 surface)
    output_datasets: List[str] = field(default_factory=list)


def _declared_cardinality(schema: Schema) -> Tuple[Dict[str, int], int]:
    """Per-leaf-column declared value cardinality from schema metadata
    ``allowedValues`` (written by hand or by schema inference from
    samples — the 'sampled cardinality' surface), plus the total count
    of distinct declared string values (the dictionary-pressure bound).
    Keyed by the leaf name because projections alias nested fields to
    their leaves (``deviceDetails.deviceId AS deviceId``)."""
    cards: Dict[str, int] = {}
    n_strings = 0
    for col in schema.columns:
        vals = (col.metadata or {}).get("allowedValues")
        if not isinstance(vals, list) or not vals:
            continue
        leaf = col.name.rsplit(".", 1)[-1]
        cards[leaf] = len(vals)
        cards.setdefault(col.name, len(vals))
        if col.ctype.value == "string":
            n_strings += len(set(map(str, vals)))
    return cards, n_strings


# ---------------------------------------------------------------------------
# Builder: from a designer flow config (gui JSON / full flow document)
# ---------------------------------------------------------------------------
def _jobconf_int(jobconf: dict, *names: str) -> Optional[int]:
    for n in names:
        v = jobconf.get(n)
        if v in (None, ""):
            continue
        try:
            return int(v)
        except (TypeError, ValueError):
            return None
    return None


def _plan_from_gui(
    gui: dict, diags: List[Diagnostic], chips: Optional[int]
) -> Optional[FlowDevicePlan]:
    name = gui.get("name") or ""
    iprops = (gui.get("input") or {}).get("properties") or {}
    proc = gui.get("process") or {}
    jobconf = proc.get("jobconfig") or {}

    batch_capacity = _jobconf_int(jobconf, "jobBatchCapacity") or 65536
    try:
        interval_s = float(
            iprops.get("windowDuration") or iprops.get("intervalInSeconds") or 1
        )
    except (TypeError, ValueError):
        interval_s = 1.0
    watermark = proc.get("watermark") or (
        f"{iprops.get('watermarkValue', 0)} "
        f"{iprops.get('watermarkUnit', 'second')}"
    )
    try:
        watermark_s = parse_duration_seconds(watermark)
    except Exception:  # noqa: BLE001 — malformed watermark: keep 0
        watermark_s = 0.0
    ts_col = proc.get("timestampColumn") or ""

    # planner capacities from the flow config (conf process.maxgroups /
    # process.joincapacity analogs in the designer's jobconfig)
    pc_kwargs = {}
    maxgroups = _jobconf_int(jobconf, "maxGroups", "maxgroups")
    if maxgroups is not None and maxgroups >= 1:
        pc_kwargs["max_group_capacity"] = maxgroups
    joincap = _jobconf_int(jobconf, "joinCapacity", "joincapacity")
    if joincap is not None and joincap >= 1:
        pc_kwargs["join_capacity"] = joincap
    planner_config = PlannerConfig(**pc_kwargs)
    dict_max = _jobconf_int(
        jobconf, "stringDictionaryMaxSize", "stringdictionarymaxsize"
    )

    # -- sources ---------------------------------------------------------
    sources: List[Tuple[str, dict, str]] = []  # (source, props, target)
    if iprops.get("inputSchemaFile"):
        sources.append(("default", iprops, DatasetName.DataStreamProjection))
    for src in (gui.get("input") or {}).get("sources") or []:
        sname = src.get("id") or src.get("name")
        if not sname:
            continue
        sprops = src.get("properties") or {}
        sources.append((sname, sprops, sprops.get("target") or sname))
    if not sources:
        diags.append(make(
            "DX291", "",
            "device analysis needs a concrete input schema "
            "(gui.input.properties.inputSchemaFile)",
        ))
        return None

    schemas: Dict[str, Schema] = {}
    raw_schemas: Dict[str, Tuple[ViewSchema, int]] = {}
    target_of: Dict[str, str] = {}
    snippets: Dict[str, Optional[str]] = {}
    for sname, sprops, target in sources:
        try:
            schema = Schema.from_spark_json(sprops.get("inputSchemaFile"))
        except (TypeError, ValueError, KeyError) as e:
            diags.append(make(
                "DX291", target,
                f"device analysis skipped: input schema for source "
                f"'{sname}' does not parse ({e})",
            ))
            return None
        schemas[sname] = schema
        raw_types = dict(schema_to_view(schema).types)
        raw_types.setdefault(ColumnName.RawPropertiesColumn, "string")
        raw_types.setdefault(ColumnName.RawSystemPropertiesColumn, "string")
        raw_schemas[sname] = (ViewSchema(raw_types), batch_capacity)
        target_of[sname] = target
        snippets[sname] = sprops.get("normalizationSnippet")
    targets = list(target_of.values())

    # -- UDFs (design-time reflection load, the JarUDFHandler path) ------
    udfs: Dict[str, object] = {}
    for fn in proc.get("functions") or []:
        ftype = (fn.get("type") or "udf").lower()
        if ftype not in ("udf", "udaf", "jarudf", "jarudaf", "pythonudf"):
            continue  # azure functions are a sink tier, not compiled
        props = fn.get("properties") or {}
        path = props.get("module") or props.get("class") or ""
        fid = fn.get("id") or ""
        if not fid or not path:
            continue
        try:
            from ..udf.api import _import_attr

            obj = _import_attr(path)
            if isinstance(obj, type) or not hasattr(obj, "compile_call"):
                obj = obj()
        except Exception as e:  # noqa: BLE001 — reflection load
            diags.append(make(
                "DX291", "",
                f"device analysis skipped: UDF '{fid}' ({path}) is not "
                f"loadable at design time ({e})",
            ))
            return None
        obj.name = fid
        udfs[fid.lower()] = obj

    # -- codegen (the S450 pass the runtime also consumes) ---------------
    queries = proc.get("queries") or []
    code = "\n".join(q if isinstance(q, str) else str(q) for q in queries)
    rules_json = RuleDefinitionGenerator().generate(gui.get("rules") or [], name)
    try:
        rc: RulesCode = CodegenEngine().generate_code(
            code, rules_json, name, windowable_tables=set(targets)
        )
    except Exception as e:  # noqa: BLE001 — base tier owns codegen findings
        diags.append(make(
            "DX291", "", f"device analysis skipped: codegen failed ({e})"
        ))
        return None

    dictionary = StringDictionary()
    pc = PipelineCompiler(dictionary, udfs, config=planner_config)

    try:
        # per-source projection lowering (the FlowProcessor path)
        projection_views: Dict[str, List[CompiledView]] = {}
        target_schemas: Dict[str, ViewSchema] = {}
        target_caps: Dict[str, int] = {}
        for sname, _sprops, target in sources:
            raw_schema, cap = raw_schemas[sname]
            snippet = snippets[sname]
            steps = [snippet] if snippet else [
                default_projection(schemas[sname], ts_col)
            ]
            proj_catalog = {
                "Raw": raw_schema, DatasetName.DataStreamRaw: raw_schema,
            }
            proj_caps = {"Raw": cap, DatasetName.DataStreamRaw: cap}
            cur = "Raw"
            views: List[CompiledView] = []
            for i, step in enumerate(steps):
                sel = projection_select(step, cur)
                compiler = SelectCompiler(
                    proj_catalog, proj_caps, dictionary, udfs,
                    planner_config, aux=pc.aux,
                )
                vname = target if i == len(steps) - 1 else f"__proj{i}"
                view = compiler.compile_select(vname, sel)
                views.append(view)
                proj_catalog[vname] = view.schema
                proj_caps[vname] = view.capacity
                cur = vname
            projection_views[sname] = views
            target_schemas[target] = proj_catalog[target]
            target_caps[target] = cap

        # windows over projected tables (ring retention model)
        windows: Dict[str, Tuple[str, float]] = {}
        ring_slots: Dict[str, int] = {}
        for wname, duration in rc.time_windows.items():
            table = window_target(wname, targets)
            if table not in target_schemas:
                raise EngineException(
                    f"timewindow {wname} targets unknown table {table!r}"
                )
            dur_s = parse_duration_seconds(duration)
            if ts_col not in target_schemas[table].types:
                raise EngineException(
                    f"timewindow {wname} requires timestamp column "
                    f"{ts_col!r} in table {table}"
                )
            windows[wname] = (table, dur_s)
            slots = num_slots(dur_s, watermark_s, interval_s)
            ring_slots[table] = max(ring_slots.get(table, 1), slots)

        # accumulation tables
        state: Dict[str, Tuple[ViewSchema, int]] = {}
        for sname_, ddl in rc.accumulation_tables.items():
            state[sname_] = (
                parse_state_table_schema(ddl), batch_capacity * 4
            )

        inputs: Dict[str, Tuple[ViewSchema, int]] = {
            t: (sch, target_caps[t]) for t, sch in target_schemas.items()
        }
        for wname, (table, _d) in windows.items():
            inputs[wname] = (
                target_schemas[table],
                ring_slots[table] * target_caps[table],
            )
        pipeline = pc.compile_transform(rc.code, inputs, state)
    except EngineException as e:
        diags.append(make("DX290", "", str(e)))
        return None
    except Exception as e:  # noqa: BLE001 — any lowering blowup is a finding
        diags.append(make("DX290", "", f"device lowering failed: {e}"))
        return None

    from ..compile.stringops import AuxTableBuilder

    aux = AuxTableBuilder(pc.aux, dictionary).tables()

    cards: Dict[str, int] = {}
    n_strings = 0
    for sname in schemas:
        c, ns = _declared_cardinality(schemas[sname])
        for k, v in c.items():
            cards.setdefault(k, v)
        n_strings += ns

    refresh = [
        u.name for u in udfs.values()
        if getattr(u, "_on_interval", None) is not None
    ]

    # OUTPUT statements name the datasets that cross D2H every batch
    view_names = {v.name for v in pipeline.views}
    out_datasets: List[str] = []
    for tables, _sink in rc.outputs:
        for t in tables.split(","):
            t = t.strip()
            if t in view_names and t not in out_datasets:
                out_datasets.append(t)

    return FlowDevicePlan(
        name=name,
        pipeline=pipeline,
        projection_views=projection_views,
        raw_schemas=raw_schemas,
        target_of=target_of,
        target_schemas=target_schemas,
        target_caps=target_caps,
        ring_slots=ring_slots,
        windows=windows,
        state=state,
        refdata={},
        aux_tables=aux,
        dict_max_size=dict_max,
        declared_cardinality=cards,
        declared_strings=n_strings,
        udf_refresh_names=refresh,
        uses_string_ops=not pc.aux.empty,
        watermark_s=watermark_s,
        interval_s=interval_s,
        chips=chips
        or _jobconf_int(jobconf, "jobNumChips", "jobNumExecutors")
        or DEFAULT_CHIPS,
        output_datasets=out_datasets,
    )


# ---------------------------------------------------------------------------
# Builder: from a live FlowProcessor (bench / tier-1 drift test path)
# ---------------------------------------------------------------------------
def flow_plan_from_processor(proc, chips: Optional[int] = None) -> FlowDevicePlan:
    """Bundle an already-built ``FlowProcessor``'s compiled plan — the
    exact views the jitted step runs — for cost analysis."""
    cards: Dict[str, int] = {}
    n_strings = 0
    for spec in proc.specs.values():
        c, ns = _declared_cardinality(spec.schema)
        for k, v in c.items():
            cards.setdefault(k, v)
        n_strings += ns
    conf_chips = None
    try:
        conf_chips = proc.process_conf.get_int_option("numchips")
    except Exception:  # noqa: BLE001 — malformed conf: fall back
        pass
    return FlowDevicePlan(
        name=proc.dict.get("datax.job.name") or "",
        pipeline=proc.pipeline,
        projection_views=dict(proc.projection_views),
        raw_schemas={
            s.name: (s.raw_schema, s.capacity) for s in proc.specs.values()
        },
        target_of={s.name: s.target for s in proc.specs.values()},
        target_schemas=dict(proc.target_schemas),
        target_caps={s.target: s.capacity for s in proc.specs.values()},
        ring_slots=dict(proc.ring_slots),
        windows=dict(proc.windows),
        state={
            n: (st.schema, st.capacity)
            for n, st in proc.state_tables.items()
        },
        refdata={
            n: (sch, t.capacity) for n, (sch, t) in proc.refdata.items()
        },
        aux_tables=proc.aux_tables.tables(),
        dict_max_size=proc.dictionary.max_size,
        declared_cardinality=cards,
        declared_strings=n_strings,
        udf_refresh_names=[
            u.name for u in proc.udfs.values()
            if getattr(u, "_on_interval", None) is not None
        ],
        uses_string_ops=not proc.aux_registry.empty,
        watermark_s=proc.watermark_s,
        interval_s=proc.interval_s,
        chips=chips or conf_chips or DEFAULT_CHIPS,
        output_datasets=list(proc.output_datasets),
    )


# ---------------------------------------------------------------------------
# Evaluator: abstract-interpret every stage of the compiled plan
# ---------------------------------------------------------------------------
def _view_stage(
    view: CompiledView,
    out_bytes: int,
    plan: FlowDevicePlan,
    catalog: Dict[str, ViewSchema],
) -> StageCost:
    p = view.plan
    kind = p.kind if p is not None else "project"
    details = []
    if p is not None:
        for s in p.joins:
            details.append(
                f"{s.kind.lower()}-join[{s.algorithm}] "
                f"{s.left_rows}x{s.right_rows}->{s.out_rows}"
            )
        if p.grouped:
            details.append(
                f"group keys={p.group_keys} aggs={p.n_aggregates} "
                f"bound={p.groups_bound}"
            )
        if p.union_branches > 1:
            details.append(f"union x{p.union_branches}")
        if p.limit is not None:
            details.append(f"limit {p.limit}")
    right_rb = {
        t: row_bytes(sch.types) for t, sch in catalog.items()
    }
    return StageCost(
        name=view.name,
        kind=kind,
        rows=view.capacity,
        hbm_bytes=out_bytes,
        model_bytes=view_output_bytes(view.schema.types, p, view.capacity),
        transient_bytes=stage_transient_bytes(p),
        flops=stage_flops(p, len(view.schema.types)),
        ici_bytes=stage_ici_bytes(
            p, row_bytes(view.schema.types), plan.chips, right_rb
        ),
        detail="; ".join(details),
    )


def _stage_walk(
    plan: FlowDevicePlan,
    make_table: Callable[[ViewSchema, int], TableData],
    eval_view: Callable[[CompiledView, Dict[str, TableData]], TableData],
) -> List[StageCost]:
    """Walk raw -> projection -> rings/windows -> state/refdata ->
    transform views, building stage costs. ``make_table`` and
    ``eval_view`` select abstract (eval_shape) or concrete evaluation —
    the same walk serves the analyzer and the drift test."""
    stages: List[StageCost] = []
    env: Dict[str, object] = {"__aux": plan.aux_tables}

    for source, views in plan.projection_views.items():
        raw_schema, cap = plan.raw_schemas[source]
        raw = make_table(raw_schema, cap)
        b = _table_data_bytes(raw)
        stages.append(StageCost(
            name=f"input:{source}", kind="input", rows=cap,
            hbm_bytes=b, model_bytes=table_bytes(raw_schema.types, cap),
            detail="raw ingest batch",
        ))
        penv: Dict[str, object] = {
            "Raw": raw, DatasetName.DataStreamRaw: raw,
            "__aux": plan.aux_tables,
        }
        proj_catalog = {"Raw": raw_schema}
        for v in views:
            out = eval_view(v, penv)
            penv[v.name] = out
            stages.append(_view_stage(
                v, _table_data_bytes(out), plan, proj_catalog
            ))
            proj_catalog[v.name] = v.schema
        target = plan.target_of[source]
        env[target] = penv[target]

    for table, slots in plan.ring_slots.items():
        rows = slots * plan.target_caps[table]
        schema = plan.target_schemas[table]
        stages.append(StageCost(
            name=f"ring:{table}", kind="ring", rows=rows,
            hbm_bytes=table_bytes(schema.types, rows),
            model_bytes=table_bytes(schema.types, rows),
            detail=f"{slots} slots x {plan.target_caps[table]} rows "
                   "(device-resident window state)",
        ))
    for wname, (table, dur_s) in plan.windows.items():
        rows = plan.ring_slots[table] * plan.target_caps[table]
        schema = plan.target_schemas[table]
        t = make_table(schema, rows)
        env[wname] = t
        stages.append(StageCost(
            name=wname, kind="window", rows=rows,
            hbm_bytes=_table_data_bytes(t),
            model_bytes=table_bytes(schema.types, rows),
            detail=f"{dur_s:g}s window over {table}",
        ))
    for sname, (schema, cap) in plan.state.items():
        t = make_table(schema, cap)
        env[sname] = t
        # display names are prefixed: an accumulation table is BOTH a
        # state input and (by the same name) a pipeline view output
        stages.append(StageCost(
            name=f"state:{sname}", kind="state", rows=cap,
            hbm_bytes=_table_data_bytes(t),
            model_bytes=table_bytes(schema.types, cap),
            detail="accumulation table",
        ))
    for rname, (schema, cap) in plan.refdata.items():
        t = make_table(schema, cap)
        env[rname] = t
        stages.append(StageCost(
            name=f"refdata:{rname}", kind="refdata", rows=cap,
            hbm_bytes=_table_data_bytes(t),
            model_bytes=table_bytes(schema.types, cap),
            detail="reference data (replicated)",
        ))

    for view in plan.pipeline.views:
        out = eval_view(view, env)
        env[view.name] = out
        stage = _view_stage(
            view, _table_data_bytes(out), plan, plan.pipeline.catalog
        )
        if view.name in plan.output_datasets:
            # the sync-stage wire cost: a full-capacity fetch of this
            # output's table crosses the device->host boundary per batch
            stage.d2h_bytes = d2h_transfer_bytes(
                view.schema.types, view.plan, view.capacity
            )
        stages.append(stage)
        if view.name in plan.output_datasets:
            # the donated double-buffered transfer slots the runtime
            # keeps resident for this output (runtime/processor.py
            # _stage_output): OUTPUT_SLOT_BUFFERS copies of the output
            # layout, persistent HBM the placer must pack. Lowered
            # bytes derive from the same evaluated table as the view
            # stage, so model == lowering stays exact.
            stages.append(StageCost(
                name=f"outslot:{view.name}", kind="outslot",
                rows=view.capacity,
                hbm_bytes=OUTPUT_SLOT_BUFFERS * _table_data_bytes(out),
                model_bytes=output_slot_bytes(
                    view.schema.types, view.plan, view.capacity
                ),
                detail=(
                    f"{OUTPUT_SLOT_BUFFERS}x donated transfer slots "
                    f"(A/B double buffer)"
                ),
            ))
    return stages


def _abstract_eval(plan: FlowDevicePlan) -> List[StageCost]:
    base = jax.ShapeDtypeStruct((), jnp.int32)
    now = jax.ShapeDtypeStruct((), jnp.int32)

    def eval_view(view, env):
        return jax.eval_shape(view.fn, env, base, now)

    return _stage_walk(plan, table_struct, eval_view)


def materialized_stage_bytes(plan: FlowDevicePlan) -> Dict[str, int]:
    """Ground truth for the drift test: run every compiled view ONCE on
    real (zero-filled) tables and return actual bytes per stage name.
    CPU-sized capacities only — this executes the plan."""
    base = jnp.asarray(0, jnp.int32)
    now = jnp.asarray(0, jnp.int32)

    def eval_view(view, env):
        return view.fn(env, base, now)

    return {
        s.name: s.hbm_bytes
        for s in _stage_walk(plan, _real_table, eval_view)
    }


# ---------------------------------------------------------------------------
# DX2xx lints over the compiled plan
# ---------------------------------------------------------------------------
def _lint(
    plan: FlowDevicePlan,
    diags: List[Diagnostic],
    match_matrix_budget: int,
) -> None:
    for view in plan.pipeline.views:
        p = view.plan
        if p is None:
            continue
        if p.grouped and p.group_key_cols:
            cards = [
                plan.declared_cardinality.get(c) for c in p.group_key_cols
            ]
            if cards and all(c is not None for c in cards):
                product = 1
                for c in cards:
                    product *= c
                if product > p.groups_bound:
                    diags.append(make(
                        "DX200", view.name,
                        f"group keys {list(p.group_key_cols)} have declared "
                        f"cardinality {product} but the static group "
                        f"capacity is {p.groups_bound} (process.maxgroups); "
                        f"overflow groups drop and surface only as "
                        f"Output_{view.name}_GroupsDropped",
                    ))
                elif (
                    view.name in plan.output_datasets
                    and view.capacity > D2H_OVERSIZE_FACTOR * product
                ):
                    per_batch = d2h_transfer_bytes(
                        view.schema.types, p, view.capacity
                    )
                    slot_bytes = output_slot_bytes(
                        view.schema.types, p, view.capacity
                    )
                    diags.append(make(
                        "DX206", view.name,
                        f"output capacity {view.capacity} exceeds the "
                        f"modeled group count {product} by more than "
                        f"{D2H_OVERSIZE_FACTOR}x: a full fetch moves "
                        f"{per_batch} D2H bytes/batch of mostly padding "
                        f"through the sync stage, and the "
                        f"{OUTPUT_SLOT_BUFFERS}x donated transfer slots "
                        f"pin {slot_bytes} HBM bytes at that padding; "
                        f"sized output transfer "
                        f"(process.pipeline.sizedtransfer, default on) "
                        f"or a tighter process.maxgroups shrinks both to "
                        f"the wire minimum",
                    ))
        for s in p.joins:
            if s.out_rows < s.left_rows:
                diags.append(make(
                    "DX201", view.name,
                    f"join output capacity {s.out_rows} is below the left "
                    f"input capacity {s.left_rows} "
                    f"(vs {s.right_table}): even a 1:1 match overflows, "
                    f"dropped pairs surface only as "
                    f"Output_{view.name}_JoinRowsDropped",
                ))
            pairs = s.left_rows * s.right_rows
            if s.algorithm == "match-matrix" and pairs > match_matrix_budget:
                diags.append(make(
                    "DX203", view.name,
                    f"non-equi ON terms force the O(n*m) match matrix: "
                    f"{s.left_rows} x {s.right_rows} = {pairs} pair "
                    f"evaluations per batch (budget "
                    f"{match_matrix_budget}); the sort-merge path needs "
                    f"a pure equality ON",
                ))
    if (
        plan.dict_max_size is not None
        and plan.declared_strings > plan.dict_max_size
    ):
        diags.append(make(
            "DX202", "",
            f"string dictionary capacity {plan.dict_max_size} is below "
            f"the declared/sampled distinct string-value count "
            f"{plan.declared_strings}; over-capacity keys collapse to "
            f"NULL (watch Input_string_dictionary_overflow_Count)",
        ))
    if plan.udf_refresh_names:
        diags.append(make(
            "DX204", "",
            f"UDF(s) {sorted(plan.udf_refresh_names)} declare interval "
            "refresh: every state change re-traces and re-compiles the "
            "whole jitted step",
        ))
    if plan.uses_string_ops and plan.dict_max_size is None:
        diags.append(make(
            "DX204", "",
            "device string ops with an unbounded dictionary: dictionary "
            "growth past the aux-table capacity re-traces the jitted "
            "step; set process.stringdictionary.maxsize",
        ))
    for wname, (_table, dur_s) in plan.windows.items():
        retention_ms = (dur_s + plan.watermark_s) * 1000.0
        if retention_ms > INT32_MS_HORIZON * REBASE_PROXIMITY_FRACTION:
            diags.append(make(
                "DX205", wname,
                f"window retention {retention_ms / 86_400_000.0:.1f} days "
                f"is past {int(REBASE_PROXIMITY_FRACTION * 100)}% of the "
                "int32 relative-millis horizon (~24.8 days); ring "
                "timestamps approach the rebase overflow guard",
            ))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def _output_model(bundle: FlowDevicePlan) -> Dict[str, dict]:
    """Per-OUTPUT modeled row occupancy: the declared-cardinality bound
    for grouped views (capped by the static group capacity), the padded
    capacity otherwise. The DX502 baseline."""
    out: Dict[str, dict] = {}
    for view in bundle.pipeline.views:
        if view.name not in bundle.output_datasets:
            continue
        p = view.plan
        rows = view.capacity
        if p is not None and p.grouped:
            rows = p.groups_bound
            cards = [
                bundle.declared_cardinality.get(c)
                for c in (p.group_key_cols or ())
            ]
            if cards and all(c is not None for c in cards):
                product = 1
                for c in cards:
                    product *= c
                rows = min(rows, product)
        out[view.name] = {
            "rows": int(rows), "capacity": int(view.capacity),
        }
    return out


def _analyze(
    bundle: Optional[FlowDevicePlan],
    diags: List[Diagnostic],
    name: str,
    chips: Optional[int],
    match_matrix_budget: int,
) -> DevicePlanReport:
    if bundle is None:
        return DevicePlanReport(
            name, chips or DEFAULT_CHIPS, [], _ordered(diags)
        )
    # lints read only the recorded plan — run them before abstract eval
    # so a plan that cannot even trace (e.g. a match matrix past the
    # int32 index space) still gets its capacity/cliff diagnostics
    _lint(bundle, diags, match_matrix_budget)
    try:
        stages = _abstract_eval(bundle)
    except Exception as e:  # noqa: BLE001 — abstract eval blowup is a finding
        diags.append(make("DX290", "", f"device plan evaluation failed: {e}"))
        return DevicePlanReport(bundle.name, bundle.chips, [], _ordered(diags))
    return DevicePlanReport(
        bundle.name, bundle.chips, stages, _ordered(diags),
        outputs=_output_model(bundle),
    )


def analyze_flow_device(
    flow: dict,
    chips: Optional[int] = None,
    match_matrix_budget: int = DEFAULT_MATCH_MATRIX_BUDGET,
) -> DevicePlanReport:
    """Device-plan analysis of a flow config (gui JSON or full flow
    document). Pure abstract interpretation: compiles with the
    production planner, derives shapes with ``jax.eval_shape``, touches
    no device."""
    gui = flow.get("gui") if isinstance(flow.get("gui"), dict) else flow
    diags: List[Diagnostic] = []
    bundle = _plan_from_gui(gui, diags, chips)
    return _analyze(
        bundle, diags, gui.get("name") or "", chips, match_matrix_budget
    )


def analyze_processor(
    proc,
    chips: Optional[int] = None,
    match_matrix_budget: int = DEFAULT_MATCH_MATRIX_BUDGET,
) -> DevicePlanReport:
    """Device-plan analysis of an already-built ``FlowProcessor`` — the
    exact compiled views the jitted step runs (bench.py's
    predicted-vs-measured cross-validation path)."""
    diags: List[Diagnostic] = []
    bundle = flow_plan_from_processor(proc, chips)
    return _analyze(bundle, diags, bundle.name, chips, match_matrix_budget)
