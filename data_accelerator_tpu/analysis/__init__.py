"""Flow static analyzer: typed diagnostics over the whole flow graph.

Design-time counterpart to the runtime compiler — reuses the production
codegen + parsers so a bad flow config fails in milliseconds with a
``DXnnn``-coded diagnostic instead of minutes into a deployed job.

CLI: ``python -m data_accelerator_tpu.analysis flow.json [--json]``
(non-zero exit on error-severity diagnostics).
"""

from .analyzer import (
    DEFAULT_MAX_STATE_ROWS,
    FlowAnalyzer,
    FlowContext,
    analyze_flow,
    analyze_script,
)
from .diagnostics import (
    CODES,
    PASS_NAMES,
    SEV_ERROR,
    SEV_WARNING,
    AnalysisReport,
    Diagnostic,
    Span,
)
from .typeprop import TableScope, schema_to_types

__all__ = [
    "AnalysisReport",
    "CODES",
    "DEFAULT_MAX_STATE_ROWS",
    "Diagnostic",
    "FlowAnalyzer",
    "FlowContext",
    "PASS_NAMES",
    "SEV_ERROR",
    "SEV_WARNING",
    "Span",
    "TableScope",
    "analyze_flow",
    "analyze_script",
    "schema_to_types",
]
