"""Flow static analyzer: typed diagnostics over the whole flow graph.

Design-time counterpart to the runtime compiler — reuses the production
codegen + parsers so a bad flow config fails in milliseconds with a
``DXnnn``-coded diagnostic instead of minutes into a deployed job.

Eight tiers:

- the semantic tier (``analyze_flow``): reference resolution, type
  propagation, legality, dead flow, device-compilation risk;
- the device tier (``analyze_flow_device``): abstract interpretation of
  the *compiled* plan — per-stage HBM/FLOP/ICI cost report plus the
  DX2xx capacity/recompilation lints (``deviceplan.py``);
- the UDF tier (``analyze_flow_udfs``): taint-lattice abstract
  interpretation of the flow's UDF device-function ASTs — the DX3xx
  tracing-safety/purity/determinism lints (``udfcheck.py``);
- the fleet tier (``analyze_fleet_flows``): whole-fleet analysis of a
  *set* of flows against a fleet spec — first-fit-decreasing placement
  consuming the DX2xx cost model plus the DX4xx capacity/interference
  lints (``fleetcheck.py``); also the runtime placement oracle behind
  ``serve/jobs.py``'s admission gate;
- the compile tier (``analyze_flow_compile``): enumerate every jit
  entry point the flow will ever dispatch, lower each over
  ``jax.eval_shape`` avals, prove the signature set finite and stable
  — the DX6xx lints — and emit the AOT **compile manifest** the
  runtime warms from at init (``compilecheck.py``);
- the mesh tier (``analyze_flow_mesh``): infer the flow's SPMD
  partition plan from the planner lowering — per-stage shard axis,
  forced reshard edges, closed-form collective bytes over chips N —
  cross-checked exactly against a real ``Mesh`` lowering, with the
  DX7xx lints and the sharding-plan artifact mesh jobs' confs embed
  for runtime ICI-drift conformance (``meshcheck.py``);
- the race tier (``analyze_flow_race``): buffer-lifetime/concurrency
  abstract interpretation of the ENGINE's own modules (``runtime/``,
  ``lq/``, ``pilot/``) under a buffer-provenance lattice — the DX8xx
  escaped-donated-view / zero-copy / lockset / re-donation /
  blocking-sync lints (``racecheck.py``); its dynamic counterpart is
  ``runtime/sanitizer.py`` (runtime DX805, conf
  ``process.debug.buffersanitizer``);
- the protocol tier (``analyze_flow_protocol``): exactly-once
  delivery-protocol analysis of the engine modules plus the rescale
  handoff (``serve/jobs.py``) — typed effect traces per entry point
  checked against the declared ordering spec (``protospec.py``), the
  DX90x durability/ordering/requeue/handoff lints (``protocheck.py``);
  its dynamic counterpart is ``runtime/protocolmonitor.py`` (runtime
  DX906, conf ``process.debug.protocolmonitor``);
- the conf tier (``analyze_flow_conf``): the configuration lattice —
  every engine conf read site and every generation-produced key
  checked against the ONE typed registry (``confspec.py``), the
  DX10xx dead-knob / dead-conf / broken-chain / default-drift /
  type-bounds / incompatible-knob lints (``confcheck.py``); its
  dynamic counterpart is ``runtime/confaudit.py`` (runtime DX1006,
  armed at every host/LQ-service init).

CLI: ``python -m data_accelerator_tpu.analysis flow.json [--json]
[--device [--chips N]] [--udfs] [--fleet [--fleet-spec=spec.json]]
[--compile [--manifest=m.json] [--manifest-out=m.json]]
[--mesh [--chips N]] [--race] [--protocol] [--all]``
(non-zero exit on error-severity diagnostics, optional tiers included
when requested; ``--all`` runs every tier in one invocation).
"""

from .analyzer import (
    DEFAULT_MAX_STATE_ROWS,
    FlowAnalyzer,
    FlowContext,
    analyze_flow,
    analyze_script,
)
from .deviceplan import (
    DEFAULT_CHIPS,
    DevicePlanReport,
    StageCost,
    analyze_flow_device,
    analyze_processor,
    combined_report_dict,
)
from .compilecheck import (
    MANIFEST_VERSION,
    CompileSurfaceReport,
    analyze_flow_compile,
    analyze_processor_compile,
)
from .diagnostics import (
    CODES,
    PASS_NAMES,
    REPORT_SCHEMA_VERSION,
    SEV_ERROR,
    SEV_WARNING,
    AnalysisReport,
    Diagnostic,
    Span,
)
from .fleetcheck import (
    DEFAULT_FLEET_CHIPS,
    FleetReport,
    FleetSpec,
    FlowFootprint,
    PlacementPlan,
    analyze_fleet,
    analyze_fleet_flows,
    flow_footprint,
    load_fleet_spec,
    pack_fleet,
)
from .chipcount import ChipCountError, parse_chip_count
from .meshcheck import (
    DEFAULT_MESH_CHIPS,
    MeshPlanReport,
    MeshStage,
    ReshardEdge,
    analyze_flow_mesh,
    analyze_processor_mesh,
)
from .confcheck import (
    ConfCheckReport,
    analyze_conf_modules,
    analyze_flow_conf,
    conf_module_paths,
)
from .confspec import (
    CONF_REGISTRY,
    ConfKey,
    check_conf_mapping,
)
from .protocheck import (
    PROTO_EXTRA_MODULES,
    ProtoCheckReport,
    ProtoModuleSummary,
    analyze_flow_protocol,
    analyze_proto_modules,
    proto_module_paths,
)
from .protospec import (
    EVENT_KINDS,
    RULES,
    RULES_BY_CODE,
    ProtocolRule,
    check_sequence,
)
from .racecheck import (
    ENGINE_PACKAGES,
    RaceCheckReport,
    RaceModuleSummary,
    analyze_flow_race,
    analyze_modules,
    engine_module_paths,
)
from .typeprop import TableScope, schema_to_types
from .udfcheck import (
    UdfCheckReport,
    UdfSummary,
    analyze_flow_udfs,
    check_udf_object,
)

__all__ = [
    "AnalysisReport",
    "CODES",
    "ChipCountError",
    "CompileSurfaceReport",
    "CONF_REGISTRY",
    "ConfCheckReport",
    "ConfKey",
    "MANIFEST_VERSION",
    "DEFAULT_CHIPS",
    "DEFAULT_FLEET_CHIPS",
    "DEFAULT_MESH_CHIPS",
    "DEFAULT_MAX_STATE_ROWS",
    "DevicePlanReport",
    "Diagnostic",
    "ENGINE_PACKAGES",
    "EVENT_KINDS",
    "PROTO_EXTRA_MODULES",
    "ProtoCheckReport",
    "ProtoModuleSummary",
    "ProtocolRule",
    "RULES",
    "RULES_BY_CODE",
    "RaceCheckReport",
    "RaceModuleSummary",
    "MeshPlanReport",
    "MeshStage",
    "ReshardEdge",
    "FleetReport",
    "FleetSpec",
    "FlowAnalyzer",
    "FlowContext",
    "FlowFootprint",
    "PASS_NAMES",
    "PlacementPlan",
    "REPORT_SCHEMA_VERSION",
    "SEV_ERROR",
    "SEV_WARNING",
    "Span",
    "StageCost",
    "TableScope",
    "UdfCheckReport",
    "UdfSummary",
    "analyze_fleet",
    "analyze_fleet_flows",
    "analyze_flow",
    "analyze_conf_modules",
    "analyze_flow_compile",
    "analyze_flow_conf",
    "analyze_flow_device",
    "analyze_flow_mesh",
    "analyze_flow_protocol",
    "analyze_flow_race",
    "analyze_flow_udfs",
    "analyze_modules",
    "analyze_processor",
    "analyze_processor_compile",
    "analyze_processor_mesh",
    "analyze_proto_modules",
    "analyze_script",
    "parse_chip_count",
    "check_udf_object",
    "combined_report_dict",
    "check_sequence",
    "check_conf_mapping",
    "conf_module_paths",
    "engine_module_paths",
    "flow_footprint",
    "proto_module_paths",
    "load_fleet_spec",
    "pack_fleet",
    "schema_to_types",
]
