"""Fleet analyzer: whole-fleet placement + cross-flow interference.

Fourth analysis tier (the ``--fleet`` tier). The first three tiers each
judge ONE flow; this one judges a *set* of flows against a *fleet spec*
(chips, HBM per chip, ICI topology) and answers the question ROADMAP
item 2(b) asks: can these flows share the fleet, and where does each
one go? The reference platform's cluster clients (Livy/Databricks,
SURVEY §1 L3) deployed blind and discovered oversubscription by
watching jobs die; we have a cost model that is asserted byte-exact
against the XLA lowering (``costmodel.py`` + the tier-1 drift test), so
placement is computed *before* anything spawns.

Two lint families plus a concrete placement plan:

- **capacity (DX400-403)** — first-fit-decreasing bin-packing of each
  flow's DX2xx HBM total onto the fleet's chips. The per-flow numbers
  are CONSUMED from ``analyze_flow_device`` (``DevicePlanReport
  .totals()``), never re-derived, so the fleet tier inherits the byte
  exactness the drift test proves: a chip's packed total is exactly the
  sum of the arrays its flows' batches materialize.
- **interference (DX410-413)** — collisions no single-flow tier can
  see: shared checkpoint/state/output directories, Kafka/EventHub
  consumer-group collisions on overlapping topics, metric-series key
  collisions in the shared store (``constants.MetricName``), and
  observability-port conflicts between co-placed flows.

The placement plan doubles as a runtime input: ``serve/jobs.py``'s
``FleetAdmissionGate`` runs this analyzer at job submission (DX400/401/
410/411 reject the submit before a process spawns) and
``serve/scheduler.py``'s ``PlacementReplanner`` re-runs it on job
stop/start so freed capacity is reusable.

Placement model (documented in ANALYSIS.md "Placement model"): each
flow is a single-chip tenant — the many-small-flows multi-tenancy case
— packed by modeled HBM under first-fit-decreasing; flows declaring a
multi-chip mesh (``jobNumChips``) still place whole but contribute
their ICI demand at the declared chip count.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..constants import MetricName
from .diagnostics import REPORT_SCHEMA_VERSION, Diagnostic, make

# ---------------------------------------------------------------------------
# Fleet spec
# ---------------------------------------------------------------------------
# default chip count: the MULTICHIP_r0x runs execute the fully-sharded
# two-source step green at 8 devices — that slice is the fleet the repo
# actually proves out (the v5e-16 north star is the --chips override)
DEFAULT_FLEET_CHIPS = 8

# v5e: 16 GiB HBM per chip
DEFAULT_HBM_PER_CHIP = 16 * 1024 ** 3

# DX402 fires when a chip's packed HBM exceeds this fraction of its
# capacity: the remaining slack is the retrace/dictionary-growth margin
DEFAULT_HEADROOM_FRACTION = 0.8

# modeled per-chip bandwidth budgets for the DX403 aggregate-demand
# lint. Deliberately conservative: D2H is the measured tunnel-path
# sync-stage budget (BENCH_r05 moves ~MBs/batch through a ~66 ms
# tunnel), ICI the per-chip share of the 1-D ring's bisection. Both are
# spec fields — override them to model real hardware.
DEFAULT_D2H_BYTES_PER_SEC = 1_000_000_000  # 1 GB/s per chip
DEFAULT_ICI_BYTES_PER_SEC = 45_000_000_000  # 45 GB/s per chip

DEFAULT_ICI_TOPOLOGY = "1d-ring"  # dist/mesh.py's 1-D data mesh


@dataclass
class FleetSpec:
    """What the fleet *is*: chip count, HBM per chip, topology and the
    modeled bandwidth budgets. ``--fleet-spec=<file.json>`` / the REST
    ``fleetSpec`` body use the camelCase keys of ``to_dict``."""

    chips: int = DEFAULT_FLEET_CHIPS
    hbm_per_chip_bytes: int = DEFAULT_HBM_PER_CHIP
    headroom_fraction: float = DEFAULT_HEADROOM_FRACTION
    d2h_bytes_per_sec_per_chip: float = DEFAULT_D2H_BYTES_PER_SEC
    ici_bytes_per_sec_per_chip: float = DEFAULT_ICI_BYTES_PER_SEC
    ici_topology: str = DEFAULT_ICI_TOPOLOGY

    @classmethod
    def from_dict(cls, d: dict) -> "FleetSpec":
        spec = cls()
        # the chips key funnels through the one shared chip-count
        # parser every surface uses (analysis/chipcount.py) — a typed
        # ChipCountError (a ValueError) on non-positive/non-integer N
        from .chipcount import parse_chip_count

        chips = parse_chip_count(d.get("chips"), "fleet spec 'chips'")
        if chips is not None:
            spec.chips = chips
        mapping = {
            "hbmPerChipBytes": ("hbm_per_chip_bytes", int),
            "headroomFraction": ("headroom_fraction", float),
            "d2hBytesPerSecPerChip": ("d2h_bytes_per_sec_per_chip", float),
            "iciBytesPerSecPerChip": ("ici_bytes_per_sec_per_chip", float),
            "iciTopology": ("ici_topology", str),
        }
        for key, (attr, conv) in mapping.items():
            if d.get(key) is not None:
                setattr(spec, attr, conv(d[key]))
        return spec

    def to_dict(self) -> dict:
        return {
            "chips": self.chips,
            "hbmPerChipBytes": self.hbm_per_chip_bytes,
            "headroomFraction": self.headroom_fraction,
            "d2hBytesPerSecPerChip": self.d2h_bytes_per_sec_per_chip,
            "iciBytesPerSecPerChip": self.ici_bytes_per_sec_per_chip,
            "iciTopology": self.ici_topology,
        }


# ---------------------------------------------------------------------------
# Per-flow footprint: DX2xx totals + statically extracted resources
# ---------------------------------------------------------------------------
@dataclass
class FlowFootprint:
    """One flow's placement-relevant surface: the DX2xx cost-model
    totals (consumed, not re-derived) plus the shared-resource claims
    the interference lints compare. ``hbm_bytes`` is ``None`` when the
    device tier could not analyze the flow (its diagnostics ride along
    and the flow is excluded from packing)."""

    name: str
    hbm_bytes: Optional[int] = None
    persistent_bytes: int = 0
    per_batch_bytes: int = 0
    flops: float = 0.0
    d2h_bytes_per_batch: int = 0
    ici_bytes_per_batch: float = 0.0
    interval_s: float = 1.0
    chips_required: int = 1
    # interference resources
    dirs: Set[str] = field(default_factory=set)  # checkpoint/state/sink
    consumer_keys: Set[Tuple[str, ...]] = field(default_factory=set)
    metric_series: Set[str] = field(default_factory=set)
    obs_port: Optional[int] = None
    diagnostics: List[Diagnostic] = field(default_factory=list)

    @property
    def placeable(self) -> bool:
        return self.hbm_bytes is not None

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "hbmBytes": self.hbm_bytes,
            "persistentBytes": self.persistent_bytes,
            "perBatchBytes": self.per_batch_bytes,
            "flops": round(self.flops, 1),
            "d2hBytesPerBatch": self.d2h_bytes_per_batch,
            "iciBytesPerBatch": round(self.ici_bytes_per_batch, 1),
            "intervalSeconds": self.interval_s,
            "chipsRequired": self.chips_required,
        }


def _jobconf_int(jobconf: dict, *names: str) -> Optional[int]:
    for n in names:
        v = jobconf.get(n)
        if v in (None, ""):
            continue
        try:
            return int(v)
        except (TypeError, ValueError):
            return None
    return None


_OUTPUT_RE = re.compile(
    r"^\s*OUTPUT\s+([A-Za-z0-9_,\s]+?)\s+TO\s+([A-Za-z0-9_]+)\s*;?\s*$",
    re.IGNORECASE | re.MULTILINE,
)


def _prop(props: dict, *names: str):
    """Case-insensitive property lookup (designer props are camelCase,
    pass-through conf keys are lowercased)."""
    lowered = {str(k).lower(): v for k, v in (props or {}).items()}
    for n in names:
        v = lowered.get(n.lower())
        if v not in (None, "", [], {}):
            return v
    return None


def flow_resources(gui: dict, footprint: FlowFootprint) -> None:
    """Statically extract the flow's shared-resource claims from its
    config — pure dict walking, no compilation. Populates ``dirs``,
    ``consumer_keys``, ``metric_series`` and ``obs_port``."""
    name = footprint.name
    inp = gui.get("input") or {}
    iprops = inp.get("properties") or {}
    proc = gui.get("process") or {}
    jobconf = proc.get("jobconfig") or {}

    # -- checkpoint/state/output directories -----------------------------
    # the generated defaults are flow-name-keyed (serve/generation.py
    # writes <runtime>/<name>/checkpoints etc.), so the derived claim is
    # the name-relative path: two same-named flows collide on it, and
    # explicit overrides collide on their literal value
    footprint.dirs.add(f"{name}/checkpoints")
    explicit = _prop(iprops, "checkpointDir", "eventhub.checkpointdir")
    if explicit:
        footprint.dirs.add(str(explicit))
    sources = inp.get("sources") or []
    for src in sources:
        sprops = src.get("properties") or {}
        sdir = _prop(sprops, "checkpointDir", "eventhub.checkpointdir")
        if sdir:
            footprint.dirs.add(str(sdir))
    for out in gui.get("outputs") or []:
        otype = (out.get("type") or "").lower()
        if otype in ("blob", "file", "local"):
            folder = _prop(out.get("properties") or {}, "folder", "path")
            if folder:
                footprint.dirs.add(str(folder))

    # -- Kafka / EventHub consumer identity ------------------------------
    # runtime/sources.py defaults kafka's group id to the literal
    # "dxtpu" for the default source — SHARED across flows — so two
    # flows on the same topics without an explicit groupid genuinely
    # split records between them
    def consumer_key(stype: str, props: dict, source: str):
        stype = (stype or "local").lower()
        if stype == "kafka":
            topics = str(_prop(props, "kafka.topics", "topics") or "")
            group = str(
                _prop(props, "kafka.groupid", "consumerGroup", "groupid")
                or ("dxtpu" if source == "default" else f"{source}.dxtpu")
            )
            for t in topics.split(";"):
                if t.strip():
                    footprint.consumer_keys.add(("kafka", group, t.strip()))
        elif stype in ("eventhub", "iothub"):
            conn = str(_prop(props, "inputEventhubConnection",
                             "connection") or "")
            group = str(_prop(props, "consumerGroup") or name)
            if conn:
                footprint.consumer_keys.add(("eventhub", conn, group))

    consumer_key(inp.get("type"), iprops, "default")
    for src in sources:
        consumer_key(src.get("type"),
                     src.get("properties") or {},
                     src.get("id") or src.get("name") or "")

    # -- metric series in the shared store -------------------------------
    # every engine series lives under the DATAX-<job> app key, and the
    # job name derives from the flow name (flowbuilder jobCommonTokens
    # jobName=_S_{name}); metric-sink tables add <app>:<table> series
    app = MetricName.metric_app_name(name)
    footprint.metric_series.add(f"{app}:{MetricName.LatencyPrefix}Batch")
    metric_sinks = {
        out.get("id") for out in gui.get("outputs") or []
        if (out.get("type") or "").lower() == "metric"
    }
    queries = (proc.get("queries") or [])
    script = "\n".join(q if isinstance(q, str) else str(q) for q in queries)
    for m in _OUTPUT_RE.finditer(script):
        tables, sink = m.group(1), m.group(2)
        if sink in metric_sinks or sink.lower() == "metrics":
            for t in tables.split(","):
                if t.strip():
                    footprint.metric_series.add(f"{app}:{t.strip()}")

    # -- observability port ----------------------------------------------
    port = _jobconf_int(jobconf, "jobObservabilityPort",
                        "observabilityPort")
    if port:  # 0/unset = ephemeral, never conflicts
        footprint.obs_port = port


def flow_footprint(flow: dict, name: Optional[str] = None) -> FlowFootprint:
    """Build one flow's fleet footprint by CONSUMING the DX2xx device
    tier (``analyze_flow_device`` at the flow's declared chip count,
    default 1 — the single-chip-tenant placement model). The HBM number
    is ``DevicePlanReport.totals()['hbmBytes']`` verbatim: the fleet
    tier never re-derives bytes, so it stays byte-exact with the
    lowering by construction."""
    from .deviceplan import analyze_flow_device

    gui = flow.get("gui") if isinstance(flow.get("gui"), dict) else flow
    fname = name or gui.get("name") or ""
    jobconf = (gui.get("process") or {}).get("jobconfig") or {}
    chips_req = _jobconf_int(jobconf, "jobNumChips", "jobNumExecutors") or 1
    fp = FlowFootprint(name=fname, chips_required=chips_req)
    try:
        fp.interval_s = float(
            _prop((gui.get("input") or {}).get("properties") or {},
                  "windowDuration", "intervalInSeconds") or 1
        )
    except (TypeError, ValueError):
        fp.interval_s = 1.0
    flow_resources(gui, fp)

    device = analyze_flow_device(flow, chips=chips_req)
    if device.stages and device.ok:
        totals = device.totals()
        fp.hbm_bytes = int(totals["hbmBytes"])
        fp.persistent_bytes = int(totals["persistentBytes"])
        fp.per_batch_bytes = int(totals["perBatchBytes"])
        fp.flops = float(totals["flops"])
        fp.d2h_bytes_per_batch = int(totals["d2hBytesPerBatch"])
        fp.ici_bytes_per_batch = float(totals["iciBytesPerBatch"])
    # carry the device tier's findings (DX290 errors / DX291 warnings)
    # so a footprint-less flow explains itself in the fleet report
    fp.diagnostics = [
        Diagnostic(d.code, d.severity, fname or d.table, d.message, d.span)
        for d in device.diagnostics
        if d.code in ("DX290", "DX291")
    ]
    return fp


# ---------------------------------------------------------------------------
# Placement: first-fit-decreasing bin-packing by modeled HBM
# ---------------------------------------------------------------------------
@dataclass
class ChipAssignment:
    chip: int
    flows: List[str] = field(default_factory=list)
    hbm_bytes: int = 0

    def utilization(self, spec: FleetSpec) -> float:
        return self.hbm_bytes / spec.hbm_per_chip_bytes

    def to_dict(self, spec: FleetSpec) -> dict:
        util = self.utilization(spec)
        return {
            "chip": self.chip,
            "flows": list(self.flows),
            "hbmBytes": self.hbm_bytes,
            "hbmCapacityBytes": spec.hbm_per_chip_bytes,
            "utilization": round(util, 6),
            "headroom": round(1.0 - util, 6),
        }


@dataclass
class PlacementPlan:
    chips: List[ChipAssignment]
    unplaced: List[str] = field(default_factory=list)  # fit nowhere (DX400)
    oversized: List[str] = field(default_factory=list)  # exceed any chip (DX401)
    unanalyzed: List[str] = field(default_factory=list)  # no footprint (DX29x)

    @property
    def feasible(self) -> bool:
        return not self.unplaced and not self.oversized

    def chip_of(self, flow: str) -> Optional[int]:
        for c in self.chips:
            if flow in c.flows:
                return c.chip
        return None

    def to_dict(self, spec: FleetSpec) -> dict:
        return {
            "feasible": self.feasible,
            "chips": [c.to_dict(spec) for c in self.chips if c.flows],
            "unplaced": list(self.unplaced),
            "oversized": list(self.oversized),
            "unanalyzed": list(self.unanalyzed),
        }


def pack_fleet(
    footprints: Sequence[FlowFootprint], spec: FleetSpec
) -> PlacementPlan:
    """First-fit-decreasing by modeled HBM: sort flows largest-first,
    place each on the first chip whose packed total stays within
    capacity. FFD is the classic 11/9·OPT bin-packing heuristic —
    deterministic (ties broken by flow name), so a re-plan over the
    same set reproduces the same assignment."""
    plan = PlacementPlan(
        chips=[ChipAssignment(chip=i) for i in range(spec.chips)]
    )
    placeable: List[FlowFootprint] = []
    for fp in footprints:
        if not fp.placeable:
            plan.unanalyzed.append(fp.name)
        elif fp.hbm_bytes > spec.hbm_per_chip_bytes:
            plan.oversized.append(fp.name)
        else:
            placeable.append(fp)
    for fp in sorted(placeable, key=lambda f: (-f.hbm_bytes, f.name)):
        for chip in plan.chips:
            if chip.hbm_bytes + fp.hbm_bytes <= spec.hbm_per_chip_bytes:
                chip.flows.append(fp.name)
                chip.hbm_bytes += fp.hbm_bytes
                break
        else:
            plan.unplaced.append(fp.name)
    return plan


# ---------------------------------------------------------------------------
# Report
# ---------------------------------------------------------------------------
def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GB"


@dataclass
class FleetReport:
    spec: FleetSpec
    footprints: List[FlowFootprint]
    placement: PlacementPlan
    diagnostics: List[Diagnostic]

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.is_error]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if not d.is_error]

    @property
    def ok(self) -> bool:
        return not self.errors

    def fleet_dict(self) -> dict:
        """The placement portion (no diagnostics) — what the designer
        renders as the placement table and what job records persist."""
        return {
            "spec": self.spec.to_dict(),
            "flows": [fp.to_dict() for fp in self.footprints],
            "placement": self.placement.to_dict(self.spec),
        }

    def to_dict(self) -> dict:
        return {
            "schemaVersion": REPORT_SCHEMA_VERSION,
            "ok": self.ok,
            "errorCount": len(self.errors),
            "warningCount": len(self.warnings),
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "fleet": self.fleet_dict(),
        }


def _ordered(diags: List[Diagnostic]) -> List[Diagnostic]:
    return sorted(
        diags,
        key=lambda d: (d.severity != "error", d.code, d.table, d.message),
    )


# ---------------------------------------------------------------------------
# Lints
# ---------------------------------------------------------------------------
def _capacity_lints(
    footprints: Sequence[FlowFootprint],
    plan: PlacementPlan,
    spec: FleetSpec,
    diags: List[Diagnostic],
) -> None:
    by_name = {fp.name: fp for fp in footprints}
    for name in plan.oversized:
        fp = by_name[name]
        diags.append(make(
            "DX401", name,
            f"modeled HBM footprint {_fmt_bytes(fp.hbm_bytes)} exceeds "
            f"every chip's capacity "
            f"{_fmt_bytes(spec.hbm_per_chip_bytes)}: the flow can never "
            f"place on this fleet",
        ))
    for name in plan.unplaced:
        fp = by_name[name]
        diags.append(make(
            "DX400", name,
            f"no feasible placement: {_fmt_bytes(fp.hbm_bytes)} does not "
            f"fit on any of the {spec.chips} chip(s) "
            f"({_fmt_bytes(spec.hbm_per_chip_bytes)} each) after packing "
            f"the co-resident flows — the fleet is oversubscribed",
        ))
    for chip in plan.chips:
        util = chip.utilization(spec)
        if chip.flows and util > spec.headroom_fraction:
            diags.append(make(
                "DX402", "/".join(sorted(chip.flows)),
                f"chip {chip.chip} packs "
                f"{_fmt_bytes(chip.hbm_bytes)} "
                f"({util:.0%} of capacity), above the "
                f"{spec.headroom_fraction:.0%} headroom fraction: one "
                f"capacity bump or dictionary retrace OOMs it",
            ))
    # aggregate bandwidth demand vs the fleet-wide modeled budget
    placed = [
        fp for fp in footprints
        if fp.placeable and fp.name not in plan.unplaced
        and fp.name not in plan.oversized
    ]
    d2h_demand = sum(
        fp.d2h_bytes_per_batch / max(fp.interval_s, 1e-9) for fp in placed
    )
    d2h_budget = spec.d2h_bytes_per_sec_per_chip * spec.chips
    if d2h_demand > d2h_budget:
        diags.append(make(
            "DX403", "",
            f"aggregate D2H demand {_fmt_bytes(d2h_demand)}/s exceeds "
            f"the fleet's modeled budget {_fmt_bytes(d2h_budget)}/s "
            f"({spec.chips} chip(s) x "
            f"{_fmt_bytes(spec.d2h_bytes_per_sec_per_chip)}/s): sync "
            f"stages will contend on the host link",
        ))
    ici_demand = sum(
        fp.ici_bytes_per_batch / max(fp.interval_s, 1e-9) for fp in placed
    )
    ici_budget = spec.ici_bytes_per_sec_per_chip * spec.chips
    if ici_demand > ici_budget:
        diags.append(make(
            "DX403", "",
            f"aggregate ICI demand {_fmt_bytes(ici_demand)}/s exceeds "
            f"the fleet's modeled {spec.ici_topology} budget "
            f"{_fmt_bytes(ici_budget)}/s: collectives will contend on "
            f"the interconnect",
        ))


def _pair_table(a: str, b: str) -> str:
    return "/".join(sorted((a, b)))


def _interference_lints(
    footprints: Sequence[FlowFootprint],
    plan: PlacementPlan,
    diags: List[Diagnostic],
) -> None:
    for i, a in enumerate(footprints):
        for b in footprints[i + 1:]:
            shared_dirs = a.dirs & b.dirs
            if shared_dirs:
                diags.append(make(
                    "DX410", _pair_table(a.name, b.name),
                    f"flows '{a.name}' and '{b.name}' share "
                    f"checkpoint/state/output path(s) "
                    f"{sorted(shared_dirs)}: restarts would corrupt "
                    f"each other's offsets and window state",
                ))
            shared_consumers = a.consumer_keys & b.consumer_keys
            if shared_consumers:
                desc = ", ".join(
                    f"{k[0]} group/conn {k[1]!r} on {k[2]!r}"
                    if k[0] == "kafka"
                    else f"{k[0]} {k[2]!r} on connection {k[1]!r}"
                    for k in sorted(shared_consumers)
                )
                diags.append(make(
                    "DX411", _pair_table(a.name, b.name),
                    f"flows '{a.name}' and '{b.name}' collide on "
                    f"{desc}: the broker splits records between them, "
                    f"so each flow silently sees a fraction of the "
                    f"stream",
                ))
            shared_series = a.metric_series & b.metric_series
            if shared_series:
                diags.append(make(
                    "DX412", _pair_table(a.name, b.name),
                    f"flows '{a.name}' and '{b.name}' emit the same "
                    f"metric series key(s) {sorted(shared_series)[:3]} "
                    f"into the shared store: dashboard series "
                    f"interleave indistinguishably",
                ))
            # port conflicts only matter between CO-PLACED flows (one
            # chip = one host process slot)
            if (
                a.obs_port is not None
                and a.obs_port == b.obs_port
                and plan.chip_of(a.name) is not None
                and plan.chip_of(a.name) == plan.chip_of(b.name)
            ):
                diags.append(make(
                    "DX413", _pair_table(a.name, b.name),
                    f"co-placed flows '{a.name}' and '{b.name}' (chip "
                    f"{plan.chip_of(a.name)}) both bind observability "
                    f"port {a.obs_port}: the second host fails to "
                    f"expose /metrics and /healthz",
                ))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def analyze_fleet(
    footprints: Sequence[FlowFootprint],
    spec: Optional[FleetSpec] = None,
) -> FleetReport:
    """Whole-fleet analysis over pre-computed footprints: FFD packing,
    DX400-403 capacity lints, DX410-413 interference lints."""
    spec = spec or FleetSpec()
    diags: List[Diagnostic] = []
    for fp in footprints:
        diags.extend(fp.diagnostics)
    plan = pack_fleet(footprints, spec)
    _capacity_lints(footprints, plan, spec, diags)
    _interference_lints(list(footprints), plan, diags)
    return FleetReport(spec, list(footprints), plan, _ordered(diags))


def analyze_fleet_flows(
    flows: Sequence[dict],
    spec: Optional[FleetSpec] = None,
    names: Optional[Sequence[str]] = None,
) -> FleetReport:
    """Convenience wrapper: build every footprint (running the DX2xx
    device tier per flow), then analyze the set."""
    footprints = [
        flow_footprint(flow, name=(names[i] if names else None))
        for i, flow in enumerate(flows)
    ]
    return analyze_fleet(footprints, spec)


def load_fleet_spec(path: str) -> FleetSpec:
    """Read a ``--fleet-spec`` JSON file (camelCase ``to_dict`` keys)."""
    with open(path, "r", encoding="utf-8") as f:
        return FleetSpec.from_dict(json.load(f))
