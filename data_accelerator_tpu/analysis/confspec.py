"""The DECLARED configuration lattice (shared by the static ``--conf``
tier and the runtime ``ConfAudit``).

The platform's whole contract is "flow JSON compiles to a flat job
``.conf`` the runtime trusts" — and until this module that contract
was stringly typed: 60+ ``datax.job.process.*`` keys hand-plumbed from
designer ``jobXxx`` knob to S400 gui token to S650 flat key to a
runtime ``conf.get`` with an inline fallback, and nothing checking any
hop. Here the lattice is a TABLE: one :class:`ConfKey` per key, with
its type, canonical default, bounds, owner subsystem and (where the
designer can set it) the knob→token chain that produces it. The static
pass (``analysis/confcheck.py``, DX1000-DX1005) checks every scanned
read site and every generated key against it; the runtime audit
(``runtime/confaudit.py``, DX1006) checks every LIVE conf against the
SAME rows via :func:`check_value` / :func:`check_conf_mapping`.

Key syntax
----------
``key`` is relative to ``datax.job.process.`` (the only namespace in
scope — ``datax.job.input.*`` / ``output.*`` belong to the source and
sink planes, configured by the template, not by engine knobs). A ``*``
segment matches exactly one dotted segment (``timewindow.*.
windowduration`` covers every named window); read sites the scanner
can only resolve to a family (``group_by_sub_namespace()`` /
``.dict`` walks) are recorded with a ``**`` tail that matches any
remainder.

``read=False`` rows are produced-for-parity keys: the generation
chain emits them (reference-template compatibility) but no runtime
module reads them yet. They are registered so DX1001 stays a typo
detector instead of flagging deliberate forward-compat keys; the
tier-1 self-lint pins their exact count so a new one is a conscious
decision.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from ..core.config import parse_duration_seconds

#: the single namespace this lattice governs
PROCESS_PREFIX = "datax.job.process."

#: value types :func:`check_value` understands
TYPES = (
    "string", "int", "float", "bool", "duration", "json", "path",
    "url", "port", "list",
)

_BOOL_WORDS = {
    "true": True, "false": False, "1": True, "0": False,
    "yes": True, "no": False, "on": True, "off": False,
}


@dataclass(frozen=True)
class ConfKey:
    """One row of the configuration lattice."""

    key: str                      # relative to ``datax.job.process.``
    type: str                     # a ``TYPES`` member
    default: Optional[str]        # canonical engine fallback (None = no default)
    subsystem: str                # owning subsystem (runtime, pipeline, lq, ...)
    knob: Optional[str] = None    # designer jobconfig knob (``jobXxx``)
    token: Optional[str] = None   # S400 gui token carrying the knob
    source: str = "generation"    # designer|template|generation|control|manual
    min: Optional[float] = None   # numeric/duration lower bound (inclusive)
    max: Optional[float] = None   # numeric/duration upper bound (inclusive)
    choices: Optional[Tuple[str, ...]] = None
    read: bool = True             # False = produced-for-parity, no reader yet
    description: str = ""

    def __post_init__(self) -> None:
        if self.type not in TYPES:
            raise ValueError(f"ConfKey {self.key}: unknown type {self.type!r}")
        if self.key.startswith(PROCESS_PREFIX):
            raise ValueError(
                f"ConfKey {self.key}: registry keys are relative to "
                f"{PROCESS_PREFIX!r}"
            )


def _segments_match(pattern: str, key: str) -> bool:
    """``*`` matches exactly one segment; a trailing ``**`` matches any
    non-empty remainder (used for family read sites, not registry rows).
    """
    pseg = pattern.split(".")
    kseg = key.split(".")
    if pseg and pseg[-1] == "**":
        head = pseg[:-1]
        if len(kseg) < len(head) + 1:
            return False
        kseg = kseg[: len(head)]
        pseg = head
    if len(pseg) != len(kseg):
        return False
    return all(p == "*" or p == k for p, k in zip(pseg, kseg))


# ---------------------------------------------------------------------------
# The registry
# ---------------------------------------------------------------------------
# Filled in below (kept at module bottom for readability: the helpers
# first, then the long table).

def registry_index() -> Dict[str, ConfKey]:
    """Exact-key index (wildcard rows excluded)."""
    return {e.key: e for e in CONF_REGISTRY if "*" not in e.key}


def match_key(key: str) -> Optional[ConfKey]:
    """Find the registry row governing ``key`` (relative form).

    Exact rows win; otherwise the first wildcard row whose pattern
    matches. Returns None for an unregistered key.
    """
    if key.startswith(PROCESS_PREFIX):
        key = key[len(PROCESS_PREFIX):]
    exact = registry_index().get(key)
    if exact is not None:
        return exact
    for e in CONF_REGISTRY:
        if "*" in e.key and _segments_match(e.key, key):
            return e
    return None


def rows_matching_family(family: str) -> List[ConfKey]:
    """Registry rows a family read site (``prefix.**`` / ``a.*.b``)
    covers — used to decide whether a family read is DX1000-dead."""
    out = []
    for e in CONF_REGISTRY:
        if _segments_match(family, e.key) or _family_covers(family, e.key):
            out.append(e)
    return out


def _family_covers(family: str, key: str) -> bool:
    """True when the family pattern's fixed head is a prefix of the
    registry row's segments (both may contain ``*`` segments)."""
    fseg = family.split(".")
    kseg = key.split(".")
    if fseg and fseg[-1] == "**":
        fseg = fseg[:-1]
        if len(kseg) < len(fseg):
            return False
        kseg = kseg[: len(fseg)]
    if len(fseg) != len(kseg):
        return False
    return all(f == "*" or k == "*" or f == k for f, k in zip(fseg, kseg))


# ---------------------------------------------------------------------------
# Value checking (shared static + runtime)
# ---------------------------------------------------------------------------
def canonical_default(entry: ConfKey) -> Optional[str]:
    return entry.default


def _num(entry: ConfKey, value: str) -> Optional[float]:
    if entry.type in ("int", "port"):
        return float(int(value))
    if entry.type == "float":
        return float(value)
    if entry.type == "duration":
        return float(parse_duration_seconds(value))
    return None


def defaults_equal(entry: ConfKey, other: Optional[str]) -> bool:
    """Compare a fallback literal against the registry default, up to
    numeric/bool canonicalization (``8`` == ``8.0``, ``True`` ==
    ``true``)."""
    if entry.default is None or other is None:
        return entry.default == other
    a, b = str(entry.default), str(other)
    if a == b:
        return True
    if entry.type == "bool":
        return _BOOL_WORDS.get(a.lower()) == _BOOL_WORDS.get(b.lower())
    try:
        na, nb = _num(entry, a), _num(entry, b)
    except (ValueError, TypeError):
        return False
    if na is None or nb is None:
        return False
    return na == nb


def check_value(entry: ConfKey, value: str) -> Optional[str]:
    """Validate one concrete value against its registry row. Returns a
    human-readable reason when the value violates the row's type,
    bounds or choices — None when it conforms."""
    v = str(value)
    if entry.choices is not None and v not in entry.choices:
        return (
            f"value {v!r} not one of {', '.join(entry.choices)}"
        )
    if entry.type == "bool":
        if v.strip().lower() not in _BOOL_WORDS:
            return f"expected a boolean, got {v!r}"
        return None
    if entry.type == "json":
        try:
            json.loads(v)
        except ValueError:
            return "expected a JSON document"
        return None
    if entry.type == "list":
        return None  # ';'-separated, any content
    if entry.type in ("string", "path", "url"):
        return None
    # numeric family: int / float / duration / port
    try:
        n = _num(entry, v)
    except (ValueError, TypeError):
        return f"expected {entry.type}, got {v!r}"
    if n is None:  # pragma: no cover — TYPES is closed
        return None
    lo = entry.min
    hi = entry.max
    if entry.type == "port":
        lo = 0 if lo is None else lo
        hi = 65535 if hi is None else hi
    if lo is not None and n < lo:
        return f"value {v} below minimum {lo:g}"
    if hi is not None and n > hi:
        return f"value {v} above maximum {hi:g}"
    return None


# ---------------------------------------------------------------------------
# Mutual-exclusion constraints
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class ConfConstraint:
    """One incompatible-knob rule, evaluated over an effective conf
    mapping of RELATIVE keys (``pipeline.depth`` -> ``"2"``)."""

    name: str
    description: str
    violated: Callable[[Mapping[str, str]], bool]


def _truthy(conf: Mapping[str, str], key: str) -> bool:
    return _BOOL_WORDS.get(str(conf.get(key, "")).strip().lower(), False)


def _is_mesh(conf: Mapping[str, str]) -> bool:
    try:
        chips = int(str(conf.get("numchips", "1") or "1"))
    except ValueError:
        chips = 1
    return chips > 1 or bool(conf.get("mesh.model"))


CONSTRAINTS: Tuple[ConfConstraint, ...] = (
    ConfConstraint(
        "mesh-sizedtransfer",
        "pipeline.sizedtransfer=true on a multi-chip mesh job: the "
        "sized D2H fetch is a single-chip optimization — under a mesh "
        "every batch fetches the full padded capacity, so the knob is "
        "silently ignored (the conf half of the DX705 lint)",
        lambda c: _is_mesh(c) and _truthy(c, "pipeline.sizedtransfer"),
    ),
    ConfConstraint(
        "mesh-backgroundtransfer",
        "pipeline.backgroundtransfer=true on a multi-chip mesh job: "
        "the double-buffered background landing path is disabled under "
        "a mesh (runtime/host.py forces it off), so an explicit 'true' "
        "documents an intent the engine will not honor",
        lambda c: _is_mesh(c) and str(
            c.get("pipeline.backgroundtransfer", "")
        ).strip().lower() in ("true", "1", "yes", "on"),
    ),
    ConfConstraint(
        "filteringest-without-partitions",
        "state.filteringest=true without state.partitions: ingest-time "
        "partition filtering keys off the state-partition plan — with "
        "no partition count declared every replica would filter "
        "against an empty plan and drop all rows",
        lambda c: _truthy(c, "state.filteringest")
        and not str(c.get("state.partitions", "")).strip(),
    ),
)


def check_conf_mapping(
    conf: Mapping[str, str],
) -> List[Tuple[str, str, str]]:
    """Validate a concrete flat conf against the lattice. Returns
    ``(kind, key, reason)`` tuples where ``kind`` is ``unknown`` (no
    registry row), ``value`` (type/bounds/choices violation) or
    ``constraint`` (incompatible-knob rule; ``key`` is the rule name).

    Shared by the static DX1004/DX1005 checks and the runtime
    ``ConfAudit`` (DX1006) — one validator, two enforcement points.
    """
    out: List[Tuple[str, str, str]] = []
    rel: Dict[str, str] = {}
    for k, v in sorted(dict(conf).items()):
        if not k.startswith(PROCESS_PREFIX):
            continue
        r = k[len(PROCESS_PREFIX):]
        rel[r] = str(v)
        entry = match_key(r)
        if entry is None:
            out.append(("unknown", r, "key is not in the conf registry"))
            continue
        reason = check_value(entry, str(v))
        if reason:
            out.append(("value", r, reason))
    for rule in CONSTRAINTS:
        if rule.violated(rel):
            out.append(("constraint", rule.name, rule.description))
    return out


# ---------------------------------------------------------------------------
# The table
# ---------------------------------------------------------------------------
# One row per ``datax.job.process.*`` key. Ordering is by subsystem —
# the auto-generated CONF.md reference table preserves it. Defaults are
# the ENGINE's canonical fallback (what the runtime does when the key
# is absent), not what any particular scenario sets; S400 token
# defaults and read-site literals are checked against these by DX1003.
_K = ConfKey

CONF_REGISTRY: Tuple[ConfKey, ...] = (
    # -- runtime core ------------------------------------------------------
    _K("batchcapacity", "int", "65536", "runtime", knob="jobBatchCapacity",
       token="guiJobBatchCapacity", source="designer", min=1,
       description="padded device batch capacity (rows per step)"),
    _K("numchips", "int", "1", "runtime", knob="jobNumChips",
       token="guiJobNumChips", source="designer", min=1,
       description="device-mesh width; >1 builds a 1-D data mesh over "
                   "the first N local chips (clamped to available)"),
    _K("transform", "path", None, "runtime", source="template",
       description="path to the flow's transform script (codegen input)"),
    _K("timestampcolumn", "string", None, "runtime", source="template",
       description="event-time column driving windows and watermarks"),
    _K("watermark", "duration", None, "runtime", source="template", min=0,
       description="allowed event-time lateness"),
    _K("projection", "list", None, "runtime", source="template",
       description="';'-separated projection column list"),
    _K("properties.enabled", "bool", "false", "runtime", source="manual",
       description="opt-in per-row properties map (documented opt-in; "
                   "off unless a flow declares it)"),
    _K("appendproperty.*", "string", None, "runtime", source="template",
       description="constant columns appended to every row"),
    # -- pipeline ----------------------------------------------------------
    _K("pipeline.depth", "int", "2", "pipeline", knob="jobPipelineDepth",
       token="guiJobPipelineDepth", source="designer", min=1,
       description="in-flight batch window (decode/dispatch overlap)"),
    _K("pipeline.sizedtransfer", "bool", "true", "pipeline",
       source="manual",
       description="bucketed sized D2H fetch (single-chip only; the "
                   "mesh-sizedtransfer constraint flags it under a mesh)"),
    _K("pipeline.backgroundtransfer", "bool", "true", "pipeline",
       source="manual",
       description="double-buffered background D2H landing thread"),
    _K("pipeline.outputslots", "bool", "true", "pipeline", source="manual",
       description="preallocated pinned output landing slots"),
    _K("ingest.decoderthreads", "int", None, "ingest",
       knob="jobDecoderThreads", token="guiJobDecoderThreads",
       source="designer", min=1,
       description="native decoder worker threads (None = serial)"),
    # -- ops ---------------------------------------------------------------
    _K("maxgroups", "int", None, "ops", source="manual", min=1,
       description="group-by capacity: max distinct groups per batch"),
    _K("groupcapacity", "int", None, "ops", source="manual", min=1,
       description="group-by capacity: max rows per group"),
    _K("joincapacity", "int", None, "ops", source="manual", min=1,
       description="broadcast-join build-side row capacity"),
    _K("stringdictionary.maxsize", "int", None, "ops", source="manual",
       min=1, description="string-dictionary slot budget"),
    _K("stringdictionary.strict", "bool", "false", "ops", source="manual",
       description="fail (vs evict) when the string dictionary is full"),
    _K("stringmap.maxrounds", "int", None, "ops", source="manual", min=1,
       description="string-map probe round budget"),
    _K("stringmap.strict", "bool", "false", "ops", source="manual",
       description="fail (vs drop) on string-map round exhaustion"),
    # -- state plane -------------------------------------------------------
    _K("state.partitions", "int", "16", "state", source="control", min=1,
       description="state-partition plan width (jobs.py replica rollout "
                   "writes it; DEFAULT_STATE_PARTITIONS otherwise)"),
    _K("state.replicaindex", "int", "1", "state", source="control", min=1,
       description="this replica's 1-based index in the group"),
    _K("state.replicacount", "int", "1", "state", source="control", min=1,
       description="replica-group size"),
    _K("state.partitionkey", "string", None, "state", source="manual",
       description="row column hashed into the partition plan"),
    _K("state.snapshoturl", "url", None, "state", source="manual",
       description="object-store URL for state snapshots/handoff"),
    _K("state.filteringest", "bool", "false", "state", source="manual",
       description="ingest-time partition filtering (requires "
                   "state.partitions — see the constraint)"),
    _K("statetable.*.schema", "string", None, "state", source="template",
       description="accumulator state-table schema ('k long, v double')"),
    _K("statetable.*.location", "path", None, "state", source="template",
       description="state-table spill/snapshot directory"),
    _K("statetable.*.partitionkey", "string", None, "state",
       source="manual",
       description="per-table partition column override"),
    # -- time windows ------------------------------------------------------
    _K("timewindow.*.windowduration", "duration", None, "window",
       source="template", min=0,
       description="tumbling window span for the named window"),
    _K("timewindow.*.table", "string", None, "window", source="manual",
       description="backing state-table override for the named window"),
    # -- compile plane -----------------------------------------------------
    _K("compile.aot", "bool", "true", "compile", source="manual",
       description="ahead-of-time compile the flow step at host start"),
    _K("compile.cachedir", "path", None, "compile", source="generation",
       description="AOT executable cache directory (S650 embed)"),
    _K("compile.cacheurl", "url", None, "compile", source="generation",
       description="shared AOT cache object-store URL (S650 embed)"),
    _K("compile.manifest", "path", None, "compile", source="generation",
       description="compile manifest path (DX601 surface pin)"),
    _K("compile.jitcachecap", "int", "32", "compile",
       knob="jobCompileJitCacheCap", token="guiJobCompileJitCacheCap",
       source="designer", min=1,
       description="transfer-helper jit cache entry cap"),
    # -- debug -------------------------------------------------------------
    _K("debug.nans", "bool", "false", "debug", source="manual",
       description="jax_debug_nans for the flow step"),
    _K("debug.tracerleaks", "bool", "false", "debug", source="manual",
       description="jax_check_tracer_leaks for the flow step"),
    _K("debug.buffersanitizer", "bool", "false", "debug", source="manual",
       description="arm the DX805 buffer sanitizer (poison freed views)"),
    _K("debug.protocolmonitor", "bool", "false", "debug", source="manual",
       description="arm the DX906 exactly-once protocol monitor"),
    # -- mesh --------------------------------------------------------------
    _K("mesh.model", "json", None, "mesh", source="generation",
       description="sharding-plan artifact (S660 embed; DX510/511 "
                   "conformance input)"),
    _K("mesh.observe", "bool", "true", "mesh", source="manual",
       description="summarize compiled collectives for ICI conformance"),
    # -- observability -----------------------------------------------------
    _K("observability.port", "port", None, "observability",
       knob="jobObservabilityPort", token="guiJobObservabilityPort",
       source="designer", min=1,
       description="/metrics + /readyz + profiler HTTP port"),
    _K("observability.profiler", "bool", "true", "observability",
       knob="jobProfiler", source="designer",
       description="on-demand device profiler endpoint"),
    _K("observability.profilerdir", "path", None, "observability",
       source="manual", description="profiler trace output directory"),
    _K("observability.hbmsample", "bool", "true", "observability",
       knob="jobHbmSample", source="designer",
       description="per-batch HBM watermark sampling"),
    _K("observability.calibration", "bool", "true", "observability",
       knob="jobCalibration", source="designer",
       description="machine-profile calibration at host start"),
    _K("observability.calibrationfile", "path", None, "observability",
       source="manual", description="pinned machine-profile JSON path"),
    _K("observability.calibrationurl", "url", None, "observability",
       source="manual", description="shared machine-profile store URL"),
    _K("observability.stallewmams", "float", None, "observability",
       knob="jobStallEwmaMs", source="designer", min=0,
       description="stall-EWMA half-life feeding /readyz + the pilot"),
    _K("observability.stallfailms", "float", None, "observability",
       source="manual", min=0,
       description="smoothed stall above this fails readiness"),
    # -- conformance -------------------------------------------------------
    _K("conformance.model", "json", None, "conformance",
       source="generation",
       description="roofline byte/time model artifact (S620 embed)"),
    _K("conformance.latency", "json", None, "conformance", source="manual",
       description="operator latency pin (stage->ms) replacing the "
                   "computed predictions"),
    _K("conformance.window", "int", "16", "conformance", source="manual",
       min=1, description="conformance evaluation window (batches)"),
    _K("conformance.warmup", "int", "4", "conformance", source="manual",
       min=0, description="batches ignored before evaluating"),
    _K("conformance.d2hratiohigh", "float", "1.5", "conformance",
       source="manual", min=0,
       description="observed/predicted D2H bytes alarm ratio"),
    _K("conformance.hbmratiohigh", "float", "1.5", "conformance",
       source="manual", min=0,
       description="observed/predicted HBM watermark alarm ratio"),
    _K("conformance.iciratiohigh", "float", "8.0", "conformance",
       source="manual", min=0,
       description="observed/predicted ICI bytes alarm ratio"),
    _K("conformance.occupancyfactor", "float", "2.0", "conformance",
       source="manual", min=0,
       description="occupancy headroom factor in the time model"),
    _K("conformance.stagetimeratiohigh", "float", "10.0", "conformance",
       source="manual", min=0,
       description="observed/predicted stage-time alarm ratio"),
    _K("conformance.stagetimefloorms", "float", "1.0", "conformance",
       source="manual", min=0,
       description="stage-time floor below which drift is ignored"),
    # -- telemetry ---------------------------------------------------------
    _K("telemetry.tracing", "bool", "true", "telemetry", source="manual",
       description="span flight-recording for the host"),
    _K("telemetry.tracefile", "path", None, "telemetry",
       source="generation",
       description="shared JSONL trace spool (telemetryTraceFile env "
                   "token; one file for control plane + jobs)"),
    _K("telemetry.tracefile.compress", "bool", "false", "telemetry",
       source="manual", description="gzip rotated trace segments"),
    _K("telemetry.tracefile.keep", "int", "1", "telemetry",
       source="manual", min=1,
       description="rotated trace segments kept"),
    _K("telemetry.tracefilemaxbytes", "int", None, "telemetry",
       source="manual", min=1,
       description="trace segment rotation size"),
    _K("telemetry.parenttrace", "string", None, "telemetry",
       source="manual",
       description="parent span context injected by the spawner"),
    _K("telemetry.httppost", "url", None, "telemetry", source="manual",
       description="telemetry event HTTP sink"),
    # -- metric sinks ------------------------------------------------------
    _K("metric.redis", "string", None, "metric", source="template",
       description="redis-analog metric sink: unset/any value keeps the "
                   "in-proc MetricStore (the dashboard feed); "
                   "'false'/'off'/'none'/'disabled' detaches it"),
    _K("metric.eventhub", "string", None, "metric", source="template",
       description="host:port of a MetricsIngestor side-car"),
    _K("metric.httppost", "url", None, "metric", source="template",
       description="metric point HTTP sink (website local mode)"),
    # -- fleet telemetry ---------------------------------------------------
    _K("fleet.publishurl", "url", None, "fleet", source="generation",
       description="object-store URL fleet frames publish to "
                   "(fleetPublishUrl env token)"),
    _K("fleet.replica", "string", None, "fleet", source="manual",
       description="replica lineage label override (r<index> default)"),
    _K("fleet.windowseconds", "float", "10", "fleet", source="manual",
       min=0, description="fleet frame publish window"),
    # -- alerts ------------------------------------------------------------
    _K("alerts.rules", "json", None, "alerts", source="generation",
       description="compiled alert rules artifact (S630 embed)"),
    # -- pilot -------------------------------------------------------------
    _K("pilot.enabled", "bool", "true", "pilot", knob="jobPilot",
       source="designer",
       description="in-host adaptive controller (jobPilot='false' "
                   "writes pilot.enabled=false)"),
    _K("pilot.windowseconds", "float", "5.0", "pilot",
       knob="jobPilotWindowSeconds", source="designer", min=0,
       description="signal evaluation cadence"),
    _K("pilot.cooldownseconds", "float", "15.0", "pilot",
       knob="jobPilotCooldownSeconds", source="designer", min=0,
       description="per-actuator-family min seconds between acts"),
    _K("pilot.budget", "int", "2", "pilot", knob="jobPilotBudget",
       source="designer", min=0,
       description="max actuations applied per window"),
    _K("pilot.mindepth", "int", "1", "pilot", source="manual", min=1,
       description="pipeline-depth actuation floor"),
    _K("pilot.maxdepth", "int", "8", "pilot", knob="jobPilotMaxDepth",
       source="designer", min=1,
       description="pipeline-depth actuation ceiling"),
    _K("pilot.stallhighms", "float", "500.0", "pilot", source="manual",
       min=0, description="smoothed stall above this: depth down"),
    _K("pilot.stalllowms", "float", "50.0", "pilot", source="manual",
       min=0, description="smoothed stall below this: headroom"),
    _K("pilot.backloghigh", "float", "2.0", "pilot", source="manual",
       min=0, description="pending landings >= this: backpressure"),
    _K("pilot.saturationhigh", "float", "0.8", "pilot", source="manual",
       min=0, max=1,
       description="full-poll fraction above this: scale out"),
    _K("pilot.laghighms", "float", "30000.0", "pilot", source="manual",
       min=0, description="source watermark lag: scale out"),
    _K("pilot.malformedhigh", "float", "0.3", "pilot", source="manual",
       min=0, max=1,
       description="malformed/total row ratio: backpressure"),
    _K("pilot.maxreplicas", "int", "4", "pilot",
       knob="jobPilotMaxReplicas", source="designer", min=1,
       description="rescale-up replica ceiling"),
    _K("pilot.minpollfraction", "float", "0.125", "pilot",
       source="manual", min=0, max=1,
       description="backpressure poll-fraction floor"),
    # -- livequery serving plane ------------------------------------------
    _K("lq.maxbatchwaitms", "float", "8.0", "lq",
       knob="jobLqMaxBatchWaitMs", source="designer", min=0,
       description="dispatch-tick coalescing deadline"),
    _K("lq.maxfanin", "int", "64", "lq", knob="jobLqMaxFanin",
       source="designer", min=1,
       description="max requests coalesced per dispatch"),
    _K("lq.exectimeoutseconds", "float", "30.0", "lq", source="manual",
       min=0, description="per-execute deadline"),
    _K("lq.sessionttlseconds", "float", "1800.0", "lq",
       knob="jobLqSessionTtlSeconds", source="designer", min=0,
       description="idle session eviction TTL"),
    _K("lq.hbmbudgetmb", "int", "0", "lq", knob="jobLqHbmBudgetMb",
       source="designer", min=0,
       description="warm-kernel HBM budget (0 = unbounded)"),
    _K("lq.maxsessions", "int", "1024", "lq", knob="jobLqMaxSessions",
       source="designer", min=1, description="global session cap"),
    _K("lq.tenant.maxsessions", "int", "8", "lq",
       knob="jobLqTenantMaxSessions", source="designer", min=1,
       description="per-tenant session cap"),
    _K("lq.tenant.maxqps", "float", "50.0", "lq",
       knob="jobLqTenantMaxQps", source="designer", min=0,
       description="per-tenant execute rate cap"),
    _K("lq.ticker", "bool", None, "lq", source="control",
       description="deadline-tick dispatcher thread (the real server "
                   "defaults it on; tickless in-process otherwise)"),
    # -- jar/external UDFs (template parity) -------------------------------
    _K("jar.udf.*.class", "string", None, "udf", source="template",
       description="registered UDF entry point"),
    _K("jar.udf.*.libs", "list", None, "udf", source="template",
       description="UDF dependency list"),
    _K("jar.udf.*.path", "path", None, "udf", source="template",
       description="UDF module path"),
    _K("jar.udaf.*.class", "string", None, "udf", source="template",
       description="registered UDAF entry point"),
    _K("jar.udaf.*.libs", "list", None, "udf", source="template",
       description="UDAF dependency list"),
    _K("jar.udaf.*.path", "path", None, "udf", source="template",
       description="UDAF module path"),
    _K("azurefunction.*.serviceendpoint", "url", None, "udf",
       source="template", read=False,
       description="external-fn sink endpoint (reference parity; the "
                   "sink plane reads it from the output namespace)"),
    _K("azurefunction.*.api", "string", None, "udf", source="template",
       read=False, description="external-fn API name (reference parity)"),
    _K("azurefunction.*.code", "string", None, "udf", source="template",
       read=False, description="external-fn auth code (reference parity)"),
    _K("azurefunction.*.methodtype", "string", None, "udf",
       source="template", read=False,
       description="external-fn HTTP method (reference parity)"),
    _K("azurefunction.*.params", "string", None, "udf", source="template",
       read=False,
       description="external-fn parameter list (reference parity)"),
)


# ---------------------------------------------------------------------------
# CONF.md renderer
# ---------------------------------------------------------------------------
def render_conf_md() -> str:
    """The CONF.md configuration reference, rendered from the registry
    (one table per subsystem, registry order preserved). CONF.md is a
    build artifact of this function — a tier-1 staleness test pins the
    file to the registry, so the doc can never drift from the lattice.
    Regenerate with::

        python -m data_accelerator_tpu.analysis.confspec > CONF.md
    """
    def cell(v) -> str:
        if v is None or v == "":
            return "—"
        return str(v).replace("|", "\\|")

    lines = [
        "# Configuration reference",
        "",
        "<!-- AUTO-GENERATED from data_accelerator_tpu/analysis/"
        "confspec.py — do not edit by hand. -->",
        "<!-- Regenerate: python -m data_accelerator_tpu.analysis."
        "confspec > CONF.md -->",
        "",
        "Every `datax.job.process.*` key the engine reads or the "
        "config chain produces, from the typed registry the `--conf` "
        "analyzer (DX1000–DX1005) and the boot-time `ConfAudit` "
        "(DX1006) both enforce. `*` in a key is one dynamic segment "
        "(a named table, window or UDF). A default of — means the "
        "subsystem has no fallback: the key is either required by its "
        "reader or the feature stays off. Sources: **designer** "
        "(jobconfig knob through S400/S640), **template** (flattener "
        "schema), **generation** (S650 embed), **control** (control "
        "plane at spawn), **manual** (hand-set / test-only).",
        "",
        f"{len(CONF_REGISTRY)} keys, {len(CONSTRAINTS)} cross-key "
        "constraints.",
    ]
    subsystems: List[str] = []
    for e in CONF_REGISTRY:
        if e.subsystem not in subsystems:
            subsystems.append(e.subsystem)
    for sub in subsystems:
        lines += [
            "",
            f"## {sub}",
            "",
            "| key | type | default | designer knob | source | "
            "bounds | description |",
            "|---|---|---|---|---|---|---|",
        ]
        for e in CONF_REGISTRY:
            if e.subsystem != sub:
                continue
            if e.choices:
                bounds = "one of " + ", ".join(e.choices)
            else:
                parts = []
                if e.min is not None:
                    parts.append(f">= {e.min:g}")
                if e.max is not None:
                    parts.append(f"<= {e.max:g}")
                bounds = " and ".join(parts)
            desc = e.description
            if not e.read:
                desc = (desc + " " if desc else "") + "*(parity key — no reader yet)*"
            lines.append(
                f"| `{e.key}` | {e.type} | {cell(e.default)} | "
                f"{cell(e.knob and '`' + e.knob + '`')} | {e.source} | "
                f"{cell(bounds)} | {cell(desc)} |"
            )
    lines += [
        "",
        "## Cross-key constraints (DX1005)",
        "",
        "| rule | description |",
        "|---|---|",
    ]
    for rule in CONSTRAINTS:
        lines.append(f"| `{rule.name}` | {cell(rule.description)} |")
    lines.append("")
    return "\n".join(lines)


if __name__ == "__main__":  # pragma: no cover — doc generator
    print(render_conf_md(), end="")
