"""Flow static analyzer CLI.

    python -m data_accelerator_tpu.analysis flow.json [flow2.json ...]
        [--json] [--device] [--chips=N] [--udfs]
        [--fleet] [--fleet-spec=spec.json]
        [--compile] [--manifest=m.json] [--manifest-out=m.json]
        [--mesh] [--race] [--protocol] [--conf] [--all]

Each argument is a flow config file: either a designer gui JSON or a
full flow document (``{"gui": {...}}``). Prints one line per diagnostic
(or, with ``--json``, a machine-readable report per file) and exits
non-zero when any file has error-severity diagnostics — the CI
self-lint contract.

``--device`` additionally runs the device-plan tier
(``analysis/deviceplan.py``): abstract interpretation of the compiled
plan under ``JAX_PLATFORMS=cpu`` — no device execution — printing the
per-stage HBM/FLOP/ICI cost report and the DX2xx lints. Exit codes
cover the device tier identically: its error diagnostics fail the run
the same way the semantic tier's do. ``--chips=N`` sets the chip count
for the ICI model (default 16, the v5e-16 north-star slice).

``--udfs`` additionally runs the UDF tier (``analysis/udfcheck.py``):
every declared UDF/UDAF resolves through the production loader and its
device functions' ASTs are abstract-interpreted under a taint lattice,
emitting the DX3xx tracing-safety/purity/determinism lints. Same exit
contract.

``--fleet`` runs the fleet tier (``analysis/fleetcheck.py``) over ALL
given flows AS A SET: first-fit-decreasing placement of each flow's
DX2xx HBM total onto the fleet's chips plus the DX4xx capacity/
interference lints, printing the placement plan (chip -> flows ->
packed HBM/headroom). ``--fleet-spec=<file.json>`` overrides the
default fleet (8 chips x 16 GiB, the MULTICHIP slice); keys: chips,
hbmPerChipBytes, headroomFraction, d2hBytesPerSecPerChip,
iciBytesPerSecPerChip, iciTopology. With ``--json`` the report gains a
``fleet`` section carrying the placement plan. Same exit contract.

``--compile`` runs the compile-surface tier
(``analysis/compilecheck.py``): every jit entry point the flow will
ever dispatch — the fused step plus one transfer helper per reachable
(output x pow2 capacity bucket) — is enumerated and lowered over
``jax.eval_shape`` avals (tracing only, no device execution), the
DX6xx finiteness/stability lints run, and the AOT **compile manifest**
is emitted (in ``--json`` under ``compile.manifest``;
``--manifest-out=<file>`` writes it standalone — single flow only).
``--manifest=<file>`` additionally checks a previously emitted manifest
for drift against the fresh lowering (DX602 donation mismatch, DX603
aval/digest drift). Same exit contract.

``--mesh`` runs the mesh-sharding tier (``analysis/meshcheck.py``):
the flow's static SPMD partition plan — per-stage shard axis, forced
reshard edges, closed-form collective bytes — with the DX7xx lints,
cross-checked EXACTLY against a real ``Mesh``+``NamedSharding``
lowering (the CLI virtualizes CPU devices for the check when the
backend has fewer than the requested chips). ``--chips=N`` sets the
mesh size (default 8, the MULTICHIP slice); the one ``--chips`` flag
feeds the device tier's ICI model and the mesh tier alike, and a
non-positive or non-integer value exits 2. Same exit contract.

``--race`` runs the buffer-lifetime/concurrency tier
(``analysis/racecheck.py``): unlike the flow tiers its subject is the
ENGINE the flow deploys onto — every ``runtime/``, ``lq/`` and
``pilot/`` module is abstract-interpreted under a buffer-provenance
lattice (donated ring / pool slot / transfer slot / plain), emitting
the DX8xx lints: escaped donated/pooled views (DX800), unannotated
zero-copy ``asarray`` (DX801), lockset/lock-ordering violations
(DX802), slot re-donation before its land ack (DX803), and blocking
syncs on non-blocking threads (DX804). A clean report certifies the
runtime for ANY flow, so the result is cached per engine-source state.
Same exit contract — this is the standing CI race gate.

``--protocol`` runs the exactly-once delivery-protocol tier
(``analysis/protocheck.py``): like ``--race`` its subject is the
ENGINE — every ``runtime/``, ``lq/`` and ``pilot/`` module plus the
rescale handoff in ``serve/jobs.py`` — per entry point a typed effect
trace of protocol events (sink emit, durable write, pointer flip,
FIFO ack, offset commit, state push, requeue, drain) is extracted and
checked against the declared ordering spec
(``analysis/protospec.py``), emitting the DX90x lints: ack before
durability (DX900), pointer flip before sink emit (DX901), double ack
(DX902), uncovered requeue window (DX903), effects outside the
requeue scope (DX904) and a successor dispatched before its handoff
pull (DX905). Cached per engine-source state; same exit contract —
this is the CI gate the exchange-plane and drain-protocol work builds
behind.

``--conf`` runs the configuration-lattice tier
(``analysis/confcheck.py``): both sides of the flow's conf contract —
the ENGINE side (every ``conf.get`` site in the runtime/serving
packages) and the GENERATION side (S400 gui tokens, S640 knob tables,
S650 flat keys, the flattener template) — are scanned and checked
against the ONE typed registry in ``analysis/confspec.py``, emitting
the DX10xx lints: runtime reads nothing can produce (DX1000),
generated-but-never-read dead conf (DX1001), broken designer
knob→token→key chains (DX1002), default-value drift between layers
(DX1003), plus type/bounds violations (DX1004) and incompatible-knob
combinations (DX1005) in THIS flow's effective conf. Cached per
engine-source state; same exit contract — the runtime half of the
same registry is the host's ``ConfAudit`` (DX1006).

``--all`` runs every tier in one invocation (semantic + device + udfs
+ fleet + compile + mesh + race + protocol + conf) with one merged
``--json`` report (single ``schemaVersion``, combined diagnostics,
same 0/1/2 exit contract) — one CI call instead of nine flags.

Unknown ``--`` flags are rejected with exit 2 (a typo like ``--devcie``
must not silently skip a tier and report a false clean pass).

Exit codes: 0 clean (warnings allowed) · 1 errors found · 2 usage/IO.
"""

from __future__ import annotations

import json
import os
import sys
from typing import List, Optional


def _fmt_bytes(n: float) -> str:
    for unit in ("B", "KB", "MB", "GB"):
        if abs(n) < 1024.0 or unit == "GB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GB"


def _fmt_count(n: float) -> str:
    for unit in ("", "k", "M", "G"):
        if abs(n) < 1000.0 or unit == "G":
            return f"{n:.1f}{unit}" if unit else f"{int(n)}"
        n /= 1000.0
    return f"{n:.1f}G"


def _print_device_plan(path: str, device) -> None:
    totals = device.totals()
    print(
        f"{path}: device plan ({device.chips} chips): "
        f"{len(device.stages)} stage(s), "
        f"HBM {_fmt_bytes(totals['hbmBytes'])} "
        f"(persistent {_fmt_bytes(totals['persistentBytes'])}, "
        f"per-batch {_fmt_bytes(totals['perBatchBytes'])}), "
        f"~{_fmt_count(totals['flops'])} FLOP/batch, "
        f"ICI {_fmt_bytes(totals['iciBytesPerBatch'])}/batch"
    )
    lm = device.latency_model()
    lt = lm["totals"]
    ici = f" + ICI {lt['iciMs']:.3f} ms" if lt["iciMs"] else ""
    print(
        f"{path}: roofline latency ({lm['profileSource']} profile): "
        f"device step {lt['deviceStepMs']:.3f} ms "
        f"+ D2H {lt['d2hMs'] or 0:.3f} ms{ici} = "
        f"{lt['batchMs']:.3f} ms/batch lower bound"
    )
    for s in device.stages:
        line = (
            f"{path}:   [{s.kind}] {s.name} rows={s.rows} "
            f"hbm={_fmt_bytes(s.hbm_bytes)}"
        )
        if s.flops:
            line += f" flops={_fmt_count(s.flops)}"
        if s.ici_bytes:
            line += f" ici={_fmt_bytes(s.ici_bytes)}"
        if s.transient_bytes:
            line += f" transient={_fmt_bytes(s.transient_bytes)}"
        if s.detail:
            line += f" ({s.detail})"
        print(line)


def _print_mesh_plan(path: str, mesh) -> None:
    t = mesh.totals()
    state = "validated" if mesh.validated else "UNVALIDATED"
    print(
        f"{path}: mesh plan ({mesh.chips} chips, {state}): "
        f"{len(mesh.stages)} stage(s), "
        f"ICI {_fmt_bytes(t['iciWireBytesPerBatch'])}/batch wire "
        f"({_fmt_bytes(t['iciResultBytesPerBatch'])} result, "
        f"{t['reshardCount']} reshard(s)), "
        f"per-chip HBM {_fmt_bytes(t['perChipHbmBytes'])}"
    )
    for s in mesh.stages:
        line = (
            f"{path}:   [{s.kind}] {s.name} axis={s.axis} rows={s.rows} "
            f"per-chip={_fmt_bytes(s.per_chip_bytes)}"
        )
        if s.ici_wire_bytes:
            line += f" ici={_fmt_bytes(s.ici_wire_bytes)}"
        if s.detail:
            line += f" ({s.detail})"
        print(line)


def _print_fleet_plan(fleet) -> None:
    spec = fleet.spec
    plan = fleet.placement
    state = "feasible" if plan.feasible else "INFEASIBLE"
    print(
        f"fleet: {len(fleet.footprints)} flow(s) on {spec.chips} chip(s) "
        f"x {_fmt_bytes(spec.hbm_per_chip_bytes)} HBM "
        f"({spec.ici_topology}): {state}"
    )
    for chip in plan.chips:
        if not chip.flows:
            continue
        util = chip.utilization(spec)
        print(
            f"fleet:   chip {chip.chip}: {', '.join(chip.flows)} — "
            f"HBM {_fmt_bytes(chip.hbm_bytes)} ({util:.1%} used, "
            f"headroom {1 - util:.1%})"
        )
    for name in plan.oversized:
        print(f"fleet:   oversized (no chip fits): {name}")
    for name in plan.unplaced:
        print(f"fleet:   unplaced (fleet oversubscribed): {name}")
    for name in plan.unanalyzed:
        print(f"fleet:   unanalyzed (no device footprint): {name}")


# flags the CLI understands; anything else --prefixed is a usage error
# (a typo like --devcie must not silently skip a tier)
KNOWN_FLAGS = {"--json", "--device", "--udfs", "--fleet", "--compile",
               "--mesh", "--race", "--protocol", "--conf", "--all"}
KNOWN_VALUE_FLAGS = ("--chips=", "--fleet-spec=", "--manifest=",
                     "--manifest-out=")


def main(argv: List[str]) -> int:
    # the device tier must never touch an accelerator: force abstract
    # eval on the CPU backend before any jax import
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    as_json = "--json" in argv
    all_tiers = "--all" in argv
    device_tier = "--device" in argv or all_tiers
    udf_tier = "--udfs" in argv or all_tiers
    fleet_tier = "--fleet" in argv or all_tiers
    compile_tier = "--compile" in argv or all_tiers
    mesh_tier = "--mesh" in argv or all_tiers
    race_tier = "--race" in argv or all_tiers
    protocol_tier = "--protocol" in argv or all_tiers
    conf_tier = "--conf" in argv or all_tiers
    chips: Optional[int] = None
    fleet_spec_path: Optional[str] = None
    manifest_path: Optional[str] = None
    manifest_out: Optional[str] = None
    for a in argv:
        if not a.startswith("--"):
            continue
        if a in KNOWN_FLAGS:
            continue
        if a.startswith("--chips="):
            # one shared, typed chip-count parser for every tier that
            # consumes N (device ICI model, mesh plan, fleet spec) — a
            # --chips=0 typo exits 2 instead of modeling nothing
            from .chipcount import ChipCountError, parse_chip_count

            try:
                chips = parse_chip_count(a.split("=", 1)[1], "--chips")
            except ChipCountError as e:
                print(str(e), file=sys.stderr)
                return 2
        elif a.startswith("--fleet-spec="):
            fleet_spec_path = a.split("=", 1)[1]
        elif a.startswith("--manifest="):
            manifest_path = a.split("=", 1)[1]
        elif a.startswith("--manifest-out="):
            manifest_out = a.split("=", 1)[1]
        else:
            print(f"unknown flag: {a}", file=sys.stderr)
            print(__doc__.strip(), file=sys.stderr)
            return 2
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print(__doc__.strip(), file=sys.stderr)
        return 2
    if manifest_out and len(paths) > 1:
        print("--manifest-out accepts a single flow", file=sys.stderr)
        return 2

    if mesh_tier and "xla_force_host_platform_device_count" not in (
        os.environ.get("XLA_FLAGS", "")
    ):
        # the mesh cross-check lowers under a real Mesh: virtualize
        # enough CPU devices (capped — result bytes are N-independent,
        # so an 8-device check validates any --chips). Must happen
        # before the first jax import below.
        n = min(chips or 8, 8)
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + f" --xla_force_host_platform_device_count={n}"
        ).strip()

    from .analyzer import analyze_flow
    from .compilecheck import analyze_flow_compile
    from .confcheck import analyze_flow_conf
    from .deviceplan import analyze_flow_device, combined_report_dict
    from .diagnostics import REPORT_SCHEMA_VERSION
    from .meshcheck import analyze_flow_mesh
    from .protocheck import analyze_flow_protocol
    from .racecheck import analyze_flow_race
    from .udfcheck import analyze_flow_udfs

    shipped_manifest = None
    if manifest_path is not None:
        try:
            with open(manifest_path, "r", encoding="utf-8") as f:
                shipped_manifest = json.load(f)
        except (OSError, ValueError) as e:
            print(
                f"{manifest_path}: cannot read manifest: {e}",
                file=sys.stderr,
            )
            return 2

    fleet_spec = None
    if fleet_spec_path is not None:
        from .fleetcheck import load_fleet_spec

        try:
            fleet_spec = load_fleet_spec(fleet_spec_path)
        except (OSError, ValueError, KeyError) as e:
            print(
                f"{fleet_spec_path}: cannot read fleet spec: {e}",
                file=sys.stderr,
            )
            return 2

    any_errors = False
    json_out = []
    flows: List[dict] = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                flow = json.load(f)
        except (OSError, ValueError) as e:
            print(f"{path}: cannot read flow config: {e}", file=sys.stderr)
            return 2
        flows.append(flow)
        report = analyze_flow(flow)
        device = analyze_flow_device(flow, chips=chips) if device_tier else None
        udfs = analyze_flow_udfs(flow) if udf_tier else None
        comp = (
            analyze_flow_compile(flow, manifest=shipped_manifest)
            if compile_tier else None
        )
        mesh = analyze_flow_mesh(flow, chips=chips) if mesh_tier else None
        race = analyze_flow_race(flow) if race_tier else None
        protocol = (
            analyze_flow_protocol(flow) if protocol_tier else None
        )
        conf = analyze_flow_conf(flow) if conf_tier else None
        any_errors |= not report.ok
        if device is not None:
            any_errors |= not device.ok
        if udfs is not None:
            any_errors |= not udfs.ok
        if comp is not None:
            any_errors |= not comp.ok
            if manifest_out and comp.manifest is not None:
                with open(manifest_out, "w", encoding="utf-8") as f:
                    json.dump(comp.manifest, f, indent=1)
        if mesh is not None:
            any_errors |= not mesh.ok
        if race is not None:
            any_errors |= not race.ok
        if protocol is not None:
            any_errors |= not protocol.ok
        if conf is not None:
            any_errors |= not conf.ok
        if as_json:
            if (
                device is not None or udfs is not None
                or comp is not None or mesh is not None
                or race is not None or protocol is not None
                or conf is not None
            ):
                json_out.append({
                    "file": path,
                    **combined_report_dict(
                        report, device, udfs, compile_surface=comp,
                        mesh=mesh, race=race, protocol=protocol,
                        conf=conf,
                    ),
                })
            else:
                json_out.append({"file": path, **report.to_dict()})
        else:
            diags = list(report.diagnostics) + (
                list(device.diagnostics) if device is not None else []
            ) + (list(udfs.diagnostics) if udfs is not None else []) + (
                list(comp.diagnostics) if comp is not None else []
            ) + (list(mesh.diagnostics) if mesh is not None else []) + (
                list(race.diagnostics) if race is not None else []
            ) + (
                list(protocol.diagnostics) if protocol is not None else []
            ) + (list(conf.diagnostics) if conf is not None else [])
            for d in diags:
                print(f"{path}: {d.render()}")
            n_e = len([d for d in diags if d.is_error])
            n_w = len(diags) - n_e
            print(f"{path}: {n_e} error(s), {n_w} warning(s)")
            if device is not None and device.stages:
                _print_device_plan(path, device)
            if udfs is not None and udfs.udfs:
                for u in udfs.udfs:
                    roles = ",".join(u.analyzed) or "none"
                    print(
                        f"{path}: udf {u.name} [{u.tier}] "
                        f"{u.kind or 'unloadable'} ({u.path}) "
                        f"analyzed={roles}"
                    )
            if comp is not None and comp.entries:
                cd = comp.compile_dict()
                print(
                    f"{path}: compile surface: {cd['entries']} entries "
                    f"(1 step + {cd['helperEntries']} transfer-helper "
                    f"over buckets {cd['buckets']}), "
                    f"{'stable' if cd['stable'] else 'OPEN'}, "
                    f"jit-cache cap {cd['jitCacheCap']}"
                )
            if mesh is not None and mesh.stages:
                _print_mesh_plan(path, mesh)
            if race is not None:
                rd = race.race_dict()
                print(
                    f"{path}: race gate: {rd['analyzedFiles']} engine "
                    f"module(s) analyzed, "
                    f"{rd['allowedZeroCopySites']} pinned zero-copy "
                    f"site(s), {rd['ownerHandoffSites']} owner "
                    f"handoff(s)"
                )
            if protocol is not None:
                pd = protocol.protocol_dict()
                print(
                    f"{path}: protocol gate: {pd['analyzedFiles']} "
                    f"engine module(s) analyzed, "
                    f"{pd['effectEvents']} effect event(s), "
                    f"{pd['postCommitSites']} pinned post-commit "
                    f"site(s), {pd['requeueUpstreamSites']} "
                    f"requeue-upstream site(s)"
                )
            if conf is not None:
                cf = conf.conf_dict()
                print(
                    f"{path}: conf gate: {cf['analyzedFiles']} "
                    f"module(s) scanned, {cf['readSites']} read "
                    f"site(s) / {cf['readKeys']} key(s), "
                    f"{cf['producedKeys']} produced key(s), "
                    f"{cf['registryKeys']} registry row(s)"
                )

    fleet = None
    if fleet_tier:
        from .fleetcheck import analyze_fleet_flows

        fleet = analyze_fleet_flows(flows, spec=fleet_spec)
        any_errors |= not fleet.ok
        if not as_json:
            for d in fleet.diagnostics:
                print(f"fleet: {d.render()}")
            print(
                f"fleet: {len(fleet.errors)} error(s), "
                f"{len(fleet.warnings)} warning(s)"
            )
            _print_fleet_plan(fleet)

    if as_json:
        if fleet is not None:
            print(json.dumps({
                "schemaVersion": REPORT_SCHEMA_VERSION,
                "files": json_out,
                **fleet.to_dict(),
            }, indent=2))
        else:
            print(json.dumps(json_out if len(json_out) > 1 else json_out[0],
                             indent=2))
    return 1 if any_errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
