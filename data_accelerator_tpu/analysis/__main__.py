"""Flow static analyzer CLI.

    python -m data_accelerator_tpu.analysis flow.json [flow2.json ...]
        [--json]

Each argument is a flow config file: either a designer gui JSON or a
full flow document (``{"gui": {...}}``). Prints one line per diagnostic
(or, with ``--json``, a machine-readable report per file) and exits
non-zero when any file has error-severity diagnostics — the CI
self-lint contract.

Exit codes: 0 clean (warnings allowed) · 1 errors found · 2 usage/IO.
"""

from __future__ import annotations

import json
import sys
from typing import List

from .analyzer import analyze_flow


def main(argv: List[str]) -> int:
    as_json = "--json" in argv
    paths = [a for a in argv if not a.startswith("--")]
    if not paths:
        print(__doc__.strip(), file=sys.stderr)
        return 2

    any_errors = False
    json_out = []
    for path in paths:
        try:
            with open(path, "r", encoding="utf-8") as f:
                flow = json.load(f)
        except (OSError, ValueError) as e:
            print(f"{path}: cannot read flow config: {e}", file=sys.stderr)
            return 2
        report = analyze_flow(flow)
        any_errors |= not report.ok
        if as_json:
            json_out.append({"file": path, **report.to_dict()})
        else:
            for d in report.diagnostics:
                print(f"{path}: {d.render()}")
            n_e, n_w = len(report.errors), len(report.warnings)
            print(f"{path}: {n_e} error(s), {n_w} warning(s)")
    if as_json:
        print(json.dumps(json_out if len(json_out) > 1 else json_out[0],
                         indent=2))
    return 1 if any_errors else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
