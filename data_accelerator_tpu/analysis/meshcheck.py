"""Mesh-sharding analyzer: static SPMD partition plans with
runtime-validated ICI cost closed forms.

Sixth analysis tier (the ``--mesh [--chips=N]`` tier, DX7xx). The mesh
path runs the whole step as ONE GSPMD-partitioned program
(``dist/mesh.py``): rows shard over the ``data`` axis, window rings
shard their capacity dim, reference/state tables replicate, and
aggregation outputs replicate — XLA inserts the collectives. Nothing
until now *proved* a flow partitions under that layout or predicted
what the interconnect will cost. This tier does both, statically:

- it infers a **partition plan** from the production planner's
  ``StagePlan``/``JoinSite`` metadata: which axis every stage keeps its
  rows on (``data`` vs ``replicated``), where a resharding all-gather
  is forced (GROUP BY / JOIN / DISTINCT / ORDER BY / LIMIT stages pull
  their sharded inputs onto every chip; sharded OUTPUT views gather at
  the step boundary), and what each stage leaves resident per chip;
- it prices every reshard edge with **closed forms** (documented in
  ANALYSIS.md "Sharding model"): result bytes are exact functions of
  the static shapes (rows x column widths, group capacity G bounding
  grouped outputs, join fan-out F bounding join outputs), and wire
  bytes apply the ring-collective factors over chips N
  (``costmodel.allgather_wire_bytes`` et al.);
- it **cross-checks the model against a real lowering**: every stage
  body is lowered with ``jax.jit`` under a real ``Mesh`` +
  ``NamedSharding`` over ``jax.eval_shape`` avals and must contain ZERO
  collectives under its planned layout (sharded elementwise stages
  communicate nothing; collective stages with replicated inputs
  compute locally), and every reshard edge is lowered as an identity
  resharding kernel whose all-gather census must equal the closed form
  byte-for-byte — the DX2xx ``model == materialized bytes`` contract,
  applied to communication. A disagreement is DX790, an error.

The per-collective *result bytes* are chip-count-independent, so a
cross-check on an M-device mesh (M = min(chips, available devices))
validates the model at any requested ``--chips=N``; with fewer than two
devices the cross-check is skipped and DX791 says so.

The emitted **sharding-plan artifact** (``runtime_model()``) is
embedded into mesh jobs' generated confs by the S660 stage
(``datax.job.process.mesh.model``); at runtime the host's
``ConformanceMonitor`` compares it against the observed
``Mesh_ICI_Bytes`` / ``Mesh_Reshard_Count`` series (the census of the
actually-executed program's collectives, ``dist/mesh.py
collective_summary``) and fires DX510/DX511 ICI-drift events beside
the existing DX501-503.
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..compile.planner import CompiledView, ViewSchema
from .costmodel import (
    allgather_wire_bytes,
    table_bytes,
    view_output_bytes,
)
from .deviceplan import (
    FlowDevicePlan,
    _ordered,
    _plan_from_gui,
    flow_plan_from_processor,
    table_struct,
)
from .diagnostics import Diagnostic, make
from .fleetcheck import DEFAULT_FLEET_CHIPS, FleetSpec

# default chip count for the mesh tier: the 8-device MULTICHIP slice
# the repo actually proves out (tier-1 cross-checks at --chips=8)
DEFAULT_MESH_CHIPS = DEFAULT_FLEET_CHIPS

# shard axes a stage's rows can live on (dist/mesh.py's 1-D data mesh)
AXIS_DATA = "data"
AXIS_REPLICATED = "replicated"

# compute-scaling classes for the DX704 cliff lint: "sharded" work
# shrinks 1/N, "collective" work shrinks 1/N plus wire cost, and
# "replicated" work is flat in N
SCALE_SHARDED = "sharded"
SCALE_COLLECTIVE = "collective"
SCALE_REPLICATED = "replicated"


# ---------------------------------------------------------------------------
# Report types
# ---------------------------------------------------------------------------
@dataclass
class ReshardEdge:
    """One forced layout transition: a ``data``-sharded table gathered
    onto every chip at a stage boundary."""

    table: str
    result_bytes: int  # full logical bytes of the gathered table
    wire_bytes: float  # ring all-gather wire cost at the plan's chips

    def to_dict(self) -> dict:
        return {
            "table": self.table,
            "collective": "all-gather",
            "resultBytes": self.result_bytes,
            "wireBytes": round(self.wire_bytes, 1),
        }


@dataclass
class MeshStage:
    """One stage of the partition plan."""

    name: str
    kind: str  # input | project | ring | window | state | refdata | group | union
    axis: str  # AXIS_DATA | AXIS_REPLICATED
    scaling: str  # SCALE_SHARDED | SCALE_COLLECTIVE | SCALE_REPLICATED
    rows: int
    hbm_bytes: int  # full logical residency (the DX2xx byte model)
    per_chip_bytes: int  # what one chip keeps resident at N chips
    reshards: List[ReshardEdge] = field(default_factory=list)
    # cross-check result: collective result bytes the real Mesh
    # lowering produced for this stage's edges (None = not lowered)
    lowered_bytes: Optional[int] = None
    detail: str = ""

    @property
    def ici_result_bytes(self) -> int:
        return sum(e.result_bytes for e in self.reshards)

    @property
    def ici_wire_bytes(self) -> float:
        return sum(e.wire_bytes for e in self.reshards)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "kind": self.kind,
            "axis": self.axis,
            "scaling": self.scaling,
            "rows": self.rows,
            "hbmBytes": self.hbm_bytes,
            "perChipBytes": self.per_chip_bytes,
            "iciResultBytes": self.ici_result_bytes,
            "iciWireBytes": round(self.ici_wire_bytes, 1),
            "reshards": [e.to_dict() for e in self.reshards],
            "loweredBytes": self.lowered_bytes,
            "detail": self.detail,
        }


@dataclass
class MeshPlanReport:
    flow: str
    chips: int
    stages: List[MeshStage]
    diagnostics: List[Diagnostic]
    # True when every stage body and reshard edge was cross-checked
    # against a real Mesh lowering (>=2 devices were available)
    validated: bool = False

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.is_error]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if not d.is_error]

    @property
    def ok(self) -> bool:
        return not self.errors

    def codes(self) -> List[str]:
        return [d.code for d in self.diagnostics]

    def totals(self) -> dict:
        return {
            "iciResultBytesPerBatch": sum(
                s.ici_result_bytes for s in self.stages
            ),
            "iciWireBytesPerBatch": round(
                sum(s.ici_wire_bytes for s in self.stages), 1
            ),
            "reshardCount": sum(len(s.reshards) for s in self.stages),
            "perChipHbmBytes": sum(s.per_chip_bytes for s in self.stages),
            "chips": self.chips,
        }

    def mesh_dict(self) -> dict:
        """The sharding-plan portion (no diagnostics) — what the
        designer renders as the sharding table and the CLI's ``--json``
        report carries under ``mesh``."""
        return {
            "flow": self.flow,
            "chips": self.chips,
            "validated": self.validated,
            "stages": [s.to_dict() for s in self.stages],
            "totals": self.totals(),
            "latencyModel": self.latency_model(),
        }

    def latency_model(
        self, profile: Optional[dict] = None, source: str = "default",
    ) -> dict:
        """The wire-time axis of the sharding plan: the DX7xx collective
        wire bytes priced over the profile's ICI link bandwidth
        (per-stage and total ms). Like the device tier's latencyModel
        this is a roofline lower bound — the datasheet default profile
        unless a calibrated one is passed."""
        from .costmodel import transfer_time_ms

        if profile is None:
            from ..obs.calibrate import DEFAULT_PROFILE

            profile = DEFAULT_PROFILE.to_dict()
            source = "default"
        gbps = profile.get("ici_gbps")
        stages = [
            {
                "name": s.name,
                "iciMs": (
                    round(transfer_time_ms(s.ici_wire_bytes, gbps), 4)
                    if gbps else None
                ),
            }
            for s in self.stages
        ]
        total = transfer_time_ms(
            self.totals()["iciWireBytesPerBatch"], gbps
        )
        return {
            "profileSource": source,
            "iciGBps": gbps,
            "stages": stages,
            "totals": {
                "iciMs": round(total, 4) if total is not None else None,
            },
        }

    def to_dict(self) -> dict:
        from .diagnostics import REPORT_SCHEMA_VERSION

        return {
            "schemaVersion": REPORT_SCHEMA_VERSION,
            "ok": self.ok,
            "errorCount": len(self.errors),
            "warningCount": len(self.warnings),
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "mesh": self.mesh_dict(),
        }

    def runtime_model(self) -> dict:
        """The machine-readable sharding-plan artifact the S660
        generation stage embeds into mesh jobs' confs
        (``datax.job.process.mesh.model``) — the slice a running host
        checks its observed collective census against
        (``obs/conformance.py`` DX510/DX511)."""
        from .costmodel import mesh_runtime_model

        return mesh_runtime_model(
            self.totals(), [s.to_dict() for s in self.stages]
        )


# ---------------------------------------------------------------------------
# Partition-plan inference
# ---------------------------------------------------------------------------
def _is_collective_view(view: CompiledView) -> bool:
    """True when the stage's lowering needs its inputs whole on every
    chip: grouping/distinct sort, join gid sort or match matrix, a
    global ORDER BY / LIMIT prefix, a host-side finishing sort, a
    multi-branch union concat, or a Pallas-kernel UDF call (a custom
    call has no SPMD partitioning rule — the partitioner replicates
    it)."""
    p = view.plan
    if view.host_order:
        return True
    if p is None:
        return False
    return bool(
        p.grouped or p.joins or p.distinct or p.order_keys
        or p.limit is not None or p.union_branches > 1
        or p.unshardable_udfs
    )


def _replication_origin(view: CompiledView) -> Optional[str]:
    """The structural reason a stage cannot scale with N, if any: a
    global sort over the raw scope, a host-side finishing sort, or an
    unshardable custom-kernel UDF. Grouped sorts don't count — they
    sort the G-row group output, and the gather itself is modeled."""
    p = view.plan
    if view.host_order:
        return "host-side ORDER BY"
    if p is not None and p.order_keys and not p.grouped:
        return "device ORDER BY"
    if p is not None and p.unshardable_udfs:
        return (
            "Pallas kernel UDF "
            + "/".join(p.unshardable_udfs)
        )
    return None


def _view_model_bytes(view: CompiledView) -> int:
    return view_output_bytes(view.schema.types, view.plan, view.capacity)


def _per_chip(bytes_: int, axis: str, chips: int) -> int:
    if axis == AXIS_DATA and chips > 1:
        return int(math.ceil(bytes_ / chips))
    return int(bytes_)


@dataclass
class _EnvEntry:
    """One table visible to pipeline views: its schema, row capacity,
    planned axis and gatherable byte size."""

    schema: ViewSchema
    rows: int
    axis: str
    gather_bytes: int  # bytes an all-gather of this table moves


def _infer_plan(
    bundle: FlowDevicePlan, chips: int,
) -> Tuple[List[MeshStage], Dict[str, _EnvEntry]]:
    """Walk raw -> projections -> rings/windows -> state/refdata ->
    transform views, assigning each stage an axis and collecting the
    reshard edges the layout forces."""
    stages: List[MeshStage] = []
    env: Dict[str, _EnvEntry] = {}

    # raw ingest + per-source projection chains: rows shard end to end
    for source, views in bundle.projection_views.items():
        raw_schema, cap = bundle.raw_schemas[source]
        raw_bytes = table_bytes(raw_schema.types, cap)
        stages.append(MeshStage(
            name=f"input:{source}", kind="input", axis=AXIS_DATA,
            scaling=SCALE_SHARDED, rows=cap, hbm_bytes=raw_bytes,
            per_chip_bytes=_per_chip(raw_bytes, AXIS_DATA, chips),
            detail="raw ingest batch (rows shard on arrival)",
        ))
        for v in views:
            b = _view_model_bytes(v)
            stages.append(MeshStage(
                name=v.name, kind="project", axis=AXIS_DATA,
                scaling=SCALE_SHARDED, rows=v.capacity, hbm_bytes=b,
                per_chip_bytes=_per_chip(b, AXIS_DATA, chips),
                detail="projection (elementwise, stays sharded)",
            ))
        target = bundle.target_of[source]
        schema = bundle.target_schemas[target]
        env[target] = _EnvEntry(
            schema, bundle.target_caps[target], AXIS_DATA,
            table_bytes(schema.types, bundle.target_caps[target]),
        )

    # window rings shard their capacity dim; the flattened window view
    # the pipeline reads inherits the data axis
    for table, slots in bundle.ring_slots.items():
        rows = slots * bundle.target_caps[table]
        schema = bundle.target_schemas[table]
        b = table_bytes(schema.types, rows)
        stages.append(MeshStage(
            name=f"ring:{table}", kind="ring", axis=AXIS_DATA,
            scaling=SCALE_SHARDED, rows=rows, hbm_bytes=b,
            per_chip_bytes=_per_chip(b, AXIS_DATA, chips),
            detail=f"{slots} slots x {bundle.target_caps[table]} rows, "
                   "capacity dim sharded",
        ))
    for wname, (table, dur_s) in bundle.windows.items():
        rows = bundle.ring_slots[table] * bundle.target_caps[table]
        schema = bundle.target_schemas[table]
        b = table_bytes(schema.types, rows)
        env[wname] = _EnvEntry(schema, rows, AXIS_DATA, b)
        stages.append(MeshStage(
            name=wname, kind="window", axis=AXIS_DATA,
            scaling=SCALE_SHARDED, rows=rows, hbm_bytes=b,
            per_chip_bytes=_per_chip(b, AXIS_DATA, chips),
            detail=f"{dur_s:g}s window over {table} (sharded with the ring)",
        ))

    # state/refdata replicate (broadcast-join sides)
    for sname, (schema, cap) in bundle.state.items():
        b = table_bytes(schema.types, cap)
        env[sname] = _EnvEntry(schema, cap, AXIS_REPLICATED, b)
        stages.append(MeshStage(
            name=f"state:{sname}", kind="state", axis=AXIS_REPLICATED,
            scaling=SCALE_REPLICATED, rows=cap, hbm_bytes=b,
            per_chip_bytes=b,
            detail="accumulation table (replicated)",
        ))
    for rname, (schema, cap) in bundle.refdata.items():
        b = table_bytes(schema.types, cap)
        env[rname] = _EnvEntry(schema, cap, AXIS_REPLICATED, b)
        stages.append(MeshStage(
            name=f"refdata:{rname}", kind="refdata", axis=AXIS_REPLICATED,
            scaling=SCALE_REPLICATED, rows=cap, hbm_bytes=b,
            per_chip_bytes=b,
            detail="reference data (replicated)",
        ))

    # transform views
    for view in bundle.pipeline.views:
        p = view.plan
        kind = p.kind if p is not None else "project"
        sources = [s for s in (p.sources if p else ()) if s in env]
        collective = _is_collective_view(view)
        if collective:
            axis, scaling = AXIS_REPLICATED, SCALE_COLLECTIVE
            if _replication_origin(view):
                # a global sort over the raw scope or a custom-kernel
                # UDF has no sharded lowering: the stage runs whole on
                # every chip regardless of N (a grouped ORDER BY only
                # sorts the G-row group output — that stays collective)
                scaling = SCALE_REPLICATED
        elif sources and all(env[s].axis == AXIS_DATA for s in sources):
            axis, scaling = AXIS_DATA, SCALE_SHARDED
        else:
            # elementwise over replicated input(s): runs replicated
            axis, scaling = AXIS_REPLICATED, SCALE_REPLICATED
        edges = []
        if collective:
            for s in sources:
                if env[s].axis == AXIS_DATA:
                    edges.append(ReshardEdge(
                        s, env[s].gather_bytes,
                        allgather_wire_bytes(env[s].gather_bytes, chips),
                    ))
        b = _view_model_bytes(view)
        details = []
        if p is not None and p.grouped:
            details.append(f"group G<={p.groups_bound}")
        for site in (p.joins if p else ()):
            details.append(
                f"{site.algorithm}-join F<={site.out_rows} vs "
                f"{site.right_table}"
            )
        if p is not None and (p.order_keys or view.host_order):
            details.append("global sort")
        if edges:
            details.append(
                "gathers " + ", ".join(e.table for e in edges)
            )
        stage = MeshStage(
            name=view.name, kind=kind, axis=axis, scaling=scaling,
            rows=view.capacity, hbm_bytes=b,
            per_chip_bytes=_per_chip(b, axis, chips),
            reshards=edges, detail="; ".join(details),
        )
        # sharded OUTPUT views gather at the step boundary: the runtime
        # replicates every output dataset before the host reads it
        if view.name in bundle.output_datasets and axis == AXIS_DATA:
            stage.reshards.append(ReshardEdge(
                f"{view.name} (output boundary)", b,
                allgather_wire_bytes(b, chips),
            ))
            if not stage.detail:
                stage.detail = "sharded output: gathered at step boundary"
        stages.append(stage)
        env[view.name] = _EnvEntry(view.schema, view.capacity, axis, b)
    return stages, env


# ---------------------------------------------------------------------------
# Lowering cross-check: the model must equal the real Mesh lowering
# ---------------------------------------------------------------------------
def _overflow_struct(view: CompiledView) -> Dict[str, jax.ShapeDtypeStruct]:
    """The hidden __overflow columns a view's output table carries —
    part of the boundary-gather bytes, so part of the cross-check."""
    p = view.plan
    out: Dict[str, jax.ShapeDtypeStruct] = {}
    if p is None or p.kind == "union":
        return out
    rows = view.capacity
    if p.grouped:
        out["__overflow.groups"] = jax.ShapeDtypeStruct((rows,), jnp.int32)
    if p.joins:
        out["__overflow.joins"] = jax.ShapeDtypeStruct((rows,), jnp.int32)
    return out


def _lower_and_census(fn, avals, in_shardings, out_shardings):
    from ..dist.mesh import summarize_compiled

    lowered = jax.jit(
        fn, in_shardings=in_shardings, out_shardings=out_shardings
    ).lower(avals)
    return summarize_compiled(lowered.compile())


def _cross_check(
    bundle: FlowDevicePlan,
    stages: List[MeshStage],
    env: Dict[str, _EnvEntry],
    mesh,
    diags: List[Diagnostic],
) -> None:
    """Lower every stage body and reshard edge under the real mesh and
    assert the closed-form model matches the partitioner's output
    exactly. Disagreement is DX790 — the model may never silently
    drift from what XLA builds."""
    from ..dist.mesh import replicated, row_sharding

    row, rep = row_sharding(mesh), replicated(mesh)
    by_name = {s.name: s for s in stages}
    aux = bundle.aux_tables

    # 1. stage bodies: zero collectives under the planned layout
    for view in bundle.pipeline.views:
        stage = by_name[view.name]
        p = view.plan
        sources = [s for s in (p.sources if p else ()) if s in env]
        if not sources:
            continue
        collective = stage.scaling in (SCALE_COLLECTIVE, SCALE_REPLICATED)
        in_sh = {
            s: (rep if (collective or env[s].axis != AXIS_DATA) else row)
            for s in sources
        }
        avals = {s: table_struct(env[s].schema, env[s].rows) for s in sources}
        out_sh = rep if stage.axis != AXIS_DATA else row

        def body(tables, _view=view, _aux=aux):
            t = dict(tables)
            t["__aux"] = _aux
            return _view.fn(t, jnp.asarray(0, jnp.int32),
                            jnp.asarray(0, jnp.int32))

        try:
            census = _lower_and_census(body, avals, (in_sh,), out_sh)
        except Exception as e:  # noqa: BLE001 — a lowering blowup is a finding
            diags.append(make(
                "DX790", view.name,
                f"mesh lowering of stage body failed under the planned "
                f"layout ({stage.axis}): {e}",
            ))
            continue
        if census.op_count:
            diags.append(make(
                "DX790", view.name,
                f"sharding model mismatch: stage body planned as "
                f"communication-free ({stage.axis} layout) but the SPMD "
                f"partitioner inserted {census.op_count} collective(s) "
                f"moving {census.result_bytes} result bytes "
                f"({census.to_dict()}) — the closed-form model no longer "
                f"describes this lowering",
            ))

    # 2. reshard edges: the identity resharding kernel's all-gather
    #    census must equal the closed form byte-for-byte
    checked: Dict[Tuple, int] = {}
    for stage in stages:
        total = 0
        for edge in stage.reshards:
            src = edge.table.split(" ")[0]
            if src in env and not edge.table.endswith("(output boundary)"):
                struct = table_struct(env[src].schema, env[src].rows)
                extra: Dict[str, jax.ShapeDtypeStruct] = {}
            else:
                # output-boundary edge: the view's own table, overflow
                # columns included
                view = next(
                    v for v in bundle.pipeline.views if v.name == src
                )
                struct = table_struct(view.schema, view.capacity)
                extra = _overflow_struct(view)
            key = (
                src, struct.valid.shape, tuple(sorted(struct.cols)),
                tuple(sorted(extra)),
            )
            if key not in checked:
                if extra:
                    cols = dict(struct.cols)
                    cols.update(extra)
                    from ..compile.planner import TableData

                    struct = TableData(cols, struct.valid)
                try:
                    census = _lower_and_census(
                        lambda t: t, struct,
                        (jax.tree_util.tree_map(lambda _: row, struct),),
                        rep,
                    )
                except Exception as e:  # noqa: BLE001
                    diags.append(make(
                        "DX790", stage.name,
                        f"mesh lowering of the {src} reshard edge "
                        f"failed: {e}",
                    ))
                    checked[key] = -1
                    continue
                gathered = census.ops.get("all-gather", (0, 0))[1]
                others = {
                    k: v for k, v in census.ops.items() if k != "all-gather"
                }
                if others:
                    diags.append(make(
                        "DX790", stage.name,
                        f"reshard edge {src} lowered to non-all-gather "
                        f"collectives {others} — the model prices "
                        f"gathers only",
                    ))
                checked[key] = gathered
            lowered = checked[key]
            if lowered >= 0 and lowered != edge.result_bytes:
                diags.append(make(
                    "DX790", stage.name,
                    f"sharding model mismatch on the {edge.table} "
                    f"reshard: closed form says {edge.result_bytes} "
                    f"all-gather result bytes, the Mesh lowering moved "
                    f"{lowered} — the byte model must match the "
                    f"lowering exactly",
                ))
            if lowered >= 0:
                total += lowered
        stage.lowered_bytes = total if stage.reshards else 0


# ---------------------------------------------------------------------------
# DX7xx lints over the partition plan
# ---------------------------------------------------------------------------
def _lint(
    bundle: FlowDevicePlan,
    stages: List[MeshStage],
    chips: int,
    spec: FleetSpec,
    jobconf: Dict[str, object],
    diags: List[Diagnostic],
) -> None:
    batch_scale = max(bundle.target_caps.values(), default=0)

    # DX700: structurally unshardable stages (global sorts over the raw
    # scope, Pallas-kernel UDF calls) replicate everything regardless
    # of N (a grouped ORDER BY only sorts the G-row output)
    for view in bundle.pipeline.views:
        p = view.plan
        origin = _replication_origin(view)
        if origin:
            rows = p.input_rows if p is not None else view.capacity
            diags.append(make(
                "DX700", view.name,
                f"unshardable stage forces full replication: the "
                f"{origin} materializes all {rows} input rows on every "
                f"chip at any chip count — this stage cannot shard",
            ))

    # DX701: the same sharded table gathered at 2+ stage boundaries
    gathers: Dict[str, List[str]] = {}
    for s in stages:
        for e in s.reshards:
            if not e.table.endswith("(output boundary)"):
                gathers.setdefault(e.table, []).append(s.name)
    for table, consumers in sorted(gathers.items()):
        if len(consumers) > 1:
            diags.append(make(
                "DX701", table,
                f"resharding between adjacent stages: {table} is "
                f"gathered onto every chip at {len(consumers)} stage "
                f"boundaries ({', '.join(consumers)}) — each pays the "
                f"all-gather again; fold the consumers or share a "
                f"gathered intermediate",
            ))

    # DX702: per-chip residency vs chip HBM at the requested N
    per_chip = sum(s.per_chip_bytes for s in stages)
    budget = spec.hbm_per_chip_bytes * spec.headroom_fraction
    if per_chip > budget:
        diags.append(make(
            "DX702", "",
            f"per-chip shard exceeds chip HBM at {chips} chips: "
            f"{per_chip} bytes resident per chip (sharded shards + "
            f"replicated tables) vs the {spec.hbm_per_chip_bytes}-byte "
            f"chip at {spec.headroom_fraction:.0%} headroom "
            f"({int(budget)} usable)",
        ))

    # DX703: ICI wire demand vs the fleet-spec interconnect budget
    wire = sum(s.ici_wire_bytes for s in stages)
    interval = bundle.interval_s or 1.0
    ici_budget = spec.ici_bytes_per_sec_per_chip * chips * interval
    if wire > ici_budget:
        diags.append(make(
            "DX703", "",
            f"predicted ICI traffic {wire:.0f} bytes/batch exceeds the "
            f"fleet-spec budget ({spec.ici_bytes_per_sec_per_chip:.0f} "
            f"B/s/chip x {chips} chips x {interval:g}s interval = "
            f"{ici_budget:.0f}) — collectives will dominate the step",
        ))

    # DX704: stages flat or worse in N (replicated compute at batch
    # scale: doubling the chips doubles the fleet's work, not the
    # speed). Only replication ORIGINS fire — a stage that merely
    # inherits a replicated input is the origin's symptom, not a second
    # finding.
    origins = {
        v.name for v in bundle.pipeline.views if _replication_origin(v)
    }
    for s in stages:
        if (
            s.scaling == SCALE_REPLICATED
            and s.name in origins
            and batch_scale
            and s.rows >= batch_scale
        ):
            diags.append(make(
                "DX704", s.name,
                f"scaling cliff: stage runs replicated over {s.rows} "
                f"rows on every chip — its modeled per-chip cost is "
                f"flat in the chip count, so the flow stops scaling "
                f"here (first {chips}-chip victim)",
            ))

    # DX705: single-chip transfer optimizations silently off under mesh
    def _off(key: str) -> bool:
        return str(jobconf.get(key, "")).lower() == "false"

    if (
        chips > 1
        and bundle.output_datasets
        and not (_off("jobSizedTransfer") and _off("jobOutputSlots"))
    ):
        diags.append(make(
            "DX705", "",
            f"sized output transfer and donated output slots "
            f"auto-disable under a {chips}-chip mesh: every batch "
            f"fetches the full padded capacity of "
            f"{sorted(bundle.output_datasets)} and the background "
            f"double-buffered landing path does not apply — the "
            f"single-chip D2H optimizations do not compound here yet",
        ))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def _analyze(
    bundle: Optional[FlowDevicePlan],
    diags: List[Diagnostic],
    name: str,
    chips: int,
    spec: Optional[FleetSpec],
    jobconf: Dict[str, object],
    lower: Optional[bool],
) -> MeshPlanReport:
    if bundle is None:
        return MeshPlanReport(name, chips, [], _ordered(diags))
    spec = spec or FleetSpec()
    try:
        stages, env = _infer_plan(bundle, chips)
    except Exception as e:  # noqa: BLE001 — plan inference blowup is a finding
        diags.append(make("DX790", "", f"partition-plan inference failed: {e}"))
        return MeshPlanReport(bundle.name, chips, [], _ordered(diags))
    _lint(bundle, stages, chips, spec, jobconf, diags)

    validated = False
    n_dev = len(jax.devices())
    want_lower = lower if lower is not None else n_dev >= 2
    if want_lower and n_dev >= 2:
        from ..dist.mesh import make_mesh

        mesh = make_mesh(min(chips, n_dev))
        _cross_check(bundle, stages, env, mesh, diags)
        validated = True
    elif want_lower or lower is None:
        diags.append(make(
            "DX791", "",
            f"mesh lowering cross-check skipped: {n_dev} device(s) "
            f"available, need >= 2 — the collective byte model is "
            f"emitted unvalidated (run under a multi-device backend; "
            f"the CLI virtualizes CPU devices)",
        ))
    return MeshPlanReport(
        bundle.name, chips, stages, _ordered(diags), validated=validated
    )


def _resolve_chips(chips: Optional[int], jobconf: Dict[str, object]) -> int:
    if chips is not None:
        return chips
    from .deviceplan import _jobconf_int

    return (
        _jobconf_int(jobconf, "jobNumChips", "jobNumExecutors")
        or DEFAULT_MESH_CHIPS
    )


def analyze_flow_mesh(
    flow: dict,
    chips: Optional[int] = None,
    spec: Optional[FleetSpec] = None,
    lower: Optional[bool] = None,
) -> MeshPlanReport:
    """Mesh-sharding analysis of a flow config (gui JSON or full flow
    document). Compiles with the production planner, infers the SPMD
    partition plan, prices the collectives, and (when >= 2 devices are
    available, or ``lower=True``) cross-checks the byte model against a
    real ``Mesh`` lowering. ``lower=False`` skips the cross-check (the
    fast model-only path config generation uses)."""
    gui = flow.get("gui") if isinstance(flow.get("gui"), dict) else flow
    jobconf = ((gui.get("process") or {}).get("jobconfig") or {})
    n_chips = _resolve_chips(chips, jobconf)
    diags: List[Diagnostic] = []
    plan_diags: List[Diagnostic] = []
    bundle = _plan_from_gui(gui, plan_diags, n_chips)
    # the bundle builder reports in DX2xx; re-code for this tier
    for d in plan_diags:
        code = "DX790" if d.code == "DX290" else "DX791"
        diags.append(make(code, d.table, d.message, d.span))
    return _analyze(
        bundle, diags, gui.get("name") or "", n_chips, spec, jobconf, lower
    )


def analyze_processor_mesh(
    proc,
    chips: Optional[int] = None,
    spec: Optional[FleetSpec] = None,
    lower: Optional[bool] = None,
) -> MeshPlanReport:
    """Mesh-sharding analysis of an already-built ``FlowProcessor`` —
    the exact compiled views the (possibly mesh-sharded) jitted step
    runs (the bench / MULTICHIP cross-validation path, mirroring
    ``deviceplan.analyze_processor``)."""
    diags: List[Diagnostic] = []
    n_chips = chips or (proc.mesh.size if proc.mesh is not None else None)
    bundle = flow_plan_from_processor(proc, n_chips)
    n_chips = n_chips or DEFAULT_MESH_CHIPS
    return _analyze(bundle, diags, bundle.name, n_chips, spec, {}, lower)
