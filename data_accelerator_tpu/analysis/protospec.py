"""The DECLARED exactly-once delivery protocol (shared by the static
``--protocol`` tier and the runtime ``ProtocolMonitor``).

The engine's delivery guarantee is an ordering contract over a small
vocabulary of effect events:

    sink emit  ->  durable checkpoint / pointer flip  ->  FIFO ack
                                                     ->  offset commit

plus the rescale A/B handoff (pull the owned-partition plan before the
first successor dispatch) and the failure half (a failed batch requeues
its whole unacked window before re-raising). Until this module, that
contract existed only as hand-ordered statements in ``runtime/host.py``
/ ``runtime/checkpoint.py`` / ``runtime/statetable.py`` /
``serve/jobs.py``, sampled by chaos drills. Here it is a TABLE: the
static pass (``analysis/protocheck.py``, DX900-DX905) checks every
engine entry point's extracted effect trace against it, and the runtime
monitor (``runtime/protocolmonitor.py``, DX906) checks every live
batch's recorded linearization against the SAME rule objects via
``check_sequence``.

Event kinds
-----------
- ``SINK_EMIT``     — rows handed to external sinks (dispatcher fan-out)
- ``DURABLE_WRITE`` — bytes forced to stable storage (fsync / durable
  replace / local state-store file put / window snapshot save)
- ``POINTER_FLIP``  — the atomic commit point: an A/B pointer flip or
  state-table persist (``processor.commit()``)
- ``FIFO_ACK``      — upstream FIFO told the batch is consumed
- ``OFFSET_COMMIT`` — source offsets checkpointed (the at-least-once
  replay cursor; legitimately AFTER the ack)
- ``STATE_PUSH``    — owned window partitions shipped to the state
  mirror for a rescale successor
- ``REQUEUE``       — unacked window pushed back for redelivery
- ``DRAIN_MARKER``  — landing-queue settle/drain barrier
- ``HANDOFF_PULL``  — a rescale successor's owned-partition plan
  computed / stamped into its record
- ``DISPATCH``      — a successor job record submitted to the cluster

Rules DX900-DX902 are also enforced at runtime (``runtime=True``):
they are orderings of per-batch events the monitor observes directly.
DX903-DX905 are static-only — requeue coverage and the rescale handoff
are control-flow properties of the SOURCE (except-handler shape, call
order across a config-build function), not of one batch's event list.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

SINK_EMIT = "SINK_EMIT"
DURABLE_WRITE = "DURABLE_WRITE"
POINTER_FLIP = "POINTER_FLIP"
FIFO_ACK = "FIFO_ACK"
OFFSET_COMMIT = "OFFSET_COMMIT"
STATE_PUSH = "STATE_PUSH"
REQUEUE = "REQUEUE"
DRAIN_MARKER = "DRAIN_MARKER"
HANDOFF_PULL = "HANDOFF_PULL"
DISPATCH = "DISPATCH"

EVENT_KINDS = (
    SINK_EMIT, DURABLE_WRITE, POINTER_FLIP, FIFO_ACK, OFFSET_COMMIT,
    STATE_PUSH, REQUEUE, DRAIN_MARKER, HANDOFF_PULL, DISPATCH,
)

# externally visible WRITES — the events whose placement relative to
# the ack decides exactly-once vs lost-or-duplicated
EFFECT_KINDS = frozenset({
    SINK_EMIT, DURABLE_WRITE, POINTER_FLIP, OFFSET_COMMIT, STATE_PUSH,
})


@dataclass(frozen=True)
class ProtocolRule:
    """One ordering invariant of the delivery protocol."""

    code: str
    name: str
    description: str
    runtime: bool  # also enforced per-batch by the ProtocolMonitor


RULES: Tuple[ProtocolRule, ...] = (
    ProtocolRule(
        "DX900", "durability-before-ack",
        "the pointer flip (and any os.replace's tmp-file + dir fsync "
        "pair) must happen before the upstream FIFO ack — an ack "
        "before durability loses the batch on a crash",
        runtime=True,
    ),
    ProtocolRule(
        "DX901", "sink-before-pointer-commit",
        "sink emit must precede the pointer flip: committing state for "
        "rows the sinks have not accepted double-counts them on replay",
        runtime=True,
    ),
    ProtocolRule(
        "DX902", "ack-at-most-once-per-batch",
        "each source is acked at most once per batch — a second ack "
        "releases a window the failure path still expects to requeue",
        runtime=True,
    ),
    ProtocolRule(
        "DX903", "requeue-covers-unacked-window",
        "a function that acks must requeue the WHOLE unacked window "
        "(every source the ack loop covers) in its failure handler",
        runtime=False,
    ),
    ProtocolRule(
        "DX904", "effect-outside-requeue-scope",
        "pre-ack effects must sit inside a try whose handler requeues; "
        "post-ack effects are at-least-once territory and must carry "
        "an explicit `# dx-proto: post-commit` marker",
        runtime=False,
    ),
    ProtocolRule(
        "DX905", "handoff-pull-before-first-dispatch",
        "a rescale must pull/stamp the successor's owned-partition "
        "plan before the first successor dispatch, or the new replica "
        "boots without its state assignment",
        runtime=False,
    ),
)

RULES_BY_CODE: Dict[str, ProtocolRule] = {r.code: r for r in RULES}
RUNTIME_RULES: Tuple[ProtocolRule, ...] = tuple(
    r for r in RULES if r.runtime
)


def check_sequence(
    events: List[dict], failed: bool = False,
) -> List[Tuple[str, str]]:
    """Validate ONE sealed batch linearization against the runtime
    rules. ``events`` is the recorded sequence, each a dict with at
    least ``kind`` (an ``EVENT_KINDS`` member) and optionally
    ``source`` (for per-source ack accounting). Returns at most one
    ``(rule_code, message)`` per rule — a batch that acks three
    sources before the flip is ONE protocol violation, not three."""
    out: List[Tuple[str, str]] = []
    first: Dict[str, int] = {}
    for i, ev in enumerate(events):
        first.setdefault(ev.get("kind", ""), i)

    ack = first.get(FIFO_ACK)
    flip = first.get(POINTER_FLIP)
    sink = first.get(SINK_EMIT)

    # DX900: an ack with no earlier pointer flip — on a failed batch
    # this is exactly the ack-before-durability reorder (the acked
    # window is gone AND requeued/aborted)
    if ack is not None and (flip is None or ack < flip):
        out.append((
            "DX900",
            "FIFO ack recorded before the durable pointer flip"
            + (" on a FAILED batch" if failed else ""),
        ))

    # DX901: pointer flip before the first sink emit (both observed)
    if flip is not None and sink is not None and flip < sink:
        out.append((
            "DX901",
            "pointer flip recorded before the sink emit",
        ))

    # DX902: a source acked more than once in one batch
    acked: Dict[str, int] = {}
    for ev in events:
        if ev.get("kind") == FIFO_ACK:
            src = str(ev.get("source", ""))
            acked[src] = acked.get(src, 0) + 1
    dup = sorted(s for s, n in acked.items() if n > 1)
    if dup:
        out.append((
            "DX902",
            f"source(s) acked more than once in one batch: "
            f"{', '.join(dup)}",
        ))
    return out
