"""Compile-surface analyzer: prove a flow's trace surface closed, then
ship it precompiled.

Fifth analysis tier (the ``--compile`` tier, DX6xx). Every job start,
preemption recovery and restart today pays a full XLA trace+compile at
first dispatch. Shipping serialized compiles ahead of time is only safe
if the set of jit entry points a flow will ever dispatch is **finite
and statically known** — which is exactly what this tier proves:

- it enumerates every entry point the runtime can dispatch — the fused
  step function (``runtime/processor.py build_step_fn``), one
  ``_slice_table``/``_pack_slot`` transfer helper per reachable
  (output x pow2 capacity bucket) from the sized-transfer lattice
  (``transfer_buckets``: the EWMA sizing buckets plus the full-capacity
  overflow fetch; the x2 overflow headroom boost only moves *within*
  this lattice, so it adds no entries),
- derives each entry's trace signature over ``jax.eval_shape`` avals
  and lowers it with ``jax.jit(...).lower()`` — tracing only, no device
  execution, no allocation,
- emits a **compile manifest**: entry -> aval signature, static args,
  donation pattern, lowering digest, and a cache key
  (flow-hash x chip count x capacity bucket) — the deployable artifact
  config generation embeds into the conf
  (``datax.job.process.compile.manifest``) and ``FlowProcessor``
  AOT-warms at init instead of first dispatch.

The byte-exactness contract (DX603): the analyzer builds the step with
the SAME ``build_step_fn`` the runtime jits and enumerates entries with
the SAME ``compile_entries_from_avals`` the runtime's
``FlowProcessor.derive_compile_entries`` uses — so the emitted manifest
can only disagree with the real lowering when the flow itself changed.

DX6xx codes: DX600 open trace surface (unbounded signature set), DX601
capacity-bucket lattice past the helper jit-cache bound (shared
constant ``DEFAULT_JIT_CACHE_CAP``), DX602 manifest donation/aliasing
mismatch, DX603 manifest-vs-lowering drift, DX690 lowering failure,
DX691 analysis unavailable. DX604 (warm start promised but missed) is
the *runtime* counterpart, surfaced as ``Compile_WarmMiss_Count``
(OBSERVABILITY.md).

LiveQuery kernels are deliberately NOT manifest entries: their query
text is user input, so their trace surface is open by design. They warm
through the shared persistent compilation cache instead
(``serve/livequery.py`` ``KernelService(compile_conf=...)``).
"""

from __future__ import annotations

import functools
import hashlib
import json
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp

from ..core.config import SettingDictionary, SettingNamespace
from ..core.schema import StringDictionary
from ..runtime.processor import (
    DEFAULT_JIT_CACHE_CAP,
    STEP_DONATE_ARGNUMS,
    _pack_impl,
    _slice_impl,
    build_step_fn,
    compile_entries_from_avals,
    load_reference_data_tables,
    packed_raw_struct,
    source_raw_form,
)
from .deviceplan import (
    FlowDevicePlan,
    _ordered,
    _plan_from_gui,
    _STRUCT_DTYPES,
    table_struct,
)
from .diagnostics import Diagnostic, make

# manifest document version; bump when the entry shape changes so a
# runtime can reject a manifest it does not understand
MANIFEST_VERSION = 1


def _aval(x):
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


def flow_config_hash(gui: dict) -> str:
    """Stable content hash of a flow config — the flow component of
    every manifest entry's cache key. Canonical JSON so key order and
    whitespace cannot fake a drift."""
    return hashlib.sha256(
        json.dumps(gui, sort_keys=True, separators=(",", ":")).encode()
    ).hexdigest()


def lowering_digest(fn, avals, donate: Tuple[int, ...] = ()) -> str:
    """sha256 of the entry's lowered StableHLO text — the ground truth
    a shipped manifest is checked against (DX603). Tracing only: no
    compile, no device execution."""
    lowered = jax.jit(fn, donate_argnums=tuple(donate)).lower(*avals)
    return hashlib.sha256(lowered.as_text().encode()).hexdigest()


# ---------------------------------------------------------------------------
# Report type
# ---------------------------------------------------------------------------
@dataclass
class CompileSurfaceReport:
    flow: str
    chips: int
    entries: List[dict]
    manifest: Optional[dict]
    diagnostics: List[Diagnostic]
    stable: bool = True
    jit_cache_cap: int = DEFAULT_JIT_CACHE_CAP

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.is_error]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if not d.is_error]

    @property
    def ok(self) -> bool:
        return not self.errors

    def codes(self) -> List[str]:
        return [d.code for d in self.diagnostics]

    def compile_dict(self) -> dict:
        """The compile-surface portion (no diagnostics) — what the
        designer renders beside the diagnostics list and the CLI's
        ``--json`` report carries under ``compile``."""
        helper = [e for e in self.entries if e["entry"] != "step"]
        caps = sorted({
            e["static"]["cap"] for e in helper if "cap" in e["static"]
        })
        return {
            "flow": self.flow,
            "chips": self.chips,
            "entries": len(self.entries),
            "helperEntries": len(helper),
            "buckets": caps,
            "stable": self.stable,
            "jitCacheCap": self.jit_cache_cap,
            "manifest": self.manifest,
        }

    def to_dict(self) -> dict:
        from .diagnostics import REPORT_SCHEMA_VERSION

        return {
            "schemaVersion": REPORT_SCHEMA_VERSION,
            "ok": self.ok,
            "errorCount": len(self.errors),
            "warningCount": len(self.warnings),
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "compile": self.compile_dict(),
        }


# ---------------------------------------------------------------------------
# Static step-input avals (the analyzer's mirror of
# FlowProcessor._step_input_avals, derived from the flow config alone)
# ---------------------------------------------------------------------------
def _source_types(gui: dict) -> Dict[str, str]:
    """input type per source name — decides the raw transfer form
    (packed single-matrix vs per-column), which is part of the step's
    trace signature (``source_raw_form``)."""
    out: Dict[str, str] = {}
    iprops = (gui.get("input") or {}).get("properties") or {}
    if iprops.get("inputSchemaFile"):
        out["default"] = (gui.get("input") or {}).get("type") or "local"
    for src in (gui.get("input") or {}).get("sources") or []:
        sname = src.get("id") or src.get("name")
        if sname:
            out[sname] = src.get("type") or "local"
    return out


def _refdata_avals(gui: dict) -> Dict[str, object]:
    """Reference-data table avals: the CSVs load through the SAME
    ``load_reference_data_tables`` the runtime uses (their row count is
    part of the step's trace signature, so there is no abstract
    shortcut). Raises when a declared file is unreadable — surfaced as
    DX691 by the caller."""
    entries = (gui.get("input") or {}).get("referenceData") or []
    if not entries:
        return {}
    conf: Dict[str, str] = {}
    ns = SettingNamespace.JobInputPrefix + "referencedata."
    for rd in entries:
        name = rd.get("id")
        props = rd.get("properties") or {}
        if not name or not props.get("path"):
            continue
        conf[f"{ns}{name}.path"] = props["path"]
        if props.get("delimiter"):
            conf[f"{ns}{name}.delimiter"] = props["delimiter"]
        if props.get("header") is not None:
            conf[f"{ns}{name}.header"] = str(props["header"])
    tables = load_reference_data_tables(
        SettingDictionary(conf), StringDictionary()
    )
    return {
        n: jax.tree_util.tree_map(_aval, t) for n, (_s, t) in tables.items()
    }


def _step_input_avals(bundle: FlowDevicePlan, gui: dict) -> tuple:
    """The 9-argument aval tuple of the fused step, built statically —
    the same structure ``FlowProcessor._step_input_avals`` derives from
    its live device state."""
    stypes = _source_types(gui)
    raw: Dict[str, object] = {}
    for sname, (raw_schema, cap) in bundle.raw_schemas.items():
        if source_raw_form(stypes.get(sname)) == "packed":
            raw[sname] = jax.tree_util.tree_map(
                _aval, packed_raw_struct(dict(raw_schema.types), cap)
            )
        else:
            raw[sname] = table_struct(raw_schema, cap)
    from ..runtime.timewindow import WindowBuffers

    rings: Dict[str, object] = {}
    for table, slots in bundle.ring_slots.items():
        schema = bundle.target_schemas[table]
        cap = bundle.target_caps[table]
        rings[table] = WindowBuffers(
            {
                c: jax.ShapeDtypeStruct(
                    (slots, cap), _STRUCT_DTYPES.get(t, jnp.int32)
                )
                for c, t in schema.types.items()
            },
            jax.ShapeDtypeStruct((slots, cap), jnp.bool_),
        )
    state = {
        n: table_struct(schema, cap) for n, (schema, cap) in bundle.state.items()
    }
    refdata = _refdata_avals(gui)
    scalar = jax.ShapeDtypeStruct((), jnp.int32)
    aux = jax.tree_util.tree_map(_aval, bundle.aux_tables)
    return (raw, rings, state, refdata, scalar, scalar, scalar, scalar, aux)


def _build_step(bundle: FlowDevicePlan, gui: dict):
    """The exact fused step the runtime jits, built from the compiled
    bundle via the shared ``build_step_fn``."""
    proc = gui.get("process") or {}
    targets = list(bundle.target_of.values())
    primary = (
        bundle.target_of.get("default")
        or (targets[0] if targets else "")
    )
    return build_step_fn(
        ts_col=proc.get("timestampColumn") or None,
        windows=dict(bundle.windows),
        output_datasets=list(bundle.output_datasets),
        state_names=list(bundle.state),
        refdata_names=sorted(_source_refdata_names(gui)),
        ring_tables=list(bundle.ring_slots),
        pipeline=bundle.pipeline,
        source_targets=[
            (s, t) for s, t in bundle.target_of.items()
        ],
        proj_views=dict(bundle.projection_views),
        primary_target=primary,
    )


def _source_refdata_names(gui: dict) -> List[str]:
    return [
        rd.get("id")
        for rd in (gui.get("input") or {}).get("referenceData") or []
        if rd.get("id") and (rd.get("properties") or {}).get("path")
    ]


# ---------------------------------------------------------------------------
# Digests per entry
# ---------------------------------------------------------------------------
def attach_digests(
    entries: List[dict], step_fn, step_avals: tuple, out_avals: Dict,
) -> None:
    """Lower every enumerated entry and record its StableHLO digest —
    the manifest side of the DX603 drift contract. Mutates in place."""
    slot_avals: Dict[Tuple[str, int], object] = {}
    for e in entries:
        name = e["entry"]
        if name == "step":
            e["loweringDigest"] = lowering_digest(
                step_fn, step_avals, tuple(e["donate"])
            )
            continue
        kind, out, cap_s = name.split(":")
        cap = int(cap_s)
        t = out_avals[out]
        if kind == "slice":
            e["loweringDigest"] = lowering_digest(
                functools.partial(_slice_impl, cap=cap), (t,)
            )
        else:  # pack
            slot = slot_avals.get((out, cap))
            if slot is None:
                slot = jax.eval_shape(
                    functools.partial(_slice_impl, cap=cap), t
                )
                slot_avals[(out, cap)] = slot
            e["loweringDigest"] = lowering_digest(
                functools.partial(_pack_impl, cap=cap), (t, slot),
                donate=(1,),
            )


def build_manifest(
    flow_name: str,
    flow_hash: str,
    entries: List[dict],
    chips: int,
    stable: bool,
    jit_cache_cap: int,
    sized: bool = True,
    slots: bool = True,
) -> dict:
    """Assemble the deployable manifest. Each entry's ``cacheKey`` is
    flow-hash x chip count x entry (which carries the capacity bucket)
    x aval signature — the coordinate a persistent compile cache or a
    fleet of replicas can dedupe compiled executables on."""
    for e in entries:
        e["cacheKey"] = hashlib.sha256(
            f"{flow_hash}|chips={chips}|{e['entry']}|"
            f"{json.dumps(e['avals'], sort_keys=True)}".encode()
        ).hexdigest()[:16]
    return {
        "manifestVersion": MANIFEST_VERSION,
        "flow": flow_name,
        "flowHash": flow_hash,
        "chips": chips,
        "stable": stable,
        "jitCacheCap": jit_cache_cap,
        "sized": sized,
        "slots": slots,
        "entries": entries,
    }


# ---------------------------------------------------------------------------
# Lints
# ---------------------------------------------------------------------------
def _lint_surface(
    bundle: FlowDevicePlan,
    entries: List[dict],
    jit_cache_cap: int,
    diags: List[Diagnostic],
) -> bool:
    """DX600/DX601 over the enumerated surface. Returns ``stable``:
    whether the manifest covers every signature the flow can EVER
    dispatch (False = the initial surface only)."""
    stable = True
    if bundle.udf_refresh_names:
        stable = False
        diags.append(make(
            "DX600", "",
            f"open trace surface: UDF(s) {sorted(bundle.udf_refresh_names)} "
            f"declare interval refresh — every state change rebuilds the "
            f"pipeline and re-traces the fused step with a NEW signature, "
            f"so the signature set is unbounded over the job's lifetime; "
            f"the manifest covers the initial surface only and AOT warm "
            f"degrades to best-effort (runtime re-traces surface as "
            f"Retrace_Count / Compile_WarmMiss_Count)",
        ))
    if bundle.uses_string_ops and bundle.dict_max_size is None:
        stable = False
        diags.append(make(
            "DX600", "",
            "open trace surface: device string ops with an unbounded "
            "dictionary — dictionary growth past the aux-table capacity "
            "re-traces the fused step at a new aux shape per growth "
            "step, so the signature set (and the jit cache) grows "
            "without bound; set process.stringdictionary.maxsize to "
            "close the surface",
        ))
    # one jitted closure per (helper kind, capacity bucket) — the SAME
    # key the runtime's LRU-bounded helper cache uses
    # (runtime/processor.py _helper_jit), so this lint and the runtime
    # bound can never disagree about what "too many buckets" means
    helper_keys = {
        (e["entry"].split(":")[0], e["static"]["cap"])
        for e in entries
        if e["entry"] != "step" and "cap" in e["static"]
    }
    if len(helper_keys) > jit_cache_cap:
        diags.append(make(
            "DX601", "",
            f"capacity-bucket lattice exceeds the transfer-helper jit "
            f"cache bound: the reachable sized-transfer buckets alone "
            f"compile {len(helper_keys)} helper closures but the LRU cap "
            f"is {jit_cache_cap} (process.compile.jitcachecap, default "
            f"{DEFAULT_JIT_CACHE_CAP}) — steady-state eviction thrash "
            f"recompiles helpers mid-stream "
            f"(Compile_JitCacheEvict_Count); lower the batch capacity "
            f"or raise the cap",
        ))
    return stable


def check_manifest(
    manifest: dict, derived: List[dict], diags: List[Diagnostic],
) -> None:
    """Compare a shipped manifest against the freshly derived surface:
    donation disagreements are DX602 (an aliasing bug waiting to donate
    a live buffer), any other entry/aval/lowering disagreement is DX603
    (the manifest no longer describes this flow — re-generate it)."""
    shipped = {
        e.get("entry"): e for e in manifest.get("entries", [])
        if isinstance(e, dict)
    }
    fresh = {e["entry"]: e for e in derived}
    missing = sorted(set(fresh) - set(shipped))
    extra = sorted(set(shipped) - set(fresh))
    if missing or extra:
        diags.append(make(
            "DX603", "",
            f"manifest drift: entry sets disagree with the lowering "
            f"(missing from manifest: {missing or 'none'}; stale in "
            f"manifest: {extra or 'none'}) — regenerate the manifest",
        ))
    for name in sorted(set(shipped) & set(fresh)):
        m, d = shipped[name], fresh[name]
        if list(m.get("donate") or []) != list(d["donate"]):
            diags.append(make(
                "DX602", name,
                f"donation/aliasing mismatch: manifest records donated "
                f"argnums {m.get('donate')} but the runtime contract is "
                f"{d['donate']} — an AOT compile honoring the manifest "
                f"would alias (or fail to alias) buffers the dispatch "
                f"path still reads",
            ))
        drift = []
        if m.get("avals") != d["avals"]:
            drift.append("aval signature")
        if (
            d.get("loweringDigest")
            and m.get("loweringDigest")
            and m["loweringDigest"] != d["loweringDigest"]
        ):
            drift.append("lowering digest")
        if m.get("static") != d["static"]:
            drift.append("static args")
        if drift:
            diags.append(make(
                "DX603", name,
                f"manifest drift on {', '.join(drift)}: the shipped "
                f"manifest no longer matches this flow's lowering — a "
                f"warm start from it would compile anyway (DX604 at "
                f"runtime); regenerate the manifest",
            ))


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def analyze_flow_compile(
    flow: dict,
    chips: Optional[int] = None,
    manifest: Optional[dict] = None,
    digests: bool = True,
    jit_cache_cap: Optional[int] = None,
) -> CompileSurfaceReport:
    """Compile-surface analysis of a flow config (gui JSON or full flow
    document). Pure tracing: compiles with the production planner,
    builds the SAME fused step the runtime jits, lowers every entry
    over ``jax.eval_shape`` avals — no device execution.

    ``manifest``: a previously emitted manifest to check for drift
    (DX602/DX603). ``digests=False`` skips the per-entry StableHLO
    lowering (enumeration + lints only — faster, used by callers that
    only need the signature set)."""
    gui = flow.get("gui") if isinstance(flow.get("gui"), dict) else flow
    name = gui.get("name") or ""
    diags: List[Diagnostic] = []
    plan_diags: List[Diagnostic] = []
    n_chips = chips or 1
    cap = jit_cache_cap or _jobconf_cache_cap(gui) or DEFAULT_JIT_CACHE_CAP
    bundle = _plan_from_gui(gui, plan_diags, chips)
    # the bundle builder reports in DX2xx; re-code for this tier
    for d in plan_diags:
        code = "DX690" if d.code == "DX290" else "DX691"
        diags.append(make(code, d.table, d.message, d.span))
    if bundle is None:
        return CompileSurfaceReport(
            name, n_chips, [], None, _ordered(diags), stable=False,
            jit_cache_cap=cap,
        )
    try:
        step_avals = _step_input_avals(bundle, gui)
    except Exception as e:  # noqa: BLE001 — e.g. unreadable refdata CSV
        diags.append(make(
            "DX691", "",
            f"compile surface unavailable: step input avals cannot be "
            f"derived at design time ({e})",
        ))
        return CompileSurfaceReport(
            name, n_chips, [], None, _ordered(diags), stable=False,
            jit_cache_cap=cap,
        )
    sized = slots = n_chips == 1
    try:
        step_fn = _build_step(bundle, gui)
        out_avals = jax.eval_shape(step_fn, *step_avals)[0]
        entries = compile_entries_from_avals(
            step_avals, out_avals, sized=sized, slots=slots
        )
        if digests:
            attach_digests(entries, step_fn, step_avals, out_avals)
    except Exception as e:  # noqa: BLE001 — any lowering blowup is a finding
        diags.append(make(
            "DX690", "", f"compile-surface lowering failed: {e}"
        ))
        return CompileSurfaceReport(
            name, n_chips, [], None, _ordered(diags), stable=False,
            jit_cache_cap=cap,
        )
    stable = _lint_surface(bundle, entries, cap, diags)
    if manifest is not None:
        check_manifest(manifest, entries, diags)
    doc = build_manifest(
        name, flow_config_hash(gui), entries, n_chips, stable, cap,
        sized=sized, slots=slots,
    )
    return CompileSurfaceReport(
        name, n_chips, entries, doc, _ordered(diags), stable=stable,
        jit_cache_cap=cap,
    )


def _jobconf_cache_cap(gui: dict) -> Optional[int]:
    jobconf = ((gui.get("process") or {}).get("jobconfig") or {})
    v = jobconf.get("jobCompileJitCacheCap")
    try:
        return int(v) if v not in (None, "") else None
    except (TypeError, ValueError):
        return None


def analyze_processor_compile(
    proc, manifest: Optional[dict] = None, digests: bool = True,
) -> CompileSurfaceReport:
    """Compile-surface analysis of an already-built ``FlowProcessor`` —
    the exact step function and device state the runtime dispatches
    (the drift-test / bench cross-validation path, mirroring
    ``deviceplan.analyze_processor``)."""
    diags: List[Diagnostic] = []
    entries = proc.derive_compile_entries()
    if digests:
        step_avals = proc._step_input_avals()
        out_avals = jax.eval_shape(proc._step_fn, *step_avals)[0]
        attach_digests(entries, proc._step_fn, step_avals, out_avals)
    name = proc.dict.get("datax.job.name") or ""
    from .deviceplan import flow_plan_from_processor

    bundle = flow_plan_from_processor(proc)
    cap = DEFAULT_JIT_CACHE_CAP
    try:
        cap = (
            proc.process_conf.get_sub_dictionary("compile.")
            .get_int_option("jitcachecap") or DEFAULT_JIT_CACHE_CAP
        )
    except ValueError:
        pass
    stable = _lint_surface(bundle, entries, cap, diags)
    if manifest is not None:
        check_manifest(manifest, entries, diags)
    doc = build_manifest(
        name, "", entries, 1, stable, cap,
        sized=proc.sized_transfer, slots=proc.output_slots_enabled,
    )
    return CompileSurfaceReport(
        name, 1, entries, doc, _ordered(diags), stable=stable,
        jit_cache_cap=cap,
    )
