"""Buffer-lifetime & concurrency analysis over the ENGINE'S OWN modules
(the ``--race`` tier, DX8xx).

Three separate PRs (8, 13, 14) each found-and-fixed a latent
use-after-free with the same root cause: donated/pooled 64-byte-aligned
buffers are ZERO-COPIED by the CPU backend's ``jnp.asarray``/
``np.asarray``, and a view escaping its guarded scope is read from a
background thread after the next dispatch donated the memory — heap
corruption, not just stale data. This pass turns the hand-written
``copy=True`` comments standing between the codebase and the next such
bug into a standing CI gate, in the style of ThreadSanitizer's
lockset discipline and the taint walk ``udfcheck.py`` runs over UDF
ASTs — except the analyzed ASTs are ``runtime/``, ``lq/`` and
``pilot/`` themselves.

Buffer provenance lattice
-------------------------
Every expression carries one of four provenances:

- ``ring``  — a window ring buffer (``self.window_buffers`` and its
  ``cols``/``valid`` members): the step's DONATED argument
  (``STEP_DONATE_ARGNUMS``); freed by XLA at the next dispatch;
- ``pool``  — a ``PackedBufferPool`` ingest slot
  (``pool.acquire()`` results, ``_ingest_pool``/``_ingest_pools``/
  ``_ingest_buffers``): reused for the next decode once its batch
  lands;
- ``slot``  — an A/B output transfer slot (``self._slots``): donated
  into the next ``_pack_slot`` once the previous batch's land ack
  fires;
- plain — everything else.

Provenance flows through assignments, attribute/subscript loads,
``.items()/.values()/.get()`` traversal, container displays and
comprehensions. A REAL copy clears it: ``np.array(x, copy=True)``
(or default-copying ``np.array(x)``), ``jnp.array(x, copy=True)``,
``x.copy()``, ``np.copy(x)``, ``copy.deepcopy``. ``np.asarray``/
``jnp.asarray`` does NOT — that is the zero-copy view the whole bug
class rides on.

The checks
----------
- **DX800** — a ``ring``/``pool``/``slot`` value escapes its guarded
  scope: returned, stored into an attribute, stored into a container
  that is itself attribute-reachable or returned, or handed to another
  thread (``executor.submit``/``Thread(...)``) — without a real copy.
  The exact PR 13 bug (``snapshot_window_state`` without
  ``copy=True``) is the canonical instance.
- **DX801** — ``np.asarray``/``jnp.asarray`` of a provenanced buffer
  outside an annotated allowed-zero-copy site.
- **DX802** — lockset discipline: an attribute written under
  ``with self.<lock>`` in one method and written WITHOUT that lock in
  another (``__init__`` and marked single-threaded paths exempt),
  plus conflicting lock-acquisition orders within a class.
- **DX803** — slot re-donated before its land ack: a ``_pack_slot``
  donation whose argument has ``slot`` provenance is not dominated by
  an ``is_set()``/``wait()`` land-ack check in the same function.
- **DX804** — blocking device sync (``block_until_ready``/
  ``device_get``/blocking waits) inside a function the pipeline model
  requires non-blocking (marked ``# dx-race: non-blocking``).

Marker contract (structured comments the analyzer reads from source)
--------------------------------------------------------------------
Line-scoped (same line as the site, or the line directly above):

- ``# dx-race: allow-zero-copy <reason>`` — pins a legitimate
  zero-copy ``asarray`` site (DX801); counted and reported, so the
  self-lint keeps an inventory of every place the engine relies on
  aliasing on purpose.
- ``# dx-race: owner-handoff <reason>`` — pins a DESIGNED ownership
  transfer (DX800): e.g. dispatch handing pooled ingest matrices to
  the ``PendingBatch`` that will release them at landing.

Function-scoped (any line inside the function):

- ``# dx-race: param <name>=<ring|pool|slot>`` — seeds a parameter's
  provenance (inter-procedural edge the walk cannot see).
- ``# dx-race: single-threaded <reason>`` — exempts a provably
  pre-thread/re-init path from the DX802 lockset rule.
- ``# dx-race: non-blocking`` — declares the function dispatch-path
  non-blocking, arming DX804 inside it.

The runtime counterpart is ``runtime/sanitizer.py`` (conf
``datax.job.process.debug.buffersanitizer``): poisons released pool
slots with a sentinel, alias-scans window snapshots against the live
rings, scans landed sink payloads for sentinel leakage, and fires
runtime **DX805** events into the flight recorder — the dynamic
ground truth the DX80x fixtures and the seeded PR 13 regression test
are proven against.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, Span, make

# provenance values
RING = "ring"
POOL = "pool"
SLOT = "slot"

# attribute names that SEED provenance when loaded (the runtime's own
# ownership roots; see the module docstring's lattice)
SEED_ATTRS = {
    "window_buffers": RING,
    "_ingest_pools": POOL,
    "_ingest_pool": POOL,
    "_ingest_buffers": POOL,
    "_slots": SLOT,
}

# attribute accesses that traverse INTO a provenanced object without
# laundering it (a member of a ring is still the ring's memory)
_TRAVERSE_CALLS = {"items", "values", "get", "setdefault", "pop"}

# calls that are blocking device syncs / blocking waits (DX804 inside a
# non-blocking-marked function)
_BLOCKING_ATTRS = {
    "block_until_ready", "device_get", "item", "tolist",
    "wait", "result", "join", "sleep",
}

_NUMPY_NAMES = {"np", "numpy", "jnp"}

_MARKER_RE = re.compile(r"#\s*dx-race:\s*([a-z-]+)\s*(.*)$")
_PARAM_RE = re.compile(r"^(\w+)\s*=\s*(ring|pool|slot)\s*$")


@dataclass
class _Markers:
    """dx-race markers harvested from one module's raw source lines."""

    # 1-based line -> set of line-scoped marker kinds on/above it
    by_line: Dict[int, Set[str]] = field(default_factory=dict)
    # 1-based line -> {param name -> provenance}
    params: Dict[int, Dict[str, str]] = field(default_factory=dict)

    def line_has(self, line: int, kind: str) -> bool:
        return kind in self.by_line.get(line, ())


def _collect_markers(
    lines: List[str], tree: Optional[ast.AST] = None,
    marker_re: "re.Pattern" = _MARKER_RE,
) -> _Markers:
    m = _Markers()
    # statement spans let a marker above a multi-line statement cover
    # every line the statement occupies (the asarray may sit two lines
    # into a wrapped call)
    spans: Dict[int, int] = {}
    if tree is not None:
        for node in ast.walk(tree):
            if isinstance(node, ast.stmt):
                spans.setdefault(
                    node.lineno, getattr(node, "end_lineno", node.lineno)
                )
    for i, text in enumerate(lines, start=1):
        match = marker_re.search(text)
        if not match:
            continue
        kind, rest = match.group(1), match.group(2).strip()
        if kind == "param":
            pm = _PARAM_RE.match(rest)
            if pm:
                m.params.setdefault(i, {})[pm.group(1)] = pm.group(2)
            continue
        # a marker names its own line, then flows forward through any
        # continuation comment/blank lines onto the next statement —
        # covering that statement's FULL span, so a marker sentence may
        # wrap and the annotated call may too
        m.by_line.setdefault(i, set()).add(kind)
        j = i + 1
        while j <= len(lines) and (
            not lines[j - 1].strip()
            or lines[j - 1].lstrip().startswith("#")
        ):
            m.by_line.setdefault(j, set()).add(kind)
            j += 1
        for covered in range(j, spans.get(j, j) + 1):
            m.by_line.setdefault(covered, set()).add(kind)
    return m


def _fn_markers(markers: _Markers, node: ast.AST) -> Set[str]:
    """Function-scoped marker kinds present anywhere inside ``node``."""
    out: Set[str] = set()
    end = getattr(node, "end_lineno", node.lineno)
    for line, kinds in markers.by_line.items():
        if node.lineno <= line <= end:
            out |= kinds
    return out


def _fn_param_seeds(markers: _Markers, node: ast.AST) -> Dict[str, str]:
    out: Dict[str, str] = {}
    end = getattr(node, "end_lineno", node.lineno)
    for line, params in markers.params.items():
        if node.lineno <= line <= end:
            out.update(params)
    return out


def _dotted(node: ast.AST) -> str:
    """'a.b.c' for nested attributes, '' when not a plain dotted name."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _is_lock_ctor(node: ast.AST) -> bool:
    if not isinstance(node, ast.Call):
        return False
    name = _dotted(node.func)
    return name.endswith("Lock") or name.endswith("RLock") \
        or name.endswith("Condition") or name.endswith("Semaphore")


@dataclass
class _ClassState:
    """Per-class lockset bookkeeping (DX802)."""

    name: str
    lock_attrs: Set[str] = field(default_factory=set)
    # attr -> set of lock attr names it was written under
    locked_writes: Dict[str, Set[str]] = field(default_factory=dict)
    # (method, attr, line) writes outside any lock
    unlocked_writes: List[Tuple[str, str, int]] = field(default_factory=list)
    # observed nested acquisition orders: (outer, inner) -> line
    lock_orders: Dict[Tuple[str, str], int] = field(default_factory=dict)


class _FnRace:
    """Provenance walk over one function/method body."""

    def __init__(self, linter: "_ModuleLinter", node, cls: Optional[_ClassState],
                 method_name: str, seeds: Dict[str, str],
                 fn_marks: Set[str], locks_held: Tuple[str, ...] = ()):
        self.l = linter
        self.node = node
        self.cls = cls
        self.method = method_name
        self.prov: Dict[str, str] = dict(seeds)
        self.marks = fn_marks
        self.non_blocking = "non-blocking" in fn_marks
        self.single_threaded = "single-threaded" in fn_marks
        self.land_ack_seen = False
        self.locks_held: Tuple[str, ...] = locks_held

    # -- provenance of an expression (also performs call-site checks) --
    def _prov(self, node: Optional[ast.AST]) -> Optional[str]:
        if node is None:
            return None
        if isinstance(node, ast.Name):
            return self.prov.get(node.id)
        if isinstance(node, ast.Attribute):
            seeded = SEED_ATTRS.get(node.attr)
            if seeded is not None:
                return seeded
            return self._prov(node.value)
        if isinstance(node, ast.Subscript):
            self._prov(node.slice)
            return self._prov(node.value)
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            provs = [self._prov(e) for e in node.elts]
            return next((p for p in provs if p), None)
        if isinstance(node, ast.Dict):
            provs = [self._prov(v) for v in node.values]
            provs += [self._prov(k) for k in node.keys if k is not None]
            return next((p for p in provs if p), None)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            saved = dict(self.prov)
            for gen in node.generators:
                self._bind_loop_target(gen.target, gen.iter)
                for cond in gen.ifs:
                    self._prov(cond)
            p = self._prov(node.elt)
            self.prov = saved
            return p
        if isinstance(node, ast.DictComp):
            saved = dict(self.prov)
            for gen in node.generators:
                self._bind_loop_target(gen.target, gen.iter)
                for cond in gen.ifs:
                    self._prov(cond)
            p = self._prov(node.value) or self._prov(node.key)
            self.prov = saved
            return p
        if isinstance(node, ast.IfExp):
            self._prov(node.test)
            return self._prov(node.body) or self._prov(node.orelse)
        if isinstance(node, ast.BoolOp):
            provs = [self._prov(v) for v in node.values]
            return next((p for p in provs if p), None)
        if isinstance(node, ast.Starred):
            return self._prov(node.value)
        if isinstance(node, ast.NamedExpr):
            p = self._prov(node.value)
            if isinstance(node.target, ast.Name):
                self.prov[node.target.id] = p
            return p
        if isinstance(node, ast.Await):
            return self._prov(node.value)
        if isinstance(node, (ast.BinOp, ast.UnaryOp, ast.Compare)):
            # arithmetic materializes a NEW array — provenance cleared,
            # but still walk for call side-effects
            for child in ast.iter_child_nodes(node):
                self._prov(child)
            return None
        if isinstance(node, ast.JoinedStr):
            return None
        # constants, lambdas, etc.
        return None

    def _call(self, node: ast.Call) -> Optional[str]:
        func = node.func
        # walk args for side-effects first (nested calls, land acks)
        arg_provs = [self._prov(a) for a in node.args]
        kw_provs = {
            (kw.arg or "**"): self._prov(kw.value) for kw in node.keywords
        }

        if isinstance(func, ast.Attribute):
            base, attr = func.value, func.attr
            base_name = _dotted(base)

            if attr in ("is_set", "wait") :
                self.land_ack_seen = True
            if attr in _BLOCKING_ATTRS:
                self._check_blocking(node, attr)
            if attr == "asarray" and base_name in _NUMPY_NAMES:
                p = arg_provs[0] if arg_provs else None
                if p is not None:
                    if self.l.allowed_zero_copy(node.lineno):
                        self.l.allowed_sites += 1
                    else:
                        self.l.emit(
                            "DX801", node.lineno,
                            f"zero-copy {base_name}.asarray of a {p} "
                            f"buffer in {self._where()}",
                        )
                return p
            if attr == "array" and base_name in _NUMPY_NAMES:
                cp = kw_provs  # walked above; now inspect the literal
                for kw in node.keywords:
                    if kw.arg == "copy" and isinstance(kw.value, ast.Constant) \
                            and kw.value.value is False:
                        return arg_provs[0] if arg_provs else None
                return None  # np.array/jnp.array default-copies
            if attr == "copy" and not node.args:
                return None  # x.copy() is a real copy
            if attr in _TRAVERSE_CALLS:
                return self._prov(base)
            if attr == "keys":
                self._prov(base)
                return None
            if attr == "acquire" and "pool" in base_name.lower():
                return POOL
            if attr.endswith("_pack_slot"):
                self._check_donation(node, arg_provs)
                return None
            if attr == "submit" or attr == "apply_async":
                self._check_thread_handoff(node, arg_provs, kw_provs)
                return None
        elif isinstance(func, ast.Name):
            name = func.id
            if name in ("deepcopy",):
                return None
            if name == "Thread" or name.endswith("Thread"):
                self._check_thread_handoff(node, arg_provs, kw_provs)
                return None
        fname = _dotted(func)
        if fname.endswith("copy.deepcopy") or fname.endswith("np.copy") \
                or fname.endswith("jnp.copy"):
            return None
        if fname.endswith("Thread"):
            self._check_thread_handoff(node, arg_provs, kw_provs)
            return None
        if fname.endswith("block_until_ready") or fname.endswith("device_get"):
            self._check_blocking(node, fname.rsplit(".", 1)[-1])
        return None

    def _where(self) -> str:
        return (
            f"{self.cls.name}.{self.method}" if self.cls else self.method
        )

    def _check_blocking(self, node: ast.Call, what: str) -> None:
        if not self.non_blocking:
            return
        self.l.emit(
            "DX804", node.lineno,
            f"blocking call {what}() inside non-blocking "
            f"{self._where()} (dispatch-path contract)",
        )

    def _check_donation(self, node: ast.Call, arg_provs) -> None:
        if SLOT not in [p for p in arg_provs if p]:
            return
        if self.land_ack_seen:
            return
        self.l.emit(
            "DX803", node.lineno,
            f"slot buffer donated in {self._where()} without a "
            f"preceding land-ack check (is_set()/wait() on the "
            f"previous batch's landed event)",
        )

    def _check_thread_handoff(self, node: ast.Call, arg_provs, kw_provs) -> None:
        carried = [p for p in arg_provs if p] + [
            p for p in kw_provs.values() if p
        ]
        if not carried:
            return
        if self.l.line_marked(node.lineno, "owner-handoff"):
            self.l.handoff_sites += 1
            return
        self.l.emit(
            "DX800", node.lineno,
            f"{carried[0]} buffer handed to another thread from "
            f"{self._where()} without a real copy",
        )

    # -- loop/comprehension target binding -----------------------------
    def _bind_loop_target(self, target: ast.AST, iter_node: ast.AST) -> None:
        p = self._prov(iter_node)
        items_iter = (
            isinstance(iter_node, ast.Call)
            and isinstance(iter_node.func, ast.Attribute)
            and iter_node.func.attr == "items"
        )
        keys_iter = (
            isinstance(iter_node, ast.Call)
            and isinstance(iter_node.func, ast.Attribute)
            and iter_node.func.attr == "keys"
        )
        if isinstance(target, ast.Tuple) and items_iter \
                and len(target.elts) == 2:
            # dict .items(): the KEY does not alias the buffer, the
            # value does — taint only the value half
            k, v = target.elts
            if isinstance(k, ast.Name):
                self.prov.pop(k.id, None)
            self._bind(v, p)
            return
        if keys_iter:
            p = None
        self._bind(target, p)

    def _bind(self, target: ast.AST, p: Optional[str]) -> None:
        if isinstance(target, ast.Name):
            if p is None:
                self.prov.pop(target.id, None)
            else:
                self.prov[target.id] = p
        elif isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._bind(e, p)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, p)

    # -- statements ----------------------------------------------------
    def run(self) -> None:
        self._stmts(self.node.body)
        if self.cls is not None and self.locks_held == ():
            pass  # class bookkeeping happens inline during the walk

    def _stmts(self, body: List[ast.stmt]) -> None:
        for st in body:
            self._stmt(st)

    def _record_attr_write(self, attr: str, line: int,
                           value: Optional[ast.AST]) -> None:
        """Class lockset bookkeeping for a ``self.X = ...`` write."""
        if self.cls is None:
            return
        if value is not None and _is_lock_ctor(value):
            self.cls.lock_attrs.add(attr)
            return
        if self.method in ("__init__", "__new__") or self.single_threaded:
            return
        if self.method.endswith("_locked"):
            # the ``_locked`` suffix is the codebase's caller-holds-the-
            # lock idiom: the write IS lock-associated, acquired upstack
            self.cls.locked_writes.setdefault(attr, set()).add(
                "(caller-held)"
            )
            return
        if self.locks_held:
            self.cls.locked_writes.setdefault(attr, set()).update(
                self.locks_held
            )
        else:
            self.cls.unlocked_writes.append((self.method, attr, line))

    def _escape_check(self, target: ast.AST, p: Optional[str],
                      line: int) -> None:
        if p is None:
            return
        if isinstance(target, ast.Attribute):
            if self.l.line_marked(line, "owner-handoff"):
                self.l.handoff_sites += 1
                return
            self.l.emit(
                "DX800", line,
                f"{p} buffer stored into attribute "
                f"{_dotted(target) or target.attr} in {self._where()} "
                f"without a real copy",
            )
        elif isinstance(target, ast.Subscript):
            root = target.value
            while isinstance(root, ast.Subscript):
                root = root.value
            if isinstance(root, ast.Attribute):
                if self.l.line_marked(line, "owner-handoff"):
                    self.l.handoff_sites += 1
                    return
                self.l.emit(
                    "DX800", line,
                    f"{p} buffer stored into {_dotted(root)}[...] in "
                    f"{self._where()} without a real copy",
                )
            elif isinstance(root, ast.Name):
                # container stays local; taint it so a later
                # return/store of the container is caught
                self.prov[root.id] = p

    def _stmt(self, st: ast.stmt) -> None:
        if isinstance(st, ast.Assign):
            p = self._prov(st.value)
            for target in st.targets:
                if isinstance(target, ast.Name):
                    self._bind(target, p)
                elif isinstance(target, (ast.Tuple, ast.List)):
                    if isinstance(st.value, ast.Tuple) and \
                            len(st.value.elts) == len(target.elts):
                        for t, v in zip(target.elts, st.value.elts):
                            vp = self._prov(v)
                            if isinstance(t, ast.Name):
                                self._bind(t, vp)
                            else:
                                self._escape_check(t, vp, st.lineno)
                                if isinstance(t, ast.Attribute):
                                    self._record_attr_write(
                                        t.attr, st.lineno, v
                                    )
                    else:
                        self._bind(target, p)
                else:
                    self._escape_check(target, p, st.lineno)
                    if isinstance(target, ast.Attribute):
                        self._record_attr_write(target.attr, st.lineno,
                                                st.value)
                    elif isinstance(target, ast.Subscript):
                        root = target.value
                        while isinstance(root, ast.Subscript):
                            root = root.value
                        if isinstance(root, ast.Attribute):
                            self._record_attr_write(root.attr, st.lineno,
                                                    None)
        elif isinstance(st, ast.AnnAssign):
            p = self._prov(st.value) if st.value else None
            if isinstance(st.target, ast.Name):
                self._bind(st.target, p)
            else:
                self._escape_check(st.target, p, st.lineno)
                if isinstance(st.target, ast.Attribute):
                    self._record_attr_write(st.target.attr, st.lineno,
                                            st.value)
        elif isinstance(st, ast.AugAssign):
            self._prov(st.value)
            if isinstance(st.target, ast.Attribute):
                self._record_attr_write(st.target.attr, st.lineno, None)
        elif isinstance(st, ast.Return):
            p = self._prov(st.value)
            if p is not None:
                if self.l.line_marked(st.lineno, "owner-handoff"):
                    self.l.handoff_sites += 1
                else:
                    self.l.emit(
                        "DX800", st.lineno,
                        f"{p} buffer escapes via return from "
                        f"{self._where()} without a real copy",
                    )
        elif isinstance(st, ast.Expr):
            self._prov(st.value)
        elif isinstance(st, ast.If):
            self._prov(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            self._bind_loop_target(st.target, st.iter)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, ast.While):
            self._prov(st.test)
            self._stmts(st.body)
            self._stmts(st.orelse)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            entered: List[str] = []
            for item in st.items:
                lock = self._lock_name(item.context_expr)
                if lock is not None:
                    if self.cls is not None:
                        for held in self.locks_held:
                            self.cls.lock_orders.setdefault(
                                (held, lock), st.lineno
                            )
                    entered.append(lock)
                else:
                    self._prov(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, None)
            saved = self.locks_held
            self.locks_held = saved + tuple(entered)
            self._stmts(st.body)
            self.locks_held = saved
        elif isinstance(st, ast.Try):
            self._stmts(st.body)
            for h in st.handlers:
                self._stmts(h.body)
            self._stmts(st.orelse)
            self._stmts(st.finalbody)
        elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested function (thread bodies, wrappers): analyze with a
            # copy of the enclosing environment — closures see it
            nested = _FnRace(
                self.l, st, self.cls, f"{self.method}.{st.name}",
                dict(self.prov), _fn_markers(self.l.markers, st)
                | (self.marks & {"single-threaded"}),
                locks_held=(),
            )
            nested.run()
        elif isinstance(st, (ast.Delete, ast.Assert)):
            for child in ast.iter_child_nodes(st):
                if isinstance(child, ast.expr):
                    self._prov(child)
        # Pass/Break/Continue/Import/Global/Nonlocal/Raise: no flow

    def _lock_name(self, expr: ast.AST) -> Optional[str]:
        """``self.<attr>`` where attr is (or looks like) a lock."""
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and expr.value.id == "self":
            attr = expr.attr
            if self.cls is not None and attr in self.cls.lock_attrs:
                return attr
            if attr.endswith("lock") or attr.endswith("_lock"):
                if self.cls is not None:
                    self.cls.lock_attrs.add(attr)
                return attr
        return None


class _ModuleLinter:
    """One engine module: parse, walk every class/function, emit."""

    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.markers = _collect_markers(self.lines, self.tree)
        self.diags: List[Diagnostic] = []
        self._seen: Set[Tuple[str, int]] = set()
        self.allowed_sites = 0
        self.handoff_sites = 0
        self.functions = 0

    def line_marked(self, line: int, kind: str) -> bool:
        return self.markers.line_has(line, kind)

    def allowed_zero_copy(self, line: int) -> bool:
        return self.markers.line_has(line, "allow-zero-copy")

    def emit(self, code: str, line: int, message: str) -> None:
        key = (code, line)
        if key in self._seen:
            return
        self._seen.add(key)
        self.diags.append(
            make(code, self.rel, message, Span(line=line))
        )

    def run(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self._class(node)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._function(node, cls=None)

    def _function(self, node, cls: Optional[_ClassState]) -> None:
        self.functions += 1
        seeds = _fn_param_seeds(self.markers, node)
        fn = _FnRace(
            self, node, cls, node.name, seeds,
            _fn_markers(self.markers, node),
        )
        fn.run()

    def _class(self, node: ast.ClassDef) -> None:
        cls = _ClassState(name=node.name)
        # pre-pass: find lock attributes (assigned threading.Lock() etc.
        # anywhere in the class) so `with self.<lock>` is recognized in
        # methods that appear before the assignment
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and _is_lock_ctor(sub.value):
                for t in sub.targets:
                    if isinstance(t, ast.Attribute) and \
                            isinstance(t.value, ast.Name) and \
                            t.value.id == "self":
                        cls.lock_attrs.add(t.attr)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._function(item, cls=cls)
        # DX802 resolution: attrs written under a lock somewhere must
        # never be written lock-free elsewhere
        for method, attr, line in cls.unlocked_writes:
            locks = cls.locked_writes.get(attr)
            if not locks:
                continue
            self.emit(
                "DX802", line,
                f"{cls.name}.{method} writes self.{attr} without "
                f"{'/'.join(sorted(locks))} (held for the same attribute "
                f"elsewhere in the class)",
            )
        for (a, b), line in cls.lock_orders.items():
            if (b, a) in cls.lock_orders and a < b:
                self.emit(
                    "DX802", line,
                    f"{cls.name} acquires {a} and {b} in conflicting "
                    f"orders (deadlock risk against the device-state "
                    f"lock discipline)",
                )


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------
@dataclass
class RaceModuleSummary:
    path: str      # package-relative, e.g. "runtime/processor.py"
    functions: int

    def to_dict(self) -> dict:
        return {"path": self.path, "functions": self.functions}


@dataclass
class RaceCheckReport:
    """The ``--race`` tier's result. Unlike the flow tiers, the analyzed
    subject is the ENGINE — ``runtime/``, ``lq/``, ``pilot/`` — so a
    clean report certifies the runtime a flow deploys onto, for any
    flow."""

    flow: str
    modules: List[RaceModuleSummary]
    diagnostics: List[Diagnostic]
    allowed_zero_copy_sites: int = 0
    owner_handoff_sites: int = 0

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.is_error]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if not d.is_error]

    @property
    def ok(self) -> bool:
        return not self.errors

    def race_dict(self) -> dict:
        return {
            "flow": self.flow,
            "analyzedFiles": len(self.modules),
            "modules": [m.to_dict() for m in self.modules],
            "allowedZeroCopySites": self.allowed_zero_copy_sites,
            "ownerHandoffSites": self.owner_handoff_sites,
        }

    def to_dict(self) -> dict:
        from .diagnostics import REPORT_SCHEMA_VERSION

        return {
            "schemaVersion": REPORT_SCHEMA_VERSION,
            "ok": self.ok,
            "errorCount": len(self.errors),
            "warningCount": len(self.warnings),
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "race": self.race_dict(),
        }


# the engine surface the standing CI race gate covers
ENGINE_PACKAGES = ("runtime", "lq", "pilot")


def engine_module_paths() -> List[str]:
    """Every .py file of the engine packages the gate analyzes."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out: List[str] = []
    for pkg in ENGINE_PACKAGES:
        root = os.path.join(pkg_root, pkg)
        for dirpath, _dirs, files in os.walk(root):
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return sorted(out)


def _rel_path(path: str) -> str:
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    rp = os.path.relpath(os.path.abspath(path), pkg_root)
    return rp.replace(os.sep, "/")


def analyze_modules(paths: List[str], flow: str = "") -> RaceCheckReport:
    """Run the DX8xx pass over explicit module files (the self-lint /
    fixture entry point)."""
    modules: List[RaceModuleSummary] = []
    diags: List[Diagnostic] = []
    allowed = 0
    handoffs = 0
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        lint = _ModuleLinter(path, _rel_path(path), src)
        lint.run()
        modules.append(RaceModuleSummary(lint.rel, lint.functions))
        diags.extend(lint.diags)
        allowed += lint.allowed_sites
        handoffs += lint.handoff_sites
    diags.sort(key=lambda d: (d.table, d.span.line, d.code))
    return RaceCheckReport(
        flow=flow, modules=modules, diagnostics=diags,
        allowed_zero_copy_sites=allowed, owner_handoff_sites=handoffs,
    )


# engine analysis cache: the race tier's subject is the engine source,
# which does not change between flows in one process — key on the
# module set + mtimes so an edited file re-analyzes (test sandboxes)
_ENGINE_CACHE: Dict[tuple, RaceCheckReport] = {}


def analyze_flow_race(flow: dict) -> RaceCheckReport:
    """Race-tier analysis for a flow config. The analyzed subject is
    the engine the flow would deploy onto (``runtime/``, ``lq/``,
    ``pilot/``) — the report is flow-independent except for the name it
    is filed under, and is cached per engine-source state."""
    gui = flow.get("gui") if isinstance(flow.get("gui"), dict) else flow
    name = (gui or {}).get("name") or ""
    paths = engine_module_paths()
    key = tuple(
        (p, os.path.getmtime(p)) for p in paths
    )
    cached = _ENGINE_CACHE.get(key)
    if cached is None:
        _ENGINE_CACHE.clear()
        cached = analyze_modules(paths)
        _ENGINE_CACHE[key] = cached
    return RaceCheckReport(
        flow=name,
        modules=cached.modules,
        diagnostics=cached.diagnostics,
        allowed_zero_copy_sites=cached.allowed_zero_copy_sites,
        owner_handoff_sites=cached.owner_handoff_sites,
    )
