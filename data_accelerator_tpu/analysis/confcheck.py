"""Static configuration-lattice analysis (the ``--conf`` tier).

The conf lattice — designer ``jobXxx`` knob → S400 gui token → S650
flat ``datax.job.process.*`` key → runtime ``conf.get`` fallback — is
the largest hand-plumbed interface in the repo, and it has broken
silently before (PR 6 shipped a designer knob whose conf key the
runtime never saw). This pass makes every hop checkable:

1. **Read-site scan** — every engine/serve module is AST-scanned for
   conf reads: typed getters on variables resolved (through
   ``get_sub_dictionary`` chains, ``SettingNamespace`` constants,
   module prefix constants, local wrapper helpers like
   ``lq/service.py:_conf_get`` and f-string families) to a
   ``datax.job.process.`` prefix, plus bulk family walks
   (``group_by_sub_namespace()`` / ``.dict``).
2. **Producer scan** — ``serve/generation.py``'s S400 token dictionary
   (knob→token, with generation defaults), the S640 knob→key tuple
   table, every ``extra["datax.job.process…"]`` S650 write, the
   declarative flattener template schema
   (``compile/flattener_schema.py`` — the reference-parity keys), and
   control-plane dict literals (scenarios, livequery, serve main).
3. **Lattice checks** against the ONE typed registry
   (``analysis/confspec.py``):

   - DX1000 — a read site's key matches no registry row: the runtime
     waits on a knob nothing can produce (dead knob / typo).
   - DX1001 — a produced key matches no registry row (or, in the
     full-tree self-lint, a registered read=True key has no read
     site): generated-but-never-read dead conf.
   - DX1002 — broken designer→runtime chain: an S400 gui token no
     generated key carries, or a registry row whose declared knob /
     key the generation scan cannot connect (the PR 6 bug class as a
     standing gate).
   - DX1003 — default drift: a read-site fallback literal (or an S400
     generation default) disagrees with the registry's canonical
     default, so "unset" means different things on different layers.
   - DX1004 — type/bounds violation in a concrete flow conf
     (``pipeline.depth=0``, a negative TTL, an HBM budget above the
     chip).
   - DX1005 — incompatible-knob combination from the declared
     constraint table (mesh+sizedtransfer, mesh+backgroundtransfer,
     ``state.filteringest`` without state partitions).

The runtime half lives in ``runtime/confaudit.py`` (DX1006): the same
registry rows audit the LIVE conf at host/LQ-service init.

Like the race/protocol tiers, flow-level entry
(:func:`analyze_flow_conf`) reuses one mtime-cached scan of the real
tree and adds per-flow value/constraint checks for the flow's
designer knobs. ``python -m data_accelerator_tpu.analysis.confcheck``
dumps the scanned inventory (read sites, produced keys, knob tokens)
as JSON — the registry in ``confspec.py`` is maintained against that
dump, and the tier-1 self-lint pins the counts so they cannot drift.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

from .diagnostics import (
    Diagnostic, REPORT_SCHEMA_VERSION, Span, make,
)
from .racecheck import _rel_path
from .confspec import (
    CONF_REGISTRY, CONSTRAINTS, ConfKey, PROCESS_PREFIX, check_conf_mapping,
    defaults_equal, check_value, match_key, registry_index,
    rows_matching_family,
)
from ..core.config import parse_conf_lines

# ---------------------------------------------------------------------------
# Scan scope
# ---------------------------------------------------------------------------
# every package that reads or produces process-namespace conf — wider
# than the race/proto engine surface because conf reads live in the
# observability, serving and compile planes too
CONF_PACKAGES = (
    "compile", "core", "dist", "lq", "native", "obs", "ops", "pilot",
    "runtime", "serve", "udf", "utils", "web",
)

_NS_CONSTS = {
    "JobPrefix": "datax.job.",
    "JobInputPrefix": "datax.job.input.",
    "JobProcessPrefix": "datax.job.process.",
    "JobOutputPrefix": "datax.job.output.",
}

# SettingDictionary getters (plus dict.get on conf mappings):
# name -> index of the literal-default argument, None = no default arg
_GETTERS: Dict[str, Optional[int]] = {
    "get": 1,
    "get_string": None,
    "get_or_else": 1,
    "get_int_option": None,
    "get_long": None,
    "get_long_option": None,
    "get_double": None,
    "get_double_option": None,
    "get_bool_option": None,
    "get_duration": None,
    "get_duration_option": None,
    "get_string_seq_option": None,
}

_MARKER_RE = re.compile(
    r"#\s*dx-conf:\s*read\s+(?P<key>[A-Za-z0-9_.*-]+)"
    r"(?:\s+default=(?P<default>\S+))?"
)
_TOKEN_RE = re.compile(r"^(gui)?[a-zA-Z][A-Za-z0-9]{1,40}$")
_GUI_TOKEN_RE = re.compile(r"^guiJob[A-Z]")
_KNOB_RE = re.compile(r"^job[A-Z]")


def conf_module_paths() -> List[str]:
    """Every .py file the standing conf gate scans."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out: List[str] = []
    for pkg in CONF_PACKAGES:
        root = os.path.join(pkg_root, pkg)
        for dirpath, _dirs, files in os.walk(root):
            for f in sorted(files):
                if f.endswith(".py"):
                    out.append(os.path.join(dirpath, f))
    return sorted(out)


# ---------------------------------------------------------------------------
# Scan records
# ---------------------------------------------------------------------------
@dataclass(frozen=True)
class ReadSite:
    """One runtime conf read. ``key`` is relative to the process
    namespace; a ``**`` tail marks a family walk (bulk read)."""

    key: str
    module: str
    line: int
    getter: str
    default: Optional[str] = None


@dataclass(frozen=True)
class ProducedKey:
    """One generated/control-plane conf key write. ``links`` carries
    the knob/token literals referenced by the producing statement —
    the designer-chain evidence DX1002 consumes."""

    key: str
    module: str
    line: int
    via: str  # subscript | dict | table | template
    links: Tuple[str, ...] = ()


@dataclass(frozen=True)
class KnobToken:
    """One S400 gui token: designer knob(s) in, generation default out."""

    token: str
    knobs: Tuple[str, ...]
    default: Optional[str]
    module: str
    line: int


def _canon_literal(node: ast.AST) -> Optional[str]:
    """Canonical string form of a literal default (bool -> true/false)."""
    if not isinstance(node, ast.Constant):
        return None
    v = node.value
    if v is None:
        return None
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


# ---------------------------------------------------------------------------
# Per-module scanner
# ---------------------------------------------------------------------------
class _ModuleConfScan:
    """Two-pass ordered AST scan of one module.

    Pass 1 resolves every name/attribute bound (possibly through
    chains) to a conf prefix string; pass 2 harvests read sites and
    produced keys using that symbol table. Unresolvable pieces become
    ``*`` (one segment) / ``**`` (rest) wildcards rather than being
    dropped, so dynamic families stay visible to the lattice.
    """

    def __init__(self, path: str):
        self.path = path
        self.rel = _rel_path(path)
        self.reads: List[ReadSite] = []
        self.produced: List[ProducedKey] = []
        self.tokens: List[KnobToken] = []
        self.knob_reads: Dict[str, int] = {}  # jobXxx literal -> line
        self.scope: Dict[str, Tuple[str, ...]] = {}
        self.paired: Dict[str, Tuple[int, Tuple[Tuple[str, ...], ...]]] = {}
        self.wrappers: Dict[str, Tuple[str, int, Optional[int]]] = {}
        self._seen_reads: set = set()
        self._seen_prod: set = set()

    # -- pass 1: symbol table ------------------------------------------
    def run(self) -> bool:
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                src = f.read()
            tree = ast.parse(src)
        except (OSError, SyntaxError):
            return False
        self._bind_loops(tree)
        # iterate binding to a fixpoint: sub-dictionary chains assign
        # through intermediate names in arbitrary textual order
        for _ in range(4):
            before = dict(self.scope)
            for node in ast.walk(tree):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    self._bind(node.targets[0], node.value)
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    self._bind(node.target, node.value)
            if self.scope == before:
                break
        self._find_wrappers(tree)
        self._harvest(tree.body, if_stack=[])
        self._harvest_markers(src)
        return True

    def _harvest_markers(self, src: str) -> None:
        """``# dx-conf: read <key> [default=<v>]`` markers: escape hatch
        for reads the AST scan cannot see (a conf sub-dictionary handed
        across a module boundary as a plain parameter — e.g. the
        ``debug.`` dict the host passes to ``sanitizer.from_conf``)."""
        for i, line in enumerate(src.splitlines(), start=1):
            m = _MARKER_RE.search(line)
            if not m:
                continue
            key = m.group("key")
            if not key.startswith(PROCESS_PREFIX):
                key = PROCESS_PREFIX + key
            self._emit_read(key, i, "marker", m.group("default"))

    def _bind_loops(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.For):
                continue
            it = node.iter
            if not isinstance(it, (ast.Tuple, ast.List)):
                continue
            tgt = node.target
            if isinstance(tgt, ast.Name):
                vals = tuple(
                    str(e.value) for e in it.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)
                )
                if vals and len(vals) == len(it.elts):
                    self.scope[tgt.id] = vals
            elif isinstance(tgt, ast.Tuple) and all(
                isinstance(n, ast.Name) for n in tgt.elts
            ):
                rows = []
                for e in it.elts:
                    if not (
                        isinstance(e, ast.Tuple)
                        and len(e.elts) == len(tgt.elts)
                        and all(
                            isinstance(c, ast.Constant)
                            and isinstance(c.value, str)
                            for c in e.elts
                        )
                    ):
                        rows = []
                        break
                    rows.append(tuple(c.value for c in e.elts))
                if rows:
                    rows_t = tuple(rows)
                    for i, n in enumerate(tgt.elts):
                        self.scope[n.id] = tuple(r[i] for r in rows_t)
                        self.paired[n.id] = (i, rows_t)

    def _bind(self, target: ast.AST, value: ast.AST) -> None:
        name: Optional[str] = None
        if isinstance(target, ast.Name):
            name = target.id
        elif isinstance(target, ast.Attribute) and isinstance(
            target.value, ast.Name
        ) and target.value.id == "self":
            name = "self." + target.attr
        if name is None or name in self.paired:
            return
        vals = tuple(
            v for v in self._resolve(value)
            if v.startswith("datax.job.") or "*" in v
        )
        if vals:
            self.scope[name] = vals
        elif (
            isinstance(value, ast.Constant)
            and isinstance(value.value, str)
        ):
            # plain module/string constant: usable as prefix material
            self.scope.setdefault(name, (value.value,))

    def _resolve(self, node: ast.AST) -> Tuple[str, ...]:
        """Resolve an expression to candidate prefix/key strings.
        Unknown f-string holes become ``*`` segments."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return (node.value,)
        if isinstance(node, ast.Name):
            return self.scope.get(node.id, ())
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "SettingNamespace"
                and node.attr in _NS_CONSTS
            ):
                return (_NS_CONSTS[node.attr],)
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
            ):
                return self.scope.get("self." + node.attr, ())
            return ()
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            left = self._resolve(node.left)
            right = self._resolve(node.right)
            return tuple(l + r for l in left for r in right)
        if isinstance(node, ast.JoinedStr):
            parts: List[Tuple[str, ...]] = []
            for v in node.values:
                if isinstance(v, ast.Constant):
                    parts.append((str(v.value),))
                elif isinstance(v, ast.FormattedValue):
                    resolved = self._resolve(v.value)
                    parts.append(resolved if resolved else ("*",))
                else:
                    parts.append(("*",))
            out: Tuple[str, ...] = ("",)
            for p in parts:
                out = tuple(o + s for o in out for s in p)
                if len(out) > 32:  # defensive: cap combinatorics
                    return out[:32]
            return out
        if isinstance(node, ast.Call):
            fn = node.func
            if (
                isinstance(fn, ast.Attribute)
                and fn.attr == "get_sub_dictionary"
                and node.args
            ):
                args = self._resolve(node.args[0])
                base = self._resolve(fn.value)
                out = []
                for a in args:
                    if a.startswith("datax.job."):
                        out.append(a)
                    else:
                        out.extend(b + a for b in base)
                return tuple(out)
            if (
                isinstance(fn, ast.Name)
                and fn.id in ("str", "format")
                and node.args
            ):
                return self._resolve(node.args[0])
        return ()

    def _find_wrappers(self, tree: ast.AST) -> None:
        """Detect local conf-helper functions so their call sites count
        as read sites with the prefix baked in. Two shapes:
        module-level ``_conf_get(conf, key, default)`` concatenating a
        prefix constant with the key param, and closure helpers
        (``def f(key, default): v = sub.get(key)``) whose receiver is
        a conf-resolved name from the enclosing scope."""
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef):
                continue
            params = [a.arg for a in node.args.args]
            d_idx = (
                params.index("default") if "default" in params
                else (1 if len(params) > 1 else None)
            )
            done = False
            for sub in ast.walk(node):
                if (
                    isinstance(sub, ast.BinOp)
                    and isinstance(sub.op, ast.Add)
                    and isinstance(sub.left, ast.Name)
                    and isinstance(sub.right, ast.Name)
                    and sub.right.id in params
                ):
                    pref = tuple(
                        p for p in self.scope.get(sub.left.id, ())
                        if p.startswith(PROCESS_PREFIX)
                    )
                    if pref:
                        self.wrappers[node.name] = (
                            pref[0], params.index(sub.right.id), d_idx,
                        )
                        done = True
                        break
            if done:
                continue
            for sub in ast.walk(node):
                if not (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Attribute)
                    and sub.func.attr in _GETTERS
                    and sub.args
                    and isinstance(sub.args[0], ast.Name)
                    and sub.args[0].id in params
                ):
                    continue
                pref = tuple(
                    p for p in self._resolve(sub.func.value)
                    if p.startswith(PROCESS_PREFIX)
                )
                if pref:
                    self.wrappers[node.name] = (
                        pref[0], params.index(sub.args[0].id), d_idx,
                    )
                    break

    # -- pass 2: harvest -----------------------------------------------
    _KEY_OK_RE = re.compile(r"^[A-Za-z0-9_.*-]+$")

    @classmethod
    def _sanitize(cls, key: str) -> Optional[str]:
        """Collapse partially-resolved segments to one ``*`` each and
        reject strings that cannot be conf keys (the module-union
        symbol table can mis-bind a reused name to metric/format
        strings — those never look like dotted conf keys)."""
        if not cls._KEY_OK_RE.match(key):
            return None
        segs = key.split(".")
        out = []
        for i, s in enumerate(segs):
            if s == "**" and i == len(segs) - 1:
                out.append(s)
            elif "*" in s:
                out.append("*")
            else:
                out.append(s)
        return ".".join(out)

    def _emit_read(
        self, key: str, line: int, getter: str, default: Optional[str],
    ) -> None:
        if not key.startswith(PROCESS_PREFIX):
            return
        rel = self._sanitize(key[len(PROCESS_PREFIX):])
        if not rel or rel == "**":
            return
        sig = (rel, line, getter)
        if sig in self._seen_reads:
            return
        self._seen_reads.add(sig)
        self.reads.append(ReadSite(rel, self.rel, line, getter, default))

    def _emit_prod(
        self, key: str, line: int, via: str, links: Sequence[str],
    ) -> None:
        if not key.startswith(PROCESS_PREFIX):
            return
        rel = self._sanitize(key[len(PROCESS_PREFIX):])
        if not rel:
            return
        sig = (rel, line)
        if sig in self._seen_prod:
            return
        self._seen_prod.add(sig)
        self.produced.append(
            ProducedKey(rel, self.rel, line, via, tuple(sorted(set(links))))
        )

    @staticmethod
    def _stmt_links(nodes: Sequence[ast.AST]) -> List[str]:
        out = []
        for root in nodes:
            for n in ast.walk(root):
                if (
                    isinstance(n, ast.Constant)
                    and isinstance(n.value, str)
                    and "." not in n.value
                    and _TOKEN_RE.match(n.value)
                ):
                    out.append(n.value)
        return out

    def _harvest(self, body: Sequence[ast.stmt], if_stack: List[ast.AST]) -> None:
        for stmt in body:
            if isinstance(stmt, ast.If):
                self._harvest_exprs([stmt.test], if_stack)
                self._harvest(stmt.body, if_stack + [stmt.test])
                self._harvest(stmt.orelse, if_stack)
                continue
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._harvest(stmt.body, [])
                continue
            if isinstance(stmt, ast.ClassDef):
                self._harvest(stmt.body, [])
                continue
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._harvest_exprs([stmt.iter], if_stack)
                self._harvest(stmt.body, if_stack)
                self._harvest(stmt.orelse, if_stack)
                continue
            if isinstance(stmt, ast.While):
                self._harvest_exprs([stmt.test], if_stack)
                self._harvest(stmt.body, if_stack)
                continue
            if isinstance(stmt, ast.Try):
                self._harvest(stmt.body, if_stack)
                for h in stmt.handlers:
                    self._harvest(h.body, if_stack)
                self._harvest(stmt.orelse, if_stack)
                self._harvest(stmt.finalbody, if_stack)
                continue
            if isinstance(stmt, ast.With):
                self._harvest_exprs(
                    [i.context_expr for i in stmt.items], if_stack
                )
                self._harvest(stmt.body, if_stack)
                continue
            # producer: subscript store  conf["datax.job.process…"] = v
            if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (
                    stmt.targets if isinstance(stmt, ast.Assign)
                    else [stmt.target]
                )
                for t in targets:
                    if isinstance(t, ast.Subscript):
                        self._harvest_subscript_store(t, stmt, if_stack)
            self._harvest_exprs([stmt], if_stack)

    def _harvest_subscript_store(
        self, target: ast.Subscript, stmt: ast.stmt, if_stack: List[ast.AST],
    ) -> None:
        sl = target.slice
        links = self._stmt_links([stmt] + list(if_stack))
        # paired-table f-string: one hole bound by a (knob, key) row
        if isinstance(sl, ast.JoinedStr):
            holes = [
                v.value.id for v in sl.values
                if isinstance(v, ast.FormattedValue)
                and isinstance(v.value, ast.Name)
            ]
            if len(holes) == 1 and holes[0] in self.paired:
                col, rows = self.paired[holes[0]]
                lit = "".join(
                    str(v.value) if isinstance(v, ast.Constant) else "\0"
                    for v in sl.values
                )
                for row in rows:
                    self._emit_prod(
                        lit.replace("\0", row[col]), target.lineno,
                        "table", links + [c for c in row if c != row[col]],
                    )
                return
        for key in self._resolve(sl):
            self._emit_prod(key, target.lineno, "subscript", links)

    def _harvest_exprs(
        self, roots: Sequence[ast.AST], if_stack: List[ast.AST],
    ) -> None:
        for root in roots:
            for node in ast.walk(root):
                if isinstance(node, ast.Call):
                    self._harvest_call(node, root, if_stack)
                elif isinstance(node, ast.Dict):
                    self._harvest_dict(node, if_stack)
                elif (
                    isinstance(node, ast.Attribute) and node.attr == "dict"
                ):
                    for p in self._resolve(node.value):
                        self._emit_read(
                            p + "**", node.lineno, ".dict", None,
                        )
                elif isinstance(node, ast.DictComp):
                    # producer: {f"datax.job.process…{k}": v for …}
                    for key in self._resolve(node.key):
                        self._emit_prod(key, node.lineno, "dict", ())

    def _harvest_dict(self, node: ast.Dict, if_stack: List[ast.AST]) -> None:
        for k, v in zip(node.keys, node.values):
            if k is None:
                continue
            # S400-style gui token rows: knob chain + generation default
            if (
                isinstance(k, ast.Constant)
                and isinstance(k.value, str)
                and _GUI_TOKEN_RE.match(k.value)
            ):
                knobs = tuple(
                    n.args[0].value for n in ast.walk(v)
                    if isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)
                    and n.func.attr == "get"
                    and n.args
                    and isinstance(n.args[0], ast.Constant)
                    and isinstance(n.args[0].value, str)
                    and _KNOB_RE.match(n.args[0].value)
                )
                default: Optional[str] = None
                for b in ast.walk(v):
                    if isinstance(b, ast.BoolOp) and isinstance(
                        b.op, ast.Or
                    ):
                        default = _canon_literal(b.values[-1])
                if knobs:
                    self.tokens.append(KnobToken(
                        k.value, knobs, default, self.rel, k.lineno,
                    ))
            keys: Tuple[str, ...] = ()
            if isinstance(k, (ast.Constant, ast.JoinedStr, ast.BinOp)):
                keys = self._resolve(k)
            for key in keys:
                self._emit_prod(
                    key, k.lineno, "dict", self._stmt_links([v] + list(if_stack)),
                )

    def _harvest_call(
        self, node: ast.Call, stmt_root: ast.AST, if_stack: List[ast.AST],
    ) -> None:
        fn = node.func
        # local wrapper helper: _conf_get(conf, "key", default)
        if isinstance(fn, ast.Name) and fn.id in self.wrappers:
            prefix, k_idx, d_idx = self.wrappers[fn.id]
            if len(node.args) > k_idx and isinstance(
                node.args[k_idx], ast.Constant
            ):
                default = None
                if d_idx is not None and len(node.args) > d_idx:
                    default = _canon_literal(node.args[d_idx])
                self._emit_read(
                    prefix + str(node.args[k_idx].value),
                    node.lineno, fn.id, default,
                )
            return
        if not isinstance(fn, ast.Attribute):
            return
        if fn.attr == "group_by_sub_namespace":
            if node.args:  # prefix passed as argument
                for p in self._resolve(node.args[0]):
                    self._emit_read(p + "**", node.lineno, fn.attr, None)
            else:
                for p in self._resolve(fn.value):
                    self._emit_read(p + "**", node.lineno, fn.attr, None)
            return
        if fn.attr == "setdefault" and len(node.args) >= 1:
            # producer: conf.setdefault("datax.job.process…", default)
            for key in self._resolve(node.args[0]):
                self._emit_prod(
                    key, node.lineno, "subscript",
                    self._stmt_links(node.args[1:]),
                )
            return
        if fn.attr not in _GETTERS:
            return
        prefixes = tuple(
            p for p in self._resolve(fn.value)
            if p.startswith("datax.job.")
        )
        if not node.args:
            return
        key_arg = node.args[0]
        fulls: List[str] = []
        key_strs = (
            self._resolve(key_arg)
            if isinstance(key_arg, (ast.Constant, ast.JoinedStr, ast.BinOp,
                                    ast.Name, ast.Attribute))
            else ()
        )
        for ks in key_strs:
            if ks.startswith("datax.job."):
                fulls.append(ks)
            else:
                fulls.extend(p + ks for p in prefixes)
        if not key_strs and prefixes:
            fulls.extend(p + "**" for p in prefixes)
        # harvest the knob vocabulary for chain checks
        if (
            isinstance(key_arg, ast.Constant)
            and isinstance(key_arg.value, str)
            and _KNOB_RE.match(key_arg.value)
        ):
            self.knob_reads.setdefault(key_arg.value, node.lineno)
        d_idx = _GETTERS[fn.attr]
        default = None
        if d_idx is not None and len(node.args) > d_idx:
            default = _canon_literal(node.args[d_idx])
        for full in fulls:
            self._emit_read(full, node.lineno, fn.attr, default)


# ---------------------------------------------------------------------------
# Template (flattener-schema) producer enumeration
# ---------------------------------------------------------------------------
def template_produced_keys() -> List[str]:
    """Process-namespace keys the declarative flattener template can
    emit — derived from ``DEFAULT_FLATTENER_SCHEMA`` itself so the doc
    and the lattice can never drift from the flattener."""
    from ..compile.flattener_schema import DEFAULT_FLATTENER_SCHEMA

    process = DEFAULT_FLATTENER_SCHEMA["fields"]["process"]
    out: List[str] = []

    def walk(node, prefix: str) -> None:
        if isinstance(node, str):
            out.append(prefix + node)
            return
        t = node.get("type")
        ns = node.get("namespace", "")
        if t in ("object",):
            for _f, sub in node.get("fields", {}).items():
                walk(sub, prefix + ns + "." if ns else prefix)
        elif t in ("stringList", "excludeDefaultValue"):
            out.append(prefix + ns)
        elif t == "mapProps":
            out.append(prefix + ns + ".*")
        elif t == "map":
            for _f, sub in node.get("fields", {}).items():
                walk(sub, prefix + ns + ".*.")
        elif t in ("array",):
            walk(node.get("element", {}), prefix + ns + "." if ns else prefix)
        elif t == "scopedObject":
            base = prefix + (ns + "." if ns else "") + "*."
            for _f, sub in node.get("fields", {}).items():
                walk(sub, base)

    for _f, sub in process.get("fields", {}).items():
        walk(sub, "")
    return sorted(set(out))


# ---------------------------------------------------------------------------
# Report
# ---------------------------------------------------------------------------
@dataclass
class ConfCheckReport:
    """Result of the configuration-lattice pass."""

    flow: str
    analyzed_files: int
    read_sites: List[ReadSite] = field(default_factory=list)
    produced: List[ProducedKey] = field(default_factory=list)
    tokens: List[KnobToken] = field(default_factory=list)
    diagnostics: List[Diagnostic] = field(default_factory=list)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "error"]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == "warning"]

    @property
    def ok(self) -> bool:
        return not self.errors

    def conf_dict(self) -> dict:
        return {
            "flow": self.flow,
            "analyzedFiles": self.analyzed_files,
            "readSites": len(self.read_sites),
            "readKeys": len({r.key for r in self.read_sites}),
            "producedKeys": len({p.key for p in self.produced}),
            "knobTokens": len(self.tokens),
            "registryKeys": len(CONF_REGISTRY),
            "constraints": len(CONSTRAINTS),
        }

    def to_dict(self) -> dict:
        return {
            "schemaVersion": REPORT_SCHEMA_VERSION,
            "flow": self.flow,
            "ok": self.ok,
            "errorCount": len(self.errors),
            "warningCount": len(self.warnings),
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "conf": self.conf_dict(),
        }

    def render(self) -> str:
        lines = [
            f"conf: {len(self.read_sites)} read site(s), "
            f"{len({p.key for p in self.produced})} produced key(s), "
            f"{len(CONF_REGISTRY)} registered",
        ]
        lines.extend(d.render() for d in self.diagnostics)
        return "\n".join(lines)


# ---------------------------------------------------------------------------
# Checks
# ---------------------------------------------------------------------------
def _derived_jt_name(token: str) -> str:
    """``guiJobNumChips`` -> ``jobNumChips`` (the flowbuilder jt hop)."""
    if token.startswith("gui") and len(token) > 4:
        return token[3].lower() + token[4:]
    return token


def _check_lattice(
    scans: List[_ModuleConfScan],
    diags: List[Diagnostic],
    full_tree: bool,
    chain_scope: bool,
) -> None:
    reads = [r for s in scans for r in s.reads]
    produced = [p for s in scans for p in s.produced]
    tokens = [t for s in scans for t in s.tokens]
    knob_reads: Dict[str, Tuple[str, int]] = {}
    for s in scans:
        for k, ln in s.knob_reads.items():
            knob_reads.setdefault(k, (s.rel, ln))

    # DX1000: read site with no lattice row behind it
    for r in reads:
        if "*" in r.key:
            if not rows_matching_family(r.key):
                diags.append(make(
                    "DX1000", r.module,
                    f"conf family '{PROCESS_PREFIX}{r.key}' is walked "
                    f"({r.getter}) but no registered key lives under it "
                    "— nothing can produce what this read consumes",
                    Span(line=r.line),
                ))
            continue
        entry = match_key(r.key)
        if entry is None:
            diags.append(make(
                "DX1000", r.module,
                f"conf key '{PROCESS_PREFIX}{r.key}' is read "
                f"({r.getter}) but is not in the conf registry — a "
                "dead knob or a typo'd key no generation path produces",
                Span(line=r.line),
            ))
        elif r.default is not None and not defaults_equal(entry, r.default):
            diags.append(make(
                "DX1003", r.module,
                f"default drift on '{PROCESS_PREFIX}{r.key}': this "
                f"read site falls back to {r.default!r} but the "
                f"registry default is {entry.default!r} — 'unset' "
                "means different things on different layers",
                Span(line=r.line),
            ))

    # DX1001: produced key with no lattice row behind it
    for p in produced:
        if "*" in p.key:
            if not rows_matching_family(
                p.key if p.key.endswith("*") else p.key
            ):
                diags.append(make(
                    "DX1001", p.module,
                    f"generated conf family '{PROCESS_PREFIX}{p.key}' "
                    f"({p.via}) matches no registered key — dead conf "
                    "no runtime reader will ever see",
                    Span(line=p.line),
                ))
            continue
        if match_key(p.key) is None:
            diags.append(make(
                "DX1001", p.module,
                f"generated conf key '{PROCESS_PREFIX}{p.key}' "
                f"({p.via}) is not in the conf registry — "
                "generated-but-never-read dead conf",
                Span(line=p.line),
            ))

    # DX1002 (local form): an S400 gui token no produced key carries
    prod_links = set()
    for p in produced:
        prod_links.update(p.links)
    for t in tokens:
        names = {t.token, _derived_jt_name(t.token)}
        if not (names & prod_links):
            diags.append(make(
                "DX1002", t.module,
                f"broken designer chain: gui token '{t.token}' (knob "
                f"{'/'.join(t.knobs)}) is built but no generated conf "
                "key carries it — the designer knob never reaches the "
                "runtime",
                Span(line=t.line),
            ))

    # DX1003 (generation form): S400 default vs registry default
    by_token = {e.token: e for e in CONF_REGISTRY if e.token}
    for t in tokens:
        entry = by_token.get(t.token)
        if (
            entry is not None
            and t.default not in (None, "")
            and entry.default is not None
            and not defaults_equal(entry, t.default)
        ):
            diags.append(make(
                "DX1003", t.module,
                f"default drift on '{PROCESS_PREFIX}{entry.key}': "
                f"generation token '{t.token}' defaults to "
                f"{t.default!r} but the registry default is "
                f"{entry.default!r}",
                Span(line=t.line),
            ))

    # DX1002 (registry form): declared knob→key chains must exist in
    # the scanned generation — only meaningful when the real
    # generation module is in the scan set
    if chain_scope:
        produced_exact = {p.key for p in produced if "*" not in p.key}
        produced_fams = {p.key for p in produced if "*" in p.key}
        tmpl = set(template_produced_keys())
        # a knob is "read by generation" when it appears as a direct
        # jobconf.get literal OR rides a produced row's links (the S640
        # paired-table rows read their knobs through the loop variable)
        knob_sites: Dict[str, Tuple[str, int]] = dict(knob_reads)
        for p in produced:
            for link in p.links:
                if _KNOB_RE.match(link):
                    knob_sites.setdefault(link, (p.module, p.line))
        for e in CONF_REGISTRY:
            if not e.knob:
                continue
            if e.knob not in knob_sites:
                diags.append(make(
                    "DX1002", "analysis/confspec.py",
                    f"broken designer chain: registry declares knob "
                    f"'{e.knob}' for '{PROCESS_PREFIX}{e.key}' but the "
                    "generation scan never reads that knob",
                ))
                continue
            if "*" in e.key:
                continue
            covered = (
                e.key in produced_exact
                or e.key in tmpl
                or any(
                    _fam_covers(f, e.key) for f in produced_fams
                )
            )
            if not covered:
                mod, ln = knob_sites[e.knob]
                diags.append(make(
                    "DX1002", mod,
                    f"broken designer chain: knob '{e.knob}' is read "
                    f"by generation but its registered key "
                    f"'{PROCESS_PREFIX}{e.key}' is never written — "
                    "the knob's value is dropped on the floor",
                    Span(line=ln),
                ))

    # DX1001 (registry form, full-tree self-lint only): a read=True
    # row no scanned module reads — stale registry / dead conf
    if full_tree:
        read_exact = {r.key for r in reads if "*" not in r.key}
        read_fams = [r.key for r in reads if "*" in r.key]
        for e in CONF_REGISTRY:
            if not e.read:
                continue
            covered = (
                e.key in read_exact
                or any(_fam_covers(f, e.key) for f in read_fams)
            )
            if not covered and "*" in e.key:
                covered = any(
                    _fam_covers(e.key, rk) for rk in read_exact
                )
            if not covered:
                diags.append(make(
                    "DX1001", "analysis/confspec.py",
                    f"registry row '{PROCESS_PREFIX}{e.key}' is marked "
                    "read=True but no scanned module reads it — dead "
                    "conf (mark read=False if it is a parity key, or "
                    "delete the production)",
                ))


def _fam_covers(family: str, key: str) -> bool:
    from .confspec import _family_covers

    return _family_covers(family, key)


# ---------------------------------------------------------------------------
# Entry points
# ---------------------------------------------------------------------------
def analyze_conf_modules(
    paths: List[str], flow: str = "",
) -> ConfCheckReport:
    """Run the DX10xx pass over explicit files — ``.py`` modules are
    scanned for read/producer sites; ``.conf`` files are parsed and
    value-checked (DX1004/DX1005) against the lattice."""
    scans: List[_ModuleConfScan] = []
    diags: List[Diagnostic] = []
    analyzed = 0
    conf_files: List[str] = []
    for p in paths:
        if p.endswith(".conf"):
            conf_files.append(p)
            continue
        s = _ModuleConfScan(p)
        if s.run():
            scans.append(s)
            analyzed += 1
    real = set(conf_module_paths())
    full_tree = real and real.issubset(set(paths))
    chain_scope = any(
        os.path.basename(p) == "generation.py" for p in paths
    )
    _check_lattice(scans, diags, full_tree, chain_scope)
    for cf in conf_files:
        analyzed += 1
        rel = _rel_path(cf)
        try:
            with open(cf, "r", encoding="utf-8") as f:
                mapping = parse_conf_lines(f.read().splitlines())
        except OSError as e:
            diags.append(make(
                "DX1004", rel, f"cannot read conf file: {e}",
            ))
            continue
        for kind, key, reason in check_conf_mapping(mapping):
            if kind == "value":
                diags.append(make(
                    "DX1004", rel,
                    f"conf value violation on "
                    f"'{PROCESS_PREFIX}{key}': {reason}",
                ))
            elif kind == "constraint":
                diags.append(make(
                    "DX1005", rel,
                    f"incompatible conf combination ({key}): {reason}",
                ))
            else:  # unknown key in a concrete conf = dead conf
                diags.append(make(
                    "DX1001", rel,
                    f"conf file carries '{PROCESS_PREFIX}{key}' but "
                    f"no registry row covers it — {reason}",
                ))
    return ConfCheckReport(
        flow=flow,
        analyzed_files=analyzed,
        read_sites=[r for s in scans for r in s.reads],
        produced=[p for s in scans for p in s.produced],
        tokens=[t for s in scans for t in s.tokens],
        diagnostics=diags,
    )


# mtime-keyed cache of the full-tree scan (the expensive part of
# analyze_flow_conf; the per-flow checks are cheap dict work)
_ENGINE_CACHE: Dict[tuple, ConfCheckReport] = {}


def _cached_tree_report() -> ConfCheckReport:
    paths = conf_module_paths()
    key = tuple((p, os.path.getmtime(p)) for p in paths)
    hit = _ENGINE_CACHE.get(key)
    if hit is None:
        _ENGINE_CACHE.clear()
        hit = analyze_conf_modules(paths)
        _ENGINE_CACHE[key] = hit
    return hit


def effective_flow_conf(flow: Mapping) -> Dict[str, str]:
    """The flow's designer-visible effective conf (relative keys):
    registry defaults overlaid with the flow's ``jobconfig`` knob
    values mapped through their registered chains."""
    gui = flow.get("gui") or flow
    jobconf = ((gui.get("process") or {}).get("jobconfig") or {})
    eff: Dict[str, str] = {
        e.key: e.default for e in CONF_REGISTRY
        if e.default is not None and "*" not in e.key
    }
    for e in CONF_REGISTRY:
        if not e.knob or "*" in e.key:
            continue
        v = jobconf.get(e.knob)
        if v not in (None, ""):
            eff[e.key] = str(v)
    return eff


def analyze_flow_conf(flow: Mapping) -> ConfCheckReport:
    """Flow-level conf gate: the cached full-tree lattice scan plus
    this flow's concrete knob values checked for type/bounds (DX1004)
    and incompatible combinations (DX1005)."""
    gui = flow.get("gui") or flow
    name = str(flow.get("name") or gui.get("name") or "")
    base = _cached_tree_report()
    diags = list(base.diagnostics)
    jobconf = ((gui.get("process") or {}).get("jobconfig") or {})
    by_knob = {e.knob: e for e in CONF_REGISTRY if e.knob}
    for knob, v in sorted(jobconf.items()):
        e = by_knob.get(knob)
        if e is None or v in (None, ""):
            continue
        reason = check_value(e, str(v))
        if reason:
            diags.append(make(
                "DX1004", name,
                f"designer knob '{knob}' "
                f"('{PROCESS_PREFIX}{e.key}'): {reason}",
            ))
    eff = effective_flow_conf(flow)
    for rule in CONSTRAINTS:
        if rule.violated(eff):
            diags.append(make(
                "DX1005", name,
                f"incompatible conf combination ({rule.name}): "
                f"{rule.description}",
            ))
    return ConfCheckReport(
        flow=name,
        analyzed_files=base.analyzed_files,
        read_sites=base.read_sites,
        produced=base.produced,
        tokens=base.tokens,
        diagnostics=diags,
    )


# ---------------------------------------------------------------------------
# Inventory dump (registry maintenance aid)
# ---------------------------------------------------------------------------
def inventory() -> dict:
    """The scanned lattice as JSON-able data — what the registry in
    ``confspec.py`` is maintained against."""
    rep = analyze_conf_modules(conf_module_paths())
    return {
        "readSites": [
            {
                "key": r.key, "module": r.module, "line": r.line,
                "getter": r.getter, "default": r.default,
            }
            for r in sorted(rep.read_sites, key=lambda r: (r.key, r.module, r.line))
        ],
        "produced": [
            {
                "key": p.key, "module": p.module, "line": p.line,
                "via": p.via, "links": list(p.links),
            }
            for p in sorted(rep.produced, key=lambda p: (p.key, p.module, p.line))
        ],
        "templateKeys": template_produced_keys(),
        "tokens": [
            {
                "token": t.token, "knobs": list(t.knobs),
                "default": t.default, "module": t.module, "line": t.line,
            }
            for t in sorted(rep.tokens, key=lambda t: t.token)
        ],
        "registered": sorted(e.key for e in CONF_REGISTRY),
        "findings": [d.render() for d in rep.diagnostics],
    }


if __name__ == "__main__":  # pragma: no cover — maintenance utility
    print(json.dumps(inventory(), indent=1))
