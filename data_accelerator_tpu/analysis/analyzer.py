"""Multi-pass static analyzer over a whole flow config.

Takes the same flow JSON the designer saves (``serve/flowbuilder.py``
gui contract, or a full flow document wrapping it) and returns typed
diagnostics without executing anything. Every stage reuses the
production toolchain — ``compile/codegen.py`` expands rules/TIMEWINDOW/
OUTPUT exactly as S450 generation does, ``compile/transform_parser.py``
and ``compile/sqlparser.py`` parse exactly what the runtime compiles —
so analysis cannot drift from runtime semantics.

Passes (see diagnostics.CODES for the full registry):

1. reference resolution — unbound tables/columns, dangling sink/UDF
   references, forward/cyclic view references (DX00x)
2. type propagation — a small lattice seeded from the input schemas,
   flagging mismatched comparisons/join keys/CASTs (DX01x)
3. aggregation/window legality — aggregates outside aggregation
   contexts, window retention vs the state-capacity budget, accumulator
   misuse (DX02x)
4. dead-flow detection — views that never reach a sink, metric,
   accumulator or downstream view (DX03x)
5. device-compilation risk — patterns the planner can only lower with
   host round-trips or per-batch table rebuilds (DX04x)
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..compile.codegen import CodegenEngine, RulesCode
from ..compile.sqlparser import (
    BinOp,
    Col,
    Select,
    SqlParseError,
    Star,
    parse_select,
)
from ..compile.transform_parser import (
    COMMAND_TYPE_QUERY,
    SqlCommand,
    TransformParser,
)
from ..constants import DatasetName
from ..core.config import parse_duration_seconds
from ..runtime.timewindow import num_slots
from ..serve.flowbuilder import RuleDefinitionGenerator
from .diagnostics import AnalysisReport, Diagnostic, Span, make
from .typeprop import (
    ExprChecker,
    SelectScope,
    TableScope,
    ddl_to_types,
    incompatible,
    schema_to_types,
)

# Windowed-table retention budget: ring rows = slots x batch capacity.
# Beyond this the window state alone dwarfs the job's working set
# (runtime/statetable.py + timewindow.py hold it all in device memory).
DEFAULT_MAX_STATE_ROWS = 16 * 1024 * 1024

_RAW_PASSTHROUGH = re.compile(r"^\s*Raw\.\*\s*$")


@dataclass
class FlowContext:
    """Everything the passes need, extracted from one flow config."""

    name: str = ""
    # design-time-known tables: name -> TableScope (inputs, windows,
    # state tables; views are added as statements are processed)
    tables: Dict[str, TableScope] = field(default_factory=dict)
    input_tables: List[str] = field(default_factory=list)
    state_tables: Dict[str, Optional[Dict[str, str]]] = field(
        default_factory=dict
    )
    windows: Dict[str, str] = field(default_factory=dict)  # table -> duration
    sinks: frozenset = frozenset()  # declared sink ids (gui.outputs)
    udfs: frozenset = frozenset()  # upper-cased declared function ids
    outputs: List[Tuple[str, str]] = field(default_factory=list)
    batch_interval_s: float = 1.0
    watermark_s: float = 0.0
    batch_capacity: int = 65536
    max_state_rows: int = DEFAULT_MAX_STATE_ROWS


class FlowAnalyzer:
    """Run all passes over a flow config; see ``analyze_flow``."""

    def __init__(self, max_state_rows: int = DEFAULT_MAX_STATE_ROWS):
        self.max_state_rows = max_state_rows

    # -- public entry ----------------------------------------------------
    def analyze_flow(self, flow: dict) -> AnalysisReport:
        gui = flow.get("gui") if isinstance(flow.get("gui"), dict) else flow
        diags: List[Diagnostic] = []
        ctx = self._build_context(gui, diags)
        code = self._generate_transform(gui, ctx, diags)
        if code is not None:
            self._analyze_transform(code, ctx, diags)
        return AnalysisReport(self._ordered(diags))

    def analyze_script(
        self, script: str, ctx: Optional[FlowContext] = None
    ) -> AnalysisReport:
        """Analyze a raw transform script against an explicit context
        (tests and conf-driven callers; no codegen involved — the script
        is taken as the runtime sees it)."""
        ctx = ctx or FlowContext()
        if DatasetName.DataStreamProjection not in ctx.tables:
            ctx.tables[DatasetName.DataStreamProjection] = TableScope(
                DatasetName.DataStreamProjection, None
            )
            ctx.input_tables.append(DatasetName.DataStreamProjection)
        diags: List[Diagnostic] = []
        self._analyze_transform(script, ctx, diags)
        return AnalysisReport(self._ordered(diags))

    # -- context construction -------------------------------------------
    def _build_context(self, gui: dict, diags: List[Diagnostic]) -> FlowContext:
        ctx = FlowContext(name=gui.get("name") or "",
                          max_state_rows=self.max_state_rows)
        iprops = (gui.get("input") or {}).get("properties") or {}
        proc = gui.get("process") or {}

        def table_from(schema_json, snippet, name) -> TableScope:
            # a custom normalization snippet can project anything; only
            # Raw.* passthrough (or no snippet) keeps the schema columns
            if snippet and not _RAW_PASSTHROUGH.match(str(snippet)):
                return TableScope(name, None)
            return TableScope(name, schema_to_types(schema_json))

        main = DatasetName.DataStreamProjection
        ctx.tables[main] = table_from(
            iprops.get("inputSchemaFile"),
            iprops.get("normalizationSnippet"), main,
        )
        ctx.input_tables.append(main)

        for src in (gui.get("input") or {}).get("sources") or []:
            sname = src.get("id") or src.get("name")
            if not sname:
                continue
            sprops = src.get("properties") or {}
            target = sprops.get("target") or sname
            ctx.tables[target] = table_from(
                sprops.get("inputSchemaFile"),
                sprops.get("normalizationSnippet"), target,
            )
            ctx.input_tables.append(target)

        ctx.sinks = frozenset(
            o.get("id") for o in gui.get("outputs") or [] if o.get("id")
        )
        ctx.udfs = frozenset(
            str(f.get("id")).upper()
            for f in proc.get("functions") or [] if f.get("id")
        )

        jobconf = proc.get("jobconfig") or {}
        try:
            ctx.batch_capacity = int(
                jobconf.get("jobBatchCapacity") or 65536
            )
        except (TypeError, ValueError):
            pass
        try:
            ctx.batch_interval_s = float(
                iprops.get("windowDuration")
                or iprops.get("intervalInSeconds") or 1
            )
        except (TypeError, ValueError):
            pass
        watermark = proc.get("watermark") or (
            f"{iprops.get('watermarkValue', 0)} "
            f"{iprops.get('watermarkUnit', 'second')}"
        )
        try:
            ctx.watermark_s = parse_duration_seconds(watermark)
        except Exception:  # noqa: BLE001 — malformed watermark: keep 0
            pass
        return ctx

    def _generate_transform(
        self, gui: dict, ctx: FlowContext, diags: List[Diagnostic]
    ) -> Optional[str]:
        """Run the production codegen (S450 semantics) and register the
        tables it derives (windows, accumulators) plus the OUTPUT map."""
        queries = (gui.get("process") or {}).get("queries") or []
        code = "\n".join(q if isinstance(q, str) else str(q) for q in queries)
        rules_json = RuleDefinitionGenerator().generate(
            gui.get("rules") or [], ctx.name
        )
        windowable = {DatasetName.DataStreamProjection, *ctx.input_tables}
        try:
            rc: RulesCode = CodegenEngine().generate_code(
                code, rules_json, ctx.name, windowable_tables=windowable
            )
        except ValueError as e:
            diags.append(make("DX009", "", str(e)))
            return None
        except Exception as e:  # noqa: BLE001 — any codegen blowup is a finding
            diags.append(make("DX008", "", f"codegen failed: {e}"))
            return None

        ctx.outputs = list(rc.outputs)
        ctx.windows = dict(rc.time_windows)
        for wname, duration in rc.time_windows.items():
            src = next(
                (t for t in ctx.input_tables
                 if wname.startswith(t + "_")), None
            )
            base = ctx.tables.get(src)
            ctx.tables[wname] = TableScope(
                wname, None if base is None else base.types
            )
        for sname, ddl in rc.accumulation_tables.items():
            types = ddl_to_types(ddl)
            ctx.state_tables[sname] = types
            ctx.tables[sname] = TableScope(sname, types)
        return rc.code

    # -- transform analysis ---------------------------------------------
    def _analyze_transform(
        self, code: str, ctx: FlowContext, diags: List[Diagnostic]
    ) -> None:
        parsed = self._parse(code, diags)
        if parsed is None:
            return
        queries = [
            c for c in parsed.commands
            if c.command_type == COMMAND_TYPE_QUERY and c.name
        ]
        all_views = {c.name for c in queries}
        defined: set = set()

        for cmd in queries:
            span = Span(cmd.line or 0, 1, cmd.end_line or None)
            sql = cmd.text.rstrip().rstrip(";")
            try:
                sel = parse_select(sql)
            except SqlParseError as e:
                col = getattr(e, "pos", None)
                diags.append(make(
                    "DX008", cmd.name, str(e),
                    Span(cmd.line or 0, (col or 0) + 1, cmd.end_line or None),
                ))
                defined.add(cmd.name)
                ctx.tables[cmd.name] = TableScope(cmd.name, None)
                continue
            out_scope = self._check_statement(
                cmd, sel, ctx, defined, all_views, diags, span
            )
            defined.add(cmd.name)
            if cmd.name in ctx.state_tables:
                self._check_state_update(cmd, out_scope, ctx, diags, span)
                # the accumulator keeps its declared shape downstream
            else:
                ctx.tables[cmd.name] = out_scope

        self._check_outputs(ctx, parsed, diags)
        self._check_windows(ctx, diags)
        self._check_state_tables(ctx, defined, diags)
        self._check_dead_views(ctx, parsed, diags)

    def _parse(self, code: str, diags: List[Diagnostic]):
        try:
            return TransformParser.parse_text(code)
        except Exception as e:  # noqa: BLE001 — surfaced as a finding
            diags.append(make("DX008", "", str(e)))
            return None

    # -- per-statement checks (passes 1, 2, 3, 5) ------------------------
    def _check_statement(
        self,
        cmd: SqlCommand,
        sel: Select,
        ctx: FlowContext,
        defined: set,
        all_views: set,
        diags: List[Diagnostic],
        span: Span,
    ) -> TableScope:
        out_types: Dict[str, str] = {}
        out_computed: set = set()
        out_open = False  # a * over an open table makes the output open
        first = True
        # walk the UNION chain: every branch resolves in the same
        # known-table universe; the first branch names the output columns
        branch: Optional[Select] = sel
        while branch is not None:
            scope = self._select_scope(
                cmd, branch, ctx, defined, all_views, diags, span
            )

            def emit(code_: str, message: str, _span=span, _cmd=cmd):
                diags.append(make(code_, _cmd.name or "", message, _span))

            checker = ExprChecker(scope, ctx.udfs, emit)
            grouped = bool(branch.group_by)

            names_seen: set = set()
            for item in branch.items:
                if isinstance(item.expr, Star):
                    if first:
                        out_open |= self._expand_star(
                            item.expr, scope, out_types, out_computed
                        )
                    continue
                info = checker.check(item.expr, agg_allowed=True)
                name = item.alias or self._item_name(item.expr)
                if first:
                    if name in names_seen:
                        emit("DX007", f"duplicate output column '{name}'")
                    names_seen.add(name)
                    out_types.setdefault(name, info.type)
                    if info.computed_string:
                        out_computed.add(name)

            # WHERE/GROUP BY/HAVING/ORDER BY also see the select-list
            # aliases (two-tier resolution, planner._OrderKeyScope role);
            # source bindings come first so shadowing resolves source-side
            alias_scope = SelectScope(list(scope.bindings))
            alias_scope.add("", TableScope(
                "", dict(out_types) if (out_types and not out_open) else None,
                frozenset(out_computed),
            ))
            achecker = ExprChecker(alias_scope, ctx.udfs, emit)

            if branch.where is not None:
                achecker.check(branch.where, agg_allowed=False)
            for g in branch.group_by:
                achecker.check(g, agg_allowed=False)
            if branch.having is not None:
                achecker.check(branch.having, agg_allowed=grouped)
            for j in branch.joins:
                self._check_join_keys(cmd, j.on, checker, diags, span)
                checker.check(j.on, agg_allowed=False)
            for ob in branch.order_by:
                info = achecker.check(ob.expr, agg_allowed=grouped)
                name = (
                    ob.expr.parts[-1] if isinstance(ob.expr, Col) else None
                )
                if info.computed_string or (name and name in out_computed):
                    diags.append(make(
                        "DX040", cmd.name or "",
                        "ORDER BY over a computed string sorts on the host "
                        "after materialization (device round-trip per batch)",
                        span,
                    ))
            first = False
            branch = branch.union

        return TableScope(
            cmd.name or "",
            None if (out_open or not out_types) else out_types,
            frozenset(out_computed),
        )

    def _select_scope(
        self, cmd, sel: Select, ctx, defined: set, all_views: set,
        diags, span,
    ) -> SelectScope:
        scope = SelectScope()
        refs = []
        if sel.from_table is not None:
            refs.append(sel.from_table)
        refs.extend(j.table for j in sel.joins)
        for ref in refs:
            t = ctx.tables.get(ref.name)
            if t is None or (
                ref.name in all_views and ref.name not in defined
                and ref.name not in ctx.state_tables
                and ref.name not in ctx.input_tables
            ):
                if ref.name in all_views and ref.name not in defined:
                    diags.append(make(
                        "DX005", cmd.name or "",
                        f"view '{ref.name}' is referenced before its "
                        "definition — a cycle needs a --DataXStates-- "
                        "accumulation table",
                        span,
                    ))
                elif t is None:
                    diags.append(make(
                        "DX001", cmd.name or "",
                        f"unknown table '{ref.name}' in FROM/JOIN",
                        span,
                    ))
                scope.add(ref.binding, TableScope(ref.name, None))
            else:
                scope.add(ref.binding, t)
        return scope

    @staticmethod
    def _expand_star(star: Star, scope: SelectScope, out_types,
                     out_computed) -> bool:
        """Expand ``*``/``t.*`` into out_types; returns True when any
        matched table is open (the output shape is then unknowable)."""
        any_open = False
        for binding, t in scope.bindings:
            if star.table is not None and binding != star.table \
                    and t.name != star.table:
                continue
            if t.open:
                any_open = True
                continue
            for c, typ in (t.types or {}).items():
                out_types.setdefault(c, typ)
                if c in t.computed:
                    out_computed.add(c)
        return any_open

    @staticmethod
    def _item_name(expr) -> str:
        if isinstance(expr, Col):
            return expr.parts[-1]
        return "expr"

    def _check_join_keys(self, cmd, on, checker: ExprChecker, diags, span):
        """ON a.x = b.y with disagreeing key types (pass 2, DX011)."""

        def walk(e):
            if not isinstance(e, BinOp):
                return
            if e.op in ("AND", "OR"):
                walk(e.left)
                walk(e.right)
                return
            if e.op == "=" and isinstance(e.left, Col) \
                    and isinstance(e.right, Col):
                lt, _ = checker.scope.resolve(e.left.parts)
                rt, _ = checker.scope.resolve(e.right.parts)
                if lt and rt and incompatible(lt.type, rt.type):
                    diags.append(make(
                        "DX011", cmd.name or "",
                        f"join keys disagree: {e.left.dotted} is {lt.type}, "
                        f"{e.right.dotted} is {rt.type}",
                        span,
                    ))

        walk(on)

    # -- flow-level checks (passes 1, 3, 4) ------------------------------
    def _check_outputs(self, ctx: FlowContext, parsed, diags) -> None:
        produced = {
            c.name for c in parsed.commands
            if c.command_type == COMMAND_TYPE_QUERY and c.name
        } | set(ctx.state_tables) | set(ctx.tables)
        for tables, sink in ctx.outputs:
            for table in (t.strip() for t in tables.split(",")):
                if table and table not in produced:
                    diags.append(make(
                        "DX003", table,
                        f"OUTPUT routes '{table}' to sink '{sink}' but no "
                        "transform statement produces it — the job would "
                        "deploy producing nothing",
                    ))
            if sink and sink.lower() != "metrics" and ctx.sinks \
                    and sink not in ctx.sinks:
                diags.append(make(
                    "DX004", "",
                    f"OUTPUT routes to sink '{sink}' which gui.outputs does "
                    "not declare (generation would silently default it to a "
                    "metric sink)",
                ))

    def _check_windows(self, ctx: FlowContext, diags) -> None:
        for wname, duration in ctx.windows.items():
            try:
                dur_s = parse_duration_seconds(duration)
            except Exception:  # noqa: BLE001
                diags.append(make(
                    "DX021", wname,
                    f"unparseable TIMEWINDOW duration '{duration}'",
                    severity="error",
                ))
                continue
            slots = num_slots(dur_s, ctx.watermark_s, ctx.batch_interval_s)
            rows = slots * ctx.batch_capacity
            if rows > ctx.max_state_rows:
                diags.append(make(
                    "DX021", wname,
                    f"window '{duration}' needs {slots} ring slots x "
                    f"{ctx.batch_capacity} batch capacity = {rows} retained "
                    f"rows, over the {ctx.max_state_rows}-row state budget",
                ))

    def _check_state_update(self, cmd, out_scope: TableScope, ctx, diags,
                            span) -> None:
        declared = ctx.state_tables.get(cmd.name)
        if declared is None or out_scope.types is None:
            return
        want, got = set(declared), set(out_scope.types)
        if want != got:
            diags.append(make(
                "DX022", cmd.name,
                f"accumulation update columns {sorted(got)} disagree with "
                f"the declared schema {sorted(want)}",
                span,
            ))

    def _check_state_tables(self, ctx: FlowContext, defined: set, diags):
        for sname in ctx.state_tables:
            if sname not in defined:
                diags.append(make(
                    "DX022", sname,
                    f"accumulation table '{sname}' is declared but no "
                    "statement ever assigns it",
                ))

    def _check_dead_views(self, ctx: FlowContext, parsed, diags) -> None:
        routed: set = set()
        for tables, _sink in ctx.outputs:
            routed.update(t.strip() for t in tables.split(","))
        queries = [
            c for c in parsed.commands
            if c.command_type == COMMAND_TYPE_QUERY and c.name
        ]
        for cmd in queries:
            refs = parsed.view_reference_count.get(cmd.name, 0)
            if refs == 0 and cmd.name not in routed \
                    and cmd.name not in ctx.state_tables:
                diags.append(make(
                    "DX030", cmd.name,
                    f"view '{cmd.name}' is computed but never reaches a "
                    "sink, metric, accumulator or downstream view",
                    Span(cmd.line or 0, 1, cmd.end_line or None),
                ))
        if queries and not ctx.outputs and not ctx.state_tables:
            diags.append(make(
                "DX031", "",
                "flow has transform statements but routes nothing to any "
                "sink or accumulator",
            ))

    @staticmethod
    def _ordered(diags: List[Diagnostic]) -> List[Diagnostic]:
        """Stable order: errors first, then by source line, then code."""
        return sorted(
            diags,
            key=lambda d: (d.severity != "error", d.span.line, d.code),
        )


def analyze_flow(flow: dict, **kw) -> AnalysisReport:
    """Analyze a flow config (gui JSON or full flow document)."""
    return FlowAnalyzer(**kw).analyze_flow(flow)


def analyze_script(script: str, ctx: Optional[FlowContext] = None,
                   **kw) -> AnalysisReport:
    """Analyze a raw transform script against an explicit context."""
    return FlowAnalyzer(**kw).analyze_script(script, ctx)
