"""Closed-form static cost model over compiled device plans.

Every capacity in a lowered flow is static, so a stage's HBM footprint,
FLOP count and expected ICI traffic are *closed-form functions* of the
shapes the planner chose — no execution, no sampling. The formulas here
consume the ``StagePlan``/``JoinSite`` metadata ``compile/planner.py``
records at lowering time; ``analysis/deviceplan.py`` cross-checks the
byte model against ``jax.eval_shape`` over the production lowering (and
``bench.py`` against the arrays a real batch materializes), so the model
cannot silently drift from what the compiler actually builds.

Documented in ANALYSIS.md ("Scaling model"): the ICI terms are the
model VERDICT Weak #2 demanded — expected bytes over the chip
interconnect per batch as a function of group cardinality and join
fan-out, for the v5e-16 extrapolation.

Column widths (core/schema.py device encoding, x64 off):
long/string/timestamp -> int32 (4 B), double -> float32 (4 B),
boolean -> bool (1 B); the validity mask is one bool per row.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

from ..compile.planner import StagePlan

# planner type name -> device bytes per element
COLUMN_WIDTH: Dict[str, int] = {
    "long": 4,
    "double": 4,
    "boolean": 1,
    "string": 4,
    "timestamp": 4,
}

# bytes of one equality/sort key element (all key-able types are 4 B)
KEY_BYTES = 4

# pairs budget above which a match-matrix join is flagged as the
# O(n*m) cliff (DX203): 2^24 pair evaluations per batch
DEFAULT_MATCH_MATRIX_BUDGET = 1 << 24


def column_width(type_name: str) -> int:
    """Device bytes per element of a planner-typed column (unknown
    types conservatively count as 4 — every device dtype except bool
    is 32-bit)."""
    return COLUMN_WIDTH.get(type_name, 4)


def table_bytes(types: Dict[str, str], rows: int) -> int:
    """HBM bytes of one materialized TableData: every device column
    (hidden ``__defer.``/``.__valid`` included — they are real arrays)
    plus the one-bool-per-row validity mask."""
    return sum(column_width(t) * rows for t in types.values()) + rows


def row_bytes(types: Dict[str, str]) -> int:
    return table_bytes(types, 1)


def view_output_bytes(
    types: Dict[str, str], plan: Optional[StagePlan], rows: int
) -> int:
    """Closed-form bytes of a compiled view's output table.

    Mirrors the planner's run() exactly: grouped views ride an
    ``__overflow.groups`` int32 column, any view whose FROM chain joined
    rides ``__overflow.joins`` (both row-broadcast), and UNION outputs
    carry neither (the concat keeps only schema columns).
    """
    b = table_bytes(types, rows)
    if plan is None or plan.kind == "union":
        return b
    if plan.grouped:
        b += 4 * rows  # __overflow.groups
    if plan.joins:
        b += 4 * rows  # __overflow.joins
    return b


def d2h_transfer_bytes(
    types: Dict[str, str], plan: Optional[StagePlan], rows_transferred: int
) -> int:
    """Closed-form device->host bytes of fetching one OUTPUT table at
    ``rows_transferred`` rows — the per-batch wire cost of the sync
    stage for that output. The transferred table has exactly the
    view-output layout (schema columns + overflow slots + validity), so
    the term is ``view_output_bytes`` evaluated at the transfer
    capacity: the full padded capacity for a plain fetch, or the sized
    (EWMA-bucketed) capacity under
    ``datax.job.process.pipeline.sizedtransfer``. See ANALYSIS.md
    "Scaling model" and the DX206 hint."""
    return view_output_bytes(types, plan, rows_transferred)


# donated double-buffered output transfer slots
# (runtime/processor.py _stage_output): each output dataset keeps this
# many transfer-ready copies of its table resident in HBM, alternating
# A/B so batch N+1's jitted pack never clobbers batch N's in-flight
# background D2H copy
OUTPUT_SLOT_BUFFERS = 2


def output_slot_bytes(
    types: Dict[str, str], plan: Optional[StagePlan], capacity: int
) -> int:
    """Closed-form HBM bytes of one output's donated transfer slots:
    ``OUTPUT_SLOT_BUFFERS`` resident copies of the view-output layout
    at the slot capacity. The runtime sizes slots at the adaptive
    (EWMA-bucketed) transfer capacity, bounded above by the padded
    output capacity — the static model charges the bound, like every
    other capacity it accounts. These bytes are persistent (the slots
    live as long as the flow), so they join the DX2xx/DX4xx HBM totals
    the fleet placer packs against."""
    return OUTPUT_SLOT_BUFFERS * view_output_bytes(types, plan, capacity)


# fraction of one chip's HBM the LiveQuery serving plane may pin in
# resident interactive kernels (lq/warmcache.py WarmKernelCache): the
# production flows placed on the chip own the rest (the DX4xx packer
# already charges them), so the serving plane takes a bounded slice
# instead of competing with them allocation-by-allocation
DEFAULT_LQ_CACHE_HEADROOM = 0.25


def warm_kernel_cache_budget_bytes(
    chip_hbm_bytes: Optional[int] = None,
    headroom: float = DEFAULT_LQ_CACHE_HEADROOM,
) -> int:
    """HBM bytes the LiveQuery warm-kernel LRU may keep resident —
    ``headroom`` of one chip (the fleet-spec default when unset). Each
    cache entry is priced with the same DX2xx byte model the fleet
    packer consumes (``deviceplan.analyze_processor(...).totals()``),
    so cache occupancy and flow placement share one currency."""
    if chip_hbm_bytes is None:
        from .fleetcheck import DEFAULT_HBM_PER_CHIP

        chip_hbm_bytes = DEFAULT_HBM_PER_CHIP
    return int(chip_hbm_bytes * float(headroom))


def runtime_conformance_model(
    totals: Dict[str, object],
    stages: Optional[list] = None,
    outputs: Optional[Dict[str, dict]] = None,
) -> dict:
    """The cost model as a *runtime artifact*: the compact JSON-ready
    slice of a device-plan report that config generation embeds into
    the flow's conf (``datax.job.process.conformance.model``) and the
    host's ``ConformanceMonitor`` judges observations against. Keeps
    only what the monitor (and humans debugging drift) need — modeled
    per-batch D2H bytes, HBM totals, per-output modeled occupancy, and
    the per-stage d2hBytes/hbmBytes/flops breakdown (the byte+FLOP
    terms the host combines with its own calibrated machine profile
    into the DX520/DX521 latency predictions — bytes and FLOPs travel
    in the conf, milliseconds are computed where the hardware is)."""
    return {
        "totals": {
            "d2hBytesPerBatch": totals.get("d2hBytesPerBatch"),
            "hbmBytes": totals.get("hbmBytes"),
            "modelBytes": totals.get("modelBytes"),
            "flops": totals.get("flops"),
        },
        "outputs": dict(outputs or {}),
        "stages": [
            {
                "name": s.get("name"),
                "kind": s.get("kind"),
                # rows ride along so the latency model can derive the
                # per-batch ingest row count (input-kind stages) for
                # the calibrated host-decode term
                "rows": s.get("rows"),
                "hbmBytes": s.get("hbmBytes"),
                "d2hBytes": s.get("d2hBytes"),
                "flops": s.get("flops"),
            }
            for s in (stages or [])
        ],
    }


# ---------------------------------------------------------------------------
# Latency closed forms (the time axis): roofline milliseconds from the
# byte/FLOP closed forms above plus a measured machine profile
# (obs/calibrate.py). The per-stage form is the classic roofline —
# a stage is either bandwidth-bound or compute-bound, never both:
#
#     stage_ms = max(bytes / HBM_BW, flops / F) [+ dispatch overhead]
#
# These are LOWER bounds by construction (peak-bandwidth streaming,
# peak dense FLOP/s); achieved efficiency on gather/sort-heavy SQL
# stages runs below peak, so the DX520 runtime band that judges
# observed-vs-predicted is wide (it catches wholesale regressions —
# a bandwidth collapse, dispatch-overhead domination, an HBM re-layout
# — not micro-inefficiency).
# ---------------------------------------------------------------------------
def stage_time_ms(
    hbm_bytes: float, flops: float, profile: Dict[str, float],
) -> float:
    """Roofline milliseconds of one stage under ``profile`` (a
    ``MachineProfile.to_dict()``): max of the memory term (stage bytes
    at the slower of the read/write streams) and the compute term.
    Dispatch overhead is NOT included — the whole jitted step pays it
    once, not per stage."""
    bw = min(
        float(profile.get("hbm_read_gbps") or 1.0),
        float(profile.get("hbm_write_gbps") or 1.0),
    )
    flop_rate = float(profile.get("flops_gflops") or 1.0)
    mem_ms = float(hbm_bytes) / max(bw, 1e-9) / 1e6
    compute_ms = float(flops) / max(flop_rate, 1e-9) / 1e6
    return max(mem_ms, compute_ms)


def transfer_time_ms(bytes_: float, gbps: Optional[float]) -> Optional[float]:
    """Milliseconds to move ``bytes_`` over a link of ``gbps`` (D2H or
    ICI); None when the link bandwidth is unknown (e.g. no mesh)."""
    if not gbps:
        return None
    return float(bytes_) / float(gbps) / 1e6


def decode_time_ms(
    input_rows: float, profile: Dict[str, float],
) -> Optional[float]:
    """The calibrated host-decode term: milliseconds to run
    ``input_rows`` through the native ingest decoder at the machine's
    measured rate (``decode_rows_per_sec``, obs/calibrate.py's decoder
    probe over a reference payload). None when the machine has no
    calibrated decode rate (native library unavailable) or the model
    carries no input rows — the missing-prediction posture (silence)
    applies, like every other absent term."""
    rate = profile.get("decode_rows_per_sec")
    if not rate or not input_rows:
        return None
    return float(input_rows) / float(rate) * 1000.0


def model_input_rows(stages: list) -> float:
    """Per-batch ingest row count of a stage list (dict-shaped): the
    summed capacities of the input-kind stages — the rows the host
    decoder must produce each batch."""
    return float(sum(
        float(s.get("rows") or 0.0)
        for s in (stages or [])
        if s.get("kind") == "input"
    ))


def latency_model(
    stages: list,
    totals: Dict[str, object],
    profile: Dict[str, float],
    profile_source: str = "default",
) -> dict:
    """The ``latencyModel`` report block: per-stage roofline ms plus
    the batch-level decomposition the runtime stages map onto —
    ``decodeMs`` (the calibrated host-decode term over the input-stage
    rows), ``deviceStepMs`` (every stage's compute, one dispatch
    overhead), ``d2hMs`` (the full-fetch output transfer), ``iciMs``
    (the DX7xx wire bytes over the calibrated link).
    ``stages``/``totals`` are dict-shaped (``StageCost.to_dict()`` /
    ``DevicePlanReport.totals()`` or the conf-embedded runtime model).
    Consumed by the ``--device`` report, the designer Validate cost
    table, bench.py's roofline block, and the host's DX520/DX521
    predictions."""
    overhead_ms = float(profile.get("dispatch_overhead_us") or 0.0) / 1000.0
    out_stages = []
    compute_ms = 0.0
    for s in stages or []:
        ms = stage_time_ms(
            float(s.get("hbmBytes") or 0.0), float(s.get("flops") or 0.0),
            profile,
        )
        compute_ms += ms
        out_stages.append({
            "name": s.get("name"),
            "kind": s.get("kind"),
            "computeMs": round(ms, 4),
        })
    d2h_bytes = float(totals.get("d2hBytesPerBatch") or 0.0)
    d2h_ms = transfer_time_ms(d2h_bytes, profile.get("d2h_gbps"))
    ici_bytes = float(
        totals.get("iciWireBytesPerBatch")
        or totals.get("iciBytesPerBatch") or 0.0
    )
    ici_ms = transfer_time_ms(ici_bytes, profile.get("ici_gbps"))
    decode_ms = decode_time_ms(model_input_rows(stages), profile)
    device_step_ms = compute_ms + overhead_ms
    return {
        "profileSource": profile_source,
        "profile": {
            k: profile.get(k)
            for k in (
                "backend", "device_kind", "hbm_read_gbps",
                "hbm_write_gbps", "flops_gflops", "dispatch_overhead_us",
                "d2h_gbps", "ici_gbps", "decode_rows_per_sec",
            )
        },
        "stages": out_stages,
        "totals": {
            "computeMs": round(compute_ms, 4),
            "dispatchOverheadMs": round(overhead_ms, 4),
            "decodeMs": (
                round(decode_ms, 4) if decode_ms is not None else None
            ),
            "deviceStepMs": round(device_step_ms, 4),
            "d2hMs": round(d2h_ms, 4) if d2h_ms is not None else None,
            "iciMs": round(ici_ms, 4) if ici_ms is not None else None,
            "batchMs": round(
                device_step_ms + (decode_ms or 0.0) + (d2h_ms or 0.0)
                + (ici_ms or 0.0), 4
            ),
        },
    }


def stage_latency_predictions(model: dict) -> Dict[str, float]:
    """Map a ``latency_model()`` block onto the runtime histogram
    stages the host measures (constants.MetricName.STAGES): the DX520
    comparison keys. Only stages the model can actually predict appear
    — ``decode`` (the calibrated host-decode rate over the flow's
    input rows), ``device-step`` (compute + one dispatch overhead) and
    ``collect`` (the D2H landing of the output tables).
    Sinks/checkpoint are host-side I/O the model deliberately does not
    cover. Like every roofline term the decode prediction is a LOWER
    bound (a saturated decoder at the calibrated rate; the runtime
    decode span also contains the source poll), judged under the wide
    DX520 band and the sub-floor silence rule."""
    totals = model.get("totals") or {}
    out: Dict[str, float] = {}
    if totals.get("decodeMs"):
        out["decode"] = float(totals["decodeMs"])
    if totals.get("deviceStepMs"):
        out["device-step"] = float(totals["deviceStepMs"])
    if totals.get("d2hMs"):
        out["collect"] = float(totals["d2hMs"])
    return out


# ---------------------------------------------------------------------------
# Mesh collective wire-cost closed forms (the DX7xx tier,
# analysis/meshcheck.py). Two byte conventions, deliberately separate:
#
# - **result bytes**: the full logical size of a collective's result —
#   chip-count-INDEPENDENT, deterministic from static shapes, and the
#   quantity the analyzer asserts exactly equal between the closed-form
#   model and the Mesh-lowered program (the DX2xx `model ==
#   materialized` analog).
# - **wire bytes**: total bytes crossing ICI links across the whole
#   slice for a ring-algorithm collective over `result bytes` — the
#   Megatron-LM closed forms over chip count N. This is the term DX703
#   budgets and the runtime's Mesh_ICI_Bytes series observes.
#
# ring all-gather of S result bytes: each chip forwards (N-1) shard
# messages of S/N bytes -> total S*(N-1). ring all-reduce =
# reduce-scatter + all-gather -> 2*S*(N-1)/N per chip, total 2*S*(N-1).
# all-to-all keeps 1/N local -> total S*(N-1)/N.
# ---------------------------------------------------------------------------
def allgather_wire_bytes(result_bytes: float, chips: int) -> float:
    """Total slice-wide ICI bytes of a ring all-gather producing
    ``result_bytes`` on every chip."""
    if chips <= 1:
        return 0.0
    return float(result_bytes) * (chips - 1)


def allreduce_wire_bytes(result_bytes: float, chips: int) -> float:
    """Total slice-wide ICI bytes of a ring all-reduce (reduce-scatter
    + all-gather) over ``result_bytes``."""
    if chips <= 1:
        return 0.0
    return 2.0 * float(result_bytes) * (chips - 1)


def alltoall_wire_bytes(result_bytes: float, chips: int) -> float:
    """Total slice-wide ICI bytes of an all-to-all over
    ``result_bytes`` (1/N of every shard stays local)."""
    if chips <= 1:
        return 0.0
    return float(result_bytes) * (chips - 1) / chips


# wire factor per compiled-HLO collective op name — the same convention
# dist/mesh.py's runtime collective_summary applies, so the model and
# the observed Mesh_ICI_Bytes series can never disagree about what a
# byte over the ICI means
COLLECTIVE_WIRE_FACTORS = {
    "all-gather": allgather_wire_bytes,
    "all-reduce": allreduce_wire_bytes,
    "reduce-scatter": alltoall_wire_bytes,  # S*(N-1)/N: one shard stays
    "all-to-all": alltoall_wire_bytes,
    "collective-permute": lambda s, n: float(s),  # every byte moves once
}


def collective_wire_bytes(op: str, result_bytes: float, chips: int) -> float:
    """Wire bytes of one collective given its result bytes — shared by
    the DX7xx model and the runtime observation path."""
    fn = COLLECTIVE_WIRE_FACTORS.get(op)
    return fn(result_bytes, chips) if fn else float(result_bytes)


def mesh_runtime_model(
    totals: Dict[str, object], stages: Optional[list] = None,
) -> dict:
    """The sharding plan as a *runtime artifact*: the compact JSON slice
    of a mesh-plan report that config generation embeds into mesh jobs'
    confs (``datax.job.process.mesh.model``, the S660 stage) and the
    host's ``ConformanceMonitor`` judges the observed ``Mesh_ICI_Bytes``
    / ``Mesh_Reshard_Count`` series against (DX510/DX511)."""
    return {
        "totals": {
            "iciResultBytesPerBatch": totals.get("iciResultBytesPerBatch"),
            "iciWireBytesPerBatch": totals.get("iciWireBytesPerBatch"),
            "reshardCount": totals.get("reshardCount"),
            "chips": totals.get("chips"),
        },
        "stages": [
            {
                "name": s.get("name"),
                "axis": s.get("axis"),
                "iciWireBytes": s.get("iciWireBytes"),
                "reshards": s.get("reshards"),
            }
            for s in (stages or [])
        ],
    }


def _log2(n: int) -> float:
    return math.log2(max(int(n), 2))


def stage_transient_bytes(plan: Optional[StagePlan]) -> int:
    """Peak in-stage intermediates that never persist: the [n, m] bool
    match matrix (+ two int32 index grids when a residual re-gathers
    pairs) of non-sort-merge joins. Sort-merge and group-by
    intermediates are O(rows) and fold into the output estimate."""
    if plan is None:
        return 0
    total = 0
    for s in plan.joins:
        if s.algorithm == "match-matrix":
            pairs = s.left_rows * s.right_rows
            total += pairs  # bool mask
            if s.has_residual:
                total += 2 * 4 * pairs  # index grids for the pair filter
    return total


def stage_flops(plan: Optional[StagePlan], n_out_cols: int) -> float:
    """Order-of-magnitude FLOP/compare estimate per batch for one stage.

    Sorts count rows*log2(rows) per key column (the planner's group-by,
    distinct, sort-merge join and ORDER BY all lower to lexsorts);
    match-matrix joins count one compare per pair per conjunct;
    projections count one op per output element.
    """
    if plan is None:
        return 0.0
    n = plan.input_rows
    out = plan.output_rows
    flops = float(n) * max(n_out_cols, 1)  # projection/eval of outputs
    for s in plan.joins:
        if s.algorithm == "match-matrix":
            flops += float(s.left_rows) * s.right_rows * (
                s.n_eq_keys + (1 if s.has_residual else 0)
            )
        else:
            nm = s.left_rows + s.right_rows
            flops += nm * _log2(nm) * s.n_eq_keys + s.out_rows
    if plan.grouped:
        flops += n * _log2(n) * max(plan.group_keys, 1)
        flops += float(n) * max(plan.n_aggregates, 1)
    if plan.distinct:
        flops += n * _log2(n)
    if plan.order_keys:
        flops += out * _log2(out) * plan.order_keys
    return flops


def ici_bytes_group(
    input_rows: int,
    group_keys: int,
    n_aggregates: int,
    groups: int,
    group_row_bytes: int,
    chips: int,
) -> float:
    """Expected ICI bytes/batch of one GROUP BY under the 1-D data-mesh
    layout (dist/mesh.py): rows shard, outputs replicate.

    - distributed sort (group_ids): each of the N rows' key + aggregated
      value elements crosses chips with probability (C-1)/C;
    - all-gather of the replicated [G]-row group output to every chip:
      G * row_bytes * (C-1).

    The second term is the one that scales with group cardinality G —
    the quantity bounded by ``process.maxgroups``.
    """
    if chips <= 1:
        return 0.0
    shuffle = (
        float(input_rows)
        * KEY_BYTES
        * (group_keys + n_aggregates)
        * (chips - 1)
        / chips
    )
    gather = float(groups) * group_row_bytes * (chips - 1)
    return shuffle + gather


def ici_bytes_join(
    left_rows: int,
    right_rows: int,
    n_eq_keys: int,
    out_rows: int,
    out_row_bytes: int,
    chips: int,
    match_matrix: bool = False,
    right_row_bytes: int = 0,
) -> float:
    """Expected ICI bytes/batch of one JOIN site.

    Sort-merge: the union gid sort shuffles (n+m) key elements like the
    group-by sort; match-matrix instead broadcasts the whole right table
    to every chip (the [n, m] compare needs it locally). Both then
    all-gather the capacity-bounded output — the term that scales with
    join fan-out F = out_rows.
    """
    if chips <= 1:
        return 0.0
    if match_matrix:
        shuffle = float(right_rows) * right_row_bytes * (chips - 1)
    else:
        shuffle = (
            float(left_rows + right_rows)
            * KEY_BYTES
            * n_eq_keys
            * (chips - 1)
            / chips
        )
    gather = float(out_rows) * out_row_bytes * (chips - 1)
    return shuffle + gather


def stage_ici_bytes(
    plan: Optional[StagePlan],
    out_row_bytes_: int,
    chips: int,
    right_row_bytes: Dict[str, int],
) -> float:
    """Total expected ICI bytes/batch for one stage at ``chips`` chips.

    ``right_row_bytes``: per right-table row bytes (match-matrix joins
    broadcast the right side). Projections/unions move nothing — rows
    stay sharded and the ops are elementwise.
    """
    if plan is None or chips <= 1:
        return 0.0
    total = 0.0
    for s in plan.joins:
        total += ici_bytes_join(
            s.left_rows,
            s.right_rows,
            s.n_eq_keys,
            s.out_rows,
            out_row_bytes_,
            chips,
            match_matrix=(s.algorithm == "match-matrix"),
            right_row_bytes=right_row_bytes.get(s.right_table, KEY_BYTES),
        )
    if plan.grouped:
        total += ici_bytes_group(
            plan.input_rows,
            plan.group_keys,
            plan.n_aggregates,
            plan.groups_bound,
            out_row_bytes_,
            chips,
        )
    return total
