"""Exactly-once protocol analysis over the ENGINE'S OWN modules
(the ``--protocol`` tier, DX9xx).

The delivery guarantee — sink emit -> durable checkpoint / pointer
flip -> FIFO ack -> offset commit, plus the rescale A/B handoff — is
hand-ordered code in ``runtime/host.py``, ``runtime/checkpoint.py``,
``runtime/statetable.py`` and ``serve/jobs.py``, defended until now
only by chaos drills that sample interleavings. This pass makes the
ordering machine-checked the way ``racecheck.py`` made the
donation/zero-copy bug class machine-checked: per engine entry point
it extracts a typed EFFECT TRACE of protocol events (the
``protospec.py`` vocabulary: SINK_EMIT, DURABLE_WRITE, POINTER_FLIP,
FIFO_ACK, OFFSET_COMMIT, STATE_PUSH, REQUEUE, DRAIN_MARKER, plus the
handoff pair HANDOFF_PULL/DISPATCH) and checks the lexical
happens-before order against the declared rule table.

Effect extraction (call-pattern recognition, like the provenance
seeds of the race tier):

- ``SINK_EMIT``     — ``<dispatcher>.dispatch(...)``, ``<sink>.write(...)``
- ``POINTER_FLIP``  — ``.persist()``, ``<processor>.commit()``,
  ``put_pointer`` on a non-mirror store
- ``DURABLE_WRITE`` — ``os.fsync``, ``os.replace``,
  ``_durable_replace``, ``put_files`` on a non-mirror store,
  ``<checkpointer>.save(...)``
- ``FIFO_ACK``      — ``.ack()``;  ``OFFSET_COMMIT`` —
  ``.checkpoint_batch(...)`` / ``.write_offsets(...)``
- ``STATE_PUSH``    — ``push_window_partitions``, ``put_files`` /
  ``put_pointer`` on a mirror store
- ``REQUEUE``       — ``.requeue_unacked()``;  ``DRAIN_MARKER`` —
  ``_settle_landings`` / ``_drain_landings``
- ``HANDOFF_PULL``  — ``_state_partition_plan(...)`` or stamping
  ``rec["statePartitionsOwned"]`` / ``rec["confOverrides"]``
- ``DISPATCH``      — ``<client>.submit(...)``

The checks (per function, main-path = outside except handlers,
lexical order):

- **DX900** — a FIFO_ACK before the POINTER_FLIP; also any
  ``os.replace`` without an fsync of the tmp file BEFORE the rename
  and of the parent directory AFTER it (the PR 4/PR 13 power-loss
  durability contract).
- **DX901** — a POINTER_FLIP before the SINK_EMIT.
- **DX902** — more than one main-path ack call site in one function.
- **DX903** — a function that acks whose failure handler does not
  requeue the whole unacked window (and: a looped ack requires a
  looped requeue — one source's requeue does not cover the window).
- **DX904** — a pre-ack effect outside any try whose handler
  requeues, or a post-ack effect without an explicit
  ``post-commit`` marker.
- **DX905** — a handoff function whose first successor DISPATCH
  precedes its first HANDOFF_PULL.

Marker contract (``# dx-proto:`` structured comments, same
span-forwarding semantics as ``# dx-race:``)
--------------------------------------------------------------------
Line-scoped (same line, or above — covering the next statement's
full span):

- ``# dx-proto: post-commit <reason>`` — pins a DESIGNED post-ack
  effect (DX904): the interval-gated window-snapshot + offset
  checkpoint block is at-least-once replay territory ON PURPOSE;
  counted and reported so the self-lint keeps an inventory.

Function-scoped (any line inside the function):

- ``# dx-proto: requeue-upstream <reason>`` — exempts a delegating
  ack wrapper from DX903: the requeue obligation is discharged by the
  caller that owns the batch failure handler.

The runtime counterpart is ``runtime/protocolmonitor.py`` (conf
``datax.job.process.debug.protocolmonitor``): records each batch's
ACTUAL event sequence into the flight recorder and validates its
linearization against the same ``protospec`` rule objects, firing
runtime **DX906** events — the dynamic ground truth the DX90x
fixtures and the seeded ack-before-durability regression test are
proven against.
"""

from __future__ import annotations

import ast
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .diagnostics import Diagnostic, Span, make
from .racecheck import (
    _collect_markers,
    _dotted,
    _fn_markers,
    _Markers,
    _rel_path,
    engine_module_paths,
)
from .protospec import (
    DISPATCH,
    DRAIN_MARKER,
    DURABLE_WRITE,
    EFFECT_KINDS,
    FIFO_ACK,
    HANDOFF_PULL,
    OFFSET_COMMIT,
    POINTER_FLIP,
    REQUEUE,
    SINK_EMIT,
    STATE_PUSH,
)

_MARKER_RE = re.compile(r"#\s*dx-proto:\s*([a-z-]+)\s*(.*)$")

# subscript keys whose stamping on a job record IS the handoff pull
_HANDOFF_KEYS = {"statePartitionsOwned", "confOverrides"}

_DRAIN_CALLS = {"_settle_landings", "_drain_landings"}


@dataclass
class _Event:
    """One extracted protocol event with its control-flow context."""

    kind: str
    line: int
    col: int
    detail: str
    in_handler: bool  # inside an except handler
    guarded: bool     # inside a try whose handler requeues
    looped: bool      # inside a For/While body


def _classify_call(node: ast.Call) -> Optional[Tuple[str, str]]:
    """Map one call to its protocol event kind, or None."""
    func = node.func
    if isinstance(func, ast.Attribute):
        base = _dotted(func.value)
        bl = base.lower()
        attr = func.attr
        if attr == "dispatch" and bl.endswith("dispatcher"):
            return SINK_EMIT, f"{base}.dispatch"
        if attr == "write" and "sink" in bl:
            return SINK_EMIT, f"{base}.write"
        if attr == "persist" and not node.args:
            return POINTER_FLIP, f"{base}.persist"
        if attr == "commit" and bl.endswith("processor"):
            return POINTER_FLIP, f"{base}.commit"
        if attr == "put_pointer":
            if "mirror" in bl:
                return STATE_PUSH, f"{base}.put_pointer"
            return POINTER_FLIP, f"{base}.put_pointer"
        if attr == "put_files":
            if "mirror" in bl:
                return STATE_PUSH, f"{base}.put_files"
            return DURABLE_WRITE, f"{base}.put_files"
        if attr == "push_window_partitions":
            return STATE_PUSH, f"{base}.push_window_partitions"
        if attr in ("checkpoint_batch", "write_offsets"):
            return OFFSET_COMMIT, f"{base}.{attr}"
        if attr == "ack" and not node.args:
            return FIFO_ACK, f"{base}.ack"
        if attr == "requeue_unacked":
            return REQUEUE, f"{base}.requeue_unacked"
        if attr == "fsync" and base == "os":
            return DURABLE_WRITE, "os.fsync"
        if attr == "replace" and base == "os":
            return DURABLE_WRITE, "os.replace"
        if attr == "save" and "checkpoint" in bl:
            return DURABLE_WRITE, f"{base}.save"
        if attr in _DRAIN_CALLS:
            return DRAIN_MARKER, f"{base}.{attr}"
        if attr == "_state_partition_plan":
            return HANDOFF_PULL, f"{base}._state_partition_plan"
        if attr == "submit" and bl.endswith("client"):
            return DISPATCH, f"{base}.submit"
    elif isinstance(func, ast.Name):
        if func.id == "_durable_replace":
            return DURABLE_WRITE, "_durable_replace"
        if func.id in _DRAIN_CALLS:
            return DRAIN_MARKER, func.id
    return None


def _handler_has_requeue(handlers: List[ast.ExceptHandler]) -> bool:
    for h in handlers:
        for sub in ast.walk(h):
            if isinstance(sub, ast.Call) and \
                    isinstance(sub.func, ast.Attribute) and \
                    sub.func.attr == "requeue_unacked":
                return True
    return False


class _FnProto:
    """Effect-trace extraction + rule check over one function body."""

    def __init__(self, linter: "_ModuleLinter", node, cls_name: str,
                 method_name: str, fn_marks: Set[str]):
        self.l = linter
        self.node = node
        self.cls_name = cls_name
        self.method = method_name
        self.marks = fn_marks
        self.events: List[_Event] = []

    def _where(self) -> str:
        return (
            f"{self.cls_name}.{self.method}" if self.cls_name
            else self.method
        )

    # -- extraction ----------------------------------------------------
    def _leaf(self, st: ast.stmt, in_handler: bool, guarded: bool,
              looped: bool) -> None:
        """Harvest events from a non-compound statement (or a compound
        statement's header expression)."""
        for sub in ast.walk(st):
            if isinstance(sub, ast.Call):
                hit = _classify_call(sub)
                if hit is not None:
                    self.events.append(_Event(
                        hit[0], sub.lineno, sub.col_offset, hit[1],
                        in_handler, guarded, looped,
                    ))
        # handoff stamping: rec["statePartitionsOwned"] = ...
        if isinstance(st, ast.Assign):
            for target in st.targets:
                if isinstance(target, ast.Subscript) and \
                        isinstance(target.slice, ast.Constant) and \
                        target.slice.value in _HANDOFF_KEYS:
                    self.events.append(_Event(
                        HANDOFF_PULL, st.lineno, st.col_offset,
                        f'["{target.slice.value}"]=',
                        in_handler, guarded, looped,
                    ))

    def _expr_events(self, expr: Optional[ast.AST], in_handler: bool,
                     guarded: bool, looped: bool) -> None:
        if expr is None:
            return
        for sub in ast.walk(expr):
            if isinstance(sub, ast.Call):
                hit = _classify_call(sub)
                if hit is not None:
                    self.events.append(_Event(
                        hit[0], sub.lineno, sub.col_offset, hit[1],
                        in_handler, guarded, looped,
                    ))

    def _stmts(self, body: List[ast.stmt], in_handler: bool,
               guarded: bool, looped: bool) -> None:
        for st in body:
            if isinstance(st, ast.Try):
                covers = guarded or _handler_has_requeue(st.handlers)
                self._stmts(st.body, in_handler, covers, looped)
                for h in st.handlers:
                    self._stmts(h.body, True, guarded, looped)
                self._stmts(st.orelse, in_handler, guarded, looped)
                self._stmts(st.finalbody, in_handler, guarded, looped)
            elif isinstance(st, ast.If):
                self._expr_events(st.test, in_handler, guarded, looped)
                self._stmts(st.body, in_handler, guarded, looped)
                self._stmts(st.orelse, in_handler, guarded, looped)
            elif isinstance(st, (ast.For, ast.AsyncFor)):
                self._expr_events(st.iter, in_handler, guarded, looped)
                self._stmts(st.body, in_handler, guarded, True)
                self._stmts(st.orelse, in_handler, guarded, looped)
            elif isinstance(st, ast.While):
                self._expr_events(st.test, in_handler, guarded, looped)
                self._stmts(st.body, in_handler, guarded, True)
                self._stmts(st.orelse, in_handler, guarded, looped)
            elif isinstance(st, (ast.With, ast.AsyncWith)):
                for item in st.items:
                    self._expr_events(
                        item.context_expr, in_handler, guarded, looped,
                    )
                self._stmts(st.body, in_handler, guarded, looped)
            elif isinstance(st, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested function (landing worker bodies): its own
                # entry point, analyzed with a fresh trace
                nested = _FnProto(
                    self.l, st, self.cls_name,
                    f"{self.method}.{st.name}",
                    _fn_markers(self.l.markers, st),
                )
                nested.run()
            elif isinstance(st, ast.ClassDef):
                pass
            else:
                self._leaf(st, in_handler, guarded, looped)

    # -- rule checking -------------------------------------------------
    def run(self) -> None:
        self._stmts(self.node.body, False, False, False)
        self.events.sort(key=lambda e: (e.line, e.col))
        self.l.effect_events += sum(
            1 for e in self.events if e.kind in EFFECT_KINDS
        )
        self._check()

    def _first(self, events: List[_Event], kind: str) -> Optional[_Event]:
        return next((e for e in events if e.kind == kind), None)

    def _check(self) -> None:
        main = [e for e in self.events if not e.in_handler]
        where = self._where()

        # DX900 (ordering half): ack before the pointer flip
        ack = self._first(main, FIFO_ACK)
        flip = self._first(main, POINTER_FLIP)
        if ack is not None and flip is not None and \
                (ack.line, ack.col) < (flip.line, flip.col):
            self.l.emit(
                "DX900", ack.line,
                f"{where} acks the upstream FIFO ({ack.detail}) before "
                f"the durable pointer flip ({flip.detail} at line "
                f"{flip.line}) — a crash between them loses the batch",
            )

        # DX900 (durability half): os.replace must be fenced by an
        # fsync of the tmp file before it and of the parent dir after
        for ev in self.events:
            if ev.detail != "os.replace":
                continue
            syncs = [e for e in self.events if e.detail == "os.fsync"]
            before = any(
                (e.line, e.col) < (ev.line, ev.col) for e in syncs
            )
            after = any(
                (e.line, e.col) > (ev.line, ev.col) for e in syncs
            )
            if not (before and after):
                missing = []
                if not before:
                    missing.append("tmp-file fsync before the rename")
                if not after:
                    missing.append("parent-dir fsync after it")
                self.l.emit(
                    "DX900", ev.line,
                    f"os.replace in {where} without "
                    f"{' and '.join(missing)} — a crash-then-power-"
                    f"loss can surface a zero-length checkpoint",
                )

        # DX901: pointer flip before the sink emit
        sink = self._first(main, SINK_EMIT)
        if sink is not None and flip is not None and \
                (flip.line, flip.col) < (sink.line, sink.col):
            self.l.emit(
                "DX901", flip.line,
                f"{where} flips the pointer ({flip.detail}) before the "
                f"sink emit ({sink.detail} at line {sink.line}) — "
                f"replay double-counts the committed rows",
            )

        # DX902: more than one main-path ack call site
        ack_sites = sorted({
            (e.line, e.col) for e in main if e.kind == FIFO_ACK
        })
        if len(ack_sites) > 1:
            self.l.emit(
                "DX902", ack_sites[1][0],
                f"{where} has {len(ack_sites)} ack call sites in one "
                f"batch path — a second ack releases a window the "
                f"failure path still expects to requeue",
            )

        # DX903/DX904 apply only to functions that ack a batch
        if ack is None:
            self._check_handoff(main, where)
            return

        handler_requeues = [
            e for e in self.events if e.in_handler and e.kind == REQUEUE
        ]
        if "requeue-upstream" in self.marks:
            # delegating ack wrapper: the caller owns the failure
            # handler, so the requeue-scope checks do not apply here
            self.l.requeue_upstream_sites += 1
            self._check_handoff(main, where)
            return
        if not handler_requeues:
            self.l.emit(
                "DX903", ack.line,
                f"{where} acks the upstream FIFO but no failure "
                f"handler requeues the unacked window (mark "
                f"`# dx-proto: requeue-upstream` if the caller owns "
                f"the handler)",
            )
            # with no requeue scope at all, DX904's outside-the-scope
            # placement checks have nothing to anchor to
            self._check_handoff(main, where)
            return
        if ack.looped and not any(e.looped for e in handler_requeues):
            self.l.emit(
                "DX903", handler_requeues[0].line,
                f"{where} acks every source but its failure handler "
                f"requeues only one — the requeue must cover the "
                f"whole unacked window",
            )

        last_ack_line = max(
            e.line for e in main if e.kind == FIFO_ACK
        )
        for ev in main:
            if ev.kind not in EFFECT_KINDS:
                continue
            if ev.kind == POINTER_FLIP and ev.line > last_ack_line:
                # a flip after the ack is DX900's finding (ordering),
                # not an undeclared post-commit effect
                continue
            if ev.line <= last_ack_line:
                if not ev.guarded:
                    self.l.emit(
                        "DX904", ev.line,
                        f"pre-ack effect {ev.detail} in {where} sits "
                        f"outside any try whose handler requeues — a "
                        f"failure after it strands the batch half-"
                        f"applied with the window still acked-pending",
                    )
            elif self.l.line_marked(ev.line, "post-commit"):
                self.l.post_commit_sites += 1
            else:
                self.l.emit(
                    "DX904", ev.line,
                    f"post-ack effect {ev.detail} in {where} without a "
                    f"`# dx-proto: post-commit` marker — effects after "
                    f"the ack are at-least-once replay territory and "
                    f"must be declared",
                )
        self._check_handoff(main, where)

    def _check_handoff(self, main: List[_Event], where: str) -> None:
        # DX905: first successor dispatch before the handoff pull
        pull = self._first(main, HANDOFF_PULL)
        disp = self._first(main, DISPATCH)
        if pull is not None and disp is not None and \
                (disp.line, disp.col) < (pull.line, pull.col):
            self.l.emit(
                "DX905", disp.line,
                f"{where} dispatches a successor ({disp.detail}) "
                f"before pulling its owned-partition plan "
                f"({pull.detail} at line {pull.line}) — the replica "
                f"boots without its state assignment",
            )


class _ModuleLinter:
    """One engine module: parse, walk every class/function, emit."""

    def __init__(self, path: str, rel: str, src: str):
        self.path = path
        self.rel = rel
        self.lines = src.splitlines()
        self.tree = ast.parse(src, filename=path)
        self.markers: _Markers = _collect_markers(
            self.lines, self.tree, marker_re=_MARKER_RE,
        )
        self.diags: List[Diagnostic] = []
        self._seen: Set[Tuple[str, int]] = set()
        self.effect_events = 0
        self.post_commit_sites = 0
        self.requeue_upstream_sites = 0
        self.functions = 0

    def line_marked(self, line: int, kind: str) -> bool:
        return self.markers.line_has(line, kind)

    def emit(self, code: str, line: int, message: str) -> None:
        key = (code, line)
        if key in self._seen:
            return
        self._seen.add(key)
        self.diags.append(
            make(code, self.rel, message, Span(line=line))
        )

    def run(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                for item in node.body:
                    if isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        self._function(item, cls_name=node.name)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._function(node, cls_name="")

    def _function(self, node, cls_name: str) -> None:
        self.functions += 1
        fn = _FnProto(
            self, node, cls_name, node.name,
            _fn_markers(self.markers, node),
        )
        fn.run()


# ---------------------------------------------------------------------------
# reports
# ---------------------------------------------------------------------------
@dataclass
class ProtoModuleSummary:
    path: str       # package-relative, e.g. "runtime/host.py"
    functions: int
    events: int     # extracted effect events (EFFECT_KINDS members)

    def to_dict(self) -> dict:
        return {
            "path": self.path,
            "functions": self.functions,
            "events": self.events,
        }


@dataclass
class ProtoCheckReport:
    """The ``--protocol`` tier's result. Like the race tier, the
    analyzed subject is the ENGINE (plus the rescale handoff in
    ``serve/jobs.py``) — a clean report certifies the delivery
    protocol of the runtime any flow deploys onto."""

    flow: str
    modules: List[ProtoModuleSummary]
    diagnostics: List[Diagnostic]
    effect_events: int = 0
    post_commit_sites: int = 0
    requeue_upstream_sites: int = 0

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.is_error]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if not d.is_error]

    @property
    def ok(self) -> bool:
        return not self.errors

    def protocol_dict(self) -> dict:
        return {
            "flow": self.flow,
            "analyzedFiles": len(self.modules),
            "modules": [m.to_dict() for m in self.modules],
            "effectEvents": self.effect_events,
            "postCommitSites": self.post_commit_sites,
            "requeueUpstreamSites": self.requeue_upstream_sites,
        }

    def to_dict(self) -> dict:
        from .diagnostics import REPORT_SCHEMA_VERSION

        return {
            "schemaVersion": REPORT_SCHEMA_VERSION,
            "ok": self.ok,
            "errorCount": len(self.errors),
            "warningCount": len(self.warnings),
            "diagnostics": [d.to_dict() for d in self.diagnostics],
            "protocol": self.protocol_dict(),
        }


# the rescale handoff lives outside the engine packages proper — the
# protocol gate covers it too
PROTO_EXTRA_MODULES = (os.path.join("serve", "jobs.py"),)


def proto_module_paths() -> List[str]:
    """The engine packages plus the rescale-handoff module."""
    pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    out = list(engine_module_paths())
    for rel in PROTO_EXTRA_MODULES:
        out.append(os.path.join(pkg_root, rel))
    return sorted(out)


def analyze_proto_modules(
    paths: List[str], flow: str = "",
) -> ProtoCheckReport:
    """Run the DX90x pass over explicit module files (the self-lint /
    fixture entry point)."""
    modules: List[ProtoModuleSummary] = []
    diags: List[Diagnostic] = []
    effects = 0
    post_commit = 0
    requeue_upstream = 0
    for path in paths:
        with open(path, "r", encoding="utf-8") as f:
            src = f.read()
        lint = _ModuleLinter(path, _rel_path(path), src)
        lint.run()
        modules.append(ProtoModuleSummary(
            lint.rel, lint.functions, lint.effect_events,
        ))
        diags.extend(lint.diags)
        effects += lint.effect_events
        post_commit += lint.post_commit_sites
        requeue_upstream += lint.requeue_upstream_sites
    diags.sort(key=lambda d: (d.table, d.span.line, d.code))
    return ProtoCheckReport(
        flow=flow, modules=modules, diagnostics=diags,
        effect_events=effects, post_commit_sites=post_commit,
        requeue_upstream_sites=requeue_upstream,
    )


# engine analysis cache, keyed on module set + mtimes (same contract
# as the race tier: the subject is the engine source, not the flow)
_ENGINE_CACHE: Dict[tuple, ProtoCheckReport] = {}


def analyze_flow_protocol(flow: dict) -> ProtoCheckReport:
    """Protocol-tier analysis for a flow config. The analyzed subject
    is the engine the flow would deploy onto plus the rescale handoff
    — flow-independent except for the name the report is filed under,
    cached per engine-source state."""
    gui = flow.get("gui") if isinstance(flow.get("gui"), dict) else flow
    name = (gui or {}).get("name") or ""
    paths = proto_module_paths()
    key = tuple(
        (p, os.path.getmtime(p)) for p in paths
    )
    cached = _ENGINE_CACHE.get(key)
    if cached is None:
        _ENGINE_CACHE.clear()
        cached = analyze_proto_modules(paths)
        _ENGINE_CACHE[key] = cached
    return ProtoCheckReport(
        flow=name,
        modules=cached.modules,
        diagnostics=cached.diagnostics,
        effect_events=cached.effect_events,
        post_commit_sites=cached.post_commit_sites,
        requeue_upstream_sites=cached.requeue_upstream_sites,
    )
