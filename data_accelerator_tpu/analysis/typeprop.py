"""Type lattice + expression checker for the flow static analyzer.

A deliberately small lattice — ``numeric | string | bool | timestamp |
unknown`` — seeded from the flow's Spark-style input schemas (the same
JSON ``serve/schemainference.py`` emits) and propagated through each
statement's select list. ``unknown`` is the top element: anything the
checker cannot prove stays unknown and produces **no** diagnostics, so
the analyzer can never be more strict than the runtime compiler
(``compile/exprs.py``), only earlier.

The checker walks expressions once doing double duty: reference
resolution (pass 1 codes) and type propagation (pass 2), plus the
aggregation-context and device-tier checks that are per-expression
properties (DX020/DX041/DX042).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from ..compile.sqlparser import (
    BinOp,
    CaseWhen,
    Cast,
    Col,
    Expr,
    Func,
    InList,
    IsNull,
    LikeOp,
    Literal,
    Star,
    UnaryOp,
)

NUMERIC = "numeric"
STRING = "string"
BOOL = "bool"
TIMESTAMP = "timestamp"
UNKNOWN = "unknown"

# Spark schema field type -> lattice type
_SPARK_TYPES = {
    "long": NUMERIC, "int": NUMERIC, "integer": NUMERIC, "bigint": NUMERIC,
    "short": NUMERIC, "byte": NUMERIC, "double": NUMERIC, "float": NUMERIC,
    "decimal": NUMERIC,
    "boolean": BOOL,
    "string": STRING,
    "timestamp": TIMESTAMP, "date": TIMESTAMP,
}

# state-table DDL type -> lattice type ("deviceId long, peak double")
DDL_TYPES = dict(_SPARK_TYPES)


def schema_to_types(schema_json) -> Optional[Dict[str, str]]:
    """Spark ``{"type":"struct","fields":[...]}`` -> {column: lattice type}.

    Returns None when the schema is absent/unparseable — callers treat
    that as an *open* table (any column resolves, typed unknown).
    """
    if not schema_json:
        return None
    try:
        schema = (
            json.loads(schema_json) if isinstance(schema_json, str)
            else schema_json
        )
        fields = schema["fields"]
    except (ValueError, KeyError, TypeError):
        return None
    out: Dict[str, str] = {}
    for f in fields:
        try:
            t = f["type"]
            name = f["name"]
        except (KeyError, TypeError):
            return None
        out[name] = (
            _SPARK_TYPES.get(t, UNKNOWN) if isinstance(t, str) else UNKNOWN
        )
    return out


def ddl_to_types(ddl: str) -> Optional[Dict[str, str]]:
    """``"deviceId long, peak double"`` -> {column: lattice type}."""
    out: Dict[str, str] = {}
    for part in ddl.split(","):
        toks = part.split()
        if len(toks) < 2:
            return None
        out[toks[0].strip("`")] = DDL_TYPES.get(toks[1].lower(), UNKNOWN)
    return out


@dataclass
class TypeInfo:
    """Lattice type + whether the value is a computed (deferred) string."""

    type: str = UNKNOWN
    computed_string: bool = False


@dataclass
class TableScope:
    """One table's design-time shape. ``types=None`` = open table: its
    columns are unknowable (custom normalization snippet, unparseable
    upstream) so member lookups succeed with type unknown."""

    name: str
    types: Optional[Dict[str, str]] = None
    # output columns carry computed-string flags across views
    computed: frozenset = frozenset()

    @property
    def open(self) -> bool:
        return self.types is None

    def lookup(self, col: str) -> Optional[TypeInfo]:
        if self.types is None:
            return TypeInfo(UNKNOWN)
        if col in self.types:
            return TypeInfo(self.types[col], col in self.computed)
        return None


# ---------------------------------------------------------------------------
# Builtin function surface (compile/exprs.py) grouped by result type.
# Unknown-but-declared UDFs type as unknown; a name in neither set is a
# dangling reference (DX006).
# ---------------------------------------------------------------------------
from ..compile.exprs import AGGREGATE_FNS  # {"AVG","MIN","MAX","SUM","COUNT"}

_STRING_RESULT_FNS = {
    "UPPER", "UCASE", "LOWER", "LCASE", "TRIM", "LTRIM", "RTRIM", "REVERSE",
    "INITCAP", "SUBSTRING", "SUBSTR", "REPLACE", "TRANSLATE", "REPEAT",
    "LPAD", "RPAD", "SPLIT_PART", "REGEXP_EXTRACT", "REGEXP_REPLACE",
    "ELEMENT_AT", "FROM_UNIXTIME", "TO_DATE",
}
_NUMERIC_RESULT_FNS = {
    "LENGTH", "CHAR_LENGTH", "CHARACTER_LENGTH", "LEN", "INSTR", "LOCATE",
    "ASCII", "UNIX_TIMESTAMP", "TO_UNIX_TIMESTAMP", "HOUR", "MINUTE",
    "SECOND", "YEAR", "MONTH", "DAY", "DAYOFMONTH", "DAYOFWEEK", "DATEDIFF",
    "POW", "POWER", "MOD", "SIGN", "ABS", "FLOOR", "CEIL", "ROUND", "SQRT",
    "EXP", "LOG", "LOG2", "LOG10",
}
_BOOL_RESULT_FNS = {"CONTAINS", "STARTSWITH", "STARTS_WITH", "ENDSWITH",
                    "ENDS_WITH"}
_TIMESTAMP_RESULT_FNS = {"CURRENT_TIMESTAMP", "DATE_TRUNC", "TO_TIMESTAMP",
                         "STRINGTOTIMESTAMP"}
_COMPOSITE_FNS = {"MAP", "STRUCT", "ARRAY", "FILTERNULL", "SPLIT",
                  "COALESCE", "IF", "GREATEST", "LEAST", "APPLYTEMPLATE"}
# string ops whose dictionary tables are keyed on a constant argument:
# {name: 1-based positions that must be literals}
_CONST_ARG_FNS = {
    "SUBSTRING": (2, 3), "SUBSTR": (2, 3), "REPLACE": (2, 3),
    "TRANSLATE": (2, 3), "INSTR": (2,), "CONTAINS": (2,),
    "STARTSWITH": (2,), "STARTS_WITH": (2,), "ENDSWITH": (2,),
    "ENDS_WITH": (2,), "REGEXP_EXTRACT": (2, 3), "REGEXP_REPLACE": (2, 3),
    "REPEAT": (2,), "LPAD": (2, 3), "RPAD": (2, 3), "SPLIT_PART": (2, 3),
    "LOCATE": (1, 3),
}
# string ops that gather through a per-distinct-string dictionary table
# and therefore reject computed (deferred) string inputs (DX042)
_DICT_TABLE_FNS = (
    _STRING_RESULT_FNS - {"ELEMENT_AT", "FROM_UNIXTIME", "TO_DATE"}
) | {"LENGTH", "CHAR_LENGTH", "CHARACTER_LENGTH", "LEN", "INSTR", "LOCATE",
     "ASCII"} | _BOOL_RESULT_FNS

BUILTIN_FNS = (
    AGGREGATE_FNS | _STRING_RESULT_FNS | _NUMERIC_RESULT_FNS
    | _BOOL_RESULT_FNS | _TIMESTAMP_RESULT_FNS | _COMPOSITE_FNS
    | {"CONCAT", "CONCAT_WS", "CAST"}
)

# comparison pairs that cannot both be right at design time (everything
# else is coercible or too close to call)
_INCOMPATIBLE = {
    frozenset((STRING, NUMERIC)), frozenset((STRING, BOOL)),
    frozenset((STRING, TIMESTAMP)), frozenset((BOOL, TIMESTAMP)),
    frozenset((BOOL, NUMERIC)),
}

_CMP_OPS = {"=", "!=", "<", "<=", ">", ">="}
_ARITH_OPS = {"+", "-", "*", "/", "%"}


def incompatible(a: str, b: str) -> bool:
    return frozenset((a, b)) in _INCOMPATIBLE


def _literal_type(lit: Literal) -> str:
    return {"int": NUMERIC, "float": NUMERIC, "str": STRING,
            "bool": BOOL, "null": UNKNOWN}[lit.kind]


_CAST_NUMERIC = {"LONG", "INT", "INTEGER", "BIGINT", "DOUBLE", "FLOAT"}


@dataclass
class SelectScope:
    """FROM/JOIN bindings of one statement: binding -> TableScope."""

    bindings: List[Tuple[str, TableScope]] = field(default_factory=list)

    def add(self, binding: str, table: TableScope) -> None:
        self.bindings.append((binding, table))

    @property
    def any_open(self) -> bool:
        return any(t.open for _, t in self.bindings)

    def resolve(self, parts: Tuple[str, ...]) -> Tuple[Optional[TypeInfo], bool]:
        """Resolve a (possibly qualified / struct-pathed) column.

        Returns (info, definite_miss): info is None when unresolved;
        definite_miss is True only when every candidate table is closed,
        so the miss is reportable without false-positive risk.
        """
        # table-qualified: first part names a binding
        if len(parts) > 1:
            for b, t in self.bindings:
                if b == parts[0]:
                    info = t.lookup(parts[1])
                    return info, not t.open
        # bare (or struct path rooted at a column): search all bindings
        hits = []
        for _, t in self.bindings:
            info = t.lookup(parts[0])
            if info is not None:
                hits.append(info)
        if hits:
            # struct member access types as unknown beyond the root
            return (hits[0] if len(parts) == 1 else TypeInfo(UNKNOWN)), False
        return None, not self.any_open


class ExprChecker:
    """Single-walk resolver + typer for one statement.

    ``emit(code, message, col_offset)`` receives pass-1/2/3/5 findings;
    the caller owns span construction (statement line + offset).
    """

    def __init__(
        self,
        scope: SelectScope,
        udfs: frozenset,
        emit: Callable[[str, str], None],
    ):
        self.scope = scope
        self.udfs = udfs  # upper-cased declared UDF/UDAF names
        self.emit = emit

    # -- entry points ----------------------------------------------------
    def check(self, e: Expr, agg_allowed: bool) -> TypeInfo:
        return self._type(e, agg_allowed)

    # -- walk ------------------------------------------------------------
    def _type(self, e: Expr, agg: bool) -> TypeInfo:
        if isinstance(e, Literal):
            return TypeInfo(_literal_type(e))
        if isinstance(e, Star):
            return TypeInfo(UNKNOWN)
        if isinstance(e, Col):
            info, definite = self.scope.resolve(e.parts)
            if info is None:
                if definite:
                    self.emit("DX002", f"unknown column '{e.dotted}'")
                return TypeInfo(UNKNOWN)
            return info
        if isinstance(e, Cast):
            return self._cast(e, agg)
        if isinstance(e, Func):
            return self._func(e, agg)
        if isinstance(e, BinOp):
            return self._binop(e, agg)
        if isinstance(e, UnaryOp):
            inner = self._type(e.operand, agg)
            if e.op == "NOT":
                return TypeInfo(BOOL)
            return TypeInfo(inner.type if inner.type == NUMERIC else UNKNOWN)
        if isinstance(e, InList):
            item = self._type(e.expr, agg)
            for opt in e.options:
                t = self._type(opt, agg)
                if incompatible(item.type, t.type):
                    self.emit(
                        "DX010",
                        f"IN list item type {t.type} does not match "
                        f"{item.type} operand",
                    )
            return TypeInfo(BOOL)
        if isinstance(e, IsNull):
            self._type(e.expr, agg)
            return TypeInfo(BOOL)
        if isinstance(e, LikeOp):
            arg = self._type(e.expr, agg)
            if not (isinstance(e.pattern, Literal) and e.pattern.kind == "str"):
                self.emit(
                    "DX041",
                    "LIKE/RLIKE pattern must be a string literal — the "
                    "predicate compiles to a per-distinct-string dictionary "
                    "table keyed on the pattern",
                )
            else:
                self._type(e.pattern, agg)
            if arg.computed_string:
                self.emit(
                    "DX042",
                    "LIKE/RLIKE over a computed string (CONCAT/CAST result) "
                    "has no device tier",
                )
            return TypeInfo(BOOL)
        if isinstance(e, CaseWhen):
            out = TypeInfo(UNKNOWN)
            for cond, val in e.whens:
                self._type(cond, agg)
                out = self._type(val, agg)
            if e.otherwise is not None:
                out2 = self._type(e.otherwise, agg)
                if out.type == UNKNOWN:
                    out = out2
            return TypeInfo(out.type, out.computed_string)
        return TypeInfo(UNKNOWN)

    def _cast(self, e: Cast, agg: bool) -> TypeInfo:
        inner = self._type(e.expr, agg)
        target = e.target
        if isinstance(e.expr, Literal) and e.expr.kind == "str" \
                and target in _CAST_NUMERIC:
            try:
                float(e.expr.value)
            except (TypeError, ValueError):
                self.emit(
                    "DX012",
                    f"CAST('{e.expr.value}' AS {target}) cannot convert",
                )
        if target in ("STRING", "VARCHAR"):
            # stringifying a non-string is a deferred host computation
            return TypeInfo(STRING, computed_string=inner.type != STRING)
        if target in _CAST_NUMERIC:
            return TypeInfo(NUMERIC)
        if target == "BOOLEAN":
            return TypeInfo(BOOL)
        if target == "TIMESTAMP":
            return TypeInfo(TIMESTAMP)
        return TypeInfo(UNKNOWN)

    def _binop(self, e: BinOp, agg: bool) -> TypeInfo:
        lt = self._type(e.left, agg)
        rt = self._type(e.right, agg)
        if e.op in ("AND", "OR"):
            return TypeInfo(BOOL)
        if e.op in _CMP_OPS:
            if incompatible(lt.type, rt.type):
                self.emit(
                    "DX010",
                    f"comparing {lt.type} {e.op} {rt.type}",
                )
            return TypeInfo(BOOL)
        if e.op in _ARITH_OPS:
            for side in (lt, rt):
                if side.type in (STRING, BOOL):
                    self.emit(
                        "DX010",
                        f"arithmetic '{e.op}' over a {side.type} operand",
                    )
            return TypeInfo(NUMERIC)
        return TypeInfo(UNKNOWN)

    def _func(self, e: Func, agg: bool) -> TypeInfo:
        name = e.name
        if name in AGGREGATE_FNS:
            if not agg:
                self.emit(
                    "DX020",
                    f"aggregate {name}() outside an aggregation context",
                )
            arg_t = TypeInfo(NUMERIC)
            # aggregate args are themselves scalar context
            for a in e.args:
                if not isinstance(a, Star):
                    arg_t = self._type(a, False)
            if name in ("MIN", "MAX"):
                return TypeInfo(arg_t.type)
            return TypeInfo(NUMERIC)

        # constant-argument positions (dictionary-table keyed)
        const_pos = _CONST_ARG_FNS.get(name, ())
        arg_infos: List[TypeInfo] = []
        for i, a in enumerate(e.args, start=1):
            info = self._type(a, agg)
            arg_infos.append(info)
            if i in const_pos and not isinstance(a, Literal):
                self.emit(
                    "DX041",
                    f"{name} argument {i} must be a literal — the string "
                    "table is keyed on it",
                )
        if name in _DICT_TABLE_FNS and arg_infos \
                and arg_infos[0].computed_string:
            self.emit(
                "DX042",
                f"{name} over a computed string (CONCAT/CAST result) has "
                "no device tier",
            )

        if name in ("CONCAT", "CONCAT_WS"):
            return TypeInfo(STRING, computed_string=True)
        if name in _STRING_RESULT_FNS:
            return TypeInfo(STRING)
        if name in _NUMERIC_RESULT_FNS:
            return TypeInfo(NUMERIC)
        if name in _BOOL_RESULT_FNS:
            return TypeInfo(BOOL)
        if name in _TIMESTAMP_RESULT_FNS:
            return TypeInfo(TIMESTAMP)
        if name in _COMPOSITE_FNS:
            if name == "IF" and len(e.args) == 3:
                return TypeInfo(arg_infos[1].type if len(arg_infos) > 1
                                else UNKNOWN)
            if name in ("COALESCE", "GREATEST", "LEAST") and arg_infos:
                return TypeInfo(arg_infos[0].type)
            return TypeInfo(UNKNOWN)
        if name in self.udfs:
            return TypeInfo(UNKNOWN)
        self.emit(
            "DX006",
            f"unknown function {name}() — not an engine builtin and not "
            "declared under gui.process.functions",
        )
        return TypeInfo(UNKNOWN)
