"""Autopilot: the closed-loop runtime controller.

reference: the reference platform's control plane deploys and MONITORS
Spark jobs — AppInsights live metrics, scheduled probe scenarios — but
never *acts* on what it sees (SURVEY §1: operators watch dashboards
and retune ``maxRate``/executor counts by hand). ROADMAP item 5 asks
ours to *pilot* them: this module closes the loop from the existing
signal surface (windowed ``Pipeline_Stall_Ms``, landing backlog,
``HealthState`` stall EWMAs, alert rules, malformed-input counters) to
bounded runtime actuations.

Shape of the loop (one pass per evaluation window):

    signals ──snapshot──▶ decision table ──budget/cooldown──▶ actuators

- **Signals** (``SignalSnapshot``): read from the SAME live surfaces
  the dashboards and probes read — ``HealthState`` (the conf'd stall
  EWMA, so ``/readyz`` and the pilot agree on "stalled"), the
  MetricStore (landing backlog), host counters (poll saturation,
  malformed rate) and the ``AlertEngine`` firing set (rules carrying an
  ``action`` field share one vocabulary with the pilot).
- **Decision table** (``decide``): a pure, ordered rule list mapping a
  snapshot to intended actuations. Pure means the replay CLI
  (``python -m data_accelerator_tpu.pilot --replay``) can re-run it
  offline over a recorded flight-recorder JSONL byte-for-byte.
- **Budget + cooldown**: at most ``budget`` actuations are APPLIED per
  window, each actuator honors a per-kind cooldown, and a kind that
  just actuated one direction must wait out a doubled cooldown before
  reversing — the no-flap property the unit suite asserts under an
  oscillating synthetic signal.
- **Actuators** (typed ``Actuator`` interface): pipeline depth within
  ``[1, maxdepth]`` (the host drains the in-flight window down to the
  new depth in FIFO order, so commit/requeue invariants are untouched),
  source backpressure (the ``TokenBucket`` the ingestor consults), and
  replica scale-out/in (``ScaleActuator`` -> ``JobOperation.rescale``,
  so the fleet admission gate still vets every scale-up).

Every evaluation is a ``pilot/evaluate`` trace in the flight recorder;
every decision — applied or suppressed — is a ``pilot/decide`` child
span carrying the signal snapshot, the rule fired and the actuation
taken. ``Pilot_Actuations_Count`` / ``Pilot_Depth`` /
``Pilot_Backpressure_Tokens`` export the loop's state as registry
metric series.
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass, field, fields
from typing import Callable, Dict, List, Optional

from .backpressure import TokenBucket

logger = logging.getLogger(__name__)

# actuation kinds — also the vocabulary of the alert rules' optional
# ``action`` field (obs/alerts.py ACTIONS mirrors this tuple; a firing
# rule with an action is a standing vote for that actuation)
ACTION_KINDS = (
    "depth-down", "depth-up", "backpressure", "backpressure-release",
    "rescale-up", "rescale-down",
)

# kind -> the actuator family it belongs to (cooldowns are per family;
# the reverse map is what makes "depth-up right after depth-down" a
# flap the controller refuses)
_FAMILY = {
    "depth-down": "depth", "depth-up": "depth",
    "backpressure": "backpressure",
    "backpressure-release": "backpressure",
    "rescale-up": "rescale", "rescale-down": "rescale",
}


@dataclass
class PilotConfig:
    """Conf surface ``datax.job.process.pilot.*`` (designer
    ``jobPilot*`` knobs, generation stage S640)."""

    enabled: bool = True
    window_s: float = 5.0          # evaluation cadence
    cooldown_s: float = 15.0       # per-family min seconds between acts
    budget: int = 2                # max actuations applied per window
    min_depth: int = 1
    max_depth: int = 8
    stall_high_ms: float = 500.0   # smoothed stall above this: depth down
    stall_low_ms: float = 50.0     # below this the device has headroom
    backlog_high: float = 2.0      # pending landings >= this: backpressure
    saturation_high: float = 0.8   # full-poll fraction above this: scale out
    lag_high_ms: float = 30_000.0  # source watermark lag: scale out
    malformed_high: float = 0.3    # malformed/total row ratio: backpressure
    max_replicas: int = 4
    min_poll_fraction: float = 0.125

    @classmethod
    def from_setting_dictionary(cls, sub) -> "PilotConfig":
        """Build from the ``datax.job.process.pilot.`` sub-dictionary
        (conf keys are the lowercase field names without underscores,
        matching the flat-conf convention: ``windowseconds``,
        ``cooldownseconds``, ``budget``, ``maxdepth``, ...)."""
        def f(key, default):
            v = sub.get(key)
            return float(v) if v not in (None, "") else default

        def i(key, default):
            v = sub.get(key)
            return int(v) if v not in (None, "") else default

        return cls(
            enabled=(sub.get_or_else("enabled", "true") or "").lower()
            != "false",
            window_s=f("windowseconds", cls.window_s),
            cooldown_s=f("cooldownseconds", cls.cooldown_s),
            budget=i("budget", cls.budget),
            min_depth=i("mindepth", cls.min_depth),
            max_depth=i("maxdepth", cls.max_depth),
            stall_high_ms=f("stallhighms", cls.stall_high_ms),
            stall_low_ms=f("stalllowms", cls.stall_low_ms),
            backlog_high=f("backloghigh", cls.backlog_high),
            saturation_high=f("saturationhigh", cls.saturation_high),
            lag_high_ms=f("laghighms", cls.lag_high_ms),
            malformed_high=f("malformedhigh", cls.malformed_high),
            max_replicas=i("maxreplicas", cls.max_replicas),
            min_poll_fraction=f("minpollfraction", cls.min_poll_fraction),
        )


@dataclass
class SignalSnapshot:
    """One evaluation window's observed state — everything ``decide``
    is allowed to look at, and exactly what the ``pilot/decide`` span
    records (so the replay CLI sees what the live controller saw)."""

    now: float = 0.0
    stall_ms: float = 0.0           # HealthState smoothed stall EWMA
    backlog: float = 0.0            # pending background landings
    source_lag_ms: float = 0.0      # wall clock - event-time watermark
    saturation: float = 0.0         # fraction of polls that came back full
    malformed_ratio: float = 0.0    # malformed/total rows this window
    depth: int = 1                  # live pipeline depth
    tokens: float = 0.0             # backpressure bucket balance
    rate_fraction: float = 1.0      # bucket refill rate / base rate
    replicas: int = 1
    batches: int = 0                # batches finished in the window
    alert_actions: tuple = ()       # actions requested by firing rules

    def to_props(self) -> Dict[str, object]:
        out = {}
        for fld in fields(self):
            v = getattr(self, fld.name)
            out[fld.name] = (
                round(v, 3) if isinstance(v, float) else
                list(v) if isinstance(v, tuple) else v
            )
        return out

    @classmethod
    def from_props(cls, props: Dict[str, object]) -> "SignalSnapshot":
        names = {f.name for f in fields(cls)}
        kw = {k: v for k, v in (props or {}).items() if k in names}
        if isinstance(kw.get("alert_actions"), list):
            kw["alert_actions"] = tuple(kw["alert_actions"])
        return cls(**kw)


@dataclass
class Decision:
    """One intended actuation: the rule that fired and its argument."""

    rule: str
    action: str          # one of ACTION_KINDS
    value: object = None  # target depth / replica count / rate factor
    applied: bool = False
    suppressed: Optional[str] = None  # "budget" | "cooldown" | "unactuated"


def decide(snap: SignalSnapshot, cfg: PilotConfig) -> List[Decision]:
    """The decision table: snapshot in, intended actuations out.

    Ordered by safety: load-shedding first (backpressure, depth down),
    recovery and scale-out after — the per-window budget then applies
    the most protective subset first. PURE: no clocks, no state — the
    same snapshot always yields the same decisions (the replay
    contract, and what makes the table unit-testable row by row).
    Hysteresis lives in the thresholds (``stall_high_ms`` ≫
    ``stall_low_ms``) and in the controller's cooldowns, not here.
    """
    out: List[Decision] = []
    alert_votes = set(snap.alert_actions or ())

    # 1. sink/landing pressure -> engage source backpressure
    if snap.backlog >= cfg.backlog_high or "backpressure" in alert_votes:
        out.append(Decision(
            rule=(
                "alert-requested-backpressure"
                if snap.backlog < cfg.backlog_high else
                "landing-backlog-backpressure"
            ),
            action="backpressure", value=0.5,
        ))

    # 2. malformed-input flood -> shrink polls (don't burn batch
    # capacity decoding garbage at full rate)
    if snap.malformed_ratio >= cfg.malformed_high:
        out.append(Decision(
            rule="malformed-flood-backpressure",
            action="backpressure", value=0.5,
        ))

    # 3. sustained stall -> the window is saturated past the device;
    # shrink it (generalizes PR 5's EWMA sizing to the whole pipeline)
    if snap.stall_ms > cfg.stall_high_ms and snap.depth > cfg.min_depth:
        out.append(Decision(
            rule="stall-high-depth-down",
            action="depth-down", value=snap.depth - 1,
        ))

    # 4. drained and healthy -> release backpressure
    if (
        snap.rate_fraction < 1.0
        and snap.backlog <= 0
        and snap.malformed_ratio < cfg.malformed_high
        and snap.stall_ms < cfg.stall_high_ms
    ):
        out.append(Decision(
            rule="drained-backpressure-release",
            action="backpressure-release", value=2.0,
        ))

    # 5. ingest saturated with an idle device -> deepen the window for
    # more overlap before asking for more hardware
    if (
        snap.saturation >= cfg.saturation_high
        and snap.stall_ms < cfg.stall_low_ms
        and snap.backlog <= 0
        and snap.rate_fraction >= 1.0
        and snap.depth < cfg.max_depth
    ):
        out.append(Decision(
            rule="saturated-depth-up",
            action="depth-up", value=snap.depth + 1,
        ))

    # 6. sustained lag the pipeline can't absorb -> scale out (the
    # admission gate still vets the submit)
    if (
        (
            snap.source_lag_ms > cfg.lag_high_ms
            or (
                snap.saturation >= cfg.saturation_high
                and snap.depth >= cfg.max_depth
            )
            or "rescale-up" in alert_votes
        )
        and snap.replicas < cfg.max_replicas
        and snap.rate_fraction >= 1.0  # never scale while load-shedding
    ):
        out.append(Decision(
            rule="sustained-lag-rescale-up",
            action="rescale-up", value=snap.replicas + 1,
        ))

    # 7. lag drained with replicas to spare -> scale back in
    if (
        snap.replicas > 1
        and snap.source_lag_ms < cfg.lag_high_ms / 4.0
        and snap.saturation < cfg.saturation_high / 2.0
        and snap.backlog <= 0
    ):
        out.append(Decision(
            rule="lag-drained-rescale-down",
            action="rescale-down", value=snap.replicas - 1,
        ))
    return out


# ---------------------------------------------------------------------------
# Actuators
# ---------------------------------------------------------------------------
class Actuator:
    """Typed actuation surface: ``kinds`` names the ACTION_KINDS this
    actuator serves; ``apply`` performs one bounded change and returns
    True when anything actually changed (a no-op apply does not spend
    budget)."""

    kinds: tuple = ()
    name = "actuator"

    def apply(self, decision: Decision) -> bool:
        raise NotImplementedError


class DepthActuator(Actuator):
    """Pipeline depth within ``[min_depth, max_depth]``. The setter
    (``StreamingHost.request_depth``) only RECORDS the target; the
    dispatch loop applies it at the window boundary by draining the
    in-flight FIFO down to the new depth first, so strict-FIFO commit
    and whole-window requeue are untouched by a resize."""

    kinds = ("depth-down", "depth-up")
    name = "depth"

    def __init__(self, get_depth: Callable[[], int],
                 set_depth: Callable[[int], None],
                 min_depth: int = 1, max_depth: int = 8):
        self.get_depth = get_depth
        self.set_depth = set_depth
        self.min_depth = min_depth
        self.max_depth = max_depth

    def apply(self, decision: Decision) -> bool:
        target = max(self.min_depth, min(self.max_depth, int(decision.value)))
        if target == self.get_depth():
            return False
        self.set_depth(target)
        decision.value = target
        return True


class BackpressureActuator(Actuator):
    """Source admission through the ``TokenBucket`` the ingestor
    consults: ``backpressure`` halves the refill rate (floored),
    ``backpressure-release`` doubles it back toward base."""

    kinds = ("backpressure", "backpressure-release")
    name = "backpressure"

    def __init__(self, bucket: TokenBucket):
        self.bucket = bucket

    def apply(self, decision: Decision) -> bool:
        before = self.bucket.rate
        if decision.action == "backpressure":
            after = self.bucket.throttle(float(decision.value or 0.5))
        else:
            after = self.bucket.recover(float(decision.value or 2.0))
        decision.value = round(after / self.bucket.base_rate, 4)
        return after != before


class ScaleActuator(Actuator):
    """Replica scale-out/in through ``JobOperation.rescale`` — the
    SAME path the REST surface uses, so the fleet admission gate vets
    every scale-up and the ``PlacementReplanner`` refreshes placement
    after every change. A rejected scale-up (``FleetAdmissionError``)
    is a no-op here: the fleet said no, and retrying won't change it
    until capacity frees."""

    kinds = ("rescale-up", "rescale-down")
    name = "rescale"

    def __init__(self, job_ops, job_name: str, max_replicas: int = 4):
        self.job_ops = job_ops
        self.job_name = job_name
        self.max_replicas = max_replicas

    def apply(self, decision: Decision) -> bool:
        target = max(1, min(self.max_replicas, int(decision.value)))
        try:
            records = self.job_ops.rescale(self.job_name, target)
        except Exception as e:  # noqa: BLE001 — admission reject / client err
            logger.warning("pilot rescale to %d rejected: %s", target, e)
            decision.suppressed = f"rejected: {e}"
            return False
        decision.value = len(records)
        return True


# ---------------------------------------------------------------------------
# The controller
# ---------------------------------------------------------------------------
class PilotController:
    """One per host (or one per replayed trace). Call ``tick()`` from
    the batch loop; every ``window_s`` it snapshots signals, runs the
    decision table, applies the budget/cooldown-bounded subset through
    the actuators, traces everything, and exports the ``Pilot_*``
    series."""

    def __init__(
        self,
        config: PilotConfig,
        flow: str = "",
        health=None,
        store=None,
        alerts=None,
        tracer=None,
        metric_logger=None,
        bucket: Optional[TokenBucket] = None,
        actuators: Optional[List[Actuator]] = None,
        now_fn=time.time,
    ):
        self.config = config
        self.flow = flow
        self.health = health
        self.store = store
        self.alerts = alerts
        self.tracer = tracer
        self.metric_logger = metric_logger
        self.bucket = bucket
        self.now = now_fn
        self.actuators: Dict[str, Actuator] = {}
        for a in (actuators or []):
            for kind in a.kinds:
                self.actuators[kind] = a
        # window accounting
        self._last_eval: Optional[float] = None
        self._window_batches_base = 0
        # host-fed poll signals, smoothed per poll (EWMAs, like the
        # stall gauge — no window reset, so an evaluation can never
        # blind the next one to a sustained condition)
        self._saturation = 0.0
        self._malformed_ewma = 0.0
        # anti-flap state: family -> (last actuation time, last action)
        self._last_act: Dict[str, tuple] = {}
        # totals
        self.actuations_count = 0
        self.suppressed_count = 0
        self.decisions: List[Decision] = []  # last window's decisions
        self.replicas = 1
        self._depth_probe: Callable[[], int] = lambda: 1

    # -- construction ------------------------------------------------------
    @classmethod
    def from_conf(cls, dict_, host) -> Optional["PilotController"]:
        """Build from ``datax.job.process.pilot.*`` for a
        ``StreamingHost``; None when disabled. Default ON: every host
        runs piloted unless the conf (or designer ``jobPilot`` knob)
        says otherwise."""
        sub = dict_.get_sub_dictionary("datax.job.process.pilot.")
        cfg = PilotConfig.from_setting_dictionary(sub)
        if not cfg.enabled:
            return None
        bucket = TokenBucket(
            base_rate=max(1.0, host.max_rate / max(host.interval_s, 1e-3)),
            min_fraction=cfg.min_poll_fraction,
        )
        actuators: List[Actuator] = [
            DepthActuator(
                get_depth=host.live_depth,
                set_depth=host.request_depth,
                min_depth=cfg.min_depth,
                max_depth=cfg.max_depth,
            ),
            BackpressureActuator(bucket),
        ]
        pilot = cls(
            cfg,
            flow=dict_.get_job_name(),
            health=host.health,
            store=host.metric_logger.store,
            alerts=host.alerts,
            tracer=host.tracer,
            metric_logger=host.metric_logger,
            bucket=bucket,
            actuators=actuators,
        )
        pilot._depth_probe = host.live_depth
        return pilot

    # -- host feed ---------------------------------------------------------
    def admit_events(self, requested: int) -> int:
        """The ingestor's admission point. Pass-through until the pilot
        has actually engaged backpressure (rate below base) — an
        unpaced loop must never be starved by its own poll cadence —
        then the token bucket meters polls until release."""
        if self.bucket is None or not self.bucket.engaged:
            return requested
        return self.bucket.take(requested)

    # EWMA weight for the per-poll signals (matches the stall gauge's
    # posture: recent polls dominate, one poll can't flip a rule)
    POLL_EWMA_ALPHA = 0.3

    def observe_poll(self, requested: int, received: int,
                     malformed: int = 0) -> None:
        """Per-poll accounting from the host: how full polls come back
        (saturation — sustained full polls mean producers outpace us)
        and how much of the stream is garbage (malformed-flood
        signal). Both smoothed, never reset."""
        a = self.POLL_EWMA_ALPHA
        full = 1.0 if received >= requested > 0 else 0.0
        self._saturation = a * full + (1.0 - a) * self._saturation
        ratio = max(0, malformed) / max(1, received + max(0, malformed))
        self._malformed_ewma = a * ratio + (1.0 - a) * self._malformed_ewma

    # -- signals -----------------------------------------------------------
    def read_signals(self, now: Optional[float] = None) -> SignalSnapshot:
        now = self.now() if now is None else now
        stall = 0.0
        lag = 0.0
        batches = 0
        if self.health is not None:
            # the SAME smoothed gauge /readyz judges (conf'd EWMA
            # half-life observability.stallewmams) — controller and
            # readiness probe agree on "stalled" by construction
            stall = float(self.health.pipeline_stall_ms or 0.0)
            lag = float(self.health.source_lag_ms(now) or 0.0)
            batches = (
                self.health.batches_processed - self._window_batches_base
            )
        backlog = 0.0
        if self.store is not None:
            key = f"DATAX-{self.flow}:Transfer_Background_Pending"
            pts = self.store.points(
                key, (now - self.config.window_s) * 1000.0, now * 1000.0
            ) or self.store.points(key)
            vals = [
                float(p["val"]) for p in pts[-8:]
                if isinstance(p.get("val"), (int, float))
            ]
            if vals:
                backlog = max(vals)
        actions = ()
        if self.alerts is not None:
            actions = tuple(sorted({
                r.get("action") for r in self.alerts.rules
                if r.get("action")
                and any(
                    f["name"] == r["name"] for f in self.alerts.firing()
                )
            }))
        return SignalSnapshot(
            now=now,
            stall_ms=stall,
            backlog=backlog,
            source_lag_ms=lag,
            saturation=self._saturation,
            malformed_ratio=self._malformed_ewma,
            depth=int(self._depth_probe()),
            tokens=self.bucket.tokens() if self.bucket else 0.0,
            rate_fraction=(
                self.bucket.rate_fraction() if self.bucket else 1.0
            ),
            replicas=self.replicas,
            batches=batches,
            alert_actions=actions,
        )

    # -- the loop ----------------------------------------------------------
    def tick(self, now: Optional[float] = None,
             batch_time_ms: Optional[int] = None) -> Optional[List[Decision]]:
        """Call from the batch loop after every iteration; evaluates at
        most once per ``window_s``. Returns the window's decisions when
        an evaluation ran, else None."""
        now = self.now() if now is None else now
        if self._last_eval is None:
            # arm the first window — never actuate on a cold snapshot
            self._last_eval = now
            if self.health is not None:
                self._window_batches_base = self.health.batches_processed
            return None
        if now - self._last_eval < self.config.window_s:
            return None
        return self.evaluate(now, batch_time_ms=batch_time_ms)

    def evaluate(self, now: Optional[float] = None,
                 batch_time_ms: Optional[int] = None) -> List[Decision]:
        """One full pass: snapshot -> decide -> bound -> actuate ->
        trace -> export. Safe to call directly (tests, replay)."""
        now = self.now() if now is None else now
        snap = self.read_signals(now)
        decisions = self.apply(decide(snap, self.config), snap, now)
        self._last_eval = now
        if self.health is not None:
            self._window_batches_base = self.health.batches_processed
        self.decisions = decisions
        self._export(snap, batch_time_ms)
        return decisions

    def apply(self, decisions: List[Decision], snap: SignalSnapshot,
              now: float) -> List[Decision]:
        """Bound and actuate: per-window budget, per-family cooldown
        (doubled against direction flips), every outcome traced as a
        ``pilot/decide`` span whether applied or suppressed."""
        trace = None
        if self.tracer is not None:
            trace = self.tracer.begin("pilot/evaluate", **snap.to_props())
        applied = 0
        try:
            for d in decisions:
                family = _FAMILY.get(d.action, d.action)
                actuator = self.actuators.get(d.action)
                if actuator is None:
                    d.suppressed = "unactuated"
                elif applied >= self.config.budget:
                    d.suppressed = "budget"
                else:
                    last = self._last_act.get(family)
                    cooldown = self.config.cooldown_s
                    if last is not None:
                        last_t, last_action = last
                        if last_action != d.action:
                            cooldown *= 2.0  # direction flip: wait longer
                        if now - last_t < cooldown:
                            d.suppressed = "cooldown"
                    if d.suppressed is None:
                        if actuator.apply(d):
                            d.applied = True
                            applied += 1
                            self.actuations_count += 1
                            self._last_act[family] = (now, d.action)
                            if d.action.startswith("rescale") and isinstance(
                                d.value, int
                            ):
                                self.replicas = max(1, d.value)
                if not d.applied and d.suppressed is None:
                    d.suppressed = "noop"
                if d.suppressed in ("budget", "cooldown"):
                    self.suppressed_count += 1
                if trace is not None:
                    with trace.span(
                        "pilot/decide",
                        rule=d.rule, action=d.action, value=d.value,
                        applied=d.applied, suppressed=d.suppressed,
                        **snap.to_props(),
                    ):
                        pass
                logger.info(
                    "pilot %s: rule=%s action=%s value=%s%s",
                    "actuated" if d.applied else "held",
                    d.rule, d.action, d.value,
                    "" if d.applied else f" ({d.suppressed})",
                )
        finally:
            if trace is not None:
                trace.end(decisions=len(decisions), applied=applied)
        return decisions

    # -- export ------------------------------------------------------------
    def _export(self, snap: SignalSnapshot,
                batch_time_ms: Optional[int]) -> None:
        if self.metric_logger is None:
            return
        try:
            self.metric_logger.send_batch_metrics({
                "Pilot_Actuations_Count": float(self.actuations_count),
                "Pilot_Suppressed_Count": float(self.suppressed_count),
                "Pilot_Depth": float(snap.depth),
                "Pilot_Backpressure_Tokens": float(snap.tokens),
            }, batch_time_ms)
        except Exception:  # noqa: BLE001 — metrics must not fail the loop
            logger.exception("pilot metric export failed")

    # -- offline -----------------------------------------------------------
    def replay(self, snapshots: List[SignalSnapshot]) -> List[List[Decision]]:
        """Re-run the decision loop over recorded snapshots with the
        same budget/cooldown state machine but NO live actuators — the
        offline debugging story (``__main__ --replay``). Actuations
        that would have fired are marked applied."""
        out: List[List[Decision]] = []
        for snap in snapshots:
            decisions = decide(snap, self.config)
            now = snap.now
            applied = 0
            for d in decisions:
                family = _FAMILY.get(d.action, d.action)
                if applied >= self.config.budget:
                    d.suppressed = "budget"
                    continue
                last = self._last_act.get(family)
                cooldown = self.config.cooldown_s
                if last is not None:
                    if last[1] != d.action:
                        cooldown *= 2.0
                    if now - last[0] < cooldown:
                        d.suppressed = "cooldown"
                        continue
                d.applied = True
                applied += 1
                self.actuations_count += 1
                self._last_act[family] = (now, d.action)
            out.append(decisions)
        return out
