"""Source backpressure: the token bucket the ingestor consults.

reference: the reference platform throttles EventHub ingest with a
STATIC ``maxRate`` chosen at deploy time (EventHubStreamingFactory
.scala:43) and leans on operators to retune it when sinks fall behind
(SURVEY §1 "babysitting"); production stream processors instead carry
a dynamic admission limiter between source and pipeline (Spark's PID
RateEstimator, Kafka quota buckets — PAPERS.md). This module is that
limiter for the TPU runtime: a token bucket whose *refill rate* is the
pilot's actuation surface.

Mechanics: the bucket holds up to ``capacity`` event-tokens and refills
at ``rate`` tokens/second. While the rate sits at base the admission
point (``PilotController.admit_events``) passes polls through without
consulting the bucket — an unpaced loop must never be starved by its
own cadence; when the pilot ``throttle()``s, the refill rate halves
(floored at ``min_fraction`` of the base rate), stored tokens clamp
down with it, and every poll asks ``take(n)`` and receives
``min(n, floor(tokens))`` — polls shrink until the landing backlog
drains, at which point ``recover()`` doubles the rate back toward
base and admission goes pass-through again. The host's existing multiplicative
``_rate_scale`` loop keeps handling *interval overruns*; this bucket
handles *downstream pressure* (sink/landing lag), which overruns never
see because the landing thread hides them from the dispatch loop.

All methods are safe to call from the dispatch loop and the pilot's
evaluation concurrently (one lock, no blocking waits — a poll that
finds an empty bucket gets the floor grant, never sleeps).
"""

from __future__ import annotations

import threading
import time


class TokenBucket:
    """Event-admission token bucket with a pilot-adjustable refill rate.

    ``base_rate``: tokens/second at full health (normally the source's
    configured maxrate). ``capacity``: burst bound (defaults to two
    base-rate seconds so a paced poll is never starved at full rate).
    ``min_fraction``: the throttle floor — matches the host rate
    limiter's 1/8 floor so backpressure can squeeze polls hard without
    ever stopping the flow (a stopped flow can't observe recovery).
    """

    def __init__(
        self,
        base_rate: float,
        capacity: float | None = None,
        min_fraction: float = 0.125,
        now_fn=time.monotonic,
    ):
        if base_rate <= 0:
            raise ValueError(f"base_rate must be > 0, got {base_rate}")
        self.base_rate = float(base_rate)
        self.capacity = float(
            capacity if capacity is not None else 2.0 * base_rate
        )
        self.min_fraction = float(min_fraction)
        self.rate = self.base_rate
        self.now = now_fn
        self._tokens = self.capacity
        self._last_refill = self.now()
        self._lock = threading.Lock()

    # -- internals --------------------------------------------------------
    def _refill_locked(self) -> None:
        now = self.now()
        dt = max(0.0, now - self._last_refill)
        self._last_refill = now
        self._tokens = min(self.capacity, self._tokens + dt * self.rate)

    # -- the ingestor's side ----------------------------------------------
    def take(self, n: int) -> int:
        """Grant up to ``n`` event-tokens (at least 1 — the flow must
        keep moving to observe the drain that ends the throttle)."""
        if n <= 0:
            return 0
        with self._lock:
            self._refill_locked()
            grant = int(min(float(n), self._tokens))
            grant = max(1, grant)
            self._tokens = max(0.0, self._tokens - grant)
            return grant

    # -- the pilot's side -------------------------------------------------
    def throttle(self, factor: float = 0.5) -> float:
        """Shrink the refill rate (and clamp stored tokens down so the
        squeeze takes effect on the very next poll, not a burst later);
        returns the new rate."""
        with self._lock:
            self._refill_locked()
            floor = self.base_rate * self.min_fraction
            self.rate = max(floor, self.rate * factor)
            self._tokens = min(self._tokens, self.rate)
            return self.rate

    def recover(self, factor: float = 2.0) -> float:
        """Grow the refill rate back toward base; returns the new rate."""
        with self._lock:
            self._refill_locked()
            self.rate = min(self.base_rate, self.rate * factor)
            return self.rate

    # -- observability ----------------------------------------------------
    def tokens(self) -> float:
        """Current token balance (the ``Pilot_Backpressure_Tokens``
        gauge)."""
        with self._lock:
            self._refill_locked()
            return self._tokens

    def rate_fraction(self) -> float:
        """Refill rate as a fraction of base — 1.0 means no
        backpressure engaged."""
        with self._lock:
            return self.rate / self.base_rate

    @property
    def engaged(self) -> bool:
        with self._lock:
            return self.rate < self.base_rate
