"""Chaos fault injectors for the runtime's recovery + pilot proofs.

reference: the reference platform's only standing fault drill is the
scheduled probe scenario suite (Services/JobRunner re-running
SaveAndDeploy against production); faults themselves — preempted
cluster jobs, throttled sinks, poisoned streams — were discovered in
production and handled by operators (SURVEY §1). This module packages
those faults as first-class injectors so the scenario suite
(serve/scenarios.py ``chaos_*``) and tier-1 tests can assert BOTH
invariants ROADMAP item 5 demands:

- **baseline survives**: with the pilot disabled, every fault ends in
  checkpointed exactly-once-per-window recovery (the fsync'd
  checkpointers + whole-window requeue machinery from PRs 4-5/8);
- **pilot reacts**: with the pilot enabled, the fault's signal drives
  the expected actuation (depth drops under stall, backpressure
  engages under sink outage / malformed flood, replicas scale under
  sustained lag) and every actuation lands as a ``pilot/decide`` span.

Injectors arm against a live ``StreamingHost`` (wrapping one seam
each) and restore it on ``disarm()``; payload helpers synthesize the
skewed / malformed event streams. Nothing here imports test
frameworks — the injectors are runtime objects a production drill
could arm too.
"""

from __future__ import annotations

import json
import random
import time
from typing import List, Optional


class ChaosFault(RuntimeError):
    """Raised by injectors that kill work mid-flight (the preemption
    SIGKILL stand-in) — distinguishable from real engine errors."""


class Injector:
    """One fault, armed against one host. ``arm`` wraps the target
    seam; ``disarm`` restores it (idempotent)."""

    name = "injector"

    def arm(self, host) -> None:
        raise NotImplementedError

    def disarm(self) -> None:
        raise NotImplementedError


class PreemptionInjector(Injector):
    """Kill the job mid-window: the Nth dispatch raises ``ChaosFault``
    with earlier batches still in flight — the in-process analog of a
    TPU-VM preemption / k8s node drain SIGKILLing the host while the
    window holds un-acked batches. Recovery = a fresh host over the
    same checkpoint dir + requeued source."""

    name = "preemption"

    def __init__(self, kill_at_dispatch: int = 3):
        self.kill_at_dispatch = kill_at_dispatch
        self._host = None
        self._real = None
        self.dispatches = 0

    def arm(self, host) -> None:
        self._host = host
        self._real = host.processor.dispatch_batch

        def dispatch(*a, **kw):
            self.dispatches += 1
            if self.dispatches == self.kill_at_dispatch:
                raise ChaosFault(
                    f"preempted at dispatch {self.dispatches}"
                )
            return self._real(*a, **kw)

        host.processor.dispatch_batch = dispatch

    def disarm(self) -> None:
        if self._host is not None and self._real is not None:
            self._host.processor.dispatch_batch = self._real
        self._host = None


class SinkOutageInjector(Injector):
    """Sink outage in two severities: ``fail=True`` makes every write
    raise (hard outage — proves whole-window requeue); ``delay_s``
    makes writes slow (brown-out — landings queue behind the dispatch
    loop, the ``Transfer_Background_Pending`` signal the pilot turns
    into backpressure). Wraps every sink of every output operator."""

    name = "sink-outage"

    def __init__(self, fail: bool = False, delay_s: float = 0.0):
        self.fail = fail
        self.delay_s = delay_s
        self.writes = 0
        self._restores: List = []

    def arm(self, host) -> None:
        for op in host.dispatcher.operators.values():
            for i, sink in enumerate(list(op.sinks)):
                self._restores.append((op, i, sink))
                op.sinks[i] = _WrappedSink(self, sink)

    def disarm(self) -> None:
        for op, i, sink in self._restores:
            op.sinks[i] = sink
        self._restores = []


class _WrappedSink:
    def __init__(self, injector: SinkOutageInjector, inner):
        self._injector = injector
        self._inner = inner
        self.kind = getattr(inner, "kind", "wrapped")

    def write(self, dataset, rows, batch_time_ms):
        self._injector.writes += 1
        if self._injector.fail:
            raise ChaosFault("sink outage")
        if self._injector.delay_s:
            time.sleep(self._injector.delay_s)
        return self._inner.write(dataset, rows, batch_time_ms)

    def __getattr__(self, name):
        return getattr(self._inner, name)


class DeviceSlowdownInjector(Injector):
    """Device-step slowdown: every counts sync takes ``extra_s``
    longer — the signal shape of a hot-key-skewed batch (one giant
    group serializes the groupby scan) without needing a real hot
    group to saturate a CPU-sim device. Drives ``Pipeline_Stall_Ms``
    and the stall EWMA, the pilot's depth-down signal."""

    name = "device-slowdown"

    def __init__(self, extra_s: float = 0.05):
        self.extra_s = extra_s
        self._host = None
        self._real = None

    def arm(self, host) -> None:
        self._host = host
        self._real = host.processor.dispatch_batch
        extra = self.extra_s

        def dispatch(*a, **kw):
            handle = self._real(*a, **kw)
            inner_counts = handle.collect_counts

            def slow_counts(*ca, **ckw):
                time.sleep(extra)
                return inner_counts(*ca, **ckw)

            handle.collect_counts = slow_counts
            return handle

        host.processor.dispatch_batch = dispatch

    def disarm(self) -> None:
        if self._host is not None and self._real is not None:
            self._host.processor.dispatch_batch = self._real
        self._host = None


class PartitionLossInjector(Injector):
    """Corrupt a state-partition snapshot MID-HANDOFF: between a
    predecessor's stop and the successor's first load, the ACTIVE
    side's snapshot for one (or every) partition is truncated or
    replaced with garbage — the torn-write / lost-object failure a
    rescale can meet in the wild. The successor's loader must fall
    back to the STANDBY side (DX530, ``State_LoadFallback_Count``) —
    or load the partition empty when both sides are gone (DX531) — and
    at-least-once replay of the un-acked window re-aggregates what the
    standby was missing.

    Targets either the local partition layout (``location=`` — a state
    table's dir) or the shared objstore mirror (``store_url=`` — what a
    cross-host successor actually pulls). ``table`` selects the prefix
    (a state-table name, or ``__window__`` for ring snapshots);
    ``partition=None`` corrupts every partition that has a pointer."""

    name = "partition-loss"
    _GARBAGE = b"\x00\xffPK-not-an-npz\x00truncated"

    def __init__(self, location: Optional[str] = None,
                 store_url: Optional[str] = None,
                 table: str = "", partition: Optional[int] = None,
                 mode: str = "truncate", filename: str = "table.npz"):
        if (location is None) == (store_url is None):
            raise ValueError("exactly one of location/store_url required")
        self.location = location
        self.store_url = store_url
        self.table = table
        self.partition = partition
        self.mode = mode
        self.filename = filename
        self.corrupted: List[str] = []

    # the stop->successor gap has no live host; arm/disarm keep the
    # Injector seam contract for drills that hold one anyway
    def arm(self, host) -> None:
        self.corrupt()

    def disarm(self) -> None:
        pass

    def _payload(self, original: Optional[bytes]) -> bytes:
        if self.mode == "truncate" and original:
            return original[: max(1, len(original) // 3)]
        return self._GARBAGE

    def corrupt(self) -> List[str]:
        """Apply the corruption; returns the snapshot paths/keys hit."""
        import os

        self.corrupted = []
        if self.location is not None:
            from ..runtime.statepartition import LocalSnapshotStore

            store = LocalSnapshotStore(self.location)
            prefixes = (
                [f"p{self.partition:02d}"] if self.partition is not None
                else sorted(
                    d for d in os.listdir(self.location)
                    if d.startswith("p") and os.path.isdir(
                        os.path.join(self.location, d))
                )
            )
            for prefix in prefixes:
                side = store.get_pointer(prefix)
                if side is None:
                    continue
                path = os.path.join(self.location, prefix, side,
                                    self.filename)
                if not os.path.exists(path):
                    continue
                with open(path, "rb") as f:
                    original = f.read()
                with open(path, "wb") as f:
                    f.write(self._payload(original))
                self.corrupted.append(path)
            return self.corrupted

        from ..compile.aotcache import _parse_objstore_url
        from ..serve.objectstore import ObjectStoreClient

        endpoint, bucket, root = _parse_objstore_url(self.store_url)
        client = ObjectStoreClient(endpoint, bucket)
        base = f"{root}/{self.table}" if root else self.table
        parts = (
            [self.partition] if self.partition is not None
            else range(64)
        )
        for p in parts:
            pkey = f"{base}/p{int(p):02d}"
            pointer = client.get(f"{pkey}/pointer")
            if pointer is None:
                continue
            side = pointer.decode().strip()
            key = f"{pkey}/{side}/{self.filename}"
            original = client.get(key)
            if original is None:
                continue
            client.put(key, self._payload(original))
            self.corrupted.append(key)
        return self.corrupted


# ---------------------------------------------------------------------------
# Harness pieces the scenario suite (and tests) assert against
# ---------------------------------------------------------------------------
class RecordingSink:
    """Sink that records every successful write in arrival order — the
    exactly-once witness: after a chaos run, the recorded event ids
    must be each expected id exactly once, in FIFO batch order."""

    kind = "recording"

    def __init__(self):
        self.batches = []  # (batch_time_ms, [row dict, ...])

    def write(self, dataset, rows, batch_time_ms):
        self.batches.append((batch_time_ms, list(rows)))
        return len(rows)

    def values(self, field: str = "seq") -> List:
        return [r[field] for _t, rows in self.batches for r in rows]


class RecordingRescaler:
    """Stand-in ``JobOperation`` for in-process chaos drills: records
    every ``rescale`` call the pilot's ``ScaleActuator`` makes (there
    is no control plane inside a host-only scenario) and reports the
    requested replica set as live."""

    def __init__(self):
        self.calls: List[int] = []

    def rescale(self, job_name: str, replicas: int) -> List[dict]:
        self.calls.append(int(replicas))
        return [
            {"name": job_name if i == 0 else f"{job_name}-r{i + 1}"}
            for i in range(int(replicas))
        ]


# ---------------------------------------------------------------------------
# Payload synthesis
# ---------------------------------------------------------------------------
def skewed_events(
    n: int,
    hot_key: int = 0,
    hot_fraction: float = 0.9,
    n_keys: int = 8,
    seed: int = 7,
) -> List[dict]:
    """Hot-key-skewed stream: ``hot_fraction`` of events carry
    ``hot_key``, the rest spread over ``n_keys``. ``seq`` makes every
    event globally unique so exactly-once delivery stays assertable
    even with key collisions."""
    rng = random.Random(seed)
    out = []
    for i in range(n):
        k = hot_key if rng.random() < hot_fraction else rng.randrange(
            1, max(2, n_keys)
        )
        out.append({"k": k, "v": float(i), "seq": i})
    return out


def malformed_payload(
    rows: List[dict], flood_ratio: float = 0.5, seed: int = 11
) -> bytes:
    """Newline-delimited JSON with ``flood_ratio`` of the LINES
    replaced by garbage (truncated JSON, bare text, binary noise) —
    the malformed-input flood. Valid rows keep their relative order;
    the decoders skip garbage lines, so exactly-once applies to the
    valid subset."""
    rng = random.Random(seed)
    garbage = (
        b'{"k": 1, "v":',
        b"not json at all",
        b'{"k"}',
        b"\x00\xff\xfe binary noise",
        b'[1, 2, "unclosed',
    )
    lines = []
    n_bad = int(len(rows) * flood_ratio / max(1e-9, 1.0 - flood_ratio))
    bad_left = n_bad
    for r in rows:
        while bad_left > 0 and rng.random() < flood_ratio:
            lines.append(garbage[rng.randrange(len(garbage))])
            bad_left -= 1
        lines.append(json.dumps(r).encode())
    for _ in range(bad_left):
        lines.append(garbage[rng.randrange(len(garbage))])
    return b"\n".join(lines) + b"\n"


def feed_socket(source, payload: bytes, expect_events: Optional[int] = None,
                timeout_s: float = 5.0) -> None:
    """Push a raw payload into a ``SocketSource`` and wait until its
    buffer holds ``expect_events`` lines (malformed lines count — the
    source buffers lines, the decoder drops garbage later)."""
    import socket as _socket

    conn = _socket.create_connection(("127.0.0.1", source.port), timeout=5)
    conn.sendall(payload)
    conn.close()
    if expect_events is None:
        expect_events = payload.count(b"\n")
    deadline = time.time() + timeout_s
    while time.time() < deadline and len(source._buf) < expect_events:
        time.sleep(0.01)
    if len(source._buf) < expect_events:
        raise TimeoutError(
            f"socket source buffered {len(source._buf)}/{expect_events}"
        )
