"""Pilot replay CLI: re-run the decision loop offline over a recorded
flight-recorder JSONL.

    python -m data_accelerator_tpu.pilot --replay <tracefile> [--json]
        [--window S] [--cooldown S] [--budget N] [--max-depth N]

The debugging story for every pilot regression: the live controller
records a ``pilot/evaluate`` span per evaluation window whose
properties ARE the signal snapshot it acted on, so this CLI can replay
the exact decision table — same rules, same budget/cooldown state
machine, optionally different knobs — and print the actuations it
*would* have taken. Flags override ``PilotConfig`` fields, so "would a
30s cooldown have prevented that flap?" is one re-run, no cluster.

Recordings from a pilot-OFF run carry no ``pilot/evaluate`` spans; the
CLI then reconstructs coarse snapshots from the batch ``sync`` spans
(stall only) and says so — enough to ask "would the pilot have
reacted?", not enough to reproduce backpressure/rescale decisions.
"""

from __future__ import annotations

import gzip
import json
import sys
from typing import List, Optional

from .controller import Decision, PilotConfig, PilotController, SignalSnapshot

USAGE = __doc__.split("\n\n")[0] + "\n"


def _read_lines(path: str):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                continue  # the recorder mixes log lines in some setups


def load_snapshots(
    path: str, window_s: float = 5.0
) -> tuple:
    """(snapshots, source) — ``source`` is ``"recorded"`` when the
    trace carries ``pilot/evaluate`` spans, ``"reconstructed"`` when
    the snapshots were rebuilt from batch sync spans."""
    evaluates = []
    syncs = []
    for rec in _read_lines(path):
        if rec.get("type") != "span":
            continue
        if rec.get("name") == "pilot/evaluate":
            evaluates.append(rec)
        elif rec.get("name") == "sync":
            syncs.append(rec)
    if evaluates:
        evaluates.sort(key=lambda r: r.get("startTs") or 0)
        snaps = []
        for rec in evaluates:
            snap = SignalSnapshot.from_props(rec.get("properties") or {})
            if not snap.now:
                snap.now = float(rec.get("startTs") or 0.0)
            snaps.append(snap)
        return snaps, "recorded"
    # coarse reconstruction: bucket sync spans into evaluation windows,
    # EWMA their durations the way HealthState.record_stall does
    syncs.sort(key=lambda r: r.get("startTs") or 0)
    snaps: List[SignalSnapshot] = []
    if not syncs:
        return snaps, "reconstructed"
    alpha = 0.3
    ewma: Optional[float] = None
    window_start = float(syncs[0].get("startTs") or 0.0)
    batches = 0
    for rec in syncs:
        ts = float(rec.get("startTs") or 0.0)
        dur = float(rec.get("durationMs") or 0.0)
        ewma = dur if ewma is None else alpha * dur + (1 - alpha) * ewma
        batches += 1
        if ts - window_start >= window_s:
            snaps.append(SignalSnapshot(
                now=ts, stall_ms=ewma, batches=batches,
            ))
            window_start = ts
            batches = 0
    if batches:
        snaps.append(SignalSnapshot(
            now=float(syncs[-1].get("startTs") or 0.0),
            stall_ms=ewma or 0.0, batches=batches,
        ))
    return snaps, "reconstructed"


def _fmt_decision(d: Decision) -> str:
    mark = "ACTUATE" if d.applied else f"held({d.suppressed})"
    return f"{mark:18s} {d.rule:32s} {d.action:22s} -> {d.value}"


def main(argv: Optional[List[str]] = None) -> int:
    args = list(sys.argv[1:] if argv is None else argv)
    path = None
    as_json = False
    overrides = {}
    flag_fields = {
        "--window": ("window_s", float),
        "--cooldown": ("cooldown_s", float),
        "--budget": ("budget", int),
        "--max-depth": ("max_depth", int),
    }
    i = 0
    while i < len(args):
        a = args[i]
        if a == "--replay":
            i += 1
            if i >= len(args):
                sys.stderr.write(USAGE)
                return 2
            path = args[i]
        elif a == "--json":
            as_json = True
        elif a in flag_fields:
            i += 1
            if i >= len(args):
                sys.stderr.write(USAGE)
                return 2
            name, conv = flag_fields[a]
            try:
                overrides[name] = conv(args[i])
            except ValueError:
                sys.stderr.write(f"bad value for {a}: {args[i]}\n")
                return 2
        elif a.startswith("--"):
            sys.stderr.write(f"unknown flag {a}\n{USAGE}")
            return 2
        else:
            path = a
        i += 1
    if not path:
        sys.stderr.write(USAGE)
        return 2

    cfg = PilotConfig(**overrides) if overrides else PilotConfig()
    try:
        snaps, source = load_snapshots(path, window_s=cfg.window_s)
    except OSError as e:
        sys.stderr.write(f"cannot read {path}: {e}\n")
        return 1
    pilot = PilotController(cfg)
    rounds = pilot.replay(snaps)

    if as_json:
        print(json.dumps({
            "tracefile": path,
            "snapshots": source,
            "evaluations": [
                {
                    "now": s.now,
                    "signals": s.to_props(),
                    "decisions": [
                        {"rule": d.rule, "action": d.action,
                         "value": d.value, "applied": d.applied,
                         "suppressed": d.suppressed}
                        for d in ds
                    ],
                }
                for s, ds in zip(snaps, rounds)
            ],
            "actuations": pilot.actuations_count,
        }, indent=2, default=str))
        return 0

    print(f"replaying {len(snaps)} evaluation window(s) "
          f"({source} snapshots) from {path}")
    for snap, decisions in zip(snaps, rounds):
        print(
            f"\n@{snap.now:.3f} stall={snap.stall_ms:.1f}ms "
            f"backlog={snap.backlog:.0f} lag={snap.source_lag_ms:.0f}ms "
            f"sat={snap.saturation:.2f} bad={snap.malformed_ratio:.2f} "
            f"depth={snap.depth} rate={snap.rate_fraction:.2f} "
            f"replicas={snap.replicas}"
        )
        if not decisions:
            print("  steady — no rule fired")
        for d in decisions:
            print("  " + _fmt_decision(d))
    print(f"\n{pilot.actuations_count} actuation(s) would have been taken")
    return 0


if __name__ == "__main__":
    sys.exit(main())
