"""Autopilot: closed-loop runtime control (ROADMAP item 5).

The control plane used to deploy and *watch*; this package makes the
runtime *act*: ``controller`` maps the observability surface to bounded
actuations (pipeline depth, batch admission, replicas) once per
evaluation window, ``backpressure`` is the token bucket the ingestor
consults, and ``chaos`` packages the fault injectors the scenario
suite uses to prove recovery both with the pilot off (baseline
survives) and on (pilot reacts). ``python -m data_accelerator_tpu.pilot
--replay <trace>`` re-runs any recorded decision loop offline.
"""

from .backpressure import TokenBucket
from .controller import (
    ACTION_KINDS,
    Actuator,
    BackpressureActuator,
    Decision,
    DepthActuator,
    PilotConfig,
    PilotController,
    ScaleActuator,
    SignalSnapshot,
    decide,
)

__all__ = [
    "ACTION_KINDS",
    "Actuator",
    "BackpressureActuator",
    "Decision",
    "DepthActuator",
    "PilotConfig",
    "PilotController",
    "ScaleActuator",
    "SignalSnapshot",
    "TokenBucket",
    "decide",
]
