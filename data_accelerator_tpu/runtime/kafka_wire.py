"""Dependency-free Kafka wire-protocol consumer.

reference: input/KafkaStreamingFactory.scala:23-70 consumes Kafka (and
EventHub through its Kafka-compatible endpoint, :43-49, SASL PLAIN with
the connection string as password) via the Kafka client library. TPU
hosts run a minimal image with no Kafka client packages, so this module
speaks the actual Kafka binary protocol directly over sockets:

- Metadata v1        partition leaders per topic
- ListOffsets v1     earliest/latest start positions
- Fetch v4           record batches (message format v2, uncompressed)
- Produce v3         egress (KafkaSink / EventHub-over-Kafka output)
- SaslHandshake v0 + raw SASL PLAIN over TLS — the EventHub-compatible
  auth path (username ``$ConnectionString``, password the namespace
  connection string), exactly the setup the reference passes to its
  Kafka DStream for EventHub-over-Kafka.

Deliberately out of scope (documented exclusions):
- consumer groups / rebalancing: partitions are assigned manually from
  metadata — the framework's own OffsetCheckpointer is the source of
  resume positions, so broker-side group state adds nothing here;
  ``commit`` is therefore a no-op.
- compressed record batches: attributes with a codec raise with a
  pointer at broker-side ``compression.type=uncompressed`` (or a full
  client library when one is installed — ``KafkaSource`` prefers
  confluent/kafka-python and only falls back to this wire client).
- native AMQP 1.0: EventHub rides the Kafka-compatible endpoint above,
  the same transport choice the reference's production path makes.

The encoder half (requests + record batches) is shared by the wire
tests' in-process fake broker, which exercises this client over a real
TCP socket with genuine protocol bytes.
"""

from __future__ import annotations

import io
import logging
import socket
import ssl
import struct
import threading
import time
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_SASL_HANDSHAKE = 17

# v2 record-batch attribute codec ids (attributes & 0x07)
CODEC_NAMES = {1: "gzip", 2: "snappy", 3: "lz4", 4: "zstd"}


class UnsupportedCodecError(NotImplementedError):
    """A compressed record batch reached a decoder that does not ship a
    decompressor. Typed (and naming the codec) so ingest surfaces a
    configuration error instead of mis-parsing: set broker/topic
    ``compression.type=uncompressed`` or install
    confluent-kafka/kafka-python."""

    def __init__(self, codec: str):
        self.codec = codec
        super().__init__(
            f"compressed kafka record batches ({codec}) are not supported "
            "by the wire client; set broker/topic "
            "compression.type=uncompressed or install "
            "confluent-kafka/kafka-python"
        )


# ---------------------------------------------------------------------------
# primitive encoding (big-endian, non-flexible protocol versions)
# ---------------------------------------------------------------------------
def enc_i8(v):
    return struct.pack(">b", v)


def enc_i16(v):
    return struct.pack(">h", v)


def enc_i32(v):
    return struct.pack(">i", v)


def enc_i64(v):
    return struct.pack(">q", v)


def enc_str(s: Optional[str]) -> bytes:
    if s is None:
        return enc_i16(-1)
    b = s.encode("utf-8")
    return enc_i16(len(b)) + b


def enc_bytes(b: Optional[bytes]) -> bytes:
    if b is None:
        return enc_i32(-1)
    return enc_i32(len(b)) + b


def enc_array(items: List[bytes]) -> bytes:
    return enc_i32(len(items)) + b"".join(items)


def enc_varint(v: int) -> bytes:
    """Zigzag varint (record fields)."""
    z = (v << 1) ^ (v >> 63)
    out = bytearray()
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


class Reader:
    def __init__(self, data: bytes):
        self.b = io.BytesIO(data)

    def read(self, n: int) -> bytes:
        d = self.b.read(n)
        if len(d) != n:
            raise EOFError("truncated kafka frame")
        return d

    def i8(self):
        return struct.unpack(">b", self.read(1))[0]

    def i16(self):
        return struct.unpack(">h", self.read(2))[0]

    def i32(self):
        return struct.unpack(">i", self.read(4))[0]

    def i64(self):
        return struct.unpack(">q", self.read(8))[0]

    def u32(self):
        return struct.unpack(">I", self.read(4))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self.read(n).decode("utf-8")

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else self.read(n)

    def varint(self) -> int:
        shift = 0
        z = 0
        while True:
            b = self.read(1)[0]
            z |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        return (z >> 1) ^ -(z & 1)

    def remaining(self) -> int:
        cur = self.b.tell()
        end = self.b.seek(0, io.SEEK_END)
        self.b.seek(cur)
        return end - cur


# ---------------------------------------------------------------------------
# record batches (message format v2)
# ---------------------------------------------------------------------------
def encode_record_batch(
    base_offset: int, records: List[bytes], timestamp_ms: int = 0
) -> bytes:
    """Uncompressed v2 record batch (shared with the test fake broker
    and a future Kafka producer sink)."""
    recs = bytearray()
    for i, value in enumerate(records):
        body = bytearray()
        body += enc_i8(0)  # attributes
        body += enc_varint(0)  # timestampDelta
        body += enc_varint(i)  # offsetDelta
        body += enc_varint(-1)  # null key
        body += enc_varint(len(value))
        body += value
        body += enc_varint(0)  # no headers
        recs += enc_varint(len(body))
        recs += body
    # batch fields after the length slot
    tail = bytearray()
    tail += enc_i32(0)  # partitionLeaderEpoch
    tail += enc_i8(2)  # magic
    crc_body = bytearray()
    crc_body += enc_i16(0)  # attributes: no compression
    crc_body += enc_i32(len(records) - 1)  # lastOffsetDelta
    crc_body += enc_i64(timestamp_ms)  # firstTimestamp
    crc_body += enc_i64(timestamp_ms)  # maxTimestamp
    crc_body += enc_i64(-1)  # producerId
    crc_body += enc_i16(-1)  # producerEpoch
    crc_body += enc_i32(-1)  # baseSequence
    crc_body += enc_i32(len(records))
    crc_body += recs
    crc = _crc32c(bytes(crc_body))
    tail += struct.pack(">I", crc)
    tail += crc_body
    return enc_i64(base_offset) + enc_i32(len(tail)) + bytes(tail)


_CRC32C_TABLE = None


def _crc32c_python(data: bytes) -> int:
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        poly = 0x82F63B78
        table = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            table.append(c)
        _CRC32C_TABLE = table
    crc = 0xFFFFFFFF
    for b in data:
        crc = _CRC32C_TABLE[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _crc32c(data: bytes) -> int:
    """CRC-32C (Castagnoli), the batch checksum Kafka v2 uses. Shares
    the native decoder's slicing-by-8 implementation when the library
    is built (checksumming every fetched batch per-byte in Python would
    dwarf the decode it guards); pure-Python fallback otherwise."""
    try:
        from ..native import native_crc32c

        crc = native_crc32c(data)
        if crc is not None:
            return crc
    except Exception:  # noqa: BLE001 — checksum must never need native
        pass
    return _crc32c_python(data)


# v2 record-batch frame layout (byte offsets within one batch frame):
# baseOffset(8) batchLength(4) | partitionLeaderEpoch(4) magic(1)
# crc(4) attributes(2) lastOffsetDelta(4) firstTimestamp(8)
# maxTimestamp(8) producerId(8) producerEpoch(2) baseSequence(4)
# recordCount(4) records... — crc covers attributes onward.
_BATCH_HEADER = 61  # frame prefix through recordCount


def iter_batch_spans(data: bytes):
    """Yield one dict per COMPLETE v2 record batch in ``data`` —
    ``{start, end, base_offset, next_offset, record_count,
    attributes}`` — from the frame headers alone (no record decode, no
    CRC). The raw-ingest path uses this to split a fetch into
    record-budgeted deliveries and advance positions; a trailing
    partial batch (normal at the fetch-size boundary) is ignored."""
    pos = 0
    n = len(data)
    while n - pos >= _BATCH_HEADER:
        base_offset, batch_len = struct.unpack_from(">qi", data, pos)
        end = pos + 12 + batch_len
        if batch_len < 49 or end > n:
            break  # partial trailing batch
        magic = data[pos + 16]
        attributes = struct.unpack_from(">h", data, pos + 21)[0]
        last_offset_delta = struct.unpack_from(">i", data, pos + 23)[0]
        record_count = struct.unpack_from(">i", data, pos + 57)[0]
        yield {
            "start": pos,
            "end": end,
            "base_offset": base_offset,
            "next_offset": base_offset + last_offset_delta + 1,
            "record_count": record_count,
            "attributes": attributes,
            "magic": magic,
        }
        pos = end


def decode_record_batches(
    data: bytes,
    stats: Optional[Dict[str, int]] = None,
) -> Tuple[List[Tuple[int, int, bytes]], int]:
    """(records, next_offset) from a Fetch response's records bytes
    (possibly several concatenated batches; a trailing partial batch —
    normal at the fetch size boundary — is skipped).

    ``records``: (offset, timestamp_ms, value) per data record.
    ``next_offset``: one past the last offset COVERED by any complete
    batch, data or not (-1 when none) — the caller must advance its
    fetch position with this, not just the last data record, or a
    skipped control batch at the log tail would be refetched forever.

    Every batch's CRC-32C is verified before its fields are trusted: a
    corrupt batch (bit flip between broker and socket buffer, torn
    page in a test fixture) is SKIPPED and counted into
    ``stats["corrupt_batches"]`` instead of mis-parsed into garbage
    rows — and since its header can't be trusted either, the position
    advances only past its frame (base_offset + 1). Compressed batches
    raise the typed :class:`UnsupportedCodecError` naming the codec.
    """
    out: List[Tuple[int, int, bytes]] = []
    next_offset = -1
    for span in iter_batch_spans(data):
        base_offset = span["base_offset"]
        if span["magic"] != 2:
            logger.warning("skipping record batch magic=%d", span["magic"])
            continue
        attributes = span["attributes"]
        if attributes & 0x07:
            raise UnsupportedCodecError(
                CODEC_NAMES.get(attributes & 0x07, str(attributes & 0x07))
            )
        frame = data[span["start"]: span["end"]]
        crc_stored = struct.unpack_from(">I", frame, 17)[0]
        if _crc32c(frame[21:]) != crc_stored:
            if stats is not None:
                stats["corrupt_batches"] = (
                    stats.get("corrupt_batches", 0) + 1
                )
            logger.warning(
                "skipping corrupt record batch at offset %d (CRC-32C "
                "mismatch)", base_offset,
            )
            # the header past the CRC is untrusted: advance only past
            # the frame so a corrupt tail can't teleport the position
            next_offset = max(next_offset, base_offset + 1)
            continue
        next_offset = max(next_offset, span["next_offset"])
        if attributes & 0x20:
            # control batch (transaction commit/abort markers):
            # metadata, not data — skipped, but next_offset above
            # still advances past it
            continue
        try:
            body = Reader(frame[12:])
            body.i32()  # partitionLeaderEpoch
            body.i8()   # magic
            body.u32()  # crc (verified above)
            body.i16()  # attributes
            body.i32()  # lastOffsetDelta
            first_ts = body.i64()
            body.i64()  # maxTimestamp
            body.i64()  # producerId
            body.i16()  # producerEpoch
            body.i32()  # baseSequence
            n = body.i32()
            for _ in range(n):
                rec_len = body.varint()
                rec = Reader(body.read(rec_len))
                rec.i8()  # attributes
                ts_delta = rec.varint()
                off_delta = rec.varint()
                klen = rec.varint()
                if klen >= 0:
                    rec.read(klen)
                vlen = rec.varint()
                value = rec.read(vlen) if vlen >= 0 else b""
                out.append(
                    (base_offset + off_delta, first_ts + ts_delta, value)
                )
        except EOFError:
            break
    return out, next_offset


# ---------------------------------------------------------------------------
# the consumer
# ---------------------------------------------------------------------------
class WireMessage:
    """confluent-style message facade the KafkaSource consume loop uses."""

    __slots__ = ("_t", "_p", "_o", "_v")

    def __init__(self, topic, partition, offset, value):
        self._t, self._p, self._o, self._v = topic, partition, offset, value

    def topic(self):
        return self._t

    def partition(self):
        return self._p

    def offset(self):
        return self._o

    def value(self):
        return self._v

    def error(self):
        return None


class KafkaWireClient:
    """Shared transport + metadata layer: framing, SASL/TLS, broker
    connections, topic metadata. The consumer and producer build on it."""

    def __init__(
        self,
        brokers: str,
        topics: List[str],
        client_id: str = "dxtpu-wire",
        security: Optional[str] = None,  # None | ssl | sasl_ssl | sasl_plaintext
        username: Optional[str] = None,
        password: Optional[str] = None,
        timeout_s: float = 10.0,
    ):
        self.bootstrap = []
        for entry in brokers.split(","):
            entry = entry.strip()
            if not entry:
                continue
            host, sep, port = entry.rpartition(":")
            if sep and port.isdigit():
                self.bootstrap.append((host, int(port)))
            else:
                # port defaults to 9092 like the client libraries
                self.bootstrap.append((entry, 9092))
        if not self.bootstrap:
            raise ValueError(f"no kafka bootstrap brokers in {brokers!r}")
        self.topics = topics
        self.client_id = client_id
        self.security = (security or "").lower() or None
        self.username = username
        self.password = password
        self.timeout_s = timeout_s
        self._corr = 0
        self._socks: Dict[Tuple[str, int], socket.socket] = {}
        # (topic, partition) -> (leader host, port)
        self._leaders: Dict[Tuple[str, int], Tuple[str, int]] = {}
        self._lock = threading.Lock()
        self._meta_loaded = False

    # -- transport -------------------------------------------------------
    def _connect(self, host: str, port: int) -> socket.socket:
        key = (host, port)
        s = self._socks.get(key)
        if s is not None:
            return s
        raw = socket.create_connection((host, port), timeout=self.timeout_s)
        if self.security in ("ssl", "sasl_ssl"):
            ctx = ssl.create_default_context()
            raw = ctx.wrap_socket(raw, server_hostname=host)
        if self.security in ("sasl_ssl", "sasl_plaintext"):
            self._sasl_plain(raw)
        self._socks[key] = raw
        return raw

    def _send_frame(self, s: socket.socket, payload: bytes) -> None:
        s.sendall(enc_i32(len(payload)) + payload)

    def _recv_frame(self, s: socket.socket) -> bytes:
        hdr = self._recv_n(s, 4)
        (n,) = struct.unpack(">i", hdr)
        return self._recv_n(s, n)

    @staticmethod
    def _recv_n(s: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = s.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("kafka broker closed connection")
            buf += chunk
        return buf

    def _request(
        self, s: socket.socket, api_key: int, api_version: int, body: bytes
    ) -> Reader:
        self._corr += 1
        header = (
            enc_i16(api_key)
            + enc_i16(api_version)
            + enc_i32(self._corr)
            + enc_str(self.client_id)
        )
        self._send_frame(s, header + body)
        resp = Reader(self._recv_frame(s))
        corr = resp.i32()
        if corr != self._corr:
            raise IOError(
                f"kafka correlation mismatch: sent {self._corr}, got {corr}"
            )
        return resp

    def _sasl_plain(self, s: socket.socket) -> None:
        """SaslHandshake v0 then the raw PLAIN token — the
        EventHub-compatible auth exchange."""
        self._corr += 1
        header = (
            enc_i16(API_SASL_HANDSHAKE) + enc_i16(0)
            + enc_i32(self._corr) + enc_str(self.client_id)
        )
        self._send_frame(s, header + enc_str("PLAIN"))
        resp = Reader(self._recv_frame(s))
        resp.i32()  # correlation
        err = resp.i16()
        if err:
            raise IOError(f"SASL handshake rejected (error {err})")
        token = b"\0" + (self.username or "").encode() + b"\0" + (
            self.password or ""
        ).encode()
        self._send_frame(s, token)
        self._recv_frame(s)  # auth response (empty bytes on success)

    # -- metadata / offsets ----------------------------------------------
    def _refresh_metadata(self) -> None:
        last_err: Optional[Exception] = None
        for host, port in self.bootstrap:
            try:
                s = self._connect(host, port)
                body = enc_array([enc_str(t) for t in self.topics])
                r = self._request(s, API_METADATA, 1, body)
                brokers = {}
                for _ in range(r.i32()):
                    node = r.i32()
                    bhost = r.string()
                    bport = r.i32()
                    r.string()  # rack
                    brokers[node] = (bhost, bport)
                r.i32()  # controller id
                for _ in range(r.i32()):
                    terr = r.i16()
                    tname = r.string()
                    r.i8()  # is_internal
                    for _ in range(r.i32()):
                        r.i16()  # partition error
                        pidx = r.i32()
                        leader = r.i32()
                        for _ in range(r.i32()):
                            r.i32()  # replicas
                        for _ in range(r.i32()):
                            r.i32()  # isr
                        if terr == 0 and leader in brokers:
                            self._leaders[(tname, pidx)] = brokers[leader]
                self._meta_loaded = True
                return
            except Exception as e:  # noqa: BLE001 — try next bootstrap
                last_err = e
        raise ConnectionError(
            f"kafka metadata unavailable from {self.bootstrap}: {last_err}"
        )

    def _list_offset(self, topic: str, partition: int, ts: int = -2) -> int:
        """Earliest (-2) / latest (-1) offset for a partition."""
        host, port = self._leaders[(topic, partition)]
        s = self._connect(host, port)
        body = enc_i32(-1) + enc_array([
            enc_str(topic)
            + enc_array([enc_i32(partition) + enc_i64(ts)])
        ])
        r = self._request(s, API_LIST_OFFSETS, 1, body)
        # NOTE: v1 responses have NO throttle_time_ms (added in v2) —
        # the topics array count comes first
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                r.i64()  # timestamp
                offset = r.i64()
                if err:
                    raise IOError(f"ListOffsets error {err}")
                return offset
        raise IOError("empty ListOffsets response")

    def close(self) -> None:
        for s in self._socks.values():
            try:
                s.close()
            except OSError:
                pass
        self._socks.clear()


class WireKafkaConsumer(KafkaWireClient):
    """Manually-assigned consumer over the raw protocol.

    Surface matches what ``KafkaSource`` drives: ``poll(timeout)`` ->
    one message or None, ``seek(topic, partition, offset)``,
    ``commit(offsets)`` (no-op — resume positions live in the
    framework's OffsetCheckpointer), ``close()``.
    """

    def __init__(self, *args, fetch_max_bytes: int = 4 * 1024 * 1024,
                 **kwargs):
        super().__init__(*args, **kwargs)
        self.fetch_max_bytes = fetch_max_bytes
        self._positions: Dict[Tuple[str, int], int] = {}
        self._buffer: List[WireMessage] = []
        # ingest-side protocol counters (corrupt batches skipped by the
        # CRC check) — drained by KafkaSource.take_ingest_stats into
        # the processor's Input_*_Count metrics
        self.ingest_stats: Dict[str, int] = {}

    # -- consumer surface ------------------------------------------------
    def seek(self, topic: str, partition: int, offset: int) -> None:
        with self._lock:
            self._positions[(topic, partition)] = offset

    def commit(self, offsets) -> None:
        """No-op by design: resume positions are the framework's
        OffsetCheckpointer's job (group-less manual assignment)."""

    def poll(self, timeout: float = 0.05) -> Optional[WireMessage]:
        with self._lock:
            if self._buffer:
                return self._buffer.pop(0)
        try:
            self._fill(timeout)
        except NotImplementedError:
            raise
        except Exception as e:  # noqa: BLE001 — transient broker errors
            logger.warning("kafka wire poll failed: %s", e)
            self.close()  # close before dropping: no fd leak per episode
            self._meta_loaded = False
            return None
        with self._lock:
            return self._buffer.pop(0) if self._buffer else None

    def _fetch_pass(self, timeout: float):
        """One Fetch round over every assigned partition; yields
        (topic, partition, requested_pos, records_bytes) per partition
        with data. Shared by the decoded poll path (``_fill``) and the
        raw-ingest path (``fetch_raw``)."""
        if not self._meta_loaded:
            self._refresh_metadata()
        deadline = time.time() + max(timeout, 0.0)
        for (topic, partition), leader in sorted(self._leaders.items()):
            with self._lock:
                pos = self._positions.get((topic, partition))
            if pos is None:
                # list-offset is a network round trip — resolve it outside
                # the lock, then publish under it (seek() may race us)
                pos = self._list_offset(topic, partition, -2)
                with self._lock:
                    pos = self._positions.setdefault(
                        (topic, partition), pos
                    )
            s = self._connect(*leader)
            wait_ms = max(0, int((deadline - time.time()) * 1000))
            body = (
                enc_i32(-1)  # replica_id
                + enc_i32(wait_ms)
                + enc_i32(1)  # min_bytes
                + enc_i32(self.fetch_max_bytes)
                + enc_i8(0)  # isolation_level
                + enc_array([
                    enc_str(topic) + enc_array([
                        enc_i32(partition)
                        + enc_i64(pos)
                        + enc_i32(self.fetch_max_bytes)
                    ])
                ])
            )
            r = self._request(s, API_FETCH, 4, body)
            r.i32()  # throttle
            for _ in range(r.i32()):
                tname = r.string()
                for _ in range(r.i32()):
                    pidx = r.i32()
                    err = r.i16()
                    r.i64()  # high watermark
                    r.i64()  # last stable offset
                    for _ in range(r.i32()):  # aborted txns
                        r.i64()
                        r.i64()
                    records = r.bytes_() or b""
                    if err:
                        logger.warning(
                            "kafka fetch error %d on %s/%d", err, tname, pidx
                        )
                        continue
                    with self._lock:
                        cur = self._positions[(tname, pidx)]
                    yield tname, pidx, cur, records

    def _fill(self, timeout: float) -> None:
        for tname, pidx, pos, records in self._fetch_pass(timeout):
            recs, next_off = decode_record_batches(
                records, stats=self.ingest_stats
            )
            msgs = []
            for offset, _ts, value in recs:
                if offset < pos:
                    continue  # batch may start before request pos
                msgs.append(WireMessage(tname, pidx, offset, value))
            if msgs:
                with self._lock:
                    self._buffer.extend(msgs)
            # advance past EVERYTHING the fetch covered — including
            # skipped control batches, which would otherwise be
            # refetched in a hot loop forever
            pos_key = (tname, pidx)
            new_pos = max(
                next_off,
                (msgs[-1].offset() + 1) if msgs else -1,
            )
            with self._lock:
                if new_pos > self._positions[pos_key]:
                    self._positions[pos_key] = new_pos

    def fetch_raw(self, timeout: float = 0.05):
        """The binary fast path's fetch: one Fetch round returning RAW
        v2 record-batch bytes per partition —
        ``[(topic, partition, requested_pos, records_bytes,
        next_offset), ...]`` — with positions advanced from the frame
        headers alone (``iter_batch_spans``; no record decode, no
        Python object per record). Compressed batches surface as the
        typed error at DECODE time, and corrupt batches are skipped +
        counted there too — this layer only frames and advances.

        A batch may start before ``requested_pos`` (Kafka serves whole
        batches); the decoder will then re-emit rows below the position
        — duplicates, never loss (the at-least-once contract every
        source here honors)."""
        out = []
        for tname, pidx, pos, records in self._fetch_pass(timeout):
            next_off = -1
            for span in iter_batch_spans(records):
                next_off = max(next_off, span["next_offset"])
            if not records:
                continue
            out.append((tname, pidx, pos, records, next_off))
            pos_key = (tname, pidx)
            with self._lock:
                if next_off > self._positions[pos_key]:
                    self._positions[pos_key] = next_off
        return out


class WireKafkaProducer(KafkaWireClient):
    """Minimal producer over Produce v3 (acks=1, uncompressed v2 record
    batches) — the egress half of the wire client. This is what lets a
    flow SINK to Kafka (and EventHub via its Kafka endpoint — the
    reference's EventHubStreamPoster role) on hosts with no client
    library; batches round-robin across the topic's partitions."""

    def __init__(self, brokers: str, topic: str, acks: int = 1, **kwargs):
        super().__init__(brokers, [topic], **kwargs)
        self.topic = topic
        self.acks = acks
        self._rr = 0

    def send(self, values: List[bytes]) -> None:
        """Produce one record batch; raises on broker error so the
        caller's batch retry owns delivery (at-least-once)."""
        if not values:
            return
        if not self._meta_loaded:
            self._refresh_metadata()
        parts = sorted(
            p for (t, p) in self._leaders if t == self.topic
        )
        if not parts:
            raise IOError(f"kafka topic {self.topic!r} has no partitions")
        partition = parts[self._rr % len(parts)]
        self._rr += 1
        records = encode_record_batch(
            0, values, timestamp_ms=int(time.time() * 1000)
        )
        body = (
            enc_str(None)  # transactional_id
            + enc_i16(self.acks)
            + enc_i32(int(self.timeout_s * 1000))
            + enc_array([
                enc_str(self.topic) + enc_array([
                    enc_i32(partition) + enc_bytes(records)
                ])
            ])
        )
        s = self._connect(*self._leaders[(self.topic, partition)])
        try:
            r = self._request(s, API_PRODUCE, 3, body)
        except (OSError, ConnectionError):
            # stale leader/socket: refresh and propagate for batch retry
            self.close()
            self._meta_loaded = False
            raise
        for _ in range(r.i32()):
            r.string()  # topic
            for _ in range(r.i32()):
                r.i32()  # partition
                err = r.i16()
                r.i64()  # base offset
                r.i64()  # log append time
                if err:
                    # broker-level error (e.g. 6 NOT_LEADER_FOR_PARTITION
                    # after a leadership move): drop cached metadata so
                    # the caller's batch retry re-resolves leaders
                    # instead of re-hitting the stale one forever
                    self.close()
                    self._meta_loaded = False
                    raise IOError(f"kafka produce error {err}")
        # NOTE: Produce responses carry throttle_time_ms LAST (v1+)
        r.i32()
