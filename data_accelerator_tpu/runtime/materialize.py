"""Device table -> host rows: the sink/display boundary.

Decodes dictionary ids back to strings, restores absolute timestamps from
the batch base, renders deferred string templates (CONCAT et al.), and
folds flattened struct/array columns back into nested JSON values —
producing the same row JSON the reference's sinks serialize
(OutputManager.scala:103-126 to_json(struct(cols))).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..compile.exprs import WS_MARKER
from ..compile.planner import TableData, ViewSchema
from ..core.schema import StringDictionary


def _render_value(v, t: str, dictionary: StringDictionary, base_ms: int):
    if t == "string":
        return dictionary.decode(int(v))
    if t == "timestamp":
        return int(v) + base_ms
    if t == "tssec":
        return int(v) + base_ms // 1000
    if t == "boolean":
        return bool(v)
    if t == "double":
        return float(v)
    return int(v)


def materialize_rows(
    table: TableData,
    schema: ViewSchema,
    dictionary: StringDictionary,
    base_ms: int = 0,
    max_rows: Optional[int] = None,
) -> List[dict]:
    """Valid rows as JSON-ready dicts with nested structs re-assembled."""
    cols = {k: np.asarray(v) for k, v in table.cols.items()}
    valid = np.asarray(table.valid)
    idx = np.nonzero(valid)[0]
    if max_rows is not None:
        idx = idx[:max_rows]

    # organize flattened names into nesting groups
    device_cols = [
        c for c in schema.types if not c.startswith("__defer.")
    ]

    out: List[dict] = []
    for i in idx:
        row: dict = {}
        for c in device_cols:
            if c.endswith(".__valid"):
                continue
            v = _render_value(cols[c][i], schema.types[c], dictionary, base_ms)
            _bury(row, c, v)
        # deferred string templates. CONCAT: a NULL part nulls the
        # whole result (matching the device hash tier). CONCAT_WS
        # (WS_MARKER-tagged): null ARGUMENTS are skipped and the rest
        # join on the separator — both per Spark semantics.
        for name, parts in schema.deferred.items():
            ws_sep = None
            if parts and isinstance(parts[0], str) \
                    and parts[0].startswith(WS_MARKER):
                ws_sep = parts[0][len(WS_MARKER):]
                parts = parts[1:]
            pieces = []
            for p in parts:
                if isinstance(p, str):
                    pieces.append(p)
                    continue
                hidden, t = p
                rendered = _render_value(
                    cols[hidden][i], t, dictionary, base_ms
                )
                if rendered is None:
                    if ws_sep is not None:
                        continue  # concat_ws skips null arguments
                    pieces = None
                    break
                pieces.append(
                    f"{rendered:g}" if t == "double" else str(rendered)
                )
            if pieces is None:
                value = None
            elif ws_sep is not None:
                value = ws_sep.join(pieces)
            else:
                value = "".join(pieces)
            _bury(row, name, value)
        # array/struct validity: drop nulled-out branches
        row = _apply_validity(row, cols, schema, i)
        out.append(row)
    return out


def _apply_validity(row: dict, cols, schema: ViewSchema, i: int) -> dict:
    """Remove subtrees whose ``__valid`` flag is False; collapse arrays
    (numeric-keyed dicts) into lists of surviving elements."""
    valid_flags = {
        c[: -len(".__valid")]: bool(cols[c][i])
        for c in schema.types
        if c.endswith(".__valid")
    }

    def prune(obj, path: str):
        if not isinstance(obj, dict):
            return obj
        if path in valid_flags and not valid_flags[path]:
            return None
        keys = list(obj.keys())
        if keys and all(k.isdigit() for k in keys):
            items = []
            for k in sorted(keys, key=int):
                sub = prune(obj[k], f"{path}.{k}" if path else k)
                if sub is not None:
                    items.append(sub)
            return items
        out = {}
        for k in keys:
            sub = prune(obj[k], f"{path}.{k}" if path else k)
            if sub is not None or (f"{path}.{k}" if path else k) not in valid_flags:
                out[k] = sub
        return out

    return {k: prune(v, k) for k, v in row.items()}


def _bury(obj: dict, dotted: str, value) -> None:
    parts = dotted.split(".")
    cur = obj
    for p in parts[:-1]:
        cur = cur.setdefault(p, {})
    cur[parts[-1]] = value
