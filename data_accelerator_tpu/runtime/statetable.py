"""Accumulation (state) tables with A/B active-standby persistence.

reference: datax-host handler/StateTableHandler.scala:17-129 — each
``--DataXStates--`` table persists as two Parquet dirs A/B plus a
``metadata.info`` pointer naming the active one; a batch writes the new
state into the standby dir, flips the pointer in memory, and persist()
writes the pointer file after outputs succeed. Restart loads the dir the
pointer names — crash between write and persist leaves the old state
active (consistent with at-least-once replay).

Here a table snapshot is a ``.npz`` of column arrays + validity + a JSON
sidecar with types and the string-dictionary entries its ids reference.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..compile.planner import TableData, ViewSchema
from ..core.schema import StringDictionary


@dataclass
class StateTable:
    name: str
    schema: ViewSchema
    capacity: int
    location: str  # base dir holding A/, B/, metadata.info

    def __post_init__(self):
        os.makedirs(self.location, exist_ok=True)
        self._active = self._read_pointer() or "A"

    # -- pointer ---------------------------------------------------------
    @property
    def _pointer_path(self) -> str:
        return os.path.join(self.location, "metadata.info")

    def _read_pointer(self) -> Optional[str]:
        try:
            with open(self._pointer_path, "r", encoding="utf-8") as f:
                p = f.read().strip()
                return p if p in ("A", "B") else None
        except FileNotFoundError:
            return None

    @property
    def active(self) -> str:
        return self._active

    @property
    def standby(self) -> str:
        return "B" if self._active == "A" else "A"

    # -- load/store ------------------------------------------------------
    def _dir(self, which: str) -> str:
        return os.path.join(self.location, which)

    def load(self, dictionary: StringDictionary) -> TableData:
        """Load the active snapshot; empty table if none exists yet."""
        d = self._dir(self._active)
        npz_path = os.path.join(d, "table.npz")
        meta_path = os.path.join(d, "meta.json")
        if not (os.path.exists(npz_path) and os.path.exists(meta_path)):
            return self.empty()
        with open(meta_path, "r", encoding="utf-8") as f:
            meta = json.load(f)
        data = np.load(npz_path)
        # remap persisted dictionary ids into the live dictionary
        id_map = {int(k): dictionary.encode(v) for k, v in meta["strings"].items()}
        cols: Dict[str, jnp.ndarray] = {}
        for col, t in self.schema.types.items():
            arr = data[col]
            if t == "string" and id_map:
                lut_keys = np.array(list(id_map.keys()), dtype=np.int64)
                lut_vals = np.array(list(id_map.values()), dtype=np.int64)
                remap = np.zeros(int(lut_keys.max()) + 1, dtype=np.int32)
                remap[lut_keys] = lut_vals.astype(np.int32)
                arr = np.where(
                    (arr >= 0) & (arr < len(remap)), remap[np.clip(arr, 0, None)], 0
                ).astype(np.int32)
            cols[col] = jnp.asarray(arr)
        valid = jnp.asarray(data["__valid"])
        return TableData(cols, valid)

    def overwrite(self, table: TableData, dictionary: StringDictionary) -> None:
        """Write new state into the standby dir and flip in memory
        (StateTableHandler.scala:99-115)."""
        d = self._dir(self.standby)
        os.makedirs(d, exist_ok=True)
        cols = {k: np.asarray(v) for k, v in table.cols.items()}
        valid = np.asarray(table.valid)
        strings: Dict[str, str] = {}
        for col, t in self.schema.types.items():
            if t == "string":
                for sid in np.unique(cols[col][valid]):
                    s = dictionary.decode(int(sid))
                    if s is not None:
                        strings[str(int(sid))] = s
        np.savez(
            os.path.join(d, "table.npz"),
            __valid=valid,
            **{c: cols[c] for c in self.schema.types},
        )
        with open(os.path.join(d, "meta.json"), "w", encoding="utf-8") as f:
            json.dump({"types": self.schema.types, "strings": strings}, f)
        self._active = self.standby  # flip in memory; persist() commits

    def persist(self) -> None:
        """Commit the pointer after outputs succeed
        (StateTableHandler.scala:117-125)."""
        tmp = self._pointer_path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self._active)
        os.replace(tmp, self._pointer_path)

    def empty(self) -> TableData:
        cols = {
            c: jnp.zeros(
                (self.capacity,),
                dtype={"double": jnp.float32, "boolean": jnp.bool_}.get(t, jnp.int32),
            )
            for c, t in self.schema.types.items()
        }
        return TableData(cols, jnp.zeros((self.capacity,), dtype=jnp.bool_))
