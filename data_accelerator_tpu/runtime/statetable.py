"""Accumulation (state) tables on key-range partitions with A/B
active-standby persistence per partition.

reference: datax-host handler/StateTableHandler.scala:17-129 — each
``--DataXStates--`` table persists as two Parquet dirs A/B plus a
``metadata.info`` pointer naming the active one; a batch writes the new
state into the standby dir, flips the pointer in memory, and persist()
writes the pointer file after outputs succeed. Restart loads the dir the
pointer names — crash between write and persist leaves the old state
active (consistent with at-least-once replay).

This module keeps exactly those semantics but PER PARTITION: rows hash
onto a small conf'd number of key-range partitions
(``datax.job.process.state.partitions``, runtime/statepartition.py),
each with its own A/B pair + pointer, laid out as
``<location>/p<NN>/{A,B}/{table.npz,meta.json}`` + ``p<NN>/pointer``.
A replica owns a contiguous partition range and reads/writes ONLY its
owned partitions — which is what turns a rescale into a partition
handoff (the successor pulls its assigned partitions, from the local
dir or the shared ``objstore://`` mirror) instead of a state loss.

Durability (the PR 4 checkpointer contract, previously missing here):
every snapshot file AND the pointer commit go through tmp-write +
fsync + ``_durable_replace`` (file and directory fsynced), so a torn
write after power loss can never surface as the active snapshot.
Corrupt/truncated snapshots no longer kill the host: ``load()`` falls
back to the standby side (counted in ``State_LoadFallback_Count``,
flight-recorded as DX530) and to an empty partition when both sides
are bad (DX531) — replay of the un-acked window re-aggregates what
the standby was missing.

A partition snapshot is a ``.npz`` of compacted row columns + a JSON
sidecar with types and the string-dictionary entries its ids reference.
"""

from __future__ import annotations

import io
import json
import logging
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import jax.numpy as jnp
import numpy as np

from ..compile.planner import TableData, ViewSchema
from ..core.schema import StringDictionary
from .statepartition import (
    DEFAULT_STATE_PARTITIONS,
    LocalSnapshotStore,
    ObjstoreSnapshotStore,
    other_side,
    partition_ids,
)

logger = logging.getLogger(__name__)


@dataclass
class StateTable:
    name: str
    schema: ViewSchema
    capacity: int
    location: str  # base dir holding p<NN>/{A,B}/... + p<NN>/pointer
    partitions: int = DEFAULT_STATE_PARTITIONS
    owned: Optional[Sequence[int]] = None  # None = every partition
    partition_key: Optional[str] = None  # default: first schema column
    mirror: Optional[ObjstoreSnapshotStore] = None
    # shared accounting surfaces (FlowProcessor.state_stats/state_events
    # when constructed by the engine): fallbacks/pushes/pulls counted
    # into State_* metrics, DX53x events flight-recorded by the host
    stats: Dict[str, float] = field(default_factory=dict)
    events: List[dict] = field(default_factory=list)

    def __post_init__(self):
        self.partitions = max(1, int(self.partitions))
        self._local = LocalSnapshotStore(self.location)
        if self.owned is None:
            self.owned = list(range(self.partitions))
        else:
            self.owned = sorted(int(p) for p in self.owned)
        if self.partition_key is None:
            self.partition_key = next(iter(self.schema.types))
        elif self.partition_key not in self.schema.types:
            raise ValueError(
                f"state table {self.name!r} has no partition-key column "
                f"{self.partition_key!r} (columns: {list(self.schema.types)})"
            )
        # per-partition in-memory active side (the flip overwrite()
        # makes before persist() commits it) and the standby sides
        # overwrite() staged but persist() has not yet committed
        self._active: Dict[int, str] = {
            p: self._local.get_pointer(self._prefix(p)) or "A"
            for p in self.owned
        }
        self._pending: Dict[int, str] = {}
        # rows last persisted per partition (-1 = unknown): lets
        # overwrite() skip partitions that stay empty, so a sparse key
        # space doesn't pay P snapshot writes per batch
        self._last_counts: Dict[int, int] = {}

    # -- layout ----------------------------------------------------------
    def _prefix(self, p: int) -> str:
        return f"p{int(p):02d}"

    def _mirror_prefix(self, p: int) -> str:
        # the mirror URL is flow-level shared; the table name keys it
        return f"{self.name}/p{int(p):02d}"

    def _key_kind(self) -> str:
        return self.schema.types[self.partition_key]

    # -- load/store ------------------------------------------------------
    def _read_side(self, p: int, side: str) -> Optional[Dict]:
        """One partition side as {'cols': {name: np rows}, 'strings':
        {id: str}} — compacted valid rows only. None when absent;
        raises on a corrupt/truncated snapshot (the caller's cue to
        fall back)."""
        prefix = self._prefix(p)
        npz = self._local.get_file(prefix, side, "table.npz")
        meta_raw = self._local.get_file(prefix, side, "meta.json")
        if npz is None or meta_raw is None:
            return None
        meta = json.loads(meta_raw.decode("utf-8"))
        with np.load(io.BytesIO(npz)) as z:
            cols = {c: z[c] for c in self.schema.types if c in z.files}
        if set(cols) != set(self.schema.types):
            raise ValueError(
                f"partition {p} snapshot missing columns "
                f"{set(self.schema.types) - set(cols)}"
            )
        return {"cols": cols, "strings": meta.get("strings", {})}

    def _pull_partition(self, p: int) -> bool:
        """Fetch one partition from the objstore mirror into the local
        layout (both sides + pointer) — the successor-replica handoff
        path. Fail-closed: mirror errors propagate."""
        if self.mirror is None:
            return False
        mprefix = self._mirror_prefix(p)
        pointer = self.mirror.get_pointer(mprefix)
        if pointer is None:
            return False
        pulled = False
        for side in ("A", "B"):
            files = {}
            for fn in ("table.npz", "meta.json"):
                data = self.mirror.get_file(mprefix, side, fn)
                if data is not None:
                    files[fn] = data
            if files:
                self._local.put_files(self._prefix(p), side, files)
                pulled = True
        if pulled:
            self._local.put_pointer(self._prefix(p), pointer)
            self._active[p] = pointer
            self.stats["Snapshot_Pull_Count"] = (
                self.stats.get("Snapshot_Pull_Count", 0) + 1
            )
        return pulled

    def _event(self, code: str, p: int, side: str, message: str) -> None:
        ev = {
            "code": code, "table": self.name, "partition": int(p),
            "side": side, "message": message, "ts": time.time(),
        }
        self.events.append(ev)
        logger.warning("%s: %s", code, message)

    def load(self, dictionary: StringDictionary) -> TableData:
        """Load the owned partitions' active snapshots and concatenate
        them into one capacity-padded table; empty where nothing exists
        yet. A corrupt active side falls back to the standby (DX530,
        ``State_LoadFallback_Count``); when both sides are bad the
        partition loads empty (DX531) and at-least-once replay of the
        un-acked window re-aggregates what it held."""
        rows: Dict[str, List[np.ndarray]] = {c: [] for c in self.schema.types}
        n_rows = 0
        for p in self.owned:
            if (
                self._local.get_pointer(self._prefix(p)) is None
                and self.mirror is not None
            ):
                self._pull_partition(p)
            pointer = self._local.get_pointer(self._prefix(p)) \
                or self._active.get(p, "A")
            part = None
            for attempt, side in enumerate((pointer, other_side(pointer))):
                try:
                    part = self._read_side(p, side)
                    # an ABSENT active side (returned None without
                    # raising) is a fresh partition and loads EMPTY —
                    # never the standby: after a crash between
                    # overwrite() (standby written, in-memory flip)
                    # and persist() (pointer never committed) the
                    # standby holds the UNCOMMITTED batch, and loading
                    # it double-counts the replayed un-acked window
                    break
                except Exception as e:  # noqa: BLE001 — corrupt snapshot
                    self.stats["LoadFallback_Count"] = (
                        self.stats.get("LoadFallback_Count", 0) + 1
                    )
                    if attempt == 0:
                        self._event(
                            "DX530", p, side,
                            f"state {self.name} partition {p}: active "
                            f"side {side} unreadable ({e}); falling back "
                            f"to standby",
                        )
                        continue
                    self._event(
                        "DX531", p, side,
                        f"state {self.name} partition {p}: BOTH sides "
                        f"unreadable ({e}); loading empty — un-acked "
                        f"window replay re-aggregates",
                    )
                    part = None
            if part is None:
                continue
            # remap persisted dictionary ids into the live dictionary
            id_map = {
                int(k): dictionary.encode(v)
                for k, v in part["strings"].items()
            }
            count = None
            for c, t in self.schema.types.items():
                arr = part["cols"][c]
                count = len(arr) if count is None else min(count, len(arr))
                if t == "string":
                    arr = np.array(
                        [id_map.get(int(v), 0) for v in arr], dtype=np.int32
                    )
                rows[c].append(arr)
            n_rows += count or 0
        if n_rows == 0:
            return self.empty()
        if n_rows > self.capacity:
            logger.warning(
                "state %s: %d restored rows exceed capacity %d; truncating",
                self.name, n_rows, self.capacity,
            )
        empty = self.empty()
        cols: Dict[str, jnp.ndarray] = {}
        for c in self.schema.types:
            merged = np.concatenate(rows[c])[: self.capacity]
            out = np.asarray(empty.cols[c]).copy()
            out[: len(merged)] = merged.astype(out.dtype)
            cols[c] = jnp.asarray(out)
        valid = np.zeros((self.capacity,), dtype=bool)
        valid[: min(n_rows, self.capacity)] = True
        return TableData(cols, jnp.asarray(valid))

    def overwrite(self, table: TableData, dictionary: StringDictionary) -> None:
        """Write new state into each owned partition's standby side and
        flip in memory (StateTableHandler.scala:99-115, per partition).
        Rows hash onto partitions by the key column; rows of un-owned
        partitions are NOT persisted here (a key-routed ingest never
        produces them — see ``process.state.filteringest``)."""
        cols = {k: np.asarray(v) for k, v in table.cols.items()}
        valid = np.asarray(table.valid)
        pids = partition_ids(
            cols[self.partition_key], self.partitions, self._key_kind(),
            dictionary=dictionary,
        )
        string_cols = [
            c for c, t in self.schema.types.items() if t == "string"
        ]
        for p in self.owned:
            member = valid & (pids == p)
            idx = np.nonzero(member)[0]
            if idx.size == 0 and self._last_counts.get(p, -1) == 0:
                continue  # stayed empty: nothing to re-snapshot
            self._last_counts[p] = int(idx.size)
            strings: Dict[str, str] = {}
            for c in string_cols:
                for sid in np.unique(cols[c][idx]) if idx.size else ():
                    s = dictionary.decode(int(sid))
                    if s is not None:
                        strings[str(int(sid))] = s
            buf = io.BytesIO()
            np.savez(buf, **{c: cols[c][idx] for c in self.schema.types})
            files = {
                "table.npz": buf.getvalue(),
                "meta.json": json.dumps(
                    {"types": dict(self.schema.types), "strings": strings}
                ).encode("utf-8"),
            }
            side = other_side(self._active.get(p, "A"))
            self._local.put_files(self._prefix(p), side, files)
            self._active[p] = side  # flip in memory; persist() commits
            self._pending[p] = side

    def persist(self) -> None:
        """Commit the pointers after outputs succeed
        (StateTableHandler.scala:117-125) — the exactly-once point,
        fsynced (file + directory) so it survives power loss. With an
        ``objstore://`` mirror the committed sides + pointers push to
        the shared store afterward, fail-closed: a push failure raises
        so the batch requeues rather than acking state that never
        shipped."""
        committed = dict(self._pending)
        for p, side in committed.items():
            self._local.put_pointer(self._prefix(p), side)
        if self.mirror is not None and committed:
            for p, side in committed.items():
                files = {}
                for fn in ("table.npz", "meta.json"):
                    data = self._local.get_file(self._prefix(p), side, fn)
                    if data is not None:
                        files[fn] = data
                mprefix = self._mirror_prefix(p)
                self.mirror.put_files(mprefix, side, files)
                self.mirror.put_pointer(mprefix, side)
            self.stats["Snapshot_Push_Count"] = (
                self.stats.get("Snapshot_Push_Count", 0) + len(committed)
            )
        self._pending.clear()

    def empty(self) -> TableData:
        cols = {
            c: jnp.zeros(
                (self.capacity,),
                dtype={"double": jnp.float32, "boolean": jnp.bool_}.get(t, jnp.int32),
            )
            for c, t in self.schema.types.items()
        }
        return TableData(cols, jnp.zeros((self.capacity,), dtype=jnp.bool_))
