"""Offset checkpointing with the reference's file semantics.

reference: datax-host checkpoint/EventhubCheckpointer.scala:13-74 —
``offsets.txt`` holds one line per partition
``<ts>,<source>,<partition>,<fromSeq>,<untilSeq>``; before each write the
previous file is copied to ``offsets.txt.old``; on (re)start offsets are
read (falling back to the .old backup) and applied as starting positions.
At-least-once: a crash between sink write and checkpoint replays the
last batch.
"""

from __future__ import annotations

import os
import shutil
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from ..obs.tracing import span as _trace_span


def _durable_replace(tmp: str, dst: str) -> None:
    """``os.replace`` with power-loss durability: fsync the temp file
    before the rename (data hits the platter, not just the page cache)
    and fsync the directory after it (the rename itself is a directory
    entry). Without both, a crash-then-power-loss can surface a zero
    -length or missing checkpoint even though the process "wrote" it."""
    fd = os.open(tmp, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, dst)
    dir_fd = os.open(os.path.dirname(dst) or ".", os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def snapshot_arrays(snap: Dict) -> Dict:
    """Flatten a window-state snapshot dict
    (``FlowProcessor.snapshot_window_state`` shape) into the named
    numpy arrays one ``np.savez`` call persists. Shared by the
    whole-file checkpoint below and the per-partition payloads the
    state-partition stores ship (runtime/statepartition.py)."""
    import json as _json

    import numpy as np

    arrays: Dict = {}
    for table, ring in snap.get("rings", {}).items():
        for c, a in ring["cols"].items():
            arrays[f"ring/{table}/col/{c}"] = a
        arrays[f"ring/{table}/valid"] = ring["valid"]
        if ring.get("cap") is not None:
            # compacted partition snapshots carry the original ring
            # capacity so the merge can rebuild the full shape
            arrays[f"ring/{table}/cap"] = np.asarray(
                int(ring["cap"]), np.int64
            )
    arrays["slot_counter"] = np.asarray(int(snap.get("slot_counter", 0)),
                                        np.int64)
    base = snap.get("base_ms")
    arrays["base_ms"] = np.asarray(-1 if base is None else int(base),
                                   np.int64)
    if snap.get("dictionary") is not None:
        # ring ids are meaningless without the dictionary that encoded
        # them; ride it along as JSON bytes
        arrays["dictionary_json"] = np.frombuffer(
            _json.dumps(snap["dictionary"]).encode("utf-8"), dtype=np.uint8
        )
    return arrays


def arrays_to_snapshot(z) -> Dict:
    """Inverse of ``snapshot_arrays`` over a loaded npz mapping."""
    import json as _json

    rings: Dict[str, Dict] = {}
    for key in z.files:
        if not key.startswith("ring/"):
            continue
        _, table, kind = key.split("/", 2)
        ring = rings.setdefault(table, {"cols": {}, "valid": None})
        if kind == "valid":
            ring["valid"] = z[key]
        elif kind == "cap":
            ring["cap"] = int(z[key])
        else:
            ring["cols"][kind.split("/", 1)[1]] = z[key]
    base = int(z["base_ms"])
    out = {
        "rings": rings,
        "slot_counter": int(z["slot_counter"]),
        "base_ms": None if base < 0 else base,
    }
    if "dictionary_json" in z.files:
        out["dictionary"] = _json.loads(
            z["dictionary_json"].tobytes().decode("utf-8")
        )
    return out


@dataclass(frozen=True)
class PartitionOffset:
    ts_ms: int
    source: str
    partition: int
    from_seq: int
    until_seq: int


class OffsetCheckpointer:
    FILE = "offsets.txt"
    BACKUP = "offsets.txt.old"

    def __init__(self, checkpoint_dir: str):
        self.dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)

    @property
    def path(self) -> str:
        return os.path.join(self.dir, self.FILE)

    @property
    def backup_path(self) -> str:
        return os.path.join(self.dir, self.BACKUP)

    def write_offsets(self, offsets: List[PartitionOffset]) -> None:
        """Backup then write, as the reference does (scala :43-61) —
        fsynced so the checkpoint survives power loss, not just a
        process crash."""
        if os.path.exists(self.path):
            shutil.copyfile(self.path, self.backup_path)
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            for o in offsets:
                f.write(
                    f"{o.ts_ms},{o.source},{o.partition},{o.from_seq},{o.until_seq}\n"
                )
            f.flush()
            os.fsync(f.fileno())
        _durable_replace(tmp, self.path)

    def read_offsets(self) -> List[PartitionOffset]:
        """Read current file, falling back to the backup (scala :63-73)."""
        for path in (self.path, self.backup_path):
            if os.path.exists(path):
                try:
                    return self._parse(path)
                except Exception:
                    continue
        return []

    @staticmethod
    def _parse(path: str) -> List[PartitionOffset]:
        out = []
        with open(path, "r", encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                ts, source, part, from_seq, until_seq = line.split(",")
                out.append(
                    PartitionOffset(
                        int(ts), source, int(part), int(from_seq), int(until_seq)
                    )
                )
        return out

    def starting_positions(self) -> Dict[Tuple[str, int], int]:
        """(source, partition) -> next sequence number to read."""
        return {
            (o.source, o.partition): o.until_seq for o in self.read_offsets()
        }

    def checkpoint_batch(
        self, consumed: Dict[Tuple[str, int], Tuple[int, int]]
    ) -> None:
        """consumed: (source, partition) -> (from_seq, until_seq)."""
        with _trace_span("checkpoint/offsets"):
            now = int(time.time() * 1000)
            merged: Dict[Tuple[str, int], PartitionOffset] = {
                (o.source, o.partition): o for o in self.read_offsets()
            }
            for (source, part), (from_seq, until_seq) in consumed.items():
                merged[(source, part)] = PartitionOffset(
                    now, source, part, from_seq, until_seq
                )
            self.write_offsets(list(merged.values()))


class WindowStateCheckpointer:
    """Persist/restore the device window ring buffers across restarts.

    The offsets file above only replays the LAST batch; TIMEWINDOW ring
    buffers hold up to window+watermark of history that a restart would
    otherwise silently zero. The reference keeps that state in the Spark
    StreamingContext checkpoint (datax-host host/StreamingHost.scala:83-89
    ``StreamingContext.getOrCreate(checkpointDir, ...)``); here the rings
    are plain arrays, so the snapshot is one ``window.npz`` written with
    the same atomic-replace + ``.old`` backup semantics as offsets.txt.

    Serialized layout (all numpy): per ring table
    ``ring/<table>/col/<name>`` + ``ring/<table>/valid``, plus the slot
    counter and the time base the ring's relative timestamps refer to.
    """

    FILE = "window.npz"
    BACKUP = "window.npz.old"

    def __init__(self, checkpoint_dir: str):
        self.dir = checkpoint_dir
        os.makedirs(checkpoint_dir, exist_ok=True)

    @property
    def path(self) -> str:
        return os.path.join(self.dir, self.FILE)

    @property
    def backup_path(self) -> str:
        return os.path.join(self.dir, self.BACKUP)

    def save(self, snap: Dict) -> None:
        """snap: FlowProcessor.snapshot_window_state() output."""
        with _trace_span("checkpoint/window"):
            self._save(snap)

    def _save(self, snap: Dict) -> None:
        import numpy as np

        arrays = snapshot_arrays(snap)
        if os.path.exists(self.path):
            shutil.copyfile(self.path, self.backup_path)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        _durable_replace(tmp, self.path)

    def load(self) -> Optional[Dict]:
        """Restore a snapshot dict, falling back to the backup; None when
        no (readable) snapshot exists — including when a crash left only
        a torn ``window.npz.tmp`` behind (the tmp is never read; the
        previous complete checkpoint wins)."""
        import numpy as np

        for path in (self.path, self.backup_path):
            if not os.path.exists(path):
                continue
            try:
                with np.load(path) as z:
                    return arrays_to_snapshot(z)
            except Exception:
                continue
        return None
