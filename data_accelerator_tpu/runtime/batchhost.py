"""BatchHost: one-shot / scheduled batch jobs over time-partitioned files.

reference: datax-host host/BlobBatchingHost.scala:25-110 — expands a
``{yyyy-MM-dd}``-style datetime pattern in the input path over
[startTime, endTime] stepping by partitionIncrement minutes (:28-53),
lists matching files, and runs the processor once over the whole file
set (``BatchApp.scala:10`` entry; batch conf read by
BatchBlobInputSetting from ``datax.job.input.batch.blob.<i>.*``).

TPU flavor: files are read host-side (gzip-aware), decoded into
fixed-capacity device batches, and pushed through the same compiled
FlowProcessor step the streaming path uses — one engine, two drivers.
A processed-files tracker makes recurring runs idempotent (the
reference gets this by scheduling disjoint [start, end) windows;
we keep that *and* tolerate overlap).

Run: ``python -m data_accelerator_tpu.runtime.batchhost conf=<flow>.conf``
"""

from __future__ import annotations

import logging
import re
import sys
import time
from datetime import datetime, timedelta, timezone
from typing import Dict, List, Optional, Tuple

from ..core.config import SettingDictionary
from ..core.confmanager import ConfigManager
from ..obs import telemetry, tracing
from ..obs.histogram import HISTOGRAMS
from ..obs.metrics import MetricLogger
from ..obs.tracing import Tracer
from ..utils import fs
from .processor import FlowProcessor
from .sinks import OutputDispatcher, build_output_operators
from .sources import read_json_file

logger = logging.getLogger(__name__)

# the reference accepts one datetime token of y/M/d/H/m/s/S with -/. or /
# separators (BlobBatchingHost.scala getDateTimePattern)
_DATETIME_TOKEN_RE = re.compile(r"\{([yMdHmsS\-/.]+)\}")


def _format_java(fmt: str, t: datetime) -> str:
    # single java-format token table lives in sources (the fs/ingest side)
    from .sources import _java_fmt_to_strftime

    return t.strftime(_java_fmt_to_strftime(fmt))


def get_input_blob_path_prefixes(
    path: str,
    start_time: datetime,
    processing_window_s: float,
    partition_increment_s: float,
) -> List[Tuple[str, datetime]]:
    """Expand the datetime token over the window, deduping partitions.

    reference: BlobBatchingHost.scala:28-53 getInputBlobPathPrefixes —
    walks t from 0..window stepping by the increment, substitutes the
    formatted partition folder, skips duplicates; a pattern-less path
    passes through unchanged.
    """
    m = _DATETIME_TOKEN_RE.search(path)
    if not m:
        logger.warning("input path has no datetime pattern: %s", path)
        return [(path, datetime.now(timezone.utc))]
    fmt = m.group(1)
    out: List[Tuple[str, datetime]] = []
    seen = set()
    t = 0.0
    while t <= processing_window_s:
        ts = start_time + timedelta(seconds=t)
        folder = _format_java(fmt, ts)
        if folder not in seen:
            seen.add(folder)
            out.append((path.replace("{" + fmt + "}", folder), ts))
        t += partition_increment_s
    return out


def get_batch_blobs_conf(dict_: SettingDictionary) -> List[Dict[str, str]]:
    """Read ``datax.job.input.batch.blob.<i>.*`` entries
    (reference: BatchBlobInputSetting.getInputBlobsArrayConf)."""
    sub = dict_.get_sub_dictionary("datax.job.input.batch.blob.")
    grouped = sub.group_by_sub_namespace()
    out = []
    for idx in sorted(grouped, key=lambda s: int(s) if s.isdigit() else 0):
        g = grouped[idx]
        out.append({
            "path": g.get_or_else("path", ""),
            "starttime": g.get_or_else("starttime", ""),
            "endtime": g.get_or_else("endtime", ""),
            "partitionincrement": g.get_or_else("partitionincrement", "1"),
        })
    return out


def _parse_iso(ts: str) -> datetime:
    t = datetime.fromisoformat(ts.replace("Z", "+00:00"))
    if t.tzinfo is None:
        t = t.replace(tzinfo=timezone.utc)
    return t


class BatchHost:
    """Drives one batch run: expand prefixes -> list -> process -> sink."""

    def __init__(
        self,
        dict_: SettingDictionary,
        udfs: Optional[dict] = None,
        table_sink_map: Optional[Dict[str, list]] = None,
        tracker_path: Optional[str] = None,
    ):
        self.dict = dict_
        self.processor = FlowProcessor(dict_, udfs=udfs)
        self.metric_logger = MetricLogger.from_conf(dict_)
        self.telemetry = telemetry.from_conf(dict_)
        # same span/histogram surface as the streaming host: each chunk
        # is one trace (decode -> dispatch -> device-step -> sync ->
        # collect -> sinks), so batch and streaming latency live in one
        # measurement vocabulary
        tele_conf = dict_.get_sub_dictionary("datax.job.process.telemetry.")
        self.tracer = Tracer(
            self.telemetry,
            histograms=HISTOGRAMS,
            flow=dict_.get_job_name(),
            enabled=(
                tele_conf.get_or_else("tracing", "true") or ""
            ).lower() != "false",
            # batch jobs launched by the control plane join the
            # launching request's trace, same as streaming hosts
            parent=tele_conf.get("parenttrace"),
        )
        if table_sink_map is None:
            from ..core.config import SettingNamespace

            conf_outputs = dict_.get_sub_dictionary(
                SettingNamespace.JobOutputPrefix
            ).group_by_sub_namespace()
            table_sink_map = {name: [name] for name in conf_outputs}
        self.dispatcher = OutputDispatcher(
            build_output_operators(dict_, self.metric_logger, table_sink_map),
            self.metric_logger,
        )
        self.tracker_path = tracker_path or dict_.get(
            "datax.job.input.batch.blob.trackerfile"
        )
        self._processed: set = set()
        if self.tracker_path:
            try:
                self._processed = set(fs.read_lines(self.tracker_path))
            except FileNotFoundError:
                pass

    def list_files_to_process(self) -> List[str]:
        blobs = get_batch_blobs_conf(self.dict)
        files: List[str] = []
        for b in blobs:
            if not b["path"]:
                continue
            if b["starttime"] and b["endtime"]:
                start = _parse_iso(b["starttime"])
                end = _parse_iso(b["endtime"])
                window_s = (end - start).total_seconds()
                incr_s = float(b["partitionincrement"]) * 60.0
                if incr_s <= 0:
                    raise ValueError(
                        "datax.job.input.batch.blob partitionincrement "
                        f"must be positive, got {b['partitionincrement']!r}"
                    )
                prefixes = get_input_blob_path_prefixes(
                    b["path"], start, window_s, incr_s
                )
            else:
                prefixes = [(b["path"], datetime.now(timezone.utc))]
            for prefix, _ts in prefixes:
                files.extend(fs.list_files(prefix))
        return [f for f in sorted(set(files)) if f not in self._processed]

    def run(self) -> Dict[str, float]:
        """Process all pending files in capacity-sized device batches.

        reference: BlobBatchingHost.runBatchApp:70-110 — one processor
        pass over the listed files; here the fixed device batch shape
        chunks the row stream, same compiled step per chunk. Up to
        ``process.pipeline.depth`` chunks stay in flight (the
        generalized P6 overlap shared with
        ``StreamingHost.run_pipelined``); finishes are strictly FIFO so
        state-table commits happen in chunk order. With
        ``process.pipeline.backgroundtransfer`` (default on) a finish
        blocks only on the chunk's counts vector — the streamed output
        tables land and sinks run on a dedicated background landing
        worker (still FIFO: one worker, submission order), so file
        reads and device steps keep flowing while results land. A
        landing failure aborts the pass before the tracker file is
        written, so every file is reprocessed on rerun (at-least-once).
        """
        from collections import deque
        from concurrent.futures import ThreadPoolExecutor

        self.telemetry.track_event("datax/batch/app/begin")
        t0 = time.time()
        files = self.list_files_to_process()
        cap = self.processor.batch_capacity
        depth = max(1, self.processor.pipeline_depth)
        background = (
            (self.dict.get_sub_dictionary("datax.job.process.pipeline.")
             .get_or_else("backgroundtransfer", "true") or "")
            .lower() != "false"
        ) and self.processor.mesh is None
        totals: Dict[str, float] = {"Batch_Files_Count": float(len(files))}
        batch_time_ms = int(t0 * 1000)
        pending = deque()  # FIFO window of (handle, trace) in flight
        landings = deque()  # futures of chunk tails on the landing worker
        land_pool = (
            ThreadPoolExecutor(1, thread_name_prefix="landing")
            if background else None
        )
        landing_failed: List[BaseException] = []

        def land(handle, trace) -> None:
            """The chunk tail behind the counts sync: resolve streamed
            tables, sinks, commit. Runs on the landing worker (or
            inline when background transfer is off)."""
            if landing_failed:
                handle.abandon()
                trace.end(status="aborted")
                return
            try:
                with trace.activate():
                    with tracing.span("collect"):
                        datasets, metrics = handle.collect_tables()
                    with tracing.span("sinks"):
                        self.dispatcher.dispatch(datasets, batch_time_ms)
                self.processor.commit()
                trace.end()
            except Exception as e:  # noqa: BLE001 — re-raised on the main pass
                trace.end(status="error")
                handle.abandon()
                landing_failed.append(e)
                return
            for k, v in metrics.items():
                # counts sum across chunks; point-in-time / per-chunk
                # latency values don't (a pipelined chunk's
                # dispatch->collect span absorbs the NEXT chunk's file
                # reads, and summing an epoch timestamp is meaningless)
                if k in ("Latency-Process", "BatchProcessedET",
                         "Transfer_Efficiency", "Pipeline_Depth",
                         "Transfer_Background_Pending",
                         "Transfer_Background_LandMs"):
                    continue
                totals[k] = totals.get(k, 0.0) + float(v)

        def check_landing_failure() -> None:
            if landing_failed:
                raise landing_failed[0]

        def finish(handle, trace) -> None:
            # counts-only sync on the main pass — the chunk's single
            # blocking device read; the tail lands out-of-band
            with trace.activate():
                with tracing.span("sync"):
                    handle.collect_counts()
                trace.record_since("device-step", "dispatch-done")
            if land_pool is not None:
                landings.append(land_pool.submit(land, handle, trace))
            else:
                land(handle, trace)
                check_landing_failure()

        def flush(chunk: List[dict]):
            # dispatch chunk N; once `depth` chunks are in flight,
            # finish the oldest while the newer ones compute — file
            # reads and sink writes hide under the device steps
            check_landing_failure()
            trace = self.tracer.begin("batch/chunk", batchTime=batch_time_ms)
            with trace.activate(), tracing.span("decode", rows=len(chunk)):
                raw = self.processor.encode_rows(
                    chunk, (batch_time_ms // 1000) * 1000
                )
            with trace.activate(), tracing.span("dispatch"):
                handle = self.processor.dispatch_batch(raw, batch_time_ms)
            trace.mark("dispatch-done")
            pending.append((handle, trace))
            if len(pending) > depth:
                finish(*pending.popleft())
            # backpressure: queued landings never outgrow the window
            while len(landings) > depth:
                landings.popleft().result()

        # linear row buffering: consume via an index instead of
        # re-slicing the tail each chunk (`rows = rows[cap:]` re-copied
        # everything after the cut, O(n^2) over a multi-million-row
        # file set); the buffer compacts only when the dead prefix
        # dominates, keeping the whole pass amortized O(n)
        rows: List[dict] = []
        pos = 0
        try:
            for f in files:
                rows.extend(read_json_file(f))
                while len(rows) - pos >= cap:
                    flush(rows[pos:pos + cap])
                    pos += cap
                    if pos >= cap and pos * 2 >= len(rows):
                        del rows[:pos]
                        pos = 0
            if len(rows) > pos:
                flush(rows[pos:])
            while pending:
                finish(*pending.popleft())
            while landings:
                landings.popleft().result()
            check_landing_failure()
        except Exception as e:
            self.telemetry.track_exception(e, {"event": "error/batch/process"})
            for h, tr in pending:
                tr.end(status="error")  # idempotent
                h.abandon()
            while landings:  # settle queued tails (post-failure no-ops)
                try:
                    landings.popleft().result(timeout=60)
                except Exception:  # noqa: BLE001 — first failure already raised
                    pass
            raise
        finally:
            if land_pool is not None:
                land_pool.shutdown(wait=True)
        # tracker written only after a fully successful pass (at-least-once)
        self._processed.update(files)
        if self.tracker_path:
            fs.write_text(self.tracker_path, "\n".join(sorted(self._processed)) + "\n")
        totals["BatchProcessedET"] = float(batch_time_ms)
        totals["Latency-Batch"] = (time.time() - t0) * 1000.0
        self.metric_logger.send_batch_metrics(totals, batch_time_ms)
        self.telemetry.track_event(
            "datax/batch/end", measurements={k: float(v) for k, v in totals.items()}
        )
        logger.info("batch run done: %s", totals)
        return totals


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    args = argv if argv is not None else sys.argv[1:]
    ConfigManager.reset()
    ConfigManager.get_configuration_from_arguments(args)
    d = ConfigManager.load_config()
    BatchHost(d).run()


if __name__ == "__main__":
    main()
