"""Micro-batch streaming runtime: hosts, sources, sinks, state, checkpoints."""
