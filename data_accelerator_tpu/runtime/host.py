"""StreamingHost: the micro-batch driver loop.

reference: datax-host host/StreamingHost.scala:22-97 — build config,
create the processor, wire the input stream, then per batch: process,
emit metrics, checkpoint offsets every checkpointInterval; per-batch
failures log + rethrow so the batch retries (at-least-once,
CommonProcessorFactory.scala:382-398).

Run one-box:
    python -m data_accelerator_tpu.runtime.host conf=<flow>.conf batches=10
"""

from __future__ import annotations

import logging
import os
import sys
import tempfile
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional

from ..constants import MetricName
from ..core.config import SettingDictionary, SettingNamespace
from ..core.confmanager import ConfigManager
from ..obs import telemetry, tracing
from ..obs.exposition import HealthState, ObservabilityServer
from ..obs.histogram import HISTOGRAMS
from ..obs.metrics import MetricLogger
from ..obs.tracing import Tracer
from .checkpoint import OffsetCheckpointer, WindowStateCheckpointer
from .processor import FlowProcessor
from .sinks import OutputDispatcher, build_output_operators
from .sources import LocalSource, StreamingSource, make_source

logger = logging.getLogger(__name__)


class StreamingHost:
    def __init__(
        self,
        dict_: SettingDictionary,
        source: Optional[StreamingSource] = None,
        udfs: Optional[dict] = None,
        table_sink_map: Optional[Dict[str, list]] = None,
    ):
        self.dict = dict_
        self.processor = FlowProcessor(dict_, udfs=udfs)
        self.metric_logger = MetricLogger.from_conf(dict_)
        # lifecycle telemetry (AppInsightLogger analog): batch begin/end
        # events + exceptions with app context (AppInsightLogger.scala:18-108)
        self.telemetry = telemetry.from_conf(dict_)
        # batch-granular span tracing + per-stage latency histograms
        # (obs/tracing.py, obs/histogram.py): every stage boundary of
        # every micro-batch is a span in the telemetry fan-out and a
        # sample in the stage's live latency distribution. Span emission
        # is conf-gated (process.telemetry.tracing, default on — the
        # overhead is a handful of clock reads per batch); histograms
        # always observe, they are the /metrics + percentile source.
        # cross-process propagation: when the control plane spawned this
        # host it passed `telemetry.parenttrace=<trace>:<span>` — every
        # batch trace then JOINS the control-plane request's trace, so
        # the flight recorder's span tree for any batch roots in the
        # REST submit that launched the job (obs/tracing.py)
        tele_conf0 = dict_.get_sub_dictionary("datax.job.process.telemetry.")
        self.tracer = Tracer(
            self.telemetry,
            histograms=HISTOGRAMS,
            flow=dict_.get_job_name(),
            enabled=(
                tele_conf0.get_or_else("tracing", "true") or ""
            ).lower() != "false",
            parent=tele_conf0.get("parenttrace"),
        )
        # model-vs-observed conformance: config generation embeds the
        # DX2xx cost-model report (process.conformance.model); the
        # monitor compares windowed observations against it and emits
        # Conformance_* gauges + DX5xx drift events (obs/conformance.py)
        from ..obs.conformance import ConformanceMonitor

        self.conformance = ConformanceMonitor.from_conf(
            dict_, flow=dict_.get_job_name()
        )
        # process.debug.protocolmonitor arms the dynamic half of the
        # DX9xx exactly-once defense (runtime/protocolmonitor.py): the
        # batch tail records its actual protocol-event sequence and
        # every sealed batch's linearization is validated against the
        # declared spec; violations fire runtime DX906
        from .protocolmonitor import from_conf as _protomon_from_conf

        self.protocol_monitor = _protomon_from_conf(
            self.processor.process_conf.get_sub_dictionary("debug.")
        )
        # boot-time conf audit (runtime/confaudit.py): the concrete conf
        # this host started with, replayed through the DX10xx lattice
        # validator — unknown/out-of-bounds keys flight-record DX1006
        # (conf/violation events + Conf_* gauges) instead of being
        # silently ignored. Advisory: never blocks boot.
        from .confaudit import from_conf as _confaudit_from_conf

        self.conf_audit = _confaudit_from_conf(
            dict_,
            subject="host",
            telemetry=self.telemetry,
            metric_logger=self.metric_logger,
        )

        input_conf = dict_.get_sub_dictionary(SettingNamespace.JobInputPrefix)
        # one StreamingSource per declared input source (multi-source
        # flows poll them all each batch; the injected ``source`` arg
        # binds to the primary for back-compat / tests)
        self.sources: Dict[str, StreamingSource] = {}
        for name, spec in self.processor.specs.items():
            if name == self.processor.primary and source is not None:
                self.sources[name] = source
            else:
                self.sources[name] = make_source(
                    spec.conf, spec.schema, source=name
                )
        self.source = self.sources[self.processor.primary]
        self.interval_s = self.processor.interval_s
        self.max_rate = int(input_conf.get_or_else("eventhub.maxrate", "1000"))
        # backpressure: when a batch overruns the interval, shrink the
        # next poll; recover multiplicatively when batches are fast
        # (the role maxRate plays statically in the reference — here the
        # effective rate adapts between maxrate/8 and maxrate)
        self._rate_scale = 1.0

        # offset checkpointing (EventhubCheckpointer semantics)
        ckpt_dir = input_conf.get("eventhub.checkpointdir") or input_conf.get(
            "streaming.checkpointdir"
        )
        self.checkpointer = (
            OffsetCheckpointer(ckpt_dir) if ckpt_dir else None
        )
        # window-state checkpointing (SURVEY §5.4): the offsets file only
        # replays the last batch; ring buffers hold up to window+watermark
        # of history that a restart would silently zero. Persist them on
        # the same cadence and restore on start (the role the Spark
        # StreamingContext checkpoint plays at StreamingHost.scala:83-89).
        self.window_checkpointer = (
            WindowStateCheckpointer(ckpt_dir)
            if ckpt_dir and self.processor.window_buffers
            else None
        )
        self.checkpoint_interval_s = (
            input_conf.get_duration_option("eventhub.checkpointinterval") or 60.0
        )
        self._last_checkpoint = 0.0

        # health/readiness state + the Prometheus/health HTTP surface
        # (/metrics, /healthz, /readyz — obs/exposition.py), served when
        # process.observability.port is set (0 = ephemeral port, useful
        # for tests and one-box)
        obs_conf = dict_.get_sub_dictionary(
            SettingNamespace.JobProcessPrefix + "observability."
        )
        stall_fail = obs_conf.get_double_option("stallfailms")
        # conf'd stall-EWMA half-life (observability.stallewmams): the
        # SAME smoothed gauge feeds /readyz and the pilot's stall
        # signal, so readiness probes and the controller agree on
        # "stalled" by construction
        stall_ewma = obs_conf.get_double_option("stallewmams")
        self.health = HealthState(
            flow=dict_.get_job_name(),
            checkpoint_interval_s=(
                self.checkpoint_interval_s if self.checkpointer else None
            ),
            batch_interval_s=self.interval_s,
            stall_fail_ms=stall_fail,
            stall_ewma_half_life_ms=stall_ewma,
        )
        # declarative alert rules from the generated conf
        # (process.alerts.rules, obs/alerts.py): evaluated every batch
        # finish and on every /alerts-/metrics request over the same
        # store/histogram/health surfaces the dashboards read
        from ..obs.alerts import AlertEngine

        self.alerts = AlertEngine.from_conf(
            dict_,
            flow=dict_.get_job_name(),
            store=self.metric_logger.store,
            histograms=HISTOGRAMS,
            health=self.health,
        )
        # fleet telemetry plane (obs/publisher.py): when
        # process.fleet.publishurl is conf'd, every batch finish folds
        # into a windowed frame shipped to the shared objstore for the
        # control plane's FleetView rollup. None = per-process only.
        from ..obs.publisher import TelemetryFramePublisher

        self.fleet_publisher = TelemetryFramePublisher.from_conf(
            dict_,
            flow=dict_.get_job_name(),
            metric_logger=self.metric_logger,
            histograms=HISTOGRAMS,
        )
        # machine-profile calibration (obs/calibrate.py): ~100 ms of
        # jit micro-probes, process-cached and persisted/shared like
        # the compile cache (observability.calibrationfile /
        # calibrationurl). The profile prices the conf-embedded
        # byte+FLOP model into the DX520/DX521 roofline predictions and
        # exports as the Calib_* series on every batch. Off with
        # observability.calibration=false (the monitor's latency checks
        # then stay disarmed unless conformance.latency pins them).
        self._calib_metrics: Dict[str, float] = {}
        if (obs_conf.get_or_else("calibration", "true") or "").lower() \
                != "false":
            from ..obs.calibrate import get_profile

            try:
                profile = get_profile(
                    cache_file=obs_conf.get("calibrationfile"),
                    share_url=obs_conf.get("calibrationurl"),
                )
                self._calib_metrics = profile.metrics()
                if self.conformance is not None \
                        and not self.conformance.latency_pinned:
                    preds, compute_ms, overhead_ms = (
                        self.conformance.model.latency_predictions(
                            profile.to_dict()
                        )
                    )
                    self.conformance.set_latency(
                        preds, compute_ms, overhead_ms
                    )
            except Exception:  # noqa: BLE001 — calibration is optional
                logger.exception(
                    "machine-profile calibration failed; "
                    "DX52x latency checks disarmed"
                )

        # live HBM watermark sampling (observability.hbmsample, default
        # on): each batch finish samples the device allocator
        # (memory_stats) into Hbm_BytesInUse/Hbm_PeakBytes — the DX522
        # observation. Silently absent on backends that don't report
        # (CPU), exactly like a missing conformance prediction.
        self.hbm_sample = (
            (obs_conf.get_or_else("hbmsample", "true") or "").lower()
            != "false"
        )

        # on-demand profiler surface (obs/profiler.py): POST
        # /profile?seconds=N on the observability port arms a
        # jax.profiler capture that lands beside the flight recorder
        # (observability.profilerdir overrides). Off with
        # observability.profiler=false.
        self.profiler = None
        if (obs_conf.get_or_else("profiler", "true") or "").lower() \
                != "false":
            from ..obs.profiler import ProfilerSurface

            prof_dir = obs_conf.get("profilerdir")
            if not prof_dir:
                tracefile = dict_.get_sub_dictionary(
                    "datax.job.process.telemetry."
                ).get("tracefile")
                base = (
                    os.path.dirname(os.path.abspath(tracefile))
                    if tracefile else tempfile.gettempdir()
                )
                prof_dir = os.path.join(
                    base, f"profiler-{dict_.get_job_name() or 'flow'}"
                )
            self.profiler = ProfilerSurface(
                prof_dir, flow=dict_.get_job_name()
            )

        self.obs_server: Optional[ObservabilityServer] = None
        obs_port = obs_conf.get_int_option("port")
        if obs_port is not None:
            self.obs_server = ObservabilityServer(
                self.health,
                histograms=HISTOGRAMS,
                store=self.metric_logger.store,
                port=obs_port,
                alerts=self.alerts,
                profiler=self.profiler,
            )
            self.obs_server.start()

        if self.checkpointer:
            positions = self.checkpointer.starting_positions()
            for s in self.sources.values():
                s.start(positions)
        # window restore, local first: a plain restart reloads its own
        # window.npz; a RESCALE SUCCESSOR (fresh dirs, objstore mirror
        # configured) pulls only the window partitions its replica
        # index owns and merges them — the handoff path
        self.window_restored_from: Optional[str] = None
        if self.window_checkpointer:
            snap = self.window_checkpointer.load()
            if snap is not None:
                if self.processor.restore_window_state(snap):
                    self.window_restored_from = "local"
                    logger.info("restored window state from checkpoint")
                else:
                    logger.warning(
                        "window-state checkpoint incompatible with current "
                        "flow config; starting with empty windows"
                    )
        if (
            self.window_restored_from is None
            and self.processor.window_buffers
            and self.processor.state_mirror is not None
        ):
            try:
                if self.processor.restore_window_partitions():
                    self.window_restored_from = "partitions"
                    logger.info(
                        "restored window state from %d assigned partitions",
                        len(self.processor.state_owned),
                    )
            except Exception:  # noqa: BLE001 — empty windows beat a dead init
                logger.exception(
                    "window partition handoff failed; starting empty"
                )
        self._drain_state_events()

        # sink routing: dataset -> output names; default: each conf output
        # name routes its same-named dataset (S500 contract)
        if table_sink_map is None:
            conf_outputs = dict_.get_sub_dictionary(
                SettingNamespace.JobOutputPrefix
            ).group_by_sub_namespace()
            table_sink_map = {name: [name] for name in conf_outputs}
        operators = build_output_operators(dict_, self.metric_logger, table_sink_map)
        self.dispatcher = OutputDispatcher(operators, self.metric_logger)

        self.batches_processed = 0
        self._stop = False

        # background result landing (the device-resident result path):
        # in the pipelined loop the only BLOCKING device read per batch
        # is the packed counts vector; the output tables stream D2H in
        # the background and the batch tail (collect_tables -> sinks ->
        # commit -> ack -> metrics -> checkpoint) runs on this dedicated
        # single-thread landing executor — one worker, so landings stay
        # strictly FIFO while the dispatch loop keeps feeding the
        # device. Conf datax.job.process.pipeline.backgroundtransfer
        # (default on); off under a mesh like sized transfer.
        pipe_conf = dict_.get_sub_dictionary(
            SettingNamespace.JobProcessPrefix + "pipeline."
        )
        self.background_transfer = (
            (pipe_conf.get_or_else("backgroundtransfer", "true") or "")
            .lower() != "false"
        ) and self.processor.mesh is None
        self._landing_pool = (
            ThreadPoolExecutor(1, thread_name_prefix="landing")
            if self.background_transfer else None
        )
        self._landings = deque()  # futures of submitted landings, FIFO
        self._landing_failed: Optional[BaseException] = None

        # live pipeline depth: starts at the conf'd depth; the pilot's
        # DepthActuator retargets it (request_depth) and run_pipelined
        # applies the change at a window boundary by draining the
        # in-flight FIFO down to the new depth first, so strict-FIFO
        # commit and whole-window requeue invariants are untouched by a
        # resize
        self._live_depth = max(1, self.processor.pipeline_depth)
        self._depth_target: Optional[int] = None

        # the autopilot (pilot/controller.py, conf
        # datax.job.process.pilot.*, default on): once per evaluation
        # window it maps the observability surface — the stall EWMA
        # /readyz judges, landing backlog, poll saturation, malformed
        # rate, alert-rule action votes — to bounded actuations
        # (pipeline depth, source backpressure, replica count) through
        # typed actuators, every decision a pilot/decide span
        from ..pilot.controller import PilotController

        self.pilot = PilotController.from_conf(dict_, host=self)

    def _drain_state_events(self) -> None:
        """Flight-record the DX53x events the state loaders queued
        (DX530 active-side fallback, DX531 both-sides-bad -> empty):
        typed events beside conformance drift, so a corrupted snapshot
        handoff is visible in `obs trace` output and the recorder."""
        events, self.processor.state_events = (
            self.processor.state_events, []
        )
        for ev in events:
            try:
                self.telemetry.track_event("state/fallback", dict(ev))
            except Exception:  # noqa: BLE001 — telemetry never fails state
                logger.exception("state event emit failed")

    # -- pilot actuation surface ------------------------------------------
    def live_depth(self) -> int:
        """The commanded pipeline depth: the pending pilot target when
        one exists, else the depth the dispatch loop is running (==
        conf'd depth until the pilot retargets it)."""
        return (
            self._depth_target if self._depth_target is not None
            else self._live_depth
        )

    def request_depth(self, depth: int) -> None:
        """Ask the dispatch loop to resize the in-flight window; the
        change applies at the next loop iteration, draining the window
        down to the new depth first (FIFO) when shrinking."""
        self._depth_target = max(1, int(depth))

    def _current_depth(self, depth: int) -> int:
        """Apply a pending pilot depth retarget (loop thread only)."""
        if self._depth_target is not None and self._depth_target != depth:
            logger.info(
                "pilot depth change: %d -> %d", depth, self._depth_target
            )
            depth = self._depth_target
        self._depth_target = None
        self._live_depth = depth
        return depth

    # -- loop -------------------------------------------------------------
    def _poll_and_encode(self):
        """Poll every source and encode one device batch per source;
        returns (raw dict, consumed offsets, batch_time_ms, t0)."""
        t0 = time.time()
        batch_time_ms = int(t0 * 1000)
        raw: Dict[str, object] = {}
        consumed: Dict = {}
        for name, src in self.sources.items():
            spec = self.processor.specs[name]
            max_events = min(
                spec.capacity,
                max(1, int(self.max_rate * self.interval_s * self._rate_scale)),
            )
            if self.pilot is not None:
                # source backpressure: the pilot's token bucket is the
                # admission point — at full rate it grants pass-through,
                # under sink/landing pressure it shrinks the poll
                max_events = max(1, self.pilot.admit_events(max_events))
            received = max_events
            malformed0 = self.processor.malformed_rows_total
            if isinstance(src, LocalSource):
                cols, now_ms, c = src.poll_columns(
                    max_events, self.processor.dictionary
                )
                raw[name] = self.processor.encode_columns(
                    cols, max_events, source=name
                )
                if len(self.sources) == 1:
                    # single-source fast path: the generator's clock IS
                    # the batch time. Multi-source keeps the one t0 base
                    # computed above — every stream must encode against
                    # the SAME base the dispatch will use, or relative
                    # timestamps shift across a second boundary
                    batch_time_ms = now_ms
            elif hasattr(src, "poll_raw"):
                # native ingest: raw wire bytes -> C++ decoder (newline
                # JSON, or whole Kafka v2 record batches when the
                # source declares raw_format="kafka-v2"); the packed
                # matrix stays numpy (to_device=False) so the
                # decode-ahead worker never touches jax off-thread —
                # the jitted step's call transfers it
                blob, _n, c = src.poll_raw(max_events)
                received = _n
                raw[name] = self.processor.encode_json_bytes(
                    blob, (batch_time_ms // 1000) * 1000, source=name,
                    to_device=False,
                    fmt=getattr(src, "raw_format", "jsonl"),
                )
            else:
                rows, c = src.poll(max_events)
                received = len(rows)
                raw[name] = self.processor.encode_rows(
                    rows, (batch_time_ms // 1000) * 1000, source=name
                )
            # source-side ingest counters (e.g. KafkaSource's malformed
            # record values on the client-library poll path, the wire
            # client's CRC-skipped corrupt batches) merge into the same
            # ingest_stats/malformed_rows_total surface the decoder
            # feeds, so the pilot's flood signal and the Input_*_Count
            # metrics cover Kafka flows too
            take = getattr(src, "take_ingest_stats", None)
            if take is not None:
                for k, v in take().items():
                    if not v:
                        continue
                    self.processor.ingest_stats[k] = (
                        self.processor.ingest_stats.get(k, 0) + v
                    )
                    if k == "malformed_rows":
                        self.processor.malformed_rows_total += v
            if self.pilot is not None:
                # saturation + malformed-rate signals for the window
                self.pilot.observe_poll(
                    max_events, received,
                    self.processor.malformed_rows_total - malformed0,
                )
            consumed.update(c)
        return raw, consumed, batch_time_ms, t0

    def _finish(
        self, handle, consumed, batch_time_ms, t0, trace,
        inflight_depth: int = 1,
        background: bool = False,
    ) -> Optional[Dict[str, float]]:
        """Finish a batch. The CALLING thread pays only the counts-only
        sync (``collect_counts`` — the packed counts vector, a few
        hundred bytes already streaming since dispatch); the tail
        (collect tables -> sinks -> commit -> ack -> metrics ->
        checkpoint) runs inline by default, or — with ``background`` —
        on the dedicated landing thread so the dispatch loop keeps
        feeding the device while results land and sinks ack
        out-of-band. Landings are strictly FIFO (one worker), so
        state-table commits, acks and offset checkpoints keep dispatch
        order at every depth. Failures requeue un-acked source batches
        and rethrow so the batch retries, at-least-once
        (CommonProcessorFactory.scala:382-398); a background landing
        failure is recorded and re-raised on the dispatch loop, which
        then requeues the whole window. ``inflight_depth``: how many
        batches (this one included) were in flight when the window
        forced this finish — the live pipeline depth gauge. Returns the
        batch metrics inline, or None when the tail went to the
        landing thread."""
        stall_ms = 0.0
        try:
            with trace.activate(), tracing.span("sync"):
                # the batch's ONLY blocking device read: the counts
                # vector. The trace separates "rules evaluated"
                # (device-step ends here) from result transport +
                # materialization (collect, backgrounded below).
                sync_t0 = time.time()
                handle.collect_counts()
                # time the dispatch loop actually stalled waiting
                # for the window's oldest batch to leave the device
                stall_ms = (time.time() - sync_t0) * 1000.0
            trace.record_since("device-step", "dispatch-done")
        except Exception as e:
            self.telemetry.track_exception(
                e, {"event": "error/streaming/process", "batchTime": batch_time_ms}
            )
            self.health.record_batch(
                batch_time_ms, ok=False, error=f"{type(e).__name__}: {e}"
            )
            trace.end(status="error")
            handle.abandon()
            if background:
                # let already-queued (earlier, independent) landings ack
                # before the requeue, so the un-acked FIFO can't race
                self._settle_landings()
            for s in self.sources.values():
                s.requeue_unacked()
            logger.exception("batch sync failed; rethrowing for retry")
            raise
        if background and self._landing_pool is not None:
            backlog = self._prune_landings()
            self._landings.append(self._landing_pool.submit(
                self._landing_run, handle, consumed, batch_time_ms, t0,
                trace, inflight_depth, stall_ms, backlog,
            ))
            return None
        return self._finish_tail(
            handle, consumed, batch_time_ms, t0, trace, inflight_depth,
            stall_ms, None, requeue_on_error=True,
        )

    def _landing_run(
        self, handle, consumed, batch_time_ms, t0, trace,
        inflight_depth, stall_ms, backlog,
    ) -> Optional[Dict[str, float]]:
        """One queued landing on the background transfer thread. After
        a recorded failure the rest of the queue drains as no-ops —
        later batches stay un-acked, and the dispatch loop (which
        re-raises the failure) requeues the whole window."""
        if self._landing_failed is not None:
            handle.abandon()
            trace.end(status="aborted")
            return None
        try:
            return self._finish_tail(
                handle, consumed, batch_time_ms, t0, trace, inflight_depth,
                stall_ms, backlog, requeue_on_error=False,
            )
        except Exception as e:  # noqa: BLE001 — re-raised on the loop thread
            self._landing_failed = e
            handle.abandon()
            return None

    def _prune_landings(self) -> int:
        """Drop completed landings from the FIFO; returns the number
        still pending (the background-transfer backlog gauge)."""
        while self._landings and self._landings[0].done():
            self._landings.popleft()
        return len(self._landings)

    def _wait_landing_backlog(self, depth: int) -> None:
        """Backpressure: never let pending landings outgrow the
        pipeline window — a landing thread that can't keep up must
        stall the dispatch loop, not grow an unbounded queue."""
        while self._prune_landings() > depth and self._landing_failed is None:
            try:
                self._landings[0].result(timeout=60)
            except Exception:  # noqa: BLE001 — failures surface via the flag
                pass

    def _check_landing_failure(self) -> None:
        if self._landing_failed is not None:
            raise self._landing_failed

    def _drain_landings(self) -> None:
        """Wait out every queued landing (FIFO), then surface any
        recorded failure on the calling thread."""
        while self._landings:
            self._landings.popleft().result()
        self._check_landing_failure()

    def _settle_landings(self) -> None:
        """Cleanup path: wait for queued landings without raising."""
        while self._landings:
            try:
                self._landings.popleft().result(timeout=60)
            except Exception:  # noqa: BLE001 — cleanup must not mask the cause
                pass

    def _finish_tail(
        self, handle, consumed, batch_time_ms, t0, trace,
        inflight_depth, stall_ms, backlog,
        requeue_on_error: bool = True,
    ) -> Dict[str, float]:
        """The batch tail behind the counts sync: land the
        background-streamed tables, run sinks, commit state, ack
        sources, emit metrics/conformance/alerts, checkpoint."""
        pm = self.protocol_monitor
        try:
            with trace.activate():
                land_t0 = time.time()
                with tracing.span("collect"):
                    datasets, metrics = handle.collect_tables()
                land_ms = (time.time() - land_t0) * 1000.0
                with tracing.span("sinks"):
                    self.dispatcher.dispatch(datasets, batch_time_ms)
                if pm is not None:
                    pm.record("SINK_EMIT", detail="dispatcher.dispatch")
                self.processor.commit()
                if pm is not None:
                    pm.record("POINTER_FLIP", detail="processor.commit")
                for name, s in self.sources.items():
                    s.ack()
                    if pm is not None:
                        pm.record("FIFO_ACK", source=name)
        except Exception as e:
            self.telemetry.track_exception(
                e, {"event": "error/streaming/process", "batchTime": batch_time_ms}
            )
            self.health.record_batch(
                batch_time_ms, ok=False, error=f"{type(e).__name__}: {e}"
            )
            trace.end(status="error")
            if requeue_on_error:
                for name, s in self.sources.items():
                    s.requeue_unacked()
                    if pm is not None:
                        pm.record("REQUEUE", source=name)
            if pm is not None:
                pm.seal_batch(batch_time_ms, failed=True)
            logger.exception("batch processing failed; rethrowing for retry")
            raise

        metrics["Latency-Batch"] = (time.time() - t0) * 1000.0
        metrics["IngestRateScale"] = self._rate_scale
        metrics["Pipeline_Depth"] = float(inflight_depth)
        metrics["Pipeline_Stall_Ms"] = stall_ms
        if backlog is not None:
            # background landing accounting: landings still queued when
            # this one was submitted (sustained > pipeline depth is the
            # default backlog alert), and the ms this batch's streamed
            # tables took to resolve on the landing thread
            metrics["Transfer_Background_Pending"] = float(backlog)
            metrics["Transfer_Background_LandMs"] = land_ms
        self.health.record_stall(stall_ms)
        # the calibrated machine profile rides every batch as Calib_*
        # gauges (constant per process — dashboards see the machine
        # model their roofline ratios are judged against)
        if self._calib_metrics:
            metrics.update(self._calib_metrics)
        # live HBM watermark (DX522's observation): the device
        # allocator's in-use/peak bytes, absent on backends that don't
        # report memory stats
        if self.hbm_sample:
            hbm = self.processor.device_memory_stats()
            if hbm is not None:
                metrics["Hbm_BytesInUse"] = float(
                    hbm.get("bytes_in_use") or 0.0
                )
                metrics["Hbm_PeakBytes"] = float(
                    hbm.get("peak_bytes_in_use") or 0.0
                )
        # per-stage latency percentiles from the live histograms — the
        # DATAX-<flow>:Latency-<Stage>-pNN series the dashboard's stat
        # tiles and stage timechart read (obs/histogram.py keeps these
        # exact over a bounded recent-sample window). Merged BEFORE the
        # conformance pass: the DX520 stage-time check judges the same
        # p50 series the dashboards render.
        for stage in MetricName.STAGES:
            stem = MetricName.stage_metric(stage)
            for q in (50, 95, 99):
                v = HISTOGRAMS.percentile(self.health.flow, stage, q)
                if v is not None:
                    metrics[f"{stem}-p{q}"] = v
        # model-vs-observed conformance: ratio gauges join this batch's
        # metrics; drift transitions become typed flight-recorder events
        # and store rows (obs/conformance.py)
        if self.conformance is not None:
            gauges, drift_events = self.conformance.observe(
                metrics, batch_time_ms
            )
            metrics.update(gauges)
            for ev in drift_events:
                props = ev.to_props()
                self.telemetry.track_event("conformance/drift", props)
                self.metric_logger.send_metric_events(
                    "Conformance_Drift", [props], batch_time_ms
                )
                logger.warning(
                    "conformance drift %s: %s", ev.code, ev.message
                )
        # finished profiler captures stitch into THIS batch's trace as
        # span events (the capture path is then one `obs trace` away
        # from the batches it overlapped) and bump the capture counter
        if self.profiler is not None:
            for cap in self.profiler.drain_finished():
                trace.record(
                    "profiler/capture", cap["startedTs"],
                    cap.get("durationMs") or 0.0, path=cap["path"],
                )
            if self.profiler.captures_count:
                metrics["Profiler_Captures_Count"] = float(
                    self.profiler.captures_count
                )
        if pm is not None:
            # Protocol_Events_Count for this batch's recorded prefix
            # (the post-ack checkpoint trio drains on the next batch)
            metrics.update(pm.drain_metric_deltas())
        self.telemetry.batch_end(batch_time_ms, {"latencyMs": metrics["Latency-Batch"]})
        self.metric_logger.send_batch_metrics(metrics, batch_time_ms)
        # alert evaluation AFTER the store flush so window aggregates
        # include this batch; the firing set rides the health payload
        # (readyz) and the Alerts_Firing series
        firing: List[dict] = []
        if self.alerts is not None:
            firing = self.alerts.evaluate()
            self.health.record_alerts(firing)
            self.metric_logger.send_metric(
                "Alerts_Firing", float(len(firing)), batch_time_ms
            )
        # fleet telemetry frame accumulation (obs/publisher.py): the
        # acked batch's metric deltas + consumed offset ranges fold
        # into the open window; record_batch is fail-open and
        # thread-safe (this tail may run on the landing thread)
        if self.fleet_publisher is not None:
            self.fleet_publisher.record_batch(
                metrics, consumed, batch_time_ms,
                health=self.health.health(), alerts=firing,
            )
        logger.info(
            "batch %d: %s",
            self.batches_processed + 1,
            " ".join(f"{k}={v:.1f}" for k, v in sorted(metrics.items())),
        )
        # DX53x state events (load fallback / both-sides-bad) land in
        # the flight recorder like conformance drift — typed, greppable
        self._drain_state_events()
        # runtime DX805: buffer-sanitizer poison hits join the recorder
        # the same way (and the Sanitizer_PoisonHit metric event stream)
        san = self.processor.buffer_sanitizer
        if san is not None:
            for ev in san.drain_events():
                try:
                    self.telemetry.track_event("sanitizer/poison", ev)
                    self.metric_logger.send_metric_events(
                        "Sanitizer_PoisonHit", [ev], batch_time_ms
                    )
                except Exception:  # noqa: BLE001 — telemetry never kills a batch
                    logger.exception("sanitizer event emit failed")
                logger.warning("buffer sanitizer %s", ev.get("message"))
        # runtime DX906: protocol-monitor ordering violations from
        # previously sealed batches join the recorder the same way
        if pm is not None:
            for ev in pm.drain_events():
                try:
                    self.telemetry.track_event("protocol/violation", ev)
                    self.metric_logger.send_metric_events(
                        "Protocol_Violation", [ev], batch_time_ms
                    )
                except Exception:  # noqa: BLE001 — telemetry never kills a batch
                    logger.exception("protocol event emit failed")
                logger.warning("protocol monitor %s", ev.get("message"))
        # dx-proto: post-commit at-least-once replay cursor: the window
        # snapshot + offset commit run AFTER the ack on purpose — a
        # crash between ack and checkpoint replays from the previous
        # offsets into rings that already hold the events (duplicates,
        # never loss)
        if self.checkpointer and (
            t0 - self._last_checkpoint >= self.checkpoint_interval_s
        ):
            with trace.activate(), tracing.span("checkpoint"):
                if self.window_checkpointer:
                    # snapshot BEFORE offsets: a crash between the two leaves
                    # old offsets + new rings, so replayed batches land in
                    # rings that already contain them (at-least-once
                    # duplicates); the reverse order would resume PAST events
                    # the restored rings never saw — a hole in window history
                    snap = self.processor.snapshot_window_state()
                    # armed sanitizer: a checkpoint must be REAL copies
                    # — shared memory with the live rings (or sentinel
                    # residue) is the PR 13 bug, caught before the
                    # snapshot is ever persisted
                    san = self.processor.buffer_sanitizer
                    if san is not None:
                        san.check_snapshot(
                            snap, self.processor.window_buffers
                        )
                    self.window_checkpointer.save(snap)
                    if pm is not None:
                        pm.record(
                            "DURABLE_WRITE",
                            detail="window_checkpointer.save",
                        )
                    if self.processor.state_mirror is not None:
                        # ship the owned window partitions (A/B + pointer
                        # per partition) so a rescale successor can pull
                        # exactly its assigned range — fail-closed: a
                        # dead store fails the batch, which requeues
                        self.processor.push_window_partitions(snap)
                        if pm is not None:
                            pm.record(
                                "STATE_PUSH",
                                detail="push_window_partitions",
                            )
                self.checkpointer.checkpoint_batch(consumed)
                if pm is not None:
                    pm.record(
                        "OFFSET_COMMIT", detail="checkpoint_batch",
                    )
            self._last_checkpoint = t0
            self.health.record_checkpoint()
        if pm is not None:
            pm.seal_batch(batch_time_ms)
        self.batches_processed += 1
        self.health.record_batch(
            batch_time_ms, ok=True, latency_ms=metrics["Latency-Batch"]
        )
        self.health.record_watermark(batch_time_ms)
        trace.end()
        return metrics

    def _traced_poll(self, trace):
        """Poll + encode under the batch's trace (the pipelined loop
        runs this on the decode-ahead worker thread, so the span needs
        explicit activation there)."""
        with trace.activate(), tracing.span("decode"):
            return self._poll_and_encode()

    def _dispatch_traced(self, trace, raw, batch_time_ms):
        """Dispatch under the batch's trace, marking the dispatch-done
        instant the later device-step span measures from."""
        trace.add(batchTime=batch_time_ms)
        if self.fleet_publisher is not None:
            # replica identity on every batch root span: what lets
            # `obs trace --stitch` group a shared flight recorder's
            # spans into the flow's cross-replica lineage segments
            trace.add(replica=self.fleet_publisher.replica)
        self.telemetry.batch_begin(batch_time_ms)
        with trace.activate(), tracing.span("dispatch"):
            handle = self.processor.dispatch_batch(raw, batch_time_ms)
        trace.mark("dispatch-done")
        return handle

    def _start_batch(self):
        """Poll + encode + dispatch one batch; a failure anywhere here
        (bad payload, re-trace error) requeues the polled batch so a
        later batch's ack can't release it unprocessed."""
        trace = self.tracer.begin("streaming/batch")
        try:
            raw, consumed, batch_time_ms, t0 = self._traced_poll(trace)
            handle = self._dispatch_traced(trace, raw, batch_time_ms)
        except Exception as e:
            self.health.record_batch(
                None, ok=False, error=f"{type(e).__name__}: {e}"
            )
            trace.end(status="error")
            for s in self.sources.values():
                s.requeue_unacked()
            raise
        return handle, consumed, batch_time_ms, t0, trace

    def _update_backpressure(self, busy_ms: float) -> None:
        """Adaptive backpressure on the loop's *busy* time (work per
        batch, pacing sleep excluded): overrunning the interval halves
        the next poll (down to 1/8 rate); fast batches recover gently.
        The static maxRate limiter stays the ceiling
        (EventHubStreamingFactory.scala:43)."""
        if busy_ms > self.interval_s * 1000.0:
            self._rate_scale = max(0.125, self._rate_scale * 0.5)
        elif busy_ms < self.interval_s * 500.0:
            self._rate_scale = min(1.0, self._rate_scale * 1.25)

    def run_batch(self) -> Dict[str, float]:
        """One micro-batch: poll -> encode -> device step -> sinks ->
        metrics -> checkpoint."""
        metrics = self._finish(*self._start_batch())
        # synchronous loop: the batch's own latency is the busy time
        self._update_backpressure(metrics["Latency-Batch"])
        if self.pilot is not None:
            self.pilot.tick(batch_time_ms=int(time.time() * 1000))
        return metrics

    def run(self, max_batches: Optional[int] = None) -> None:
        """Paced loop (streaming.intervalInSeconds cadence,
        StreamingHost.scala:66-67)."""
        try:
            while not self._stop:
                start = time.time()
                self.run_batch()
                if max_batches is not None and self.batches_processed >= max_batches:
                    break
                sleep = self.interval_s - (time.time() - start)
                if sleep > 0:
                    time.sleep(sleep)
        finally:
            self._stop_profiler()

    def _stop_profiler(self) -> None:
        """Close any in-flight on-demand capture so its trace flushes
        before the loop (or the process) goes away."""
        if self.profiler is not None:
            self.profiler.stop()

    def run_pipelined(
        self,
        max_batches: Optional[int] = None,
        depth: Optional[int] = None,
    ) -> None:
        """Unpaced loop with up to ``depth`` batches in flight (conf
        ``datax.job.process.pipeline.depth``, default 2): a decode-ahead
        worker thread polls + decodes batch N+1 (the C++ JSON decoder
        releases the GIL, so this genuinely overlaps) while the main
        thread dispatches batch N to the device and — once the window
        is full — finishes the OLDEST in-flight batch (collect + sinks
        + commit + ack). Throughput mode: the wall-clock per batch
        approaches max(decode, device, transport) instead of their sum,
        and at depth >= 2 a batch's D2H transfer and sink I/O hide
        under the device steps of the batches behind it.

        Ordering/recovery invariants at every depth:
        - finish/commit is strictly FIFO (the window is a deque popped
          from the left, and background landings run on ONE worker in
          submission order), so state-table commits, acks and offset
          checkpoints happen in dispatch order;
        - each batch joins its source's un-acked FIFO at poll time and
          is acked (in order) only after its own sinks succeed; a
          failure anywhere — including on the landing thread, with
          background transfers still in flight — drains the landing
          queue and requeues EVERY un-acked batch in the window before
          rethrowing (at-least-once);
        - a UDF ``on_interval`` refresh mid-window is safe: every
          ``PendingBatch`` snapshots the pipeline/schemas of the step
          that produced it, so deep windows decode against their own
          compiled shapes.

        With ``process.pipeline.backgroundtransfer`` (default on) each
        finish blocks only on the counts vector; the streamed output
        tables land and sinks ack on the background landing thread,
        bounded to at most ``depth`` queued landings (backpressure)."""
        if depth is None:
            # resume from the COMMANDED depth: a pilot retarget from an
            # earlier run persists across loop restarts (== the conf'd
            # depth until the pilot ever actuates)
            depth = self.live_depth()
        depth = max(1, depth)
        self._depth_target = None
        self._live_depth = depth
        background = self.background_transfer and self._landing_pool is not None
        # FIFO window of (PendingBatch, consumed, batch_time_ms, t0, trace)
        pending = deque()
        pool = ThreadPoolExecutor(1)
        fut = None
        fut_trace = None  # the trace of the batch `fut` is decoding
        # batches started over the host's lifetime: landings may lag
        # batches_processed, so the loop counts dispatches itself
        # (previous runs' landings are fully drained at this point)
        started = self.batches_processed
        self._landing_failed = None

        def drain(f):
            """Wait out an in-flight poll so its delivery lands in the
            un-acked FIFO BEFORE any requeue — abandoning it would
            strand a polled batch in _inflight, where a later ack would
            release (and for Kafka, commit) it unprocessed."""
            if f is None:
                return
            try:
                f.result(timeout=60)
            except Exception:  # noqa: BLE001 — failed poll requeued below
                pass

        try:
            while not self._stop:
                # a failed background landing surfaces here: stop
                # feeding the device and run the whole-window requeue
                self._check_landing_failure()
                if max_batches is not None and started >= max_batches:
                    break
                iter_t0 = time.time()
                if fut is None:
                    fut_trace = self.tracer.begin("streaming/batch")
                    fut = pool.submit(self._traced_poll, fut_trace)
                raw, consumed, batch_time_ms, t0 = fut.result()
                trace, fut, fut_trace = fut_trace, None, None
                handle = self._dispatch_traced(trace, raw, batch_time_ms)
                started += 1
                # decode-ahead: the NEXT batch's poll starts now,
                # overlapping this window's collects + sinks — but only
                # if a next iteration will actually run
                if not self._stop and (
                    max_batches is None or started < max_batches
                ):
                    fut_trace = self.tracer.begin("streaming/batch")
                    fut = pool.submit(self._traced_poll, fut_trace)
                pending.append((handle, consumed, batch_time_ms, t0, trace))
                # a pilot depth retarget lands here, at the window
                # boundary: shrinking drains the FIFO below, growing
                # just admits more batches — either way commit order
                # and the requeue window are the ordinary ones
                depth = self._current_depth(depth)
                while len(pending) > depth:
                    # window full: retire the oldest batch (strict
                    # FIFO). depth=1 is the legacy single-`pending`
                    # overlap: finish N-1 right after dispatching N.
                    # In background mode this blocks only on the counts
                    # vector; the tail lands out-of-band.
                    self._finish(
                        *pending.popleft(), inflight_depth=len(pending) + 1,
                        background=background,
                    )
                    self._wait_landing_backlog(depth)
                # backpressure on iteration time, not Latency-Batch: a
                # pipelined batch's latency spans ~depth iterations by
                # design
                self._update_backpressure((time.time() - iter_t0) * 1000.0)
                if self.pilot is not None:
                    self.pilot.tick(batch_time_ms=batch_time_ms)
            while pending and not self._stop:
                self._check_landing_failure()
                self._finish(
                    *pending.popleft(), inflight_depth=len(pending) + 1,
                    background=background,
                )
            # all tails must land before the loop returns (or reports
            # the failure): collect/sink/ack work is only done when the
            # landing queue is empty
            self._drain_landings()
        except Exception:
            # settle the in-flight poll FIRST, then the landing queue
            # (queued landings after a failure no-op and leave their
            # batches un-acked), then requeue everything un-acked
            # across the whole window (covers poll/dispatch failures;
            # _finish requeues its own failures before rethrowing, and
            # requeue_unacked is idempotent)
            drain(fut)
            fut = None
            if fut_trace is not None:
                fut_trace.end(status="aborted")
            for item in pending:
                item[4].end(status="aborted")  # idempotent
                item[0].abandon()  # release transfer slots
            self._settle_landings()
            for s in self.sources.values():
                s.requeue_unacked()
            raise
        finally:
            drain(fut)
            if fut_trace is not None:
                fut_trace.end(status="aborted")  # idempotent
            pool.shutdown(wait=False, cancel_futures=True)
            self._stop_profiler()

    def stop(self, close_sources: bool = True) -> None:
        """``close_sources=False`` tears the host down but leaves its
        sources open — the chaos preemption drill's 'killed process':
        a successor host takes over the surviving source/checkpoint
        state the way a rescheduled job takes over its partitions."""
        self._stop = True
        self._stop_profiler()
        if self._landing_pool is not None:
            # let queued landings flush their sinks/acks before the
            # dispatcher and sources close underneath them
            self._settle_landings()
            self._landing_pool.shutdown(wait=True)
            self._landing_pool = None
        if self.fleet_publisher is not None:
            # ship the tail window with the final drain marker — the
            # fleet view's clean-shutdown signal (a replica that dies
            # before this goes DX542-stale instead)
            self.fleet_publisher.flush(final=True)
        if self.obs_server is not None:
            self.obs_server.stop()
            self.obs_server = None
        self.dispatcher.close()
        if close_sources:
            for s in self.sources.values():
                s.close()


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    args = argv if argv is not None else sys.argv[1:]
    named = {
        a.split("=", 1)[0]: a.split("=", 1)[1] for a in args if "=" in a
    }
    ConfigManager.reset()
    ConfigManager.get_configuration_from_arguments(args)
    d = ConfigManager.load_config()
    host = StreamingHost(d)
    max_batches = int(named["batches"]) if "batches" in named else None
    logger.info(
        "starting flow %s (interval=%ss, capacity=%s)",
        d.get_job_name(), host.interval_s, host.processor.batch_capacity,
    )
    host.run(max_batches)


if __name__ == "__main__":
    main()
