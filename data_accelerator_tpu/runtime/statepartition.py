"""Key-range state partitions: the unit of stateful rescale.

reference: the reference platform's cross-batch accumulators are whole
A/B Parquet tables with one active/standby pointer per table
(StateTableHandler.scala:99-125) and its jobs are fixed-size — state
never moves. Here every stateful surface (accumulator tables,
TIMEWINDOW ring snapshots) is hashed onto a small conf'd number of
key-range partitions (``datax.job.process.state.partitions``, default
16); each replica owns a CONTIGUOUS partition range, so a rescale is a
partition handoff (the successor pulls only the partitions the new map
assigns it), not a state loss.

Pieces:

- **hashing** (``partition_ids``): a splitmix64-style finalizer over
  the key column — deterministic across processes and restarts (python
  ``hash()`` is salted; this must not be), vectorized in numpy, with
  string keys hashed by their decoded utf-8 (dictionary ids are
  process-local and must never leak into placement).
- **ownership** (``owned_partitions`` / ``partition_map``): the
  contiguous balanced split of P partitions over N replicas — replica
  i's range only shrinks/grows at the EDGES as N changes, which is
  what keeps a rescale's handoff set small (the consistent-hash
  property restated for contiguous ranges).
- **snapshot stores**: the per-partition A/B + pointer layout
  (``<prefix>/p<NN>/{A,B}/<file>`` + ``<prefix>/p<NN>/pointer``) over
  two backends — the local filesystem (power-loss durable: tmp-write +
  fsync + directory fsync, ``runtime/checkpoint._durable_replace``)
  and the shared object store (``objstore://`` — what lets a successor
  replica on ANOTHER host warm its partitions). Object-store I/O is
  **fail-closed**: state is correctness, so push/pull retries
  (bounded, jittered — serve/objectstore.py) and then raises; the
  batch fails and the un-acked window requeues rather than committing
  a pointer whose snapshot never landed.
- **window split/merge**: a window-state snapshot
  (``FlowProcessor.snapshot_window_state``) splits into per-partition
  snapshots by hashing the key column per ring row, and partitions
  from SEVERAL predecessors (a scale-down) merge back — rows re-packed
  per slot, timestamps rebased across differing batch bases, string
  ids remapped through each source's own dictionary.
"""

from __future__ import annotations

import io
import logging
import os
import zlib
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

logger = logging.getLogger(__name__)

# conf datax.job.process.state.partitions — small on purpose: a
# partition is the handoff granularity, not a parallelism unit, and
# P >> max replicas keeps every contiguous range balanced within one
DEFAULT_STATE_PARTITIONS = 16

SIDES = ("A", "B")


class SnapshotStoreError(IOError):
    """A state-snapshot store operation failed permanently (after the
    bounded retries). Fail-closed: callers let this propagate so the
    batch requeues instead of committing state that never landed."""


def other_side(side: str) -> str:
    return "B" if side == "A" else "A"


# ---------------------------------------------------------------------------
# Hashing
# ---------------------------------------------------------------------------
_MIX1 = np.uint64(0xFF51AFD7ED558CCD)
_MIX2 = np.uint64(0xC4CEB9FE1A85EC53)
_S33 = np.uint64(33)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64/murmur3 finalizer — a deterministic avalanche so
    adjacent keys don't land in adjacent partitions."""
    with np.errstate(over="ignore"):
        x = x.astype(np.uint64, copy=True)
        x ^= x >> _S33
        x *= _MIX1
        x ^= x >> _S33
        x *= _MIX2
        x ^= x >> _S33
    return x


def _string_hash(s: str) -> int:
    # crc32 over utf-8: stable across processes (unlike hash()), cheap,
    # and fed through the mixer below so its distribution doesn't matter
    return zlib.crc32(s.encode("utf-8"))


def partition_ids(
    values: np.ndarray,
    partitions: int,
    kind: str = "long",
    dictionary=None,
) -> np.ndarray:
    """Per-row partition id for a key column. ``kind`` follows the
    ViewSchema vocabulary; ``string`` columns carry dictionary ids and
    need the dictionary to hash the DECODED value (ids are assigned in
    encounter order per process — hashing them raw would scatter the
    same logical key across partitions between restarts)."""
    a = np.asarray(values)
    if kind == "string":
        if dictionary is None:
            raise ValueError("string partition keys need the dictionary")
        ids = a.astype(np.int64)
        uniq = np.unique(ids)
        lut = {
            int(i): _string_hash(dictionary.decode(int(i)) or "")
            for i in uniq
        }
        h = np.array([lut[int(i)] for i in ids.ravel()], dtype=np.uint64)
        h = h.reshape(ids.shape)
    elif kind == "double" or a.dtype.kind == "f":
        h = a.astype(np.float32).view(np.uint32).astype(np.uint64)
    elif kind == "boolean" or a.dtype.kind == "b":
        h = a.astype(np.uint64)
    else:
        h = a.astype(np.int64).view(np.uint64)
    return (_mix64(h) % np.uint64(max(1, int(partitions)))).astype(np.int64)


def partition_of(value, partitions: int, kind: str = "long",
                 dictionary=None) -> int:
    """Scalar convenience over ``partition_ids``."""
    if kind == "string" and isinstance(value, str):
        h = np.array([_string_hash(value)], dtype=np.uint64)
        return int(_mix64(h)[0] % np.uint64(max(1, int(partitions))))
    return int(partition_ids(np.array([value]), partitions, kind,
                             dictionary)[0])


# ---------------------------------------------------------------------------
# Ownership
# ---------------------------------------------------------------------------
def owned_partitions(
    replica_index: int, replica_count: int, partitions: int
) -> List[int]:
    """The contiguous partition range replica ``replica_index`` (1-based)
    owns out of ``partitions`` under ``replica_count`` replicas: the
    balanced split where the first ``P % N`` replicas take one extra.
    Every partition is owned by exactly one replica; ranges only move
    at their edges as N changes."""
    if replica_count < 1 or not 1 <= replica_index <= replica_count:
        raise ValueError(
            f"replica index {replica_index} out of range 1..{replica_count}"
        )
    if partitions < 1:
        raise ValueError(f"partitions must be >= 1, got {partitions}")
    base, extra = divmod(partitions, replica_count)
    start = (replica_index - 1) * base + min(replica_index - 1, extra)
    size = base + (1 if replica_index <= extra else 0)
    return list(range(start, start + size))


def partition_map(replica_count: int, partitions: int) -> Dict[int, List[int]]:
    """replica index (1-based) -> owned partition list, covering every
    partition exactly once."""
    return {
        i: owned_partitions(i, replica_count, partitions)
        for i in range(1, max(1, replica_count) + 1)
    }


def reassigned_partitions(
    old_map: Dict, new_map: Dict
) -> List[int]:
    """Partitions whose owner changed between two maps (the handoff
    set of a rescale). Keys may be int or str (JSON round-trip)."""
    def owner_of(m):
        out = {}
        for idx, parts in m.items():
            for p in parts:
                out[int(p)] = int(idx)
        return out

    old_o, new_o = owner_of(old_map), owner_of(new_map)
    return sorted(
        p for p in new_o if old_o.get(p) is not None and old_o[p] != new_o[p]
    ) + sorted(p for p in new_o if p not in old_o and len(old_o) > 0)


# ---------------------------------------------------------------------------
# Snapshot stores: per-partition A/B + pointer over two backends
# ---------------------------------------------------------------------------
class LocalSnapshotStore:
    """The on-disk partition layout with the checkpointers' power-loss
    durability: every file lands via tmp-write + fsync +
    ``_durable_replace`` (file AND directory fsynced), and the pointer
    commit — the exactly-once point — gets the same treatment."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _dir(self, prefix: str, side: Optional[str] = None) -> str:
        return os.path.join(self.root, prefix, side) if side else \
            os.path.join(self.root, prefix)

    def put_files(self, prefix: str, side: str,
                  files: Dict[str, bytes]) -> None:
        from .checkpoint import _durable_replace

        d = self._dir(prefix, side)
        os.makedirs(d, exist_ok=True)
        for fn, data in files.items():
            path = os.path.join(d, fn)
            tmp = path + ".tmp"
            with open(tmp, "wb") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            _durable_replace(tmp, path)

    def get_file(self, prefix: str, side: str, name: str) -> Optional[bytes]:
        try:
            with open(os.path.join(self._dir(prefix, side), name), "rb") as f:
                return f.read()
        except (FileNotFoundError, NotADirectoryError, IsADirectoryError):
            return None

    def put_pointer(self, prefix: str, side: str) -> None:
        from .checkpoint import _durable_replace

        d = self._dir(prefix)
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, "pointer")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(side)
            f.flush()
            os.fsync(f.fileno())
        _durable_replace(tmp, path)

    def get_pointer(self, prefix: str) -> Optional[str]:
        try:
            with open(os.path.join(self._dir(prefix), "pointer"),
                      encoding="utf-8") as f:
                p = f.read().strip()
                return p if p in SIDES else None
        except (FileNotFoundError, NotADirectoryError):
            return None


class ObjstoreSnapshotStore:
    """The same partition layout over the shared object store — what a
    successor replica on another host pulls its assigned partitions
    from. FAIL-CLOSED: the underlying client already retries transient
    failures with bounded jittered backoff (serve/objectstore.py); a
    still-failing operation raises ``SnapshotStoreError`` so the
    caller's batch requeues instead of acking state that never shipped
    (contrast the compile cache, which fails OPEN — a cold compile
    beats a dead host, but silently dropped state does not)."""

    def __init__(self, url: str, token: Optional[str] = None):
        from ..compile.aotcache import _parse_objstore_url
        from ..serve.objectstore import ObjectStoreClient

        endpoint, bucket, prefix = _parse_objstore_url(url)
        token = token or os.environ.get("DATAX_OBJSTORE_TOKEN")
        self.url = url
        self._client = ObjectStoreClient(endpoint, bucket, token=token)
        self._prefix = prefix

    def _key(self, prefix: str, *rest: str) -> str:
        parts = [p for p in (self._prefix, prefix) + rest if p]
        return "/".join(parts)

    def put_files(self, prefix: str, side: str,
                  files: Dict[str, bytes]) -> None:
        try:
            for fn, data in files.items():
                self._client.put(self._key(prefix, side, fn), data)
        except Exception as e:
            raise SnapshotStoreError(
                f"state snapshot push {prefix}/{side} failed: {e}"
            ) from e

    def get_file(self, prefix: str, side: str, name: str) -> Optional[bytes]:
        try:
            return self._client.get(self._key(prefix, side, name))
        except Exception as e:
            raise SnapshotStoreError(
                f"state snapshot pull {prefix}/{side}/{name} failed: {e}"
            ) from e

    def put_pointer(self, prefix: str, side: str) -> None:
        try:
            self._client.put(self._key(prefix, "pointer"), side.encode())
        except Exception as e:
            raise SnapshotStoreError(
                f"state pointer commit {prefix} failed: {e}"
            ) from e

    def get_pointer(self, prefix: str) -> Optional[str]:
        try:
            data = self._client.get(self._key(prefix, "pointer"))
        except Exception as e:
            raise SnapshotStoreError(
                f"state pointer read {prefix} failed: {e}"
            ) from e
        if data is None:
            return None
        p = data.decode("utf-8", "replace").strip()
        return p if p in SIDES else None


# ---------------------------------------------------------------------------
# Window snapshot split / merge
# ---------------------------------------------------------------------------
def snapshot_to_bytes(snap: Dict) -> bytes:
    """Serialize a window-state snapshot dict (the
    ``snapshot_window_state`` shape) to npz bytes — the per-partition
    payload the snapshot stores ship."""
    from .checkpoint import snapshot_arrays

    buf = io.BytesIO()
    np.savez(buf, **snapshot_arrays(snap))
    return buf.getvalue()


def snapshot_from_bytes(data: bytes) -> Dict:
    """Parse npz bytes back into a snapshot dict. Raises on a corrupt
    or truncated payload — the caller's cue to fall back to the
    standby side."""
    from .checkpoint import arrays_to_snapshot

    with np.load(io.BytesIO(data)) as z:
        return arrays_to_snapshot(z)


def split_window_snapshot(
    snap: Dict,
    partitions: int,
    key_cols: Dict[str, Tuple[str, str]],
    dictionary=None,
    only: Optional[Sequence[int]] = None,
) -> Dict[int, Dict]:
    """Split one window snapshot into per-partition snapshots.

    ``key_cols``: ring table -> (key column, kind). Rows of a table
    with no usable key column all land in partition 0 (documented —
    an unkeyed window can't follow a key-range handoff any finer).
    Each partition snapshot is COMPACTED to its member rows (re-packed
    per slot, capacity truncated to the widest slot) — the merge
    re-packs rows anyway, so the positions don't need to survive, and
    the mirror push ships O(member rows) per partition instead of P
    copies of the entire ring. The original ring capacity rides along
    as ``cap`` per table so the merge can rebuild the full shape."""
    want = set(int(p) for p in only) if only is not None else None
    targets = [
        p for p in range(partitions) if want is None or p in want
    ]
    out: Dict[int, Dict] = {
        p: {
            "rings": {},
            "slot_counter": snap.get("slot_counter", 0),
            "base_ms": snap.get("base_ms"),
            "dictionary": snap.get("dictionary"),
        }
        for p in targets
    }
    for table, ring in snap.get("rings", {}).items():
        valid = np.asarray(ring["valid"])
        cols = {c: np.asarray(a) for c, a in ring["cols"].items()}
        k_slots, cap = valid.shape
        kc = key_cols.get(table)
        pids = None
        if kc is not None and kc[0] in cols:
            pids = partition_ids(
                cols[kc[0]], partitions, kc[1], dictionary=dictionary,
            )
        for p in targets:
            if pids is not None:
                member = valid & (pids == p)
            else:
                member = valid if p == 0 else np.zeros_like(valid)
            new_cap = int(member.sum(axis=1).max()) if k_slots else 0
            p_cols = {
                c: np.zeros((k_slots, new_cap), dtype=a.dtype)
                for c, a in cols.items()
            }
            p_valid = np.zeros((k_slots, new_cap), dtype=bool)
            for k in range(k_slots):
                idx = np.nonzero(member[k])[0]
                n = int(idx.size)
                if n:
                    for c, a in cols.items():
                        p_cols[c][k, :n] = a[k][idx]
                    p_valid[k, :n] = True
            out[p]["rings"][table] = {
                "cols": p_cols, "valid": p_valid, "cap": int(cap),
            }
    return out


def merge_window_snapshots(
    parts: List[Dict],
    schema_types: Dict[str, Dict[str, str]],
    dictionary,
    ts_col: Optional[str],
) -> Optional[Dict]:
    """Merge per-partition window snapshots — possibly from SEVERAL
    predecessor replicas (a scale-down) — into one restorable snapshot.

    Rows are re-packed per ring slot (positions from different
    predecessors collide, so a positional union would lose rows),
    relative timestamps are rebased onto the newest predecessor's batch
    base, and string-typed ring ids are remapped through each source
    snapshot's OWN dictionary into the live one — the merged snapshot
    carries ``dictionary: None`` because its ids are already live.
    Rows past a slot's capacity are dropped oldest-last (counted in
    the returned snapshot's ``dropped_rows``)."""
    parts = [p for p in parts if p and p.get("rings")]
    if not parts:
        return None
    bases = [p.get("base_ms") for p in parts if p.get("base_ms") is not None]
    base_target = max(bases) if bases else None
    first = parts[0]["rings"]
    out_rings: Dict[str, Dict] = {}
    fill: Dict[str, np.ndarray] = {}
    for table, ring in first.items():
        # partition snapshots are compacted to their member rows
        # (split_window_snapshot); the FULL ring shape is rebuilt from
        # the ``cap`` each carries (whole, uncompacted snapshots fall
        # back to their own width)
        same = [
            p["rings"][table] for p in parts if table in p.get("rings", {})
        ]
        k_slots = max(np.asarray(r["valid"]).shape[0] for r in same)
        cap = max(
            int(r.get("cap", np.asarray(r["valid"]).shape[1]))
            for r in same
        )
        out_rings[table] = {
            "cols": {
                c: np.zeros((k_slots, cap), dtype=np.asarray(a).dtype)
                for c, a in ring["cols"].items()
            },
            "valid": np.zeros((k_slots, cap), dtype=bool),
        }
        fill[table] = np.zeros(k_slots, dtype=np.int64)
    dropped = 0
    for part in parts:
        delta = 0
        if base_target is not None and part.get("base_ms") is not None:
            delta = int(part["base_ms"]) - int(base_target)
        src_dict = part.get("dictionary")
        id_map: Dict[int, int] = {}
        if src_dict is not None:
            # source id i (1-based over entries) -> live id
            for i, s in enumerate(src_dict):
                id_map[i + 1] = dictionary.encode(s)
        for table, ring in part.get("rings", {}).items():
            if table not in out_rings:
                continue
            types = schema_types.get(table, {})
            dst = out_rings[table]
            valid = np.asarray(ring["valid"])
            k_slots = valid.shape[0]
            cap = dst["valid"].shape[1]  # room in the REBUILT ring,
            # not the source part's compacted width
            for k in range(min(k_slots, fill[table].shape[0])):
                idx = np.nonzero(valid[k])[0]
                if idx.size == 0:
                    continue
                n0 = int(fill[table][k])
                room = cap - n0
                if idx.size > room:
                    dropped += int(idx.size - room)
                    idx = idx[:room]
                n = idx.size
                if n == 0:
                    continue
                for c, a in ring["cols"].items():
                    if c not in dst["cols"]:
                        continue
                    vals = np.asarray(a)[k][idx]
                    if c == ts_col and delta:
                        vals = vals + np.int32(delta)
                    elif types.get(c) == "string" and id_map:
                        vals = np.array(
                            [id_map.get(int(v), 0) for v in vals],
                            dtype=np.asarray(a).dtype,
                        )
                    dst["cols"][c][k, n0:n0 + n] = vals
                dst["valid"][k, n0:n0 + n] = True
                fill[table][k] = n0 + n
    return {
        "rings": out_rings,
        "slot_counter": max(int(p.get("slot_counter", 0)) for p in parts),
        "base_ms": base_target,
        "dictionary": None,  # ids already remapped into the live dictionary
        "dropped_rows": dropped,
    }
