"""Streaming/batch input sources.

reference: datax-host input/ package —
- LocalStreamingSource.scala:19-41: random JSON from the input schema (the
  no-cloud "one-box" source) -> ``LocalSource`` here, with a vectorized
  column fast path for high event rates.
- BlobBatchingHost.scala:28-53: ``{yyyy-MM-dd}`` path-pattern expansion
  over a time window for batch jobs -> ``expand_time_patterns`` +
  ``FileSource`` (local filesystem stands in for WASB/ADLS).
- EventHub/Kafka direct streams -> ``SocketSource`` (newline-JSON over
  TCP, the DCN ingest path) and a Kafka stub gated on library presence.

Sources produce (events, consumed-offsets); offsets feed the
OffsetCheckpointer for at-least-once resume.
"""

from __future__ import annotations

import glob
import json
import os
import re
import socket
import threading
import time
from datetime import datetime, timedelta, timezone
from typing import Dict, Iterator, List, Optional, Tuple

from ..core.schema import Schema, StringDictionary
from ..utils import fs
from ..utils.datagen import DataGenerator

Offsets = Dict[Tuple[str, int], Tuple[int, int]]


class UnackedFifo:
    """The at-least-once delivery ledger shared by buffering sources:
    every delivered batch is held until its in-order ``ack``; a failure
    puts all un-acked batches back for re-delivery. Thread-safe — the
    pipelined host acks from the same thread it polls, but socket
    readers touch adjacent state under the same discipline."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: List = []
        self._redeliver: List = []

    def next_redelivery(self):
        """The oldest requeued batch, or None (caller then polls fresh
        data; either way the result must be ``deliver``-ed)."""
        with self._lock:
            return self._redeliver.pop(0) if self._redeliver else None

    def deliver(self, item) -> None:
        with self._lock:
            self._inflight.append(item)

    def ack_oldest(self):
        """Release and return the oldest in-flight batch (None if empty)."""
        with self._lock:
            return self._inflight.pop(0) if self._inflight else None

    def requeue_all(self) -> None:
        with self._lock:
            self._redeliver = self._inflight + self._redeliver
            self._inflight = []


class StreamingSource:
    """Interface: poll() returns (rows, consumed offsets)."""

    name: str = "source"

    def start(self, positions: Dict[Tuple[str, int], int]) -> None:
        """Apply checkpointed starting positions (source, partition)->seq."""

    def poll(self, max_events: int) -> Tuple[List[dict], Offsets]:
        raise NotImplementedError

    def ack(self) -> None:
        """Oldest un-acked batch fully processed + sunk: the source may
        release events it retained for retry. Called once per polled
        batch, in order — a pipelined host may hold several un-acked
        batches in flight."""

    def requeue_unacked(self) -> None:
        """A batch failed: put every un-acked batch back so the next
        polls re-deliver them in order (at-least-once within process)."""

    def close(self) -> None:
        pass


class LocalSource(StreamingSource):
    """Schema-driven random event generator (one-box source).

    reference: LocalStreamingSource.scala:19-41 (500 ms cadence there;
    here rate-controlled by maxRate like the EventHub path's rate limiter,
    EventHubStreamingFactory.scala:43).
    """

    def __init__(self, schema: Schema, name: str = "local", seed: Optional[int] = None):
        self.name = name
        self.schema = schema
        self.gen = DataGenerator(schema, seed)
        self._seq = 0

    def start(self, positions) -> None:
        self._seq = positions.get((self.name, 0), 0)

    def poll(self, max_events: int) -> Tuple[List[dict], Offsets]:
        now_ms = int(time.time() * 1000)
        rows = self.gen.random_rows(max_events, now_ms=now_ms)
        frm = self._seq
        self._seq += len(rows)
        return rows, {(self.name, 0): (frm, self._seq)}

    def poll_columns(self, max_events: int, dictionary: StringDictionary):
        """Vectorized fast path: encoded numpy columns, no row dicts."""
        now_ms = int(time.time() * 1000)
        cols = self.gen.random_columns(max_events, dictionary, now_ms=now_ms)
        frm = self._seq
        self._seq += max_events
        return cols, now_ms, {(self.name, 0): (frm, self._seq)}


_TIME_TOKEN_RE = re.compile(r"\{([^}]+)\}")

_FMT_MAP = [
    ("yyyy", "%Y"), ("MM", "%m"), ("dd", "%d"),
    ("HH", "%H"), ("mm", "%M"), ("ss", "%S"),
]


def _java_fmt_to_strftime(fmt: str) -> str:
    for java, py in _FMT_MAP:
        fmt = fmt.replace(java, py)
    return fmt


def expand_time_patterns(
    pattern: str, start: datetime, end: datetime, increment: timedelta
) -> List[str]:
    """Expand ``.../{yyyy-MM-dd}/{HH}/...`` over [start, end].

    reference: BlobBatchingHost.scala:28-53 getInputBlobPathPrefixes.
    """
    out: List[str] = []
    seen = set()
    t = start
    while t <= end:
        path = _TIME_TOKEN_RE.sub(
            lambda m: t.strftime(_java_fmt_to_strftime(m.group(1))), pattern
        )
        if path not in seen:
            seen.add(path)
            out.append(path)
        t = t + increment
    return out


def read_json_file(path: str) -> List[dict]:
    """Read newline-delimited JSON via the fs chokepoint (gzip-aware,
    HadoopClient.scala gzip read)."""
    return [
        json.loads(line)
        for line in fs.read_lines(path)
        if line.strip()
    ]


class FileSource(StreamingSource):
    """Batch/streaming source over local files matching glob patterns
    (the blob-input analog). In streaming mode remembers which files were
    already consumed (sequence number = file index in sorted order)."""

    def __init__(self, patterns: List[str], name: str = "files"):
        self.name = name
        self.patterns = patterns
        self._consumed: set = set()
        self._leftover: List[dict] = []
        self._resume_skip = 0

    def start(self, positions: Dict[Tuple[str, int], int]) -> None:
        """Resume: the checkpointed offset is the count of fully-emitted
        files in sorted order; skip that many on the first listing."""
        self._resume_skip = positions.get((self.name, 0), 0)

    def list_files(self) -> List[str]:
        files: List[str] = []
        for p in self.patterns:
            files.extend(glob.glob(p))
        return sorted(set(files))

    def poll(self, max_events: int) -> Tuple[List[dict], Offsets]:
        """Rows beyond max_events carry over to the next poll — a file is
        only offset-committed once fully emitted (at-least-once)."""
        rows: List[dict] = self._leftover
        self._leftover = []
        if self._resume_skip and not self._consumed:
            self._consumed.update(self.list_files()[: self._resume_skip])
            self._resume_skip = 0
        n_before = len(self._consumed)
        for f in self.list_files():
            if f in self._consumed or len(rows) >= max_events:
                continue
            self._consumed.add(f)
            rows.extend(read_json_file(f))
        self._leftover = rows[max_events:]
        committed = (
            len(self._consumed) if not self._leftover else len(self._consumed) - 1
        )
        return rows[:max_events], {
            (self.name, 0): (n_before, committed)
        }


class SocketSource(StreamingSource):
    """Newline-delimited JSON over TCP — the ingest-over-DCN stand-in for
    the EventHub/Kafka receivers. A background thread accepts connections
    and buffers events; poll() drains up to max_events."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0, name: str = "socket"):
        self.name = name
        self._buf: List[bytes] = []
        # un-acked delivered batches (from_seq, lines); ack() releases
        # the oldest — a pipelined host holds several in flight
        self._fifo = UnackedFifo()
        self._lock = threading.Lock()
        self._seq = 0
        self._server = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._server.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._server.bind((host, port))
        self._server.listen(4)
        self.port = self._server.getsockname()[1]
        self._closing = False
        self._thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._thread.start()

    def _accept_loop(self):
        while not self._closing:
            try:
                conn, _ = self._server.accept()
            except OSError:
                return
            threading.Thread(
                target=self._reader, args=(conn,), daemon=True
            ).start()

    def _reader(self, conn):
        with conn:
            f = conn.makefile("rb")
            for line in f:
                line = line.strip()
                if not line:
                    continue
                with self._lock:
                    self._buf.append(line)

    def poll_raw(self, max_events: int) -> Tuple[bytes, int, Offsets]:
        """Drain up to max_events raw JSON lines as one newline-joined
        blob for the native decoder — no per-event Python parse.

        Delivered lines join an in-flight FIFO until their ``ack()``;
        after ``requeue_unacked()`` (a failed batch) the next polls
        re-deliver the un-acked batches in order (at-least-once within
        the process; cross-restart replay needs a replayable upstream
        like the file/blob source)."""
        requeued = self._fifo.next_redelivery()
        if requeued is not None:
            frm, lines = requeued
        else:
            with self._lock:
                lines = self._buf[:max_events]
                self._buf = self._buf[max_events:]
                frm = self._seq
                self._seq += len(lines)
        self._fifo.deliver((frm, lines))
        blob = b"\n".join(lines) + (b"\n" if lines else b"")
        return blob, len(lines), {(self.name, 0): (frm, frm + len(lines))}

    def ack(self) -> None:
        self._fifo.ack_oldest()

    def requeue_unacked(self) -> None:
        self._fifo.requeue_all()

    def poll(self, max_events: int) -> Tuple[List[dict], Offsets]:
        blob, n, offsets = self.poll_raw(max_events)
        rows = []
        for line in blob.splitlines():
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue
        return rows, offsets

    def close(self):
        self._closing = True
        try:
            self._server.close()
        except OSError:
            pass


class BlobPointerSource(StreamingSource):
    """Streaming input of *pointer* events ``{"BlobPath": ...}`` whose
    referenced files hold the actual event rows.

    reference: input/BlobPointerInput.scala:30-160 — EventHub events carry
    blob paths; the engine extracts a source id per path by regex
    (``extractSourceId``), drops out-of-scope paths (``filterPathGroups``),
    extracts the file time from the path (``extractTimeFromBlobPath``
    with ``fileTimeRegex``/``fileTimeFormat``), then reads the files.

    Here the pointer stream rides any inner StreamingSource (socket for
    DCN ingest, file for replay); referenced files are read host-side,
    gzip-aware. Each emitted row gains the reserved ``__DataX_FileInfo``
    field with {path, sourceId, target, fileTimeMs} so projections and
    per-source routing can use it (ColumnName.InternalColumnFileInfo).
    """

    def __init__(
        self,
        inner: StreamingSource,
        sources: Dict[str, str],
        source_id_regex: str = r"/([\w\d]+)/[^/]*$",
        file_time_regex: str = r"(\d{4}-\d{2}-\d{2}[T_ ][\d_:]+(?:\.\d+)?)",
        file_time_format: Optional[str] = None,
        name: str = "blobpointer",
    ):
        self.name = name
        self.inner = inner
        self.sources = sources  # source id -> target label
        self.source_id_re = re.compile(source_id_regex)
        self.file_time_re = re.compile(file_time_regex)
        self.file_time_format = file_time_format
        self.out_of_scope = 0

    def start(self, positions) -> None:
        self.inner.start(positions)

    def ack(self) -> None:
        # dx-proto: requeue-upstream delegating wrapper: the host's
        # batch tail owns the failure handler and requeues via
        # requeue_unacked() below
        self.inner.ack()

    def requeue_unacked(self) -> None:
        self.inner.requeue_unacked()

    def close(self) -> None:
        self.inner.close()

    def extract_source_id(self, path: str) -> Optional[str]:
        m = self.source_id_re.search(path)
        return m.group(1) if m else None

    def extract_file_time_ms(self, path: str) -> Optional[int]:
        m = self.file_time_re.search(path)
        if not m:
            return None
        text = m.group(1)
        try:
            if self.file_time_format:
                t = datetime.strptime(text, _java_fmt_to_strftime(self.file_time_format))
            else:
                # reference: Timestamp.valueOf(str.replace('_',':').replace('T',' '))
                # — but normalize the date/time separator first so paths
                # like 2024-03-01_12_30_00 parse (the default regex
                # accepts T/_/space there)
                iso = text[:10] + "T" + text[11:].replace("_", ":")
                t = datetime.fromisoformat(iso)
            if t.tzinfo is None:
                t = t.replace(tzinfo=timezone.utc)
            return int(t.timestamp() * 1000)
        except ValueError:
            return None

    def poll(self, max_events: int) -> Tuple[List[dict], Offsets]:
        pointers, offsets = self.inner.poll(max_events)
        rows: List[dict] = []
        for p in pointers:
            path = p.get("BlobPath")
            if not path:
                continue
            source_id = self.extract_source_id(path)
            if source_id is None or source_id not in self.sources:
                # out-of-scope path group (filterPathGroups warning path)
                self.out_of_scope += 1
                continue
            file_time_ms = self.extract_file_time_ms(path)
            info = {
                "path": path,
                "sourceId": source_id,
                "target": self.sources[source_id],
                "fileTimeMs": file_time_ms,
            }
            try:
                for r in read_json_file(path):
                    r["__DataX_FileInfo"] = info
                    rows.append(r)
            except (OSError, ValueError, EOFError):
                # unreadable/corrupt/truncated blob (e.g. a pointer that
                # raced its writer): skip, count, keep the stream alive
                self.out_of_scope += 1
        return rows, offsets


class KafkaSource(StreamingSource):
    """Kafka consumer input, gated on a client library being present.

    reference: input/KafkaStreamingFactory.scala:55-70 — direct Kafka
    DStream with SASL support for EventHub-over-Kafka (:43-49); offset
    checkpointing is an acknowledged TODO there (:51) — here offsets
    ride the same OffsetCheckpointer as every other source, keyed
    (topic, partition).

    The protocol client comes from ``confluent_kafka`` or
    ``kafka-python`` when installed; in their absence the built-in
    dependency-free wire client takes over
    (``runtime/kafka_wire.py`` — Metadata/ListOffsets/Fetch over raw
    sockets, incl. the EventHub-compatible SASL PLAIN path). Message
    values must be JSON event bodies.
    """

    # wire format the raw fast path delivers: whole Kafka v2 record
    # batches, decoded natively by encode_json_bytes(fmt="kafka-v2")
    raw_format = "kafka-v2"

    def __init__(
        self,
        brokers: str,
        topics: List[str],
        group_id: str = "dxtpu",
        name: str = "kafka",
        consumer=None,
        security: Optional[str] = None,
        username: Optional[str] = None,
        password: Optional[str] = None,
    ):
        self.name = name
        self.topics = topics
        # un-acked delivered batches (rows, offsets) — the pipelined
        # host may hold several in flight (same ledger as SocketSource)
        self._fifo = UnackedFifo()
        # checkpointed positions to seek once partitions are assigned
        self._pending_seek: Dict[Tuple[str, int], int] = {}
        # malformed record values dropped by the Python poll paths —
        # drained by the host into ingest_stats/malformed_rows_total so
        # the pilot's flood signal covers Kafka flows too
        self._stats: Dict[str, int] = {}
        # fetched-but-undelivered raw batch spans (binary fast path):
        # (topic, partition, frame bytes, record budget, from, until)
        self._raw_pending: List[Tuple[str, int, bytes, int, int, int]] = []
        if consumer is not None:
            self._consumer = consumer  # injected for tests
            if hasattr(consumer, "fetch_raw"):
                self.poll_raw = self._poll_raw
        else:
            try:
                from confluent_kafka import Consumer  # type: ignore
            except ImportError:
                try:
                    from kafka import KafkaConsumer  # type: ignore
                except ImportError:
                    # no client library installed: the built-in wire
                    # client speaks the Kafka protocol directly (incl.
                    # the EventHub-compatible SASL_SSL path) —
                    # runtime/kafka_wire.py
                    from .kafka_wire import WireKafkaConsumer

                    self._consumer = WireKafkaConsumer(
                        brokers, topics, client_id=group_id,
                        security=security, username=username,
                        password=password,
                    )
                    self._flavor = "wire"
                    # the wire client serves raw v2 record-batch bytes:
                    # expose poll_raw so StreamingHost routes this
                    # source through the native binary fast path
                    # (encode_json_bytes fmt="kafka-v2") like every
                    # other raw source
                    self.poll_raw = self._poll_raw
                    return
                kp_kwargs = {}
                if security:
                    kp_kwargs["security_protocol"] = security.upper()
                    if security.lower().startswith("sasl"):
                        kp_kwargs.update(
                            sasl_mechanism="PLAIN",
                            sasl_plain_username=username,
                            sasl_plain_password=password,
                        )
                self._consumer = KafkaConsumer(
                    *topics, bootstrap_servers=brokers, group_id=group_id,
                    enable_auto_commit=False, **kp_kwargs,
                )
                self._flavor = "kafka-python"
                return
            conf = {
                "bootstrap.servers": brokers,
                "group.id": group_id,
                "enable.auto.commit": False,
                "auto.offset.reset": "earliest",
            }
            if security:
                conf["security.protocol"] = security.upper()
                if security.lower().startswith("sasl"):
                    conf.update({
                        "sasl.mechanism": "PLAIN",
                        "sasl.username": username or "",
                        "sasl.password": password or "",
                    })
            c = Consumer(conf)
            c.subscribe(topics)
            self._consumer = c
            self._flavor = "confluent"
            return
        self._flavor = "injected"

    def start(self, positions: Dict[Tuple[str, int], int]) -> None:
        """Record checkpointed offsets to seek (the reference left Kafka
        offset checkpointing as a TODO, KafkaStreamingFactory.scala:51;
        here OffsetCheckpointer positions override the group's committed
        position). Seeking is deferred until the broker assigns
        partitions — seek-before-assignment errors on both client
        libraries — and applied at the top of each consume pass."""
        self._pending_seek.update(positions)
        if self._pending_seek:
            self._force_assignment()
        self._apply_pending_seeks()

    def _force_assignment(self) -> None:
        """Trigger the group rebalance BEFORE the first data batch so
        checkpoint seeks take effect from batch 1 (assignment happens
        lazily inside poll on both client libraries). confluent: swap in
        an on_assign callback that applies the checkpointed offsets at
        assignment time; kafka-python: a zero-timeout poll assigns (any
        records it returns are before the seek and re-read after it —
        duplicates only, at-least-once)."""
        try:
            if self._flavor == "confluent":
                from confluent_kafka import TopicPartition  # type: ignore

                def on_assign(consumer, partitions):
                    for tp in partitions:
                        seq = self._pending_seek.pop(
                            (tp.topic, tp.partition), None
                        )
                        if seq is not None:
                            tp.offset = seq
                    consumer.assign(partitions)

                self._consumer.subscribe(self.topics, on_assign=on_assign)
                self._consumer.poll(0)
            elif self._flavor == "kafka-python":
                self._consumer.poll(timeout_ms=0, max_records=1)
        except Exception as e:  # noqa: BLE001 — seeks retry per pass
            logger.warning("kafka assignment warm-up failed: %s", e)

    def _apply_pending_seeks(self) -> None:
        if not self._pending_seek:
            return
        seek = getattr(self._consumer, "seek", None)
        if seek is None:
            return
        assignment = getattr(self._consumer, "assignment", None)
        assigned = None
        if assignment is not None:
            try:
                assigned = {
                    (tp.topic, tp.partition) for tp in (assignment() or [])
                }
            except Exception:  # noqa: BLE001 — treat as not-yet-assigned
                assigned = set()
        for (topic, partition), seq in list(self._pending_seek.items()):
            if assigned is not None and (topic, partition) not in assigned:
                continue  # not assigned to this consumer (yet)
            try:
                if self._flavor == "kafka-python":
                    from kafka import TopicPartition  # type: ignore

                    seek(TopicPartition(topic, partition), seq)
                elif self._flavor == "confluent":
                    from confluent_kafka import TopicPartition  # type: ignore

                    seek(TopicPartition(topic, partition, seq))
                else:
                    seek(topic, partition, seq)
                del self._pending_seek[(topic, partition)]
            except Exception as e:  # noqa: BLE001 — retried next pass
                logger.warning(
                    "kafka seek %s/%s -> %s failed (will retry): %s",
                    topic, partition, seq, e,
                )

    def _count_malformed(self, n: int = 1) -> None:
        """A record value that isn't JSON is dropped but COUNTED — the
        host drains this into ``ingest_stats["malformed_rows"]`` /
        ``malformed_rows_total``, so the pilot's malformed-flood signal
        (and the Input_malformed_rows_Count metric) see Kafka garbage
        exactly like socket-line garbage instead of being blind to it."""
        self._stats["malformed_rows"] = (
            self._stats.get("malformed_rows", 0) + n
        )

    def take_ingest_stats(self) -> Dict[str, int]:
        """Drain ingest-side counters accumulated since the last take:
        this source's malformed record values plus any protocol-layer
        counters the wire consumer kept (CRC-skipped corrupt batches)."""
        out, self._stats = self._stats, {}
        wire_stats = getattr(self._consumer, "ingest_stats", None)
        if wire_stats:
            for k, v in wire_stats.items():
                if k == "corrupt_batches":
                    k = "CorruptBatch"
                out[k] = out.get(k, 0) + v
            wire_stats.clear()
        return out

    def _consume(self, max_events: int) -> Tuple[List[dict], Offsets]:
        self._apply_pending_seeks()
        rows: List[dict] = []
        offsets: Offsets = {}
        if self._flavor == "kafka-python":
            while len(rows) < max_events:
                batch = self._consumer.poll(
                    timeout_ms=50, max_records=max_events - len(rows)
                )
                if not batch:
                    break
                for tp, msgs in batch.items():
                    for m in msgs:
                        try:
                            rows.append(json.loads(m.value))
                        except ValueError:
                            self._count_malformed()
                        key = (tp.topic, tp.partition)
                        frm = offsets.get(key, (m.offset, m.offset))[0]
                        offsets[key] = (frm, m.offset + 1)
            return rows, offsets
        # confluent-style consumer: poll one message at a time
        while len(rows) < max_events:
            msg = self._consumer.poll(0.05)
            if msg is None:
                break
            if msg.error():
                # surface broker-side errors and end the pass instead of
                # spinning on instantly-returned error events
                logger.warning("kafka message error: %s", msg.error())
                break
            try:
                rows.append(json.loads(msg.value()))
            except ValueError:
                self._count_malformed()
            key = (msg.topic(), msg.partition())
            frm = offsets.get(key, (msg.offset(), msg.offset()))[0]
            offsets[key] = (frm, msg.offset() + 1)
        return rows, offsets

    # -- the binary fast path ---------------------------------------------
    def _consume_raw(self, max_events: int) -> Tuple[bytes, int, Offsets]:
        """One raw delivery: whole v2 record-batch frames (concatenated
        — exactly what ``decode_record_batches`` / the native walker
        accept), budgeted to ~max_events records at BATCH granularity
        so the decoder's row slots can't silently overflow. Leftover
        batches stay queued for the next poll with their offset
        ranges."""
        self._apply_pending_seeks()
        if not self._raw_pending:
            from .kafka_wire import iter_batch_spans

            for topic, partition, pos, records, next_off in (
                self._consumer.fetch_raw(0.05)
            ):
                cur = pos
                for span in iter_batch_spans(records):
                    until = max(cur, span["next_offset"])
                    self._raw_pending.append((
                        topic, partition,
                        records[span["start"]: span["end"]],
                        max(0, int(span["record_count"])),
                        cur, until,
                    ))
                    cur = until
        parts: List[bytes] = []
        offsets: Offsets = {}
        total = 0
        while self._raw_pending:
            _t, _p, frame, count, frm, until = self._raw_pending[0]
            if parts and total + count > max_events:
                break  # batch granularity: never split a batch
            self._raw_pending.pop(0)
            parts.append(frame)
            total += count
            key = (_t, _p)
            prev = offsets.get(key)
            offsets[key] = (
                (min(prev[0], frm), max(prev[1], until))
                if prev else (frm, until)
            )
        return b"".join(parts), total, offsets

    def _poll_raw(self, max_events: int) -> Tuple[bytes, int, Offsets]:
        """Raw record-batch delivery for the native Kafka fast path
        (bound to ``poll_raw`` when the consumer can serve raw bytes).
        Same un-acked FIFO contract as every buffering source: ack()
        releases + commits oldest-first, requeue_unacked() re-delivers
        after a failed batch."""
        requeued = self._fifo.next_redelivery()
        if requeued is not None:
            blob, n, offsets = requeued
        else:
            blob, n, offsets = self._consume_raw(max_events)
        self._fifo.deliver((blob, n, offsets))
        return blob, n, offsets

    def poll(self, max_events: int) -> Tuple[List[dict], Offsets]:
        """Polled batches join an un-acked FIFO (same contract as
        SocketSource): ack() releases + commits oldest-first, and
        requeue_unacked() re-delivers after a failed batch — the
        broker's committed position only ever advances past sunk data."""
        requeued = self._fifo.next_redelivery()
        if requeued is not None:
            rows, offsets = requeued
        else:
            rows, offsets = self._consume(max_events)
        self._fifo.deliver((rows, offsets))
        return rows, offsets

    def ack(self) -> None:
        released = self._fifo.ack_oldest()
        if released is not None:
            # fifo entries are (rows, offsets) from poll() or
            # (blob, n, offsets) from poll_raw(): offsets ride last
            self._commit(released[-1])

    def requeue_unacked(self) -> None:
        self._fifo.requeue_all()

    def _commit(self, offsets: Offsets) -> None:
        """Commit exactly this batch's end offsets (not the consumer's
        read position, which may include un-sunk in-flight batches)."""
        try:
            if self._flavor == "kafka-python":
                from kafka import TopicPartition  # type: ignore
                from kafka.structs import OffsetAndMetadata  # type: ignore

                # kafka-python-ng adds a required leader_epoch field to
                # the OffsetAndMetadata namedtuple; build by arity so
                # commits don't silently TypeError on the maintained fork
                if len(getattr(OffsetAndMetadata, "_fields", ())) >= 3:
                    def _om(until):
                        return OffsetAndMetadata(until, None, -1)
                else:
                    def _om(until):
                        return OffsetAndMetadata(until, None)
                self._consumer.commit({
                    TopicPartition(t, p): _om(until)
                    for (t, p), (_frm, until) in offsets.items()
                })
            elif self._flavor == "confluent":
                from confluent_kafka import TopicPartition  # type: ignore

                self._consumer.commit(offsets=[
                    TopicPartition(t, p, until)
                    for (t, p), (_frm, until) in offsets.items()
                ], asynchronous=True)
            else:
                self._consumer.commit(offsets)
            # a success re-arms the warning so a NEW failure episode
            # (e.g. ACL revoked weeks later) is not silently muted
            self._commit_warned = False
        except Exception as e:  # noqa: BLE001 — commit is best-effort;
            # at-least-once comes from the in-flight FIFO, commit only
            # narrows the cross-restart replay window
            if not getattr(self, "_commit_warned", False):
                self._commit_warned = True
                logger.warning("kafka commit failed (muting repeats): %s", e)

    def close(self) -> None:
        try:
            self._consumer.close()
        except Exception:  # noqa: BLE001
            pass


def make_source(conf, schema: Schema, source: str = "default") -> StreamingSource:
    """Build the source declared by ``datax.job.input.default.*`` (or one
    ``input.sources.<name>.*`` entry, passed as ``source``) conf.

    reference: the per-mode app entry points (DirectStreamingApp etc.)
    pick the input factory; here one factory keys off ``inputtype``.

    Each named source gets its own offset-ledger name (prefixed with the
    source name for non-default sources) so a multi-source flow's
    checkpoints never collide; the default source keeps the legacy names
    so existing single-source checkpoints stay readable.
    """
    input_type = (conf.get("inputtype") or "local").lower()

    def nm(base: str) -> str:
        return base if source == "default" else f"{source}.{base}"

    if input_type == "local":
        return LocalSource(schema, name=nm("local"))
    if input_type in ("file", "blob"):
        patterns = (conf.get("blobpathregex") or conf.get("path") or "").split(";")
        return FileSource([p for p in patterns if p], name=nm("files"))
    if input_type == "socket":
        port = conf.get_int_option("socket.port") or 0
        return SocketSource(port=port, name=nm("socket"))
    if input_type in ("kafka", "eventhub-kafka"):
        # eventhub-kafka: EventHub through its Kafka-compatible endpoint
        # (reference: KafkaStreamingFactory.scala:43-49 — SASL PLAIN,
        # username $ConnectionString, password the connection string)
        topics = (conf.get("kafka.topics") or "").split(";")
        username = conf.get("kafka.username")
        password = conf.get("kafka.password")
        security = conf.get("kafka.security")
        if input_type == "eventhub-kafka":
            security = security or "sasl_ssl"
            username = username or "$ConnectionString"
            password = password or conf.get("eventhub.connectionstring")
        return KafkaSource(
            conf.get_or_else("kafka.bootstrapservers", "localhost:9092"),
            [t for t in topics if t],
            group_id=conf.get_or_else("kafka.groupid", nm("dxtpu")),
            name=nm("kafka"),
            security=security,
            username=username,
            password=password,
        )
    if input_type == "blobpointer":
        # pointer events arrive over socket or from a pointer file
        pointer_path = conf.get("pointerfile")
        inner: StreamingSource = (
            FileSource([pointer_path], name=nm("pointers"))
            if pointer_path
            else SocketSource(
                port=conf.get_int_option("socket.port") or 0,
                name=nm("socket"),
            )
        )
        sources = {
            sid: sub.get_or_else("target", sid)
            for sid, sub in conf.get_sub_dictionary("source.")
            .group_by_sub_namespace().items()
        }
        kwargs = {}
        if conf.get("sourceidregex"):
            kwargs["source_id_regex"] = conf.get("sourceidregex")
        if conf.get("filetimeregex"):
            kwargs["file_time_regex"] = conf.get("filetimeregex")
        return BlobPointerSource(
            inner, sources, file_time_format=conf.get("filetimeformat"), **kwargs
        )
    raise ValueError(f"unsupported input type {input_type!r}")
