"""Protocol monitor — the DYNAMIC half of the DX9xx exactly-once
story (``analysis/protocheck.py`` is the static half; both check the
SAME rule table, ``analysis/protospec.py``).

The static pass proves the SOURCE orders the delivery protocol
correctly (sink emit -> pointer flip -> FIFO ack -> offset commit,
requeue on failure). This monitor proves each LIVE batch did: the
host's batch tail records every protocol event it performs —
``SINK_EMIT`` after dispatcher fan-out, ``POINTER_FLIP`` after
``processor.commit()``, one ``FIFO_ACK`` per source, the post-commit
``DURABLE_WRITE``/``STATE_PUSH``/``OFFSET_COMMIT`` checkpoint trio,
``REQUEUE`` on the failure path — and at the end of the tail the
sequence is SEALED and its linearization validated with
``protospec.check_sequence`` against the runtime rules (DX900
durability-before-ack, DX901 sink-before-pointer-commit, DX902
ack-at-most-once-per-batch).

Every violated rule becomes ONE runtime **DX906** event per batch —
drained by the host into the flight recorder beside sanitizer poison
hits — and bumps ``Protocol_Violation_Count``; every recorded event
bumps ``Protocol_Events_Count``. A bounded ring of recent sealed
linearizations is kept for post-mortem inspection
(``recent_sequences``). The rescale handoff (DX905) is static-only:
it is a call-order property of the control plane's config build, not
of a batch's event list — the chaos rescale drill covers it end to
end at the batch level instead.

Armed via conf ``datax.job.process.debug.protocolmonitor`` (a debug
mode like the buffer sanitizer: the cost is a few appends + one list
scan per batch — bench.py's ``protocheck`` block keeps the overhead a
committed number). Armed in every chaos drill, asserting the engine
holds its ordering under preemption, sink outage, slowdown, partition
loss and rescale.
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Deque, Dict, List, Optional

from ..analysis.protospec import check_sequence

# sealed linearizations kept for post-mortem (per monitor instance)
HISTORY = 64


class ProtocolMonitor:
    """Record per-batch protocol events; validate each sealed batch.

    Thread-safe: the batch tail runs on the landing worker (or inline)
    while the host drains events/metrics at collect time.
    """

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.events_recorded = 0   # lifetime protocol events
        self.batches_sealed = 0    # lifetime sealed linearizations
        self.violations = 0        # lifetime DX906s fired
        self._batch: List[Dict[str, object]] = []
        self._history: Deque[Dict[str, object]] = deque(maxlen=HISTORY)
        self._events: List[Dict[str, object]] = []
        self._events_drained = 0
        self._violations_drained = 0

    # -- the recording half (batch-tail hooks) ----------------------------
    def record(self, kind: str, source: str = "",
               detail: str = "") -> None:
        """One protocol event performed by the current batch."""
        with self._lock:
            self.events_recorded += 1
            self._batch.append({
                "kind": kind,
                "source": str(source),
                "detail": str(detail),
            })

    def seal_batch(
        self, batch_time_ms: Optional[float] = None,
        failed: bool = False,
    ) -> int:
        """Close the current batch's sequence and validate its
        linearization against the runtime rules. Returns the number of
        NEW violations (at most one per rule per batch)."""
        with self._lock:
            seq, self._batch = self._batch, []
        if not seq:
            return 0
        found = check_sequence(seq, failed=failed)
        with self._lock:
            self.batches_sealed += 1
            self._history.append({
                "batchTime": batch_time_ms,
                "failed": failed,
                "sequence": seq,
                "violations": [c for c, _ in found],
            })
            for code, msg in found:
                self.violations += 1
                self._events.append({
                    "code": "DX906",
                    "rule": code,
                    "failed": failed,
                    "batchTime": batch_time_ms,
                    "sequence": [str(e.get("kind")) for e in seq],
                    "message": (
                        f"DX906: delivery-protocol violation ({code}) "
                        f"— {msg}"
                    ),
                })
        return len(found)

    def recent_sequences(self) -> List[Dict[str, object]]:
        """The last ``HISTORY`` sealed linearizations (post-mortem)."""
        with self._lock:
            return list(self._history)

    # -- event/metric drains (host collect cadence) -----------------------
    def drain_events(self) -> List[Dict[str, object]]:
        """DX906 events since the last drain (flight-recorder feed)."""
        with self._lock:
            events, self._events = self._events, []
        return events

    def drain_metric_deltas(self) -> Dict[str, float]:
        """Protocol_* metric deltas since the last drain; the violation
        count is only reported once nonzero (silence == health, like
        the sanitizer's poison-hit counter)."""
        with self._lock:
            ev = self.events_recorded - self._events_drained
            self._events_drained = self.events_recorded
            v = self.violations - self._violations_drained
            self._violations_drained = self.violations
        out: Dict[str, float] = {}
        if ev:
            out["Protocol_Events_Count"] = float(ev)
        if v:
            out["Protocol_Violation_Count"] = float(v)
        return out


def from_conf(dbg_conf) -> Optional[ProtocolMonitor]:
    """``datax.job.process.debug.protocolmonitor=true`` arms the
    monitor (``dbg_conf`` is the ``debug.`` sub-dictionary)."""
    # dx-conf: read debug.protocolmonitor default=false
    flag = (dbg_conf.get_or_else("protocolmonitor", "false") or "").lower()
    return ProtocolMonitor() if flag == "true" else None
