"""Time windows as device-resident ring buffers.

The reference implements ``TIMEWINDOW('5 minutes')`` by caching each
batch's filtered RDD in driver memory, evicting stale ones, and
re-unioning per batch (CommonProcessorFactory.scala:156-236,
TimeWindowHandler.scala:23-68) — recompute-by-union, O(window/batch)
cached RDDs. TPU-native instead: a fixed ring of K batch slots lives on
device as [K, capacity] column arrays; each batch overwrites one slot
in-jit, timestamps are kept relative to the current batch base (shifted
by the base delta each step), and a window table is just the flattened
ring masked by ``ts >= now - duration`` — no host round-trips, no
recompute, O(1) per batch.

Windowed views (``DataXProcessedInput_5minutes``) are exposed to the
pipeline as plain input tables of capacity K*capacity.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..compile.planner import TableData, ViewSchema


@jax.tree_util.register_pytree_node_class
@dataclass
class WindowBuffers:
    """Ring of K batch slots: cols are [K, capacity]."""

    cols: Dict[str, jnp.ndarray]
    valid: jnp.ndarray  # [K, capacity]

    def tree_flatten(self):
        names = tuple(sorted(self.cols))
        return tuple(self.cols[n] for n in names) + (self.valid,), names

    @classmethod
    def tree_unflatten(cls, names, children):
        return cls(dict(zip(names, children[:-1])), children[-1])

    @property
    def slots(self) -> int:
        return int(self.valid.shape[0])

    @property
    def capacity(self) -> int:
        return int(self.valid.shape[1])


def num_slots(max_window_s: float, watermark_s: float, interval_s: float) -> int:
    """Slots needed to retain max_window + watermark of history
    (the eviction horizon at CommonProcessorFactory.scala:185-194)."""
    return max(1, math.ceil((max_window_s + watermark_s) / max(interval_s, 1e-9))) + 1


def make_buffers(schema: ViewSchema, capacity: int, slots: int) -> WindowBuffers:
    dtypes = {"double": jnp.float32, "boolean": jnp.bool_}
    cols = {
        c: jnp.zeros((slots, capacity), dtype=dtypes.get(t, jnp.int32))
        for c, t in schema.types.items()
    }
    return WindowBuffers(cols, jnp.zeros((slots, capacity), dtype=jnp.bool_))


def update_buffers(
    buf: WindowBuffers,
    batch: TableData,
    slot: jnp.ndarray,  # scalar int32
    delta_ms: jnp.ndarray,  # scalar int32: new_base_ms - old_base_ms
    ts_col: str,
) -> WindowBuffers:
    """Rebase stored timestamps to the new batch base, then overwrite the
    ring slot with the new batch. Traced; runs inside the step jit."""
    new_cols = {}
    for c, arr in buf.cols.items():
        if c == ts_col:
            arr = arr - delta_ms
        new_cols[c] = jax.lax.dynamic_update_index_in_dim(
            arr, batch.cols[c], slot, axis=0
        )
    new_valid = jax.lax.dynamic_update_index_in_dim(
        buf.valid, batch.valid, slot, axis=0
    )
    return WindowBuffers(new_cols, new_valid)


def window_table(
    buf: WindowBuffers,
    duration_ms: int,
    now_rel_ms: jnp.ndarray,
    ts_col: str,
) -> TableData:
    """Flattened ring masked to the window span [now - duration, now]."""
    k, cap = buf.valid.shape
    ts = buf.cols[ts_col].reshape(k * cap)
    valid = buf.valid.reshape(k * cap)
    in_window = (ts >= (now_rel_ms - jnp.int32(duration_ms))) & (ts <= now_rel_ms)
    cols = {c: a.reshape(k * cap) for c, a in buf.cols.items()}
    return TableData(cols, valid & in_window)
